"""Flax feature-extractor architectures for embedding-network metrics
(SURVEY.md §2.9: FID-InceptionV3, LPIPS backbones) + weight conversion."""
from .inception import FIDInceptionV3, convert_torch_state_dict, make_fid_inception
from .lpips import LPIPSNet, convert_lpips_torch, lpips_head_params, make_lpips

__all__ = [
    "FIDInceptionV3",
    "LPIPSNet",
    "convert_lpips_torch",
    "convert_torch_state_dict",
    "lpips_head_params",
    "make_fid_inception",
    "make_lpips",
]
