"""Runtime tracer-hygiene guards — the dynamic complement to ``tools/tpulint``.

The static analyzer proves the *code* can't host-sync or retrace; this module
proves the *process* didn't. ``strict_mode()`` arms ``jax.transfer_guard`` so
any implicit device↔host transfer raises at the offending line, and registers
a compile observer on the process-global executable cache
(``metric._COMPILE_OBSERVERS``) so an unexpected retrace — a new input
shape/dtype hitting an already-warm executable — fails fast instead of
silently recompiling every step.

Usage::

    from torchmetrics_tpu.debug import strict_mode

    metric.update(p, t)           # warm-up: compiles are expected here
    with strict_mode():           # steady state: no transfers, no retraces
        metric.update(p, t)
        metric.update(p, t)

Used by ``tests/test_strict_mode.py`` and ``bench.py --smoke``.
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax

from . import metric as _metric
from .observability import ledger as _ledger
from .observability import spans as _spans
from .parallel import elastic as _elastic
from .parallel import strategies as _strategies


class StrictModeViolation(RuntimeError):
    """A dispatch-contract violation observed at runtime under strict_mode()."""


@dataclass
class StrictStats:
    """Counters accumulated while a ``strict_mode()`` context is active.

    The ``bytes_*``/``collectives_issued`` fields are wire-counter deltas
    (``parallel.strategies.wire_stats``) captured between entering and
    leaving the context: modelled sync traffic issued while it was active
    (in-graph collectives count once per trace, eager backend gathers once
    per call). Filled in at context exit — read them after the ``with``.
    """

    compiles: int = 0
    retraces: int = 0
    new_executables: int = 0
    bytes_reduced: int = 0
    bytes_gathered: int = 0
    collectives_issued: int = 0
    degraded_syncs: int = 0
    sync_retries: int = 0
    coverage_fraction: Optional[float] = None
    # filled at exit when span tracing is armed (observability.enable_tracing):
    # per-phase {name: {count, total_s, max_s}} over spans completed inside the
    # context, and the top-3 slowest (name, duration_s) — so a blown budget
    # names the phase that blew it
    span_phase_totals: Dict[str, Dict[str, float]] = field(default_factory=dict)
    slowest_spans: List[Tuple[str, float]] = field(default_factory=list)


def _looks_like_transfer_guard_error(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return "transfer" in msg and ("disallow" in msg or "guard" in msg)


@contextlib.contextmanager
def strict_mode(
    *,
    transfer_guard: Optional[str] = "disallow",
    max_retraces: int = 0,
    max_new_executables: Optional[int] = None,
    max_degraded_syncs: int = 0,
) -> Iterator[StrictStats]:
    """Context that raises :class:`StrictModeViolation` on contract breaks.

    Args:
        transfer_guard: value for ``jax.transfer_guard`` (``"disallow"``,
            ``"log"``, ``"allow"``, ...) or ``None`` to leave transfers
            unguarded. Compilation itself transfers constants host→device, so
            pass ``"allow"`` (or warm up first) when compiles are expected
            inside the context.
        max_retraces: how many retraces (recompiles of an already-compiled
            executable under a new input signature) to tolerate. Default 0:
            steady-state code must not retrace.
        max_new_executables: budget for first-time compiles inside the
            context, or ``None`` for unlimited. Set to 0 to assert fully-warm
            steady state.
        max_degraded_syncs: how many degraded elastic sync rounds (coverage
            below 100% — a peer dropped out or a retry budget was exhausted,
            see ``parallel.elastic``) to tolerate. Default 0: existing tests
            stay strict — any partial compute raises. Raise it for
            preemption-tolerant eval loops that accept annotated partial
            results.
    """
    stats = StrictStats()
    spans_before = len(_spans.collected_spans()) if _spans.ENABLED else 0

    def _span_report() -> str:
        """One-line per-phase summary naming where the time went (tracing on)."""
        if not _spans.ENABLED:
            return ""
        inside = _spans.collected_spans()[spans_before:]
        if not inside:
            return ""
        totals = _spans.phase_totals(inside)
        parts = [
            f"{name}: {agg['count']}x {agg['total_s'] * 1e3:.2f}ms"
            for name, agg in sorted(
                totals.items(), key=lambda kv: kv[1]["total_s"], reverse=True
            )
        ]
        return " [span phases — " + ", ".join(parts) + "]"

    def _observe(key: Any, new_compiles: int, retraces: int) -> None:
        stats.compiles += new_compiles
        stats.retraces += retraces
        stats.new_executables += new_compiles - retraces
        if stats.retraces > max_retraces:
            # ledger attribution names the metric/op instead of an opaque
            # key tuple; works unarmed (pure key introspection)
            raise StrictModeViolation(
                f"unexpected retrace under strict_mode in "
                f"{_ledger.describe_key(key)} (executable key={key!r}): "
                f"{stats.retraces} retrace(s) > budget {max_retraces}. Input "
                "shapes/dtypes are churning against a warm executable — pad or "
                "bucket inputs, or raise max_retraces if this churn is intended."
                + _span_report()
            )
        if max_new_executables is not None and stats.new_executables > max_new_executables:
            raise StrictModeViolation(
                f"unexpected compile under strict_mode in "
                f"{_ledger.describe_key(key)} (executable key={key!r}): "
                f"{stats.new_executables} new executable(s) > budget "
                f"{max_new_executables}. Warm the metric up before entering "
                "strict_mode, or raise max_new_executables."
                + _span_report()
            )

    def _observe_degrade(coverage: Any) -> None:
        stats.degraded_syncs += 1
        stats.coverage_fraction = coverage.fraction
        if stats.degraded_syncs > max_degraded_syncs:
            raise StrictModeViolation(
                f"degraded sync under strict_mode: coverage "
                f"{coverage.fraction:.3f} ({coverage.ranks_present}/"
                f"{coverage.ranks_expected} ranks, {coverage.samples_present}/"
                f"{coverage.samples_expected} samples); {stats.degraded_syncs} "
                f"degraded round(s) > budget {max_degraded_syncs}. A peer "
                "dropped out or a retry budget was exhausted — raise "
                "max_degraded_syncs to accept annotated partial results."
                + _span_report()
            )

    _metric._COMPILE_OBSERVERS.append(_observe)
    _elastic._DEGRADE_OBSERVERS.append(_observe_degrade)
    guard = jax.transfer_guard(transfer_guard) if transfer_guard is not None else contextlib.nullcontext()
    wire_before = _strategies.wire_stats()
    elastic_before = _elastic.elastic_stats()
    try:
        with guard:
            yield stats
    except StrictModeViolation:
        raise
    except Exception as exc:
        if _looks_like_transfer_guard_error(exc):
            raise StrictModeViolation(
                f"implicit device<->host transfer under strict_mode: {exc}"
            ) from exc
        raise
    finally:
        _metric._COMPILE_OBSERVERS.remove(_observe)
        _elastic._DEGRADE_OBSERVERS.remove(_observe_degrade)
        wire_after = _strategies.wire_stats()
        stats.bytes_reduced = wire_after["bytes_reduced"] - wire_before["bytes_reduced"]
        stats.bytes_gathered = wire_after["bytes_gathered"] - wire_before["bytes_gathered"]
        stats.collectives_issued = (
            wire_after["collectives_issued"] - wire_before["collectives_issued"]
        )
        stats.sync_retries = (
            _elastic.elastic_stats()["retries"] - elastic_before["retries"]
        )
        if _spans.ENABLED:
            inside = _spans.collected_spans()[spans_before:]
            stats.span_phase_totals = _spans.phase_totals(inside)
            stats.slowest_spans = [
                (s.name, s.duration_s) for s in _spans.slowest_spans(3, inside)
            ]


__all__ = ["StrictModeViolation", "StrictStats", "strict_mode"]
