"""Multilabel ranking metric classes.

Parity: reference ``src/torchmetrics/classification/ranking.py``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..functional.classification.ranking import (
    _format_ml,
    _multilabel_coverage_error_update,
    _multilabel_ranking_average_precision_update,
    _multilabel_ranking_loss_update,
)
from ..metric import Metric

Array = jax.Array


class _AbstractRanking(Metric):
    is_differentiable = False
    full_state_update = False

    def __init__(self, num_labels: int, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measure", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def compute(self) -> Array:
        return self.measure / self.total


class MultilabelCoverageError(_AbstractRanking):
    """Parity: reference ``classification/ranking.py:32``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MultilabelCoverageError
        >>> metric = MultilabelCoverageError(num_labels=3)
        >>> preds = jnp.asarray([[0.9, 0.1, 0.6], [0.2, 0.8, 0.3], [0.7, 0.4, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [1, 0, 1]])
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        1.6667
    """

    higher_is_better = False
    plot_lower_bound = 0.0

    def update(self, preds: Array, target: Array) -> None:
        p, t, mask = _format_ml(preds, target, self.num_labels, self.ignore_index)
        measure, total = _multilabel_coverage_error_update(p, t, mask)
        self.measure = self.measure + measure
        self.total = self.total + total


class MultilabelRankingAveragePrecision(_AbstractRanking):
    """Parity: reference ``classification/ranking.py:127``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MultilabelRankingAveragePrecision
        >>> metric = MultilabelRankingAveragePrecision(num_labels=3)
        >>> preds = jnp.asarray([[0.9, 0.1, 0.6], [0.2, 0.8, 0.3], [0.7, 0.4, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [1, 0, 1]])
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        1.0
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def update(self, preds: Array, target: Array) -> None:
        p, t, mask = _format_ml(preds, target, self.num_labels, self.ignore_index)
        measure, total = _multilabel_ranking_average_precision_update(p, t, mask)
        self.measure = self.measure + measure
        self.total = self.total + total


class MultilabelRankingLoss(_AbstractRanking):
    """Parity: reference ``classification/ranking.py:221``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MultilabelRankingLoss
        >>> metric = MultilabelRankingLoss(num_labels=3)
        >>> preds = jnp.asarray([[0.9, 0.1, 0.6], [0.2, 0.8, 0.3], [0.7, 0.4, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [1, 0, 1]])
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.0
    """

    higher_is_better = False
    plot_lower_bound = 0.0

    def update(self, preds: Array, target: Array) -> None:
        p, t, mask = _format_ml(preds, target, self.num_labels, self.ignore_index)
        measure, total = _multilabel_ranking_loss_update(p, t, mask)
        self.measure = self.measure + measure
        self.total = self.total + total
