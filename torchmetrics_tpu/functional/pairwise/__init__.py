"""Pairwise distance/similarity matrices (functional-only domain).

Parity targets: reference ``functional/pairwise/{cosine,euclidean,linear,
manhattan,minkowski}.py`` + ``helpers.py``. All are single dense XLA
programs; the euclidean/linear forms are expressed via one matmul so the
MXU does the work.
"""
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _mm(a, b):
    """fp32-exact matmul even on TPU (metrics must not silently bf16)."""
    return jnp.matmul(a, b, precision=lax.Precision.HIGHEST)

Array = jax.Array


def _check_input(x: Array, y: Optional[Array], zero_diagonal: Optional[bool]):
    """Parity: reference ``functional/pairwise/helpers.py:_check_input``."""
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                f" `d` should be same as the last dimension of `x`, but got {y.shape}"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x.astype(jnp.float32), y.astype(jnp.float32), zero_diagonal


def _reduce(matrix: Array, reduction: Optional[str]) -> Array:
    """Parity: reference ``helpers.py:_reduce_distance_matrix``."""
    if reduction == "mean":
        return jnp.mean(matrix, axis=-1)
    if reduction == "sum":
        return jnp.sum(matrix, axis=-1)
    if reduction in (None, "none"):
        return matrix
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")


def _zero_diag(matrix: Array, zero_diagonal: bool) -> Array:
    if zero_diagonal:
        n = min(matrix.shape)
        matrix = matrix.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    return matrix


def pairwise_cosine_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Cosine similarity matrix x·yᵀ/(|x||y|). Parity: ``pairwise/cosine.py``."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
    yn = y / jnp.maximum(jnp.linalg.norm(y, axis=-1, keepdims=True), 1e-12)
    return _reduce(_zero_diag(_mm(xn, yn.T), zero_diagonal), reduction)


def pairwise_euclidean_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Euclidean distance matrix via the |x|²+|y|²-2x·y matmul expansion."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_sq = jnp.sum(x * x, axis=-1, keepdims=True)
    y_sq = jnp.sum(y * y, axis=-1, keepdims=True)
    d2 = x_sq + y_sq.T - 2.0 * _mm(x, y.T)
    matrix = jnp.sqrt(jnp.maximum(d2, 0.0))
    return _reduce(_zero_diag(matrix, zero_diagonal), reduction)


def pairwise_linear_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Inner-product similarity matrix x·yᵀ. Parity: ``pairwise/linear.py``."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    return _reduce(_zero_diag(_mm(x, y.T), zero_diagonal), reduction)


def pairwise_manhattan_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """L1 distance matrix. Parity: ``pairwise/manhattan.py``."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    matrix = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    return _reduce(_zero_diag(matrix, zero_diagonal), reduction)


def pairwise_minkowski_distance(
    x: Array, y: Optional[Array] = None, exponent: float = 2.0,
    reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None,
) -> Array:
    """Lp distance matrix. Parity: ``pairwise/minkowski.py``."""
    if not (isinstance(exponent, (int, float)) and exponent >= 1):
        raise ValueError(f"Argument `exponent` must be a float larger than 1, but got {exponent}")
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    matrix = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent, axis=-1) ** (1.0 / exponent)
    return _reduce(_zero_diag(matrix, zero_diagonal), reduction)


__all__ = [
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
    "pairwise_minkowski_distance",
]
