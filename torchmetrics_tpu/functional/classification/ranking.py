"""Multilabel ranking metrics.

Parity: reference ``src/torchmetrics/functional/classification/ranking.py``
(399 LoC): coverage error, label ranking average precision, label ranking
loss. All are O(N·L log L) rank transforms — sorts are cheap on TPU.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utils.compute import normalize_logits_if_needed

Array = jax.Array


def _rank_data(x: Array) -> Array:
    """1-indexed ranks along the last axis (tie-unaware; used on continuous
    scores)."""
    order = jnp.argsort(x, axis=-1)
    idx = jnp.broadcast_to(jnp.arange(x.shape[-1]), x.shape)
    ranks = jnp.put_along_axis(jnp.zeros_like(order), order, idx, axis=-1, inplace=False)
    return ranks + 1


def _format_ml(preds: Array, target: Array, num_labels: int, ignore_index: Optional[int]):
    # reference routes through the multilabel confusion format, which
    # sigmoids before masking (confusion_matrix.py:503-509)
    target = target.reshape(-1, num_labels)
    preds = normalize_logits_if_needed(preds.reshape(-1, num_labels).astype(jnp.float32), "sigmoid")
    if ignore_index is not None:
        mask = target != ignore_index
        target = jnp.clip(target, 0, 1)
    else:
        mask = jnp.ones_like(target, dtype=bool)
    return preds, target, mask


def _multilabel_coverage_error_update(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array]:
    """Parity: reference ``ranking.py:66`` (sklearn coverage_error)."""
    big = jnp.where(target == 1, preds, jnp.inf)
    min_relevant = jnp.min(jnp.where(mask, big, jnp.inf), axis=1, keepdims=True)
    coverage_per = jnp.sum((preds >= min_relevant) & mask, axis=1).astype(jnp.float32)
    has_rel = jnp.isfinite(min_relevant[:, 0])
    coverage = jnp.sum(jnp.where(has_rel, coverage_per, 0.0))
    return coverage, jnp.asarray(target.shape[0], dtype=jnp.float32)


def multilabel_coverage_error(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Parity: reference ``ranking.py:94``."""
    preds, target, mask = _format_ml(preds, target, num_labels, ignore_index)
    coverage, total = _multilabel_coverage_error_update(preds, target, mask)
    return coverage / total


def _multilabel_ranking_average_precision_update(
    preds: Array, target: Array, mask: Array
) -> Tuple[Array, Array]:
    """Parity: reference ``ranking.py:157`` (sklearn LRAP)."""
    n, l = preds.shape
    neg_preds = -preds
    order = jnp.argsort(neg_preds, axis=1)
    ranks = jnp.put_along_axis(
        jnp.zeros_like(order), order, jnp.broadcast_to(jnp.arange(l), (n, l)), axis=1, inplace=False
    ) + 1  # rank of each label by decreasing score

    rel = (target == 1) & mask
    # L_ij = number of relevant labels ranked at or above label j
    def per_sample(r, rl):
        # for each relevant j: count of relevant k with rank_k <= rank_j, / rank_j
        rr = jnp.where(rl, r, jnp.inf)
        cnt = jnp.sum((rr[None, :] <= rr[:, None]) & rl[None, :], axis=1)
        score = jnp.where(rl, cnt / r, 0.0)
        n_rel = jnp.sum(rl)
        return jnp.where(n_rel > 0, jnp.sum(score) / jnp.maximum(n_rel, 1), 1.0)

    scores = jax.vmap(per_sample)(ranks, rel)
    return jnp.sum(scores), jnp.asarray(n, dtype=jnp.float32)


def multilabel_ranking_average_precision(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Parity: reference ``ranking.py:186``."""
    preds, target, mask = _format_ml(preds, target, num_labels, ignore_index)
    score, total = _multilabel_ranking_average_precision_update(preds, target, mask)
    return score / total


def _multilabel_ranking_loss_update(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array]:
    """Parity: reference ``ranking.py:255`` (sklearn label_ranking_loss)."""
    rel = (target == 1) & mask
    irr = (target == 0) & mask

    def per_sample(p, r, i):
        # fraction of (relevant, irrelevant) pairs that are mis-ordered
        n_rel = jnp.sum(r)
        n_irr = jnp.sum(i)
        bad = jnp.sum((p[:, None] <= p[None, :]) & r[:, None] & i[None, :])
        denom = jnp.maximum(n_rel * n_irr, 1)
        return jnp.where((n_rel > 0) & (n_irr > 0), bad / denom, 0.0)

    losses = jax.vmap(per_sample)(preds, rel, irr)
    return jnp.sum(losses), jnp.asarray(preds.shape[0], dtype=jnp.float32)


def multilabel_ranking_loss(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Parity: reference ``ranking.py:284``."""
    preds, target, mask = _format_ml(preds, target, num_labels, ignore_index)
    loss, total = _multilabel_ranking_loss_update(preds, target, mask)
    return loss / total
