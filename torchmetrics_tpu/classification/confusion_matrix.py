"""ConfusionMatrix metric classes.

Parity: reference ``src/torchmetrics/classification/confusion_matrix.py``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_update,
    _confusion_matrix_reduce,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_update,
)
from ..metric import Metric
from ..utils.enums import ClassificationTask
from .base import _ClassificationTaskWrapper

Array = jax.Array


class BinaryConfusionMatrix(Metric):
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 normalize: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, mask = _binary_confusion_matrix_format(preds, target, self.threshold, self.ignore_index)
        self.confmat = self.confmat + _binary_confusion_matrix_update(preds, target, mask)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)

    def plot(self, val=None, ax=None, add_text=True, labels=None):
        from ..utils.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels)


class MulticlassConfusionMatrix(Metric):
    """Confusion matrix for multiclass tasks. Parity: reference ``classification/confusion_matrix.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import MulticlassConfusionMatrix
        >>> metric = MulticlassConfusionMatrix(num_classes=3)
        >>> metric.update(jnp.asarray([0, 1, 2, 2]), jnp.asarray([0, 1, 1, 2]))
        >>> metric.compute().tolist()
        [[1, 0, 0], [0, 1, 1], [0, 0, 1]]
    """
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, num_classes: int, ignore_index: Optional[int] = None,
                 normalize: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, mask = _multiclass_confusion_matrix_format(preds, target, self.num_classes, self.ignore_index)
        self.confmat = self.confmat + _multiclass_confusion_matrix_update(preds, target, mask, self.num_classes)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)

    def plot(self, val=None, ax=None, add_text=True, labels=None):
        from ..utils.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels)


class MultilabelConfusionMatrix(Metric):
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 normalize: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_labels = num_labels
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_labels, 2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, mask = _multilabel_confusion_matrix_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        self.confmat = self.confmat + _multilabel_confusion_matrix_update(preds, target, mask, self.num_labels)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)


class ConfusionMatrix(_ClassificationTaskWrapper):
    """Task facade. Parity: reference ``classification/confusion_matrix.py:376``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ConfusionMatrix
        >>> metric = ConfusionMatrix(task="multiclass", num_classes=3)
        >>> preds = jnp.asarray([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.6, 0.1]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> metric.update(preds, target)
        >>> metric.compute().tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """

    def __new__(cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
                num_labels: Optional[int] = None, normalize: Optional[str] = None,
                ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"normalize": normalize, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryConfusionMatrix(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
            return MulticlassConfusionMatrix(num_classes, **kwargs)
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)}` was passed.")
        return MultilabelConfusionMatrix(num_labels, threshold, **kwargs)
