"""Lightweight metric-overhead instrumentation.

The reference has no profiling beyond a usage ping (SURVEY.md §5); the
north-star benchmark here is *metric-sync wallclock/step*, so the framework
ships a small built-in timer:

- :class:`StepTimer` — accumulates wall-clock per named phase with
  block-until-ready semantics so device work is actually counted;
- :func:`annotate` — wraps a phase in ``jax.profiler.TraceAnnotation`` so
  the phases show up in TPU profiler traces (xprof) too.
"""
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Any, Dict

import jax

__all__ = ["StepTimer", "annotate"]


@contextmanager
def annotate(name: str):
    """jax.profiler trace annotation (visible in xprof timelines)."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Accumulate per-phase wall-clock across steps.

    Example::

        timer = StepTimer()
        for batch in loader:
            with timer.phase("metric_update"):
                state = metric.update_state(state, *batch)
        print(timer.summary())   # {"metric_update": {"total_s": ..., "count": ..., "mean_ms": ...}}
    """

    def __init__(self, block_until_ready: bool = True) -> None:
        self._totals: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self._block = block_until_ready
        self._live: Any = None

    @contextmanager
    def phase(self, name: str, result: Any = None):
        """Time a phase; set ``timer.live = device_value`` inside the block
        (or pass ``result``) to block on it before stopping the clock.
        Reentrant (nested phases keep their own live slots) and
        exception-safe (time is recorded even if the block raises)."""
        outer_live = self._live
        self._live = result
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield self
            if self._block and self._live is not None:
                jax.block_until_ready(self._live)
        finally:
            self._totals[name] += time.perf_counter() - t0
            self._counts[name] += 1
            self._live = outer_live

    @property
    def live(self) -> Any:
        return self._live

    @live.setter
    def live(self, value: Any) -> None:
        self._live = value

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "total_s": self._totals[name],
                "count": self._counts[name],
                "mean_ms": 1000.0 * self._totals[name] / max(self._counts[name], 1),
            }
            for name in self._totals
        }

    def reset(self) -> None:
        self._totals.clear()
        self._counts.clear()
