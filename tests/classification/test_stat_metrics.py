"""Stat-scores engine consumers vs sklearn oracles.

Parity model: reference ``tests/unittests/classification/test_accuracy.py`` et
al. — functional + class results compared against sklearn on single batches
and on the accumulated union, in eager/jit/ddp-emulated/shard_map modes.
"""
from functools import partial

import numpy as np
import pytest
from sklearn import metrics as skm

import jax.numpy as jnp

from tests.conftest import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES
from tests.helpers.testers import MetricTester

from torchmetrics_tpu.classification import (
    BinaryAccuracy,
    BinaryConfusionMatrix,
    BinaryF1Score,
    BinaryMatthewsCorrCoef,
    BinaryPrecision,
    BinaryRecall,
    BinarySpecificity,
    MulticlassAccuracy,
    MulticlassCohenKappa,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassJaccardIndex,
    MulticlassMatthewsCorrCoef,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    MultilabelF1Score,
    MultilabelHammingDistance,
)
from torchmetrics_tpu.functional.classification import (
    binary_accuracy,
    binary_f1_score,
    multiclass_accuracy,
    multiclass_f1_score,
    multilabel_f1_score,
)

NUM_LABELS = 4
seed = np.random.RandomState(7)
BIN_PROBS = seed.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
BIN_TARGET = seed.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
MC_PROBS = seed.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32)
MC_PROBS /= MC_PROBS.sum(-1, keepdims=True)
MC_TARGET = seed.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
ML_PROBS = seed.rand(NUM_BATCHES, BATCH_SIZE, NUM_LABELS).astype(np.float32)
ML_TARGET = seed.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_LABELS))


def _sk_binary(fn):
    return lambda p, t: fn(t, p > 0.5)


def _sk_multiclass(fn, **kw):
    return lambda p, t: fn(t, p.argmax(-1) if p.ndim > t.ndim else p, **kw)


def _sk_multilabel(fn, **kw):
    return lambda p, t: fn(t.reshape(-1, NUM_LABELS), (p > 0.5).reshape(-1, NUM_LABELS).astype(int), **kw)


class TestBinaryFamily(MetricTester):
    @pytest.mark.parametrize(
        ("metric_class", "sk_fn"),
        [
            (BinaryAccuracy, _sk_binary(skm.accuracy_score)),
            (BinaryPrecision, _sk_binary(partial(skm.precision_score, zero_division=0))),
            (BinaryRecall, _sk_binary(partial(skm.recall_score, zero_division=0))),
            (BinaryF1Score, _sk_binary(partial(skm.f1_score, zero_division=0))),
            (BinaryMatthewsCorrCoef, _sk_binary(skm.matthews_corrcoef)),
        ],
    )
    def test_binary(self, metric_class, sk_fn):
        self.run_class_metric_test(BIN_PROBS, BIN_TARGET, metric_class, sk_fn, ddp=True)

    def test_binary_specificity(self):
        def sk_spec(p, t):
            tn, fp, fn, tp = skm.confusion_matrix(t, p > 0.5).ravel()
            return tn / (tn + fp)

        self.run_class_metric_test(BIN_PROBS, BIN_TARGET, BinarySpecificity, sk_spec)

    def test_binary_confusion_matrix(self):
        self.run_class_metric_test(
            BIN_PROBS, BIN_TARGET, BinaryConfusionMatrix,
            lambda p, t: skm.confusion_matrix(t, p > 0.5), check_batch=False,
        )

    def test_binary_functional(self):
        self.run_functional_metric_test(BIN_PROBS, BIN_TARGET, binary_accuracy, _sk_binary(skm.accuracy_score))
        self.run_functional_metric_test(
            BIN_PROBS, BIN_TARGET, binary_f1_score, _sk_binary(partial(skm.f1_score, zero_division=0))
        )

    def test_binary_shard_map(self):
        self.run_shard_map_test(BIN_PROBS, BIN_TARGET, BinaryAccuracy, _sk_binary(skm.accuracy_score))


class TestMulticlassFamily(MetricTester):
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    def test_accuracy_averages(self, average):
        if average == "micro":
            sk = _sk_multiclass(skm.accuracy_score)
        elif average is None:
            sk = _sk_multiclass(partial(skm.recall_score, average=None, labels=range(NUM_CLASSES), zero_division=0))
        else:
            sk = _sk_multiclass(partial(skm.recall_score, average=average, zero_division=0))
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassAccuracy, sk,
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            ddp=(average == "micro"),
        )

    @pytest.mark.parametrize(
        ("metric_class", "sk_base"),
        [
            (MulticlassPrecision, skm.precision_score),
            (MulticlassRecall, skm.recall_score),
            (MulticlassF1Score, skm.f1_score),
        ],
    )
    def test_prf_macro(self, metric_class, sk_base):
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, metric_class,
            _sk_multiclass(partial(sk_base, average="macro", zero_division=0)),
            metric_args={"num_classes": NUM_CLASSES, "average": "macro"},
        )

    def test_confusion_matrix(self):
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassConfusionMatrix,
            _sk_multiclass(partial(skm.confusion_matrix, labels=range(NUM_CLASSES))),
            metric_args={"num_classes": NUM_CLASSES}, check_batch=False, ddp=True,
        )

    def test_cohen_kappa(self):
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassCohenKappa, _sk_multiclass(skm.cohen_kappa_score),
            metric_args={"num_classes": NUM_CLASSES}, check_batch=False,
        )

    def test_matthews(self):
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassMatthewsCorrCoef, _sk_multiclass(skm.matthews_corrcoef),
            metric_args={"num_classes": NUM_CLASSES}, check_batch=False,
        )

    def test_jaccard(self):
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassJaccardIndex,
            _sk_multiclass(partial(skm.jaccard_score, average="macro", zero_division=0)),
            metric_args={"num_classes": NUM_CLASSES}, check_batch=False,
        )

    def test_top_k(self):
        sk = lambda p, t: skm.top_k_accuracy_score(t, p, k=2, labels=range(NUM_CLASSES))
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassAccuracy, sk,
            metric_args={"num_classes": NUM_CLASSES, "average": "micro", "top_k": 2},
        )

    def test_ignore_index(self):
        t2 = MC_TARGET.copy()
        t2[:, :5] = -1

        def sk(p, t):
            valid = t != -1
            return skm.accuracy_score(t[valid], p.argmax(-1)[valid])

        self.run_class_metric_test(
            MC_PROBS, t2, MulticlassAccuracy, sk,
            metric_args={"num_classes": NUM_CLASSES, "average": "micro", "ignore_index": -1},
        )

    def test_functional(self):
        self.run_functional_metric_test(
            MC_PROBS, MC_TARGET, multiclass_accuracy, _sk_multiclass(skm.accuracy_score),
            metric_args={"num_classes": NUM_CLASSES, "average": "micro"},
        )
        self.run_functional_metric_test(
            MC_PROBS, MC_TARGET, multiclass_f1_score,
            _sk_multiclass(partial(skm.f1_score, average="macro", zero_division=0)),
            metric_args={"num_classes": NUM_CLASSES, "average": "macro"},
        )

    def test_shard_map(self):
        self.run_shard_map_test(
            MC_PROBS, MC_TARGET, MulticlassAccuracy, _sk_multiclass(skm.accuracy_score),
            metric_args={"num_classes": NUM_CLASSES, "average": "micro"},
        )

    def test_samplewise(self):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", multidim_average="samplewise")
        p = np.random.rand(8, NUM_CLASSES, 10).astype(np.float32)
        t = np.random.randint(0, NUM_CLASSES, (8, 10))
        m.update(jnp.asarray(p), jnp.asarray(t))
        got = np.asarray(m.compute())
        ref = np.array([skm.accuracy_score(t[i], p[i].argmax(0)) for i in range(8)])
        np.testing.assert_allclose(got, ref, atol=1e-6)


class TestMultilabelFamily(MetricTester):
    @pytest.mark.parametrize("average", ["micro", "macro"])
    def test_f1(self, average):
        self.run_class_metric_test(
            ML_PROBS, ML_TARGET, MultilabelF1Score,
            _sk_multilabel(partial(skm.f1_score, average=average, zero_division=0)),
            metric_args={"num_labels": NUM_LABELS, "average": average}, ddp=(average == "macro"),
        )

    def test_hamming(self):
        self.run_class_metric_test(
            ML_PROBS, ML_TARGET, MultilabelHammingDistance, _sk_multilabel(skm.hamming_loss),
            metric_args={"num_labels": NUM_LABELS, "average": "micro"},
        )

    def test_functional(self):
        self.run_functional_metric_test(
            ML_PROBS, ML_TARGET, multilabel_f1_score,
            _sk_multilabel(partial(skm.f1_score, average="macro", zero_division=0)),
            metric_args={"num_labels": NUM_LABELS, "average": "macro"},
        )
