"""Engine A — true/false positive/negative counters for binary, multiclass
and multilabel tasks.

Parity: reference ``src/torchmetrics/functional/classification/stat_scores.py``
(1129 LoC): binary ``_format`` :90 / ``_update`` :120 / ``_compute`` :134;
multiclass ``_format`` :325 / ``_update`` :344; multilabel ``_format`` :647 /
``_update`` :672.

TPU-first design decisions (SURVEY.md §7 hard-part 1):

- ``ignore_index`` is handled by a **weight-0 sample mask**, never boolean
  indexing — every shape stays static under jit.
- The multiclass confusion path is a *weighted* static-length bincount over
  ``num_classes * target + preds`` (an XLA scatter-add feeding the MXU-free
  path); masked entries get weight 0 and clipped indices.
- Logit detection (``sigmoid/softmax`` if any value outside [0,1]) is a traced
  ``jnp.where`` so the same compiled program serves probs and logits.
- Value validation (label ranges etc.) runs only on concrete (non-traced)
  arrays — under jit it is a no-op, matching "validation outside jit".
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape, is_tracing
from ...utils.compute import normalize_logits_if_needed
from ...utils.data import _bincount, select_topk, to_onehot

Array = jax.Array

# one-hot footprint gate for the MXU stat-scores path (elements per one-hot;
# ~128 MiB bf16 each); module-level so tests can shrink it to exercise the
# scatter-histogram fallback branch
_ONEHOT_MATMUL_MAX_ELEMENTS = 64 * 1024 * 1024


# ---------------------------------------------------------------------------
# shared validation helpers (host-side; skipped while tracing)
# ---------------------------------------------------------------------------

def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(threshold, float) and 0 <= threshold <= 1):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_stat_scores_tensor_validation(
    preds: Array, target: Array, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError("Expected input to be at least 2D when multidim_average is set to `samplewise`")
    if is_tracing(target):
        return
    unique = jnp.unique(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not set(jnp.asarray(unique).tolist()).issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {unique} but expected only the following values {sorted(allowed)}."
        )
    if not is_tracing(preds) and not jnp.issubdtype(preds.dtype, jnp.floating):
        up = set(jnp.asarray(jnp.unique(preds)).tolist())
        if not up.issubset(allowed):
            raise RuntimeError(f"Detected the following values in `preds`: {up} but expected only 0s and 1s.")


def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) and top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None)")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError("Expected argument `multidim_average` to be one of ('global', 'samplewise')")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multiclass_stat_scores_tensor_validation(
    preds: Array, target: Array, num_classes: int, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError("If `preds` have one dimension more than `target`, the shape of `preds` should be"
                             " (N, C, ...), and the shape of `target` should be (N, ...).")
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape.")
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError("when `preds` and `target` have the same shape and `multidim_average` is `samplewise`,"
                             " they should have at least 2 dimensions.")
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be"
                         " (N, ...) and `preds` should be (N, C, ...).")
    if is_tracing(target):
        return
    check_value = num_classes if ignore_index is None else max(num_classes, ignore_index + 1)
    t_max, t_min = int(jnp.max(target)), int(jnp.min(target))
    if t_max >= check_value or (t_min < 0 and t_min != ignore_index):
        raise RuntimeError(f"Detected values in `target` outside the expected range [0, {num_classes}).")
    if not jnp.issubdtype(preds.dtype, jnp.floating) and not is_tracing(preds):
        if int(jnp.max(preds)) >= num_classes:
            raise RuntimeError(f"Detected values in `preds` outside the expected range [0, {num_classes}).")


def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None)")


def _multilabel_stat_scores_tensor_validation(
    preds: Array, target: Array, num_labels: int, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.ndim < 2:
        raise ValueError(f"Expected both `target` and `preds` to be at least 2D, got {preds.ndim}D")
    if preds.shape[1] != num_labels:
        raise ValueError(f"Expected `preds.shape[1]`={preds.shape[1]} to equal `num_labels`={num_labels}")
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError("Expected input to be at least 3D when multidim_average is set to `samplewise`")


# ---------------------------------------------------------------------------
# binary
# ---------------------------------------------------------------------------

def _binary_stat_scores_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Sigmoid-if-logits → threshold → flatten-to-(N, -1); returns a sample
    mask instead of dropping ignored entries (static shapes under jit)."""
    if jnp.issubdtype(preds.dtype, jnp.floating):
        # the reference sigmoids BEFORE masking ignore_index here
        # (stat_scores.py:103-107) — unlike its confusion-matrix/curve
        # formats, which filter first; both asymmetries are mirrored
        preds = normalize_logits_if_needed(preds, "sigmoid")
        preds = (preds > threshold).astype(jnp.int32)
    preds = preds.reshape(preds.shape[0], -1) if preds.ndim > 1 else preds.reshape(-1, 1)
    target_r = target.reshape(target.shape[0], -1) if target.ndim > 1 else target.reshape(-1, 1)
    if ignore_index is not None:
        mask = (target_r != ignore_index).astype(jnp.int32)
        target_r = jnp.clip(target_r, 0, 1)
    else:
        mask = jnp.ones_like(target_r, dtype=jnp.int32)
    return preds.astype(jnp.int32), target_r.astype(jnp.int32), mask


def _binary_stat_scores_update(
    preds: Array, target: Array, mask: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    axis = None if multidim_average == "global" else 1
    tp = jnp.sum((preds == 1) & (target == 1) & (mask == 1), axis=axis)
    fp = jnp.sum((preds == 1) & (target == 0) & (mask == 1), axis=axis)
    tn = jnp.sum((preds == 0) & (target == 0) & (mask == 1), axis=axis)
    fn = jnp.sum((preds == 0) & (target == 1) & (mask == 1), axis=axis)
    return tp, fp, tn, fn


def _binary_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, multidim_average: str = "global"
) -> Array:
    stats = [tp, fp, tn, fn, tp + fn]
    if multidim_average == "global":
        return jnp.stack([jnp.atleast_1d(s).squeeze() for s in stats], axis=0)
    return jnp.stack(stats, axis=-1)


def binary_stat_scores(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """One-shot binary tp/fp/tn/fn/support.

    Parity: reference ``functional/classification/stat_scores.py:170``.
    """
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# ---------------------------------------------------------------------------
# multiclass
# ---------------------------------------------------------------------------

def _multiclass_stat_scores_format(
    preds: Array,
    target: Array,
    top_k: int = 1,
) -> Tuple[Array, Array]:
    """argmax dense predictions when top_k == 1; flatten trailing dims."""
    if preds.ndim == target.ndim + 1 and top_k == 1:
        preds = jnp.argmax(preds, axis=1)
    if top_k == 1:
        preds = preds.reshape(preds.shape[0], -1)
        target = target.reshape(target.shape[0], -1)
    else:  # keep (N, C, S) probs for the top-k one-hot path
        preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
        target = target.reshape(target.shape[0], -1)
    return preds, target


def _multiclass_stat_scores_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Per-class tp/fp/tn/fn of shape (C,) (global) or (N, C) (samplewise)."""
    if ignore_index is not None:
        mask = (target != ignore_index)
        target = jnp.clip(target, 0, num_classes - 1)
    else:
        mask = jnp.ones_like(target, dtype=bool)

    if top_k > 1:
        # preds (N, C, S) probs → top-k one-hot vs target one-hot
        pred_topk = select_topk(preds, topk=top_k, dim=1)  # (N, C, S)
        tgt_oh = jnp.moveaxis(jax.nn.one_hot(target, num_classes, dtype=jnp.int32), -1, 1)  # (N, C, S)
        m = mask[:, None, :].astype(jnp.int32)
        axes = (0, 2) if multidim_average == "global" else (2,)
        tp = jnp.sum(pred_topk * tgt_oh * m, axis=axes)
        fp = jnp.sum(pred_topk * (1 - tgt_oh) * m, axis=axes)
        fn = jnp.sum((1 - pred_topk) * tgt_oh * m, axis=axes)
        tn = jnp.sum((1 - pred_topk) * (1 - tgt_oh) * m, axis=axes)
        return tp, fp, tn, fn

    preds_c = jnp.clip(preds, 0, num_classes - 1)
    w = mask.astype(jnp.float32)

    if multidim_average == "global":
        # per-class tp / tp+fn (target counts) / tp+fp (prediction counts)
        # determine all four counters without ever building the C^2
        # confusion matrix (which the old path bincounted: O(C^2) memory —
        # fine at C=100, fatal at vocab scale; the full matrix lives in
        # confusion_matrix.py, which needs it as its output).
        tgt = target.reshape(-1).astype(jnp.int32)
        prd = preds_c.reshape(-1).astype(jnp.int32)
        # out-of-range targets drop the whole (pred, target) pair — the
        # historical bincount semantics (OOB flattened index fell outside
        # every bin), kept uniform across both branches below
        wf = w.reshape(-1) * ((tgt >= 0) & (tgt < num_classes))
        correct = wf * (prd == tgt)
        # one-hot matmul rides the MXU and vmaps natively under the
        # epoch-fused update path (measured ~5x faster than scatter
        # histograms at C=100 on v5e); 0/1 weights accumulate exactly in f32
        # only while every count stays <= 2^24, so n is bounded too — beyond
        # that (or beyond the ~128 MiB bf16 one-hot footprint) the O(n)
        # scatter histograms take over. (The scatter path shares the f32
        # integer-precision ceiling per *bin*, but single-update batches
        # putting >16.7M samples in one class are past both gates here.)
        if (
            tgt.shape[0] * num_classes <= _ONEHOT_MATMUL_MAX_ELEMENTS
            and tgt.shape[0] <= 2**24
        ):
            oh_t = jax.nn.one_hot(tgt, num_classes, dtype=jnp.bfloat16)
            oh_p = jax.nn.one_hot(prd, num_classes, dtype=jnp.bfloat16)
            lhs_t = jnp.stack([correct, wf]).astype(jnp.bfloat16)  # (2, n)
            tp_tc = jnp.dot(lhs_t, oh_t, preferred_element_type=jnp.float32)
            tp, tgt_cnt = tp_tc[0], tp_tc[1]
            prd_cnt = jnp.dot(wf.astype(jnp.bfloat16), oh_p, preferred_element_type=jnp.float32)
        else:
            from ...ops.bincount import weighted_bincount

            tp = weighted_bincount(tgt, correct, num_classes)
            tgt_cnt = weighted_bincount(tgt, wf, num_classes)
            prd_cnt = weighted_bincount(prd, wf, num_classes)
        fn = tgt_cnt - tp
        fp = prd_cnt - tp
        tn = jnp.sum(wf) - tp - fp - fn
    else:
        idx = (num_classes * target + preds_c).astype(jnp.int32)
        def per_sample(ix, ww):
            cm = jnp.zeros((num_classes * num_classes,), jnp.float32).at[ix].add(ww)
            return cm.reshape(num_classes, num_classes)

        cm = jax.vmap(per_sample)(idx, w)  # (N, C, C)
        tp = jnp.diagonal(cm, axis1=1, axis2=2)
        fn = jnp.sum(cm, axis=2) - tp
        fp = jnp.sum(cm, axis=1) - tp
        tn = jnp.sum(cm, axis=(1, 2))[:, None] - tp - fp - fn
    return tp.astype(jnp.int32), fp.astype(jnp.int32), tn.astype(jnp.int32), fn.astype(jnp.int32)


def _multiclass_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str], multidim_average: str = "global"
) -> Array:
    """Stack [tp, fp, tn, fn, support] and reduce the class axis per
    ``average`` (reference ``stat_scores.py:422-448``)."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    if average == "micro":
        return jnp.sum(res, axis=-2)
    if average == "macro":
        return jnp.mean(res.astype(jnp.float32), axis=-2)
    if average == "weighted":
        weight = (tp + fn).astype(jnp.float32)
        norm = weight / jnp.sum(weight, axis=-1, keepdims=True)
        return jnp.sum(res.astype(jnp.float32) * norm[..., None], axis=-2)
    return res


def multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """One-shot multiclass tp/fp/tn/fn/support.

    Parity: reference ``functional/classification/stat_scores.py:468``.
    """
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    tp, fp, tn, fn = _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, multidim_average, ignore_index
    )
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ---------------------------------------------------------------------------
# multilabel
# ---------------------------------------------------------------------------

def _multilabel_stat_scores_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    if jnp.issubdtype(preds.dtype, jnp.floating):
        # reference sigmoids before masking (stat_scores.py:657-660)
        preds = normalize_logits_if_needed(preds, "sigmoid")
        preds = (preds > threshold).astype(jnp.int32)
    preds = preds.reshape(preds.shape[0], num_labels, -1)
    target = target.reshape(target.shape[0], num_labels, -1)
    if ignore_index is not None:
        mask = (target != ignore_index).astype(jnp.int32)
        target = jnp.clip(target, 0, 1)
    else:
        mask = jnp.ones_like(target, dtype=jnp.int32)
    return preds.astype(jnp.int32), target.astype(jnp.int32), mask


def _multilabel_stat_scores_update(
    preds: Array, target: Array, mask: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    axes = (0, 2) if multidim_average == "global" else (2,)
    tp = jnp.sum((preds == 1) & (target == 1) & (mask == 1), axis=axes)
    fp = jnp.sum((preds == 1) & (target == 0) & (mask == 1), axis=axes)
    tn = jnp.sum((preds == 0) & (target == 0) & (mask == 1), axis=axes)
    fn = jnp.sum((preds == 0) & (target == 1) & (mask == 1), axis=axes)
    return tp, fp, tn, fn


def _multilabel_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str], multidim_average: str = "global"
) -> Array:
    """Stack [tp, fp, tn, fn, support] and reduce the label axis per
    ``average`` (reference ``stat_scores.py:684-708``).

    Deliberate reference quirk mirrored: multilabel ``weighted`` normalizes
    by the GLOBAL support sum even under samplewise (``:705``, ``w.sum()``),
    where the multiclass path normalizes per sample (``:445``)."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    if average == "micro":
        return jnp.sum(res, axis=-2)
    if average == "macro":
        return jnp.mean(res.astype(jnp.float32), axis=-2)
    if average == "weighted":
        weight = (tp + fn).astype(jnp.float32)
        norm = weight / jnp.sum(weight)
        return jnp.sum(res.astype(jnp.float32) * norm[..., None], axis=-2)
    return res


def multilabel_stat_scores(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """One-shot multilabel tp/fp/tn/fn/support.

    Parity: reference ``functional/classification/stat_scores.py:820``.
    """
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


def stat_scores(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher. Parity: reference ``stat_scores.py:1030``."""
    from ...utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if not isinstance(num_labels, int):
        raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)}` was passed.")
    return multilabel_stat_scores(
        preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )
