"""Multimodal functional metrics (SURVEY.md §2.8)."""
from .clip_iqa import clip_image_quality_assessment
from .clip_score import clip_score

__all__ = ["clip_image_quality_assessment", "clip_score"]
