"""Accuracy (binary / multiclass / multilabel).

Parity: reference ``src/torchmetrics/functional/classification/accuracy.py``
(``_accuracy_reduce`` :24, public fns :66-475).
"""
from functools import partial
from typing import Optional

import jax

from ._factory import _binary_stat_metric, _multiclass_stat_metric, _multilabel_stat_metric
from ._reduce import _accuracy_reduce

Array = jax.Array


def binary_accuracy(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    return _binary_stat_metric(
        preds, target, _accuracy_reduce, threshold, multidim_average, ignore_index, validate_args
    )


def multiclass_accuracy(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    return _multiclass_stat_metric(
        preds, target, _accuracy_reduce, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )


def multilabel_accuracy(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    return _multilabel_stat_metric(
        preds, target, _accuracy_reduce, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )


def accuracy(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher. Parity: reference ``accuracy.py:411-475``."""
    from ...utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_accuracy(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
        return multiclass_accuracy(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if not isinstance(num_labels, int):
        raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)}` was passed.")
    return multilabel_accuracy(
        preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )
