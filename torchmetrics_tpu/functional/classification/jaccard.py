"""Jaccard index (IoU) over the confusion-matrix engine.

Parity: reference ``src/torchmetrics/functional/classification/jaccard.py``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from ...utils.compute import _safe_divide
from .confusion_matrix import (
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_update,
)

Array = jax.Array


def _jaccard_index_reduce(confmat: Array, average: Optional[str], ignore_index: Optional[int] = None,
                          zero_division: float = 0.0) -> Array:
    """Parity: reference ``jaccard.py:28``."""
    allowed = ("binary", "micro", "macro", "weighted", "none", None)
    if average not in allowed:
        raise ValueError(f"The `average` has to be one of {allowed}, got {average}.")
    confmat = confmat.astype(jnp.float32)
    if average == "binary":
        return _safe_divide(confmat[1, 1], confmat[0, 1] + confmat[1, 0] + confmat[1, 1], zero_division)

    if confmat.ndim == 3:  # multilabel (L, 2, 2)
        num = confmat[:, 1, 1]
        denom = confmat[:, 1, 1] + confmat[:, 0, 1] + confmat[:, 1, 0]
        support = jnp.sum(confmat[:, 1, :], axis=-1)
    else:  # multiclass (C, C)
        num = jnp.diagonal(confmat)
        denom = jnp.sum(confmat, axis=0) + jnp.sum(confmat, axis=1) - num
        support = jnp.sum(confmat, axis=1)

    mask = jnp.ones_like(num, dtype=bool)
    if ignore_index is not None and confmat.ndim == 2 and 0 <= ignore_index < confmat.shape[0]:
        mask = mask.at[ignore_index].set(False)

    if average == "micro":
        return _safe_divide(jnp.sum(jnp.where(mask, num, 0.0)), jnp.sum(jnp.where(mask, denom, 0.0)), zero_division)
    jaccard = _safe_divide(num, denom, zero_division)
    if average in (None, "none"):
        return jnp.where(mask, jaccard, zero_division) if ignore_index is not None else jaccard
    if average == "weighted":
        weights = jnp.where(mask, support, 0.0)
    else:  # macro: exclude classes absent everywhere (denominator 0)
        weights = jnp.where(mask & (denom != 0), 1.0, 0.0)
    return jnp.sum(_safe_divide(weights * jaccard, jnp.sum(weights)))


def binary_jaccard_index(preds, target, threshold=0.5, ignore_index=None, validate_args=True, zero_division=0.0):
    preds, target, mask = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    return _jaccard_index_reduce(_binary_confusion_matrix_update(preds, target, mask), "binary",
                                 zero_division=zero_division)


def multiclass_jaccard_index(preds, target, num_classes, average="macro", ignore_index=None, validate_args=True,
                             zero_division=0.0):
    preds, target, mask = _multiclass_confusion_matrix_format(preds, target, num_classes, ignore_index)
    cm = _multiclass_confusion_matrix_update(preds, target, mask, num_classes)
    return _jaccard_index_reduce(cm, average, ignore_index, zero_division)


def multilabel_jaccard_index(preds, target, num_labels, threshold=0.5, average="macro", ignore_index=None,
                             validate_args=True, zero_division=0.0):
    preds, target, mask = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    cm = _multilabel_confusion_matrix_update(preds, target, mask, num_labels)
    return _jaccard_index_reduce(cm, average, zero_division=zero_division)


def jaccard_index(preds, target, task, threshold=0.5, num_classes=None, num_labels=None, average="macro",
                  ignore_index=None, validate_args=True, zero_division=0.0):
    """Task dispatcher. Parity: reference ``jaccard.py:291``."""
    from ...utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_jaccard_index(preds, target, threshold, ignore_index, validate_args, zero_division)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
        return multiclass_jaccard_index(preds, target, num_classes, average, ignore_index, validate_args,
                                        zero_division)
    if not isinstance(num_labels, int):
        raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)}` was passed.")
    return multilabel_jaccard_index(preds, target, num_labels, threshold, average, ignore_index, validate_args,
                                    zero_division)
