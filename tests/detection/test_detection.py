"""Detection domain tests: IoU family, panoptic quality, COCO mAP.

Oracle values are the reference implementation's doctest outputs
(``/root/reference/src/torchmetrics/detection/*.py``, produced by
torchvision / pycocotools there) plus hand-computed cases.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu.detection import (
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
    MeanAveragePrecision,
    ModifiedPanopticQuality,
    PanopticQuality,
)
from torchmetrics_tpu.functional.detection import (
    box_convert,
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
    modified_panoptic_quality,
    panoptic_quality,
)

_PREDS3 = np.array(
    [
        [296.55, 93.96, 314.97, 152.79],
        [328.94, 97.05, 342.49, 122.98],
        [356.62, 95.47, 372.33, 147.55],
    ]
)
_TARGET3 = np.array(
    [
        [300.00, 100.00, 315.00, 150.00],
        [330.00, 100.00, 350.00, 125.00],
        [350.00, 100.00, 375.00, 150.00],
    ]
)


class TestFunctionalIoUVariants:
    @pytest.mark.parametrize(
        ("fn", "expected"),
        [
            (intersection_over_union, 0.5879),
            (generalized_intersection_over_union, 0.5638),
            (distance_intersection_over_union, 0.5793),
            (complete_intersection_over_union, 0.5790),
        ],
    )
    def test_reference_doctest_values(self, fn, expected):
        val = fn(jnp.asarray(_PREDS3), jnp.asarray(_TARGET3))
        assert np.allclose(np.asarray(val), expected, atol=1e-3)

    def test_matrix_mode(self):
        mat = intersection_over_union(jnp.asarray(_PREDS3), jnp.asarray(_TARGET3), aggregate=False)
        assert mat.shape == (3, 3)
        assert np.allclose(np.diag(np.asarray(mat)), [0.6898, 0.5086, 0.5654], atol=1e-3)
        # off-diagonal pairs don't overlap
        assert np.allclose(np.asarray(mat) - np.diag(np.diag(np.asarray(mat))), 0.0)

    def test_threshold_replacement(self):
        mat = intersection_over_union(
            jnp.asarray(_PREDS3), jnp.asarray(_TARGET3), iou_threshold=0.6, replacement_val=-1.0, aggregate=False
        )
        m = np.asarray(mat)
        assert m[0, 0] > 0.6 and m[1, 1] == -1.0 and m[2, 2] == -1.0

    def test_box_convert_roundtrip(self):
        boxes = jnp.asarray(_PREDS3)
        for fmt in ("xywh", "cxcywh"):
            out = box_convert(box_convert(boxes, "xyxy", fmt), fmt, "xyxy")
            assert np.allclose(np.asarray(out), np.asarray(boxes), atol=1e-4)


class TestModularIoU:
    _preds = [
        {
            "boxes": np.array([[296.55, 93.96, 314.97, 152.79], [298.55, 98.96, 314.97, 151.79]]),
            "scores": np.array([0.236, 0.56]),
            "labels": np.array([4, 5]),
        }
    ]
    _target = [{"boxes": np.array([[300.00, 100.00, 315.00, 150.00]]), "labels": np.array([5])}]

    def test_iou_respect_labels(self):
        metric = IntersectionOverUnion()
        res = metric(self._preds, self._target)
        assert np.allclose(np.asarray(res["iou"]), 0.8614, atol=1e-3)

    def test_giou(self):
        metric = GeneralizedIntersectionOverUnion()
        res = metric(self._preds, self._target)
        assert np.allclose(np.asarray(res["giou"]), 0.8613, atol=1e-3)

    @pytest.mark.parametrize("cls", [DistanceIntersectionOverUnion, CompleteIntersectionOverUnion])
    def test_diou_ciou_run(self, cls):
        metric = cls()
        res = metric(self._preds, self._target)
        key = metric._iou_type
        assert 0.0 < float(np.asarray(res[key])) <= 1.0

    def test_class_metrics(self):
        preds = [
            {
                "boxes": np.array([[296.55, 93.96, 314.97, 152.79], [298.55, 98.96, 314.97, 151.79]]),
                "scores": np.array([0.236, 0.56]),
                "labels": np.array([4, 5]),
            }
        ]
        target = [
            {
                "boxes": np.array([[300.00, 100.00, 315.00, 150.00], [300.00, 100.00, 315.00, 150.00]]),
                "labels": np.array([4, 5]),
            }
        ]
        metric = IntersectionOverUnion(class_metrics=True)
        res = metric(preds, target)
        assert np.allclose(np.asarray(res["iou"]), 0.7756, atol=1e-3)
        assert np.allclose(np.asarray(res["iou/cl_4"]), 0.6898, atol=1e-3)
        assert np.allclose(np.asarray(res["iou/cl_5"]), 0.8614, atol=1e-3)

    def test_accumulation_over_updates(self):
        metric = IntersectionOverUnion()
        metric.update(self._preds, self._target)
        metric.update(self._preds, self._target)
        res = metric.compute()
        assert np.allclose(np.asarray(res["iou"]), 0.8614, atol=1e-3)

    def test_input_validation(self):
        metric = IntersectionOverUnion()
        with pytest.raises(ValueError, match="Expected argument"):
            metric.update(self._preds, [{"boxes": np.zeros((0, 4)), "labels": np.zeros(0)}, {"boxes": np.zeros((0, 4)), "labels": np.zeros(0)}])
        with pytest.raises(ValueError, match="`boxes` key"):
            metric.update([{"labels": np.array([1])}], self._target)


class TestPanopticQuality:
    _preds = np.array(
        [[[[6, 0], [0, 0], [6, 0], [6, 0]],
          [[0, 0], [0, 0], [6, 0], [0, 1]],
          [[0, 0], [0, 0], [6, 0], [0, 1]],
          [[0, 0], [7, 0], [6, 0], [1, 0]],
          [[0, 0], [7, 0], [7, 0], [7, 0]]]]
    )
    _target = np.array(
        [[[[6, 0], [0, 1], [6, 0], [0, 1]],
          [[0, 1], [0, 1], [6, 0], [0, 1]],
          [[0, 1], [0, 1], [6, 0], [1, 0]],
          [[0, 1], [7, 0], [1, 0], [1, 0]],
          [[0, 1], [7, 0], [7, 0], [7, 0]]]]
    )

    def test_reference_doctest(self):
        pq = PanopticQuality(things={0, 1}, stuffs={6, 7})
        assert np.allclose(np.asarray(pq(self._preds, self._target)), 0.5463, atol=1e-3)

    def test_functional(self):
        val = panoptic_quality(self._preds, self._target, things={0, 1}, stuffs={6, 7})
        assert np.allclose(np.asarray(val, np.float64), 0.5463, atol=1e-3)

    def test_modified_pq(self):
        preds = np.array([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2], [1, 0]]])[:, :, None, :]
        target = np.array([[[0, 1], [0, 0], [6, 0], [7, 0], [6, 0], [255, 0]]])[:, :, None, :]
        pq = ModifiedPanopticQuality(things={0, 1}, stuffs={6, 7})
        assert np.allclose(np.asarray(pq(preds, target)), 0.7667, atol=1e-3)
        val = modified_panoptic_quality(preds, target, things={0, 1}, stuffs={6, 7})
        assert np.allclose(np.asarray(val, np.float64), 0.7667, atol=1e-3)

    def test_accumulates_across_batches(self):
        pq = PanopticQuality(things={0, 1}, stuffs={6, 7})
        pq.update(self._preds, self._target)
        pq.update(self._preds, self._target)
        # duplicated data: identical PQ
        assert np.allclose(np.asarray(pq.compute()), 0.5463, atol=1e-3)

    def test_unknown_category_raises(self):
        pq = PanopticQuality(things={0}, stuffs={6})
        bad = np.array([[[[9, 0], [0, 0]], [[0, 0], [6, 0]]]])
        tgt = np.array([[[[0, 0], [0, 0]], [[0, 0], [6, 0]]]])
        with pytest.raises(ValueError, match="Unknown categories"):
            pq.update(bad, tgt)
        pq_ok = PanopticQuality(things={0}, stuffs={6}, allow_unknown_preds_category=True)
        pq_ok.update(bad, tgt)  # mapped to void

    def test_category_validation(self):
        with pytest.raises(ValueError, match="distinct"):
            PanopticQuality(things={0, 1}, stuffs={1, 6})


class TestMeanAveragePrecision:
    def test_bbox_doctest(self):
        preds = [dict(boxes=np.array([[258.0, 41.0, 606.0, 285.0]]), scores=np.array([0.536]), labels=np.array([0]))]
        target = [dict(boxes=np.array([[214.0, 41.0, 562.0, 285.0]]), labels=np.array([0]))]
        metric = MeanAveragePrecision(iou_type="bbox")
        metric.update(preds, target)
        res = metric.compute()
        assert np.allclose(np.asarray(res["map"]), 0.6, atol=1e-4)
        assert np.allclose(np.asarray(res["map_50"]), 1.0, atol=1e-4)
        assert np.allclose(np.asarray(res["map_75"]), 1.0, atol=1e-4)
        assert np.allclose(np.asarray(res["map_large"]), 0.6, atol=1e-4)
        assert np.asarray(res["map_small"]) == -1.0
        for k in ("mar_1", "mar_10", "mar_100"):
            assert np.allclose(np.asarray(res[k]), 0.6, atol=1e-4)

    def test_segm_doctest(self):
        mask_pred = np.array(
            [[0, 0, 0, 0, 0], [0, 0, 1, 1, 0], [0, 0, 1, 1, 0], [0, 0, 0, 0, 0], [0, 0, 0, 0, 0]], bool
        )
        mask_tgt = np.array(
            [[0, 0, 0, 0, 0], [0, 0, 1, 0, 0], [0, 0, 1, 1, 0], [0, 0, 1, 0, 0], [0, 0, 0, 0, 0]], bool
        )
        metric = MeanAveragePrecision(iou_type="segm")
        metric.update(
            [dict(masks=mask_pred[None], scores=np.array([0.536]), labels=np.array([0]))],
            [dict(masks=mask_tgt[None], labels=np.array([0]))],
        )
        res = metric.compute()
        assert np.allclose(np.asarray(res["map"]), 0.2, atol=1e-4)
        assert np.allclose(np.asarray(res["map_50"]), 1.0, atol=1e-4)
        assert np.allclose(np.asarray(res["map_75"]), 0.0, atol=1e-4)

    def test_perfect_detections(self):
        boxes = np.array([[10.0, 10.0, 50.0, 50.0], [60.0, 60.0, 120.0, 120.0]])
        preds = [dict(boxes=boxes, scores=np.array([0.9, 0.8]), labels=np.array([0, 1]))]
        target = [dict(boxes=boxes, labels=np.array([0, 1]))]
        metric = MeanAveragePrecision()
        metric.update(preds, target)
        res = metric.compute()
        assert np.allclose(np.asarray(res["map"]), 1.0, atol=1e-4)
        assert np.allclose(np.asarray(res["mar_100"]), 1.0, atol=1e-4)

    def test_false_positive_penalty(self):
        gt = np.array([[10.0, 10.0, 50.0, 50.0]])
        # one perfect match + one high-scoring false positive
        preds = [
            dict(
                boxes=np.vstack([gt, [[200.0, 200.0, 250.0, 250.0]]]),
                scores=np.array([0.5, 0.9]),
                labels=np.array([0, 0]),
            )
        ]
        target = [dict(boxes=gt, labels=np.array([0]))]
        metric = MeanAveragePrecision()
        metric.update(preds, target)
        res = metric.compute()
        # FP ranked above TP: interpolated precision 0.5 at all recall points
        assert np.allclose(np.asarray(res["map_50"]), 0.5, atol=1e-3)

    def test_crowd_not_penalized(self):
        gt = np.array([[10.0, 10.0, 50.0, 50.0]])
        crowd = np.array([[100.0, 100.0, 200.0, 200.0]])
        preds = [
            dict(
                boxes=np.vstack([gt, [[100.0, 100.0, 200.0, 200.0]], [[101.0, 101.0, 199.0, 199.0]]]),
                scores=np.array([0.9, 0.8, 0.7]),
                labels=np.array([0, 0, 0]),
            )
        ]
        target = [
            dict(
                boxes=np.vstack([gt, crowd]),
                labels=np.array([0, 0]),
                iscrowd=np.array([0, 1]),
            )
        ]
        metric = MeanAveragePrecision()
        metric.update(preds, target)
        res = metric.compute()
        # both extra detections match the crowd region -> ignored, not FPs
        assert np.allclose(np.asarray(res["map_50"]), 1.0, atol=1e-4)

    def test_class_metrics_and_classes(self):
        boxes = np.array([[10.0, 10.0, 50.0, 50.0]])
        preds = [dict(boxes=boxes, scores=np.array([0.9]), labels=np.array([3]))]
        target = [dict(boxes=boxes, labels=np.array([3]))]
        metric = MeanAveragePrecision(class_metrics=True)
        metric.update(preds, target)
        res = metric.compute()
        # single observed class squeezes to a scalar (reference parity:
        # doctest shows `'classes': tensor(0, dtype=torch.int32)`)
        assert np.asarray(res["classes"]).tolist() == 3
        assert np.allclose(np.asarray(res["map_per_class"]), [1.0], atol=1e-4)

    def test_micro_average(self):
        boxes = np.array([[10.0, 10.0, 50.0, 50.0]])
        # wrong label but perfect box: micro (class-agnostic) scores it
        preds = [dict(boxes=boxes, scores=np.array([0.9]), labels=np.array([1]))]
        target = [dict(boxes=boxes, labels=np.array([2]))]
        macro = MeanAveragePrecision(average="macro")
        macro.update(preds, target)
        micro = MeanAveragePrecision(average="micro")
        micro.update(preds, target)
        assert np.asarray(macro.compute()["map"]) == 0.0
        assert np.allclose(np.asarray(micro.compute()["map"]), 1.0, atol=1e-4)

    def test_max_detection_thresholds(self):
        gt = np.array([[10.0, 10.0, 50.0, 50.0], [60.0, 60.0, 100.0, 100.0]])
        preds = [dict(boxes=gt, scores=np.array([0.9, 0.8]), labels=np.array([0, 0]))]
        target = [dict(boxes=gt, labels=np.array([0, 0]))]
        metric = MeanAveragePrecision(max_detection_thresholds=[1, 2])
        metric.update(preds, target)
        res = metric.compute()
        assert "mar_1" in res and "mar_2" in res
        assert np.allclose(np.asarray(res["mar_1"]), 0.5, atol=1e-4)
        assert np.allclose(np.asarray(res["mar_2"]), 1.0, atol=1e-4)

    def test_empty_preds_and_targets(self):
        metric = MeanAveragePrecision()
        metric.update(
            [dict(boxes=np.zeros((0, 4)), scores=np.zeros(0), labels=np.zeros(0, np.int64))],
            [dict(boxes=np.zeros((0, 4)), labels=np.zeros(0, np.int64))],
        )
        res = metric.compute()
        assert np.asarray(res["map"]) == -1.0  # nothing to evaluate

    def test_merge_states_across_ranks(self):
        """Emulated DDP: states from two ranks merged -> same result as union."""
        boxes1 = np.array([[10.0, 10.0, 50.0, 50.0]])
        boxes2 = np.array([[60.0, 60.0, 120.0, 120.0]])
        m_union = MeanAveragePrecision()
        m_union.update(
            [dict(boxes=boxes1, scores=np.array([0.9]), labels=np.array([0])),
             dict(boxes=boxes2, scores=np.array([0.8]), labels=np.array([0]))],
            [dict(boxes=boxes1, labels=np.array([0])), dict(boxes=boxes2, labels=np.array([0]))],
        )
        r1 = MeanAveragePrecision()
        r1.update([dict(boxes=boxes1, scores=np.array([0.9]), labels=np.array([0]))],
                  [dict(boxes=boxes1, labels=np.array([0]))])
        r2 = MeanAveragePrecision()
        r2.update([dict(boxes=boxes2, scores=np.array([0.8]), labels=np.array([0]))],
                  [dict(boxes=boxes2, labels=np.array([0]))])
        # host-side object merge of ragged list states
        for name in r1._defaults:
            r1._state[name] = list(r1._state[name]) + list(r2._state[name])
        assert np.allclose(np.asarray(r1.compute()["map"]), np.asarray(m_union.compute()["map"]), atol=1e-6)
