"""Optional-dependency gating.

Parity: reference ``src/torchmetrics/utilities/imports.py:22-64``
(``RequirementCache`` flags). Implemented without lightning_utilities.
"""
import importlib.util
from functools import lru_cache


@lru_cache(maxsize=None)
def _module_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_SCIPY_AVAILABLE = _module_available("scipy")
_SKLEARN_AVAILABLE = _module_available("sklearn")
_TRANSFORMERS_AVAILABLE = _module_available("transformers")
_MATPLOTLIB_AVAILABLE = _module_available("matplotlib")
_NLTK_AVAILABLE = _module_available("nltk")
_REGEX_AVAILABLE = _module_available("regex")
_PIL_AVAILABLE = _module_available("PIL")
_PESQ_AVAILABLE = _module_available("pesq")
_PYSTOI_AVAILABLE = _module_available("pystoi")
_FLAX_AVAILABLE = _module_available("flax")


class ModuleNotFoundHint(ModuleNotFoundError):
    """Raised at metric construction when an optional backend is missing."""

    def __init__(self, metric: str, module: str, extra: str):
        super().__init__(
            f"Metric `{metric}` requires `{module}` which is not installed. "
            f"Install it or use `pip install torchmetrics_tpu[{extra}]`."
        )
