"""Weighted bincount as a Pallas TPU kernel.

The hottest op in the classification stack is the (weighted) bincount that
builds confusion matrices and stat scores (reference
``utilities/data.py:179`` ``_bincount``; ``functional/classification/
stat_scores.py`` / ``confusion_matrix.py`` use
``_bincount(num_classes * target + preds)``). XLA lowers ``.at[idx].add(w)``
to a scatter-add, which serializes on TPU. This kernel instead does a tiled
compare-and-reduce on the VPU:

    grid = (bin_tiles, n_tiles); each cell computes a (TILE_N, TILE_B)
    equality matrix between the index tile and the bin-id tile and
    accumulates ``sum(w * eq)`` into its output bin block.

Total work is N*num_bins comparisons — embarrassingly vectorizable, no
atomics, deterministic. The n-axis is the *inner* (minor) grid dimension so
each output block is initialized once at n==0 and accumulated in place
(sequential minor iterations on TPU make this race-free).

On non-TPU backends (or when Pallas is unavailable) the jnp scatter path is
used; ``interpret=True`` runs the same kernel on CPU for tests.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

TILE_N = 2048
# 1024 matches XLA's 1D f32 layout tiling T(1024) for large arrays — a
# smaller block makes Mosaic's operand layout disagree with XLA's and fail
# verification ("XLA layout {0:T(1024)} does not match Mosaic layout")
TILE_B = 1024


def _kernel(idx_ref, w_ref, out_ref):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    n = pl.program_id(1)

    @pl.when(n == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    idx = idx_ref[:]  # (TILE_N,)
    w = w_ref[:]
    bins = b * TILE_B + jax.lax.broadcasted_iota(jnp.int32, (TILE_N, TILE_B), 1)
    eq = (idx[:, None] == bins).astype(out_ref.dtype)
    out_ref[:] += jnp.sum(w[:, None].astype(out_ref.dtype) * eq, axis=0)


@functools.lru_cache(maxsize=None)
def _pallas_call_cached(padded_bins: int, padded_n: int, interpret: bool, out_dtype_name: str,
                        batch_rule: str = "scatter"):
    """Build the pallas_call for a (padded_bins, padded_n) problem size.

    Under ``vmap`` the kernel's 1D block shape would become an un-tileable
    (1, TILE), so a batching rule is attached. ``batch_rule="scatter"``
    (production) switches the whole batch to the scatter path, which vmaps
    natively and runs in parallel; ``"sequential"`` (``force_pallas`` tests)
    lowers to an in-graph ``lax.map`` over the kernel so vmapped tests still
    exercise the kernel itself.
    """
    import jax.experimental.pallas as pl

    out_dtype = jnp.dtype(out_dtype_name)

    if batch_rule == "sequential":
        def make(f):
            return jax.custom_batching.sequential_vmap(f)
    else:
        def make(f):
            return jax.custom_batching.custom_vmap(f)

    @make
    def call(idx_p: Array, w_p: Array) -> Array:
        try:  # under shard_map with vma checking, the output inherits the
            vma = jax.typeof(idx_p).vma  # inputs' varying-axes set
        except AttributeError:
            vma = None
        out_shape = (
            jax.ShapeDtypeStruct((padded_bins,), out_dtype, vma=vma)
            if vma is not None
            else jax.ShapeDtypeStruct((padded_bins,), out_dtype)
        )
        return pl.pallas_call(
            _kernel,
            out_shape=out_shape,
            grid=(padded_bins // TILE_B, padded_n // TILE_N),
            in_specs=[
                pl.BlockSpec((TILE_N,), lambda b, i: (i,)),
                pl.BlockSpec((TILE_N,), lambda b, i: (i,)),
            ],
            out_specs=pl.BlockSpec((TILE_B,), lambda b, i: (b,)),
            interpret=interpret,
        )(idx_p, w_p)

    if batch_rule != "sequential":

        @call.def_vmap
        def _batched(axis_size, in_batched, idx_b, w_b):
            idx_bat, w_bat = in_batched
            if not idx_bat:
                idx_b = jnp.broadcast_to(idx_b, (axis_size,) + idx_b.shape)
            if not w_bat:
                w_b = jnp.broadcast_to(w_b, (axis_size,) + w_b.shape)
            out = jax.vmap(lambda i, ww: _scatter_bincount(i, ww, padded_bins, out_dtype))(idx_b, w_b)
            return out, True

    return call


@functools.partial(jax.jit, static_argnames=("num_bins", "interpret", "out_dtype", "batch_rule"))
def _bincount_pallas(idx: Array, weights: Array, num_bins: int, interpret: bool = False,
                     out_dtype=jnp.float32, batch_rule: str = "scatter") -> Array:
    n = idx.shape[0]
    if n == 0:  # zero-length grid would skip the output zero-init
        return jnp.zeros((num_bins,), out_dtype)
    n_pad = -n % TILE_N
    b_pad = -num_bins % TILE_B
    # padded indices get weight 0, so they can never contribute
    idx_p = jnp.concatenate([idx.astype(jnp.int32), jnp.full((n_pad,), -1, jnp.int32)])
    w_p = jnp.concatenate([weights, jnp.zeros((n_pad,), weights.dtype)])
    padded_bins = num_bins + b_pad

    call = _pallas_call_cached(padded_bins, n + n_pad, bool(interpret), jnp.dtype(out_dtype).name, batch_rule)
    return call(idx_p, w_p)[:num_bins]


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _scatter_bincount(idx: Array, w: Array, num_bins: int, dtype) -> Array:
    valid = (idx >= 0) & (idx < num_bins)
    safe = jnp.where(valid, idx, 0)
    return jnp.zeros((num_bins,), dtype).at[safe].add(jnp.where(valid, w, jnp.zeros((), dtype)))


def weighted_bincount(idx: Array, weights: Array = None, num_bins: int = 0,
                      force_pallas: bool = False, interpret: bool = False) -> Array:
    """``sum of weights per bin`` over int indices in [0, num_bins).

    Pallas compare-reduce kernel on TPU; XLA scatter-add elsewhere.
    Negative / out-of-range indices contribute nothing (mask upstream).
    Unweighted calls (``weights=None``) count in int32 (exact); weighted
    calls accumulate in float32 (same as the reference's weighted scatter).
    """
    if num_bins <= 0:
        raise ValueError(f"num_bins must be positive, got {num_bins}")
    idx = idx.reshape(-1)
    unweighted = weights is None
    dtype = jnp.int32 if unweighted else jnp.float32
    w = jnp.ones(idx.shape, dtype) if unweighted else weights.reshape(-1).astype(jnp.float32)
    if force_pallas:
        # sequential batching rule so vmapped tests exercise the kernel
        return _bincount_pallas(idx, w, num_bins, interpret=interpret or not _on_tpu(),
                                out_dtype=dtype, batch_rule="sequential")
    # the compare-reduce kernel does O(N * num_bins) VPU work — a win over
    # the serialized scatter only while all bins fit one TILE_B block (one
    # vectorized pass per element); beyond that XLA's scatter is preferred.
    # platform_dependent picks the branch at LOWERING time, so a program
    # jitted onto CPU devices takes the scatter path even when the process
    # default backend is TPU (mixed-backend dryruns/tests).
    if num_bins <= TILE_B:
        return jax.lax.platform_dependent(
            idx, w,
            tpu=lambda i, ww: _bincount_pallas(i, ww, num_bins, interpret=False, out_dtype=dtype),
            default=lambda i, ww: _scatter_bincount(i, ww, num_bins, dtype),
        )
    return _scatter_bincount(idx, w, num_bins, dtype)
