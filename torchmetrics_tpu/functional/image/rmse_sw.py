"""Sliding-window RMSE (+ ERGAS / RASE which build on it).

Parity: reference ``src/torchmetrics/functional/image/{rmse_sw,ergas,rase}.py``.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape
from .helper import depthwise_conv2d, uniform_kernel_2d

Array = jax.Array


def _rmse_sw_update(
    preds: Array, target: Array, window_size: int
) -> Tuple[Array, Array, Array]:
    """Returns (rmse_per_sample_mean, rmse_map_sum, total_windows)."""
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    channel = preds.shape[1]
    kernel = uniform_kernel_2d(channel, (window_size, window_size))
    diff_sq = (preds - target) ** 2
    mse_map = depthwise_conv2d(diff_sq, kernel)  # local mean of squared error
    rmse_map = jnp.sqrt(jnp.clip(mse_map, min=0.0))
    n = preds.shape[0]
    rmse_per_sample = jnp.sqrt(jnp.mean(mse_map.reshape(n, -1), axis=-1))
    return rmse_per_sample, rmse_map, jnp.asarray(rmse_map[0].size, dtype=jnp.float32)


def root_mean_squared_error_using_sliding_window(
    preds: Array, target: Array, window_size: int = 8, return_rmse_map: bool = False
):
    """Parity: reference ``rmse_sw.py:74``."""
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_per_sample, rmse_map, _ = _rmse_sw_update(preds, target, window_size)
    rmse = jnp.mean(rmse_per_sample)
    if return_rmse_map:
        return rmse, rmse_map
    return rmse


def _ergas_update(preds: Array, target: Array, ratio: float = 4.0) -> Array:
    """Per-sample ERGAS. Parity: reference ``ergas.py:28``."""
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    b, c, h, w = preds.shape
    preds_f = preds.reshape(b, c, -1)
    target_f = target.reshape(b, c, -1)
    diff = preds_f - target_f
    rmse_per_band = jnp.sqrt(jnp.mean(diff * diff, axis=-1))
    mean_target = jnp.mean(target_f, axis=-1)
    return 100.0 * ratio * jnp.sqrt(jnp.mean((rmse_per_band / mean_target) ** 2, axis=1))


def error_relative_global_dimensionless_synthesis(
    preds: Array, target: Array, ratio: float = 4.0, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """Parity: reference ``ergas.py:77``."""
    scores = _ergas_update(preds, target, ratio)
    if reduction == "elementwise_mean":
        return jnp.mean(scores)
    if reduction == "sum":
        return jnp.sum(scores)
    return scores


def relative_average_spectral_error(preds: Array, target: Array, window_size: int = 8) -> Array:
    """RASE. Parity: reference ``rase.py:54``."""
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    channel = preds.shape[1]
    kernel = uniform_kernel_2d(channel, (window_size, window_size))
    # per-window mean target and rmse per band
    mean_target_map = depthwise_conv2d(target, kernel)  # (N,C,h',w')
    mse_map = depthwise_conv2d((preds - target) ** 2, kernel)
    rmse_map = jnp.sqrt(jnp.clip(mse_map, min=0.0))
    # RASE = 100 / mu * sqrt(mean_over_bands(rmse^2)), averaged over windows
    mu = jnp.mean(mean_target_map, axis=1, keepdims=True)
    rase_map = 100.0 / mu * jnp.sqrt(jnp.mean(rmse_map**2, axis=1, keepdims=True))
    return jnp.mean(rase_map)
