"""Count-min frequency sketch: fixed-shape, jit-clean, merge = SUM.

An ``(depth, width)`` int32 table of counters. Each item id hashes to one
column per row via an independent stateless mixer; updates scatter-add,
queries take the minimum over rows. Merging two tables is *elementwise
addition*, so the sketch registers its reduction as a plain
``Reduction.SUM`` alias: it rides the psum / reduce-scatter buckets of the
existing sync routes bitwise-exactly (integer leaves are never quantized),
needs no custom gather epilogue at all, and is trivially associative.

Guarantees (classic Cormode & Muthukrishnan bounds, asserted in tests):

- **overestimate-only**: ``query(x) ≥ true_count(x)`` always (collisions can
  only add);
- with width ``w`` and depth ``d``, ``query(x) ≤ true_count(x) + εN`` with
  probability ``1 − e^{-d}`` where ``ε = e/w`` and N is the total count.
"""
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["countmin_init", "countmin_update", "countmin_query", "countmin_merge"]

_ROW_SALTS = (0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F, 0x165667B1, 0xD3A2646C, 0xFD7046C5, 0xB55A4F09)


def countmin_init(depth: int = 4, width: int = 1024) -> Array:
    if not (1 <= depth <= len(_ROW_SALTS)):
        raise ValueError(f"depth must be in [1, {len(_ROW_SALTS)}], got {depth}")
    if width < 2:
        raise ValueError(f"width must be >= 2, got {width}")
    return jnp.zeros((depth, width), dtype=jnp.int32)


def _mix_u32(x: Array) -> Array:
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _columns(items: Array, depth: int, width: int, seed: int) -> Array:
    """Per-row hash columns for each item: (depth, B) int32."""
    x = jnp.asarray(items).astype(jnp.uint32)
    cols = []
    for d in range(depth):
        h = _mix_u32(x ^ jnp.uint32(_ROW_SALTS[d]) ^ (jnp.uint32(seed) * jnp.uint32(0x94D049BB)))
        cols.append((h % jnp.uint32(width)).astype(jnp.int32))
    return jnp.stack(cols, axis=0)


def countmin_update(
    table: Array, items: Array, counts: Optional[Array] = None, *, seed: int = 0
) -> Array:
    """Scatter-add a batch of integer item ids (optionally with counts)."""
    items = jnp.asarray(items).reshape(-1)
    if counts is None:
        counts = jnp.ones(items.shape, dtype=table.dtype)
    counts = jnp.asarray(counts, dtype=table.dtype).reshape(-1)
    depth, width = table.shape
    cols = _columns(items, depth, width, seed)
    for d in range(depth):
        table = table.at[d].add(
            jax.ops.segment_sum(counts, cols[d], num_segments=width).astype(table.dtype)
        )
    return table


def countmin_query(table: Array, items: Array, *, seed: int = 0) -> Array:
    """Point estimate per item id: min over rows (overestimate-only)."""
    items = jnp.asarray(items).reshape(-1)
    depth, width = table.shape
    cols = _columns(items, depth, width, seed)
    ests = jnp.stack([table[d, cols[d]] for d in range(depth)], axis=0)
    return jnp.min(ests, axis=0)


def countmin_merge(stack: Array) -> Array:
    """n-way merge = elementwise sum (provided for symmetry; the registered
    reduction is the plain ``Reduction.SUM`` alias, so sync never calls
    this)."""
    return jnp.sum(jnp.asarray(stack), axis=0)
