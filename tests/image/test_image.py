"""Image metrics vs scipy-based / analytic oracles."""
import numpy as np
import pytest
import scipy.ndimage

import jax.numpy as jnp

from torchmetrics_tpu.functional.image import (
    peak_signal_noise_ratio,
    spectral_angle_mapper,
    structural_similarity_index_measure,
    total_variation,
    universal_image_quality_index,
)
from torchmetrics_tpu.image import (
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
)

rng = np.random.RandomState(23)
IMGS_A = rng.rand(2, 4, 3, 64, 64).astype(np.float32)
IMGS_B = np.clip(IMGS_A + 0.1 * rng.randn(2, 4, 3, 64, 64), 0, 1).astype(np.float32)


def np_gaussian_ssim(p, t, data_range=1.0, sigma=1.5, ksize=11, k1=0.01, k2=0.03):
    """Independent SSIM oracle via scipy.ndimage (truncated gaussian window)."""
    c1, c2 = (k1 * data_range) ** 2, (k2 * data_range) ** 2
    trunc = ((ksize - 1) / 2) / sigma

    def g(x):
        return scipy.ndimage.gaussian_filter(x, sigma, truncate=trunc, mode="reflect")

    vals = []
    for n in range(p.shape[0]):
        per_c = []
        for c in range(p.shape[1]):
            x, y = p[n, c].astype(np.float64), t[n, c].astype(np.float64)
            mx, my = g(x), g(y)
            vx = np.clip(g(x * x) - mx * mx, 0, None)
            vy = np.clip(g(y * y) - my * my, 0, None)
            cxy = g(x * y) - mx * my
            s = ((2 * mx * my + c1) * (2 * cxy + c2)) / ((mx**2 + my**2 + c1) * (vx + vy + c2))
            pad = (ksize - 1) // 2
            per_c.append(s[pad:-pad, pad:-pad].mean())
        vals.append(np.mean(per_c))
    return np.asarray(vals)


def test_psnr():
    p, t = IMGS_A[0], IMGS_B[0]
    mse = np.mean((p - t) ** 2)
    ref = 10 * np.log10(1.0 / mse)
    got = float(peak_signal_noise_ratio(jnp.asarray(p), jnp.asarray(t), data_range=1.0))
    np.testing.assert_allclose(got, ref, rtol=1e-4)

    m = PeakSignalNoiseRatio(data_range=1.0)
    m.update(jnp.asarray(IMGS_A[0]), jnp.asarray(IMGS_B[0]))
    m.update(jnp.asarray(IMGS_A[1]), jnp.asarray(IMGS_B[1]))
    mse = np.mean((IMGS_A - IMGS_B) ** 2)
    np.testing.assert_allclose(float(m.compute()), 10 * np.log10(1.0 / mse), rtol=1e-4)


def test_ssim_vs_scipy():
    p, t = IMGS_A[0], IMGS_B[0]
    ref = np_gaussian_ssim(p, t).mean()
    got = float(structural_similarity_index_measure(jnp.asarray(p), jnp.asarray(t), data_range=1.0))
    np.testing.assert_allclose(got, ref, atol=2e-4)


def test_ssim_class_accumulates():
    m = StructuralSimilarityIndexMeasure(data_range=1.0)
    m.update(jnp.asarray(IMGS_A[0]), jnp.asarray(IMGS_B[0]))
    m.update(jnp.asarray(IMGS_A[1]), jnp.asarray(IMGS_B[1]))
    ref = np.concatenate([np_gaussian_ssim(IMGS_A[i], IMGS_B[i]) for i in range(2)]).mean()
    np.testing.assert_allclose(float(m.compute()), ref, atol=2e-4)


def test_ssim_identical_is_one():
    got = float(structural_similarity_index_measure(jnp.asarray(IMGS_A[0]), jnp.asarray(IMGS_A[0]), data_range=1.0))
    assert got == pytest.approx(1.0, abs=1e-5)


def test_ms_ssim_bounds():
    big_a = rng.rand(2, 1, 192, 192).astype(np.float32)
    big_b = np.clip(big_a + 0.05 * rng.randn(*big_a.shape), 0, 1).astype(np.float32)
    m = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0)
    m.update(jnp.asarray(big_a), jnp.asarray(big_b))
    v = float(m.compute())
    assert 0.0 < v <= 1.0
    m2 = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0)
    m2.update(jnp.asarray(big_a), jnp.asarray(big_a))
    assert float(m2.compute()) == pytest.approx(1.0, abs=1e-5)


def test_total_variation():
    img = IMGS_A[0]
    ref = np.abs(np.diff(img, axis=-1)).sum() + np.abs(np.diff(img, axis=-2)).sum()
    got = float(total_variation(jnp.asarray(img)))
    np.testing.assert_allclose(got, ref, rtol=1e-4)
    m = TotalVariation()
    m.update(jnp.asarray(img))
    np.testing.assert_allclose(float(m.compute()), ref, rtol=1e-4)


def test_sam():
    p, t = IMGS_A[0], IMGS_B[0]
    dot = (p * t).sum(1)
    ref = np.arccos(dot / (np.linalg.norm(p, axis=1) * np.linalg.norm(t, axis=1))).mean()
    got = float(spectral_angle_mapper(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_uqi_self_is_one():
    got = float(universal_image_quality_index(jnp.asarray(IMGS_A[0]), jnp.asarray(IMGS_A[0])))
    assert got == pytest.approx(1.0, abs=1e-3)


def test_fid_analytic():
    """FID between two gaussian feature clouds ~ analytic Frechet distance."""
    d = 16
    extractor = lambda x: x.reshape(x.shape[0], -1)[:, :d]
    fid = FrechetInceptionDistance(feature=extractor)
    real = rng.randn(2000, d).astype(np.float32)
    fake = (rng.randn(2000, d) + 1.0).astype(np.float32)  # shifted mean
    fid.update(jnp.asarray(real), real=True)
    fid.update(jnp.asarray(fake), real=False)
    got = float(fid.compute())
    # analytic: |mu1-mu2|^2 = d * 1.0 (cov identical) → ~16
    assert abs(got - d * 1.0) < 2.0

    # identical distributions → ~0
    fid2 = FrechetInceptionDistance(feature=extractor)
    fid2.update(jnp.asarray(real[:1000]), real=True)
    fid2.update(jnp.asarray(real[1000:]), real=False)
    assert float(fid2.compute()) < 0.5


def test_fid_streaming_matches_onebatch():
    d = 8
    extractor = lambda x: x
    a = rng.randn(512, d).astype(np.float32)
    b = rng.randn(512, d).astype(np.float32)
    f1 = FrechetInceptionDistance(feature=extractor)
    f1.update(jnp.asarray(a), real=True)
    f1.update(jnp.asarray(b), real=False)
    f2 = FrechetInceptionDistance(feature=extractor)
    for i in range(0, 512, 128):
        f2.update(jnp.asarray(a[i : i + 128]), real=True)
        f2.update(jnp.asarray(b[i : i + 128]), real=False)
    np.testing.assert_allclose(float(f1.compute()), float(f2.compute()), rtol=1e-3)


def test_kid():
    extractor = lambda x: x
    kid = KernelInceptionDistance(feature=extractor, subsets=10, subset_size=100)
    real = rng.randn(300, 8).astype(np.float32)
    fake = (rng.randn(300, 8) * 1.5).astype(np.float32)
    kid.update(jnp.asarray(real), real=True)
    kid.update(jnp.asarray(fake), real=False)
    mean, std = kid.compute()
    assert float(mean) > 0
    kid2 = KernelInceptionDistance(feature=extractor, subsets=10, subset_size=100)
    kid2.update(jnp.asarray(real[:150]), real=True)
    kid2.update(jnp.asarray(real[150:]), real=False)
    assert abs(float(kid2.compute()[0])) < float(mean)


def test_inception_score():
    extractor = lambda x: x  # inputs are already logits
    m = InceptionScore(feature=extractor, splits=4)
    # confident, diverse predictions → high IS
    logits = np.eye(10)[rng.randint(0, 10, 400)] * 10.0
    m.update(jnp.asarray(logits.astype(np.float32)))
    mean, std = m.compute()
    assert float(mean) > 5.0
    # uniform predictions → IS ~ 1
    m2 = InceptionScore(feature=extractor, splits=4)
    m2.update(jnp.asarray(np.zeros((400, 10), dtype=np.float32)))
    assert float(m2.compute()[0]) == pytest.approx(1.0, abs=1e-3)


def test_fid_without_extractor_raises():
    with pytest.raises(ModuleNotFoundError):
        FrechetInceptionDistance(feature=2048)
