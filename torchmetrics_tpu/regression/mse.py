"""MeanSquaredError class. Parity: reference ``src/torchmetrics/regression/mse.py``."""
from typing import Any

import jax
import jax.numpy as jnp

from ..functional.regression.mse import _mean_squared_error_compute, _mean_squared_error_update
from ..metric import Metric

Array = jax.Array


class MeanSquaredError(Metric):
    """Mean squared error (or RMSE with ``squared=False``).

    Parity: reference ``regression/mse.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> metric = MeanSquaredError()
        >>> metric.update(jnp.asarray([1.0, 2.0, 3.0]), jnp.asarray([1.0, 2.0, 5.0]))
        >>> round(float(metric.compute()), 4)
        1.3333
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_squared_error", jnp.zeros((num_outputs,)).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_error, num_obs = _mean_squared_error_update(preds, target, self.num_outputs)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return _mean_squared_error_compute(self.sum_squared_error, self.total, self.squared)
