"""ROUGE score family (rouge1/rouge2/rougeL/rougeLsum, P/R/F).

Parity target: reference ``functional/text/rouge.py`` (524 LoC,
``_rouge_score_update`` at :287) which mirrors the ``rouge_score`` package:
alphanumeric tokenization + lowercase, optional Porter stemming (gated on
nltk), per-sample best/avg accumulation over multiple references.
"""
import re
from typing import Dict, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .helper import ngram_counts

Array = jax.Array

ALLOWED_ROUGE_KEYS = ("rouge1", "rouge2", "rouge3", "rouge4", "rouge5", "rouge6", "rouge7", "rouge8", "rouge9", "rougeL", "rougeLsum")
ALLOWED_ACCUMULATE = ("avg", "best")


def _rouge_tokenize(text: str, stemmer=None) -> List[str]:
    tokens = re.split(r"[^a-z0-9]+", text.lower())
    if stemmer is not None:
        tokens = [stemmer.stem(t) if len(t) > 3 else t for t in tokens]
    return [t for t in tokens if t]


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    """Longest-common-subsequence length via numpy row DP."""
    if not a or not b:
        return 0
    prev = np.zeros(len(b) + 1, dtype=np.int64)
    for x in a:
        cur = np.zeros_like(prev)
        for j, y in enumerate(b, start=1):
            cur[j] = prev[j - 1] + 1 if x == y else max(prev[j], cur[j - 1])
        prev = cur
    return int(prev[-1])


def _prf(hits: float, pred_n: int, tgt_n: int) -> Tuple[float, float, float]:
    p = hits / pred_n if pred_n else 0.0
    r = hits / tgt_n if tgt_n else 0.0
    f = 2 * p * r / (p + r) if (p + r) else 0.0
    return p, r, f


def _rouge_n(pred_tokens: List[str], tgt_tokens: List[str], n: int) -> Tuple[float, float, float]:
    pc = ngram_counts(pred_tokens, n)
    tc = ngram_counts(tgt_tokens, n)
    hits = sum(min(v, tc.get(k, 0)) for k, v in pc.items())
    return _prf(hits, max(len(pred_tokens) - n + 1, 0), max(len(tgt_tokens) - n + 1, 0))


def _rouge_l(pred_tokens: List[str], tgt_tokens: List[str]) -> Tuple[float, float, float]:
    return _prf(_lcs_len(pred_tokens, tgt_tokens), len(pred_tokens), len(tgt_tokens))


def _split_sentences(text: str) -> List[str]:
    return [s for s in re.split(r"[.!?]\s*|\n", text) if s.strip()]


def _union_lcs_hits(pred_sents: List[List[str]], tgt_sents: List[List[str]]) -> float:
    """rougeLsum: summary-level LCS union (rouge_score package semantics)."""
    hits = 0.0
    for t in tgt_sents:
        union: set = set()
        for p in pred_sents:
            # indices of t participating in LCS with p
            li = _lcs_indices(p, t)
            union |= li
        hits += len(union)
    return hits


def _lcs_indices(a: Sequence[str], b: Sequence[str]) -> set:
    """Indices of b on an LCS path between a and b."""
    if not a or not b:
        return set()
    dp = np.zeros((len(a) + 1, len(b) + 1), dtype=np.int64)
    for i, x in enumerate(a, 1):
        for j, y in enumerate(b, 1):
            dp[i, j] = dp[i - 1, j - 1] + 1 if x == y else max(dp[i - 1, j], dp[i, j - 1])
    out = set()
    i, j = len(a), len(b)
    while i > 0 and j > 0:
        if a[i - 1] == b[j - 1] and dp[i, j] == dp[i - 1, j - 1] + 1:
            out.add(j - 1)
            i, j = i - 1, j - 1
        elif dp[i - 1, j] >= dp[i, j - 1]:
            i -= 1
        else:
            j -= 1
    return out


def _rouge_lsum(pred: str, tgt: str, stemmer=None) -> Tuple[float, float, float]:
    pred_sents = [_rouge_tokenize(s, stemmer) for s in _split_sentences(pred)]
    tgt_sents = [_rouge_tokenize(s, stemmer) for s in _split_sentences(tgt)]
    pred_n = sum(len(s) for s in pred_sents)
    tgt_n = sum(len(s) for s in tgt_sents)
    hits = _union_lcs_hits(pred_sents, tgt_sents)
    return _prf(hits, pred_n, tgt_n)


def _score_pair(pred: str, tgt: str, rouge_keys: Sequence[str], stemmer) -> Dict[str, Tuple[float, float, float]]:
    pred_tokens = _rouge_tokenize(pred, stemmer)
    tgt_tokens = _rouge_tokenize(tgt, stemmer)
    out = {}
    for key in rouge_keys:
        if key == "rougeL":
            out[key] = _rouge_l(pred_tokens, tgt_tokens)
        elif key == "rougeLsum":
            out[key] = _rouge_lsum(pred, tgt, stemmer)
        else:
            out[key] = _rouge_n(pred_tokens, tgt_tokens, int(key[5:]))
    return out


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys: Sequence[str],
    accumulate: str = "best",
    stemmer=None,
) -> Dict[str, List[Tuple[float, float, float]]]:
    """Per-sample (P, R, F) triplets per rouge key (host-side)."""
    results: Dict[str, List[Tuple[float, float, float]]] = {k: [] for k in rouge_keys}
    for pred, refs in zip(preds, target):
        refs = [refs] if isinstance(refs, str) else list(refs)
        per_ref = [_score_pair(pred, r, rouge_keys, stemmer) for r in refs]
        for key in rouge_keys:
            triplets = [pr[key] for pr in per_ref]
            if accumulate == "best":
                best = max(triplets, key=lambda x: x[2])
                results[key].append(best)
            else:
                arr = np.asarray(triplets)
                results[key].append(tuple(arr.mean(axis=0)))
    return results


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """Aggregated ROUGE scores. Parity: reference ``rouge.py:rouge_score``.

    Returns dict with ``<key>_precision/_recall/_fmeasure`` scalar entries.
    """
    if accumulate not in ALLOWED_ACCUMULATE:
        raise ValueError(f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE}")
    if isinstance(rouge_keys, str):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {ALLOWED_ROUGE_KEYS}")
    stemmer = None
    if use_stemmer:
        try:
            import nltk.stem.porter

            stemmer = nltk.stem.porter.PorterStemmer()
        except ImportError as err:
            raise ModuleNotFoundError(
                "Stemmer requires that `nltk` is installed. Use `pip install nltk`."
            ) from err
    preds_ = [preds] if isinstance(preds, str) else list(preds)
    target_ = [target] if isinstance(target, str) else list(target)
    results = _rouge_score_update(preds_, target_, rouge_keys, accumulate, stemmer)
    out: Dict[str, Array] = {}
    for key, triplets in results.items():
        arr = np.asarray(triplets) if triplets else np.zeros((1, 3))
        out[f"{key}_precision"] = jnp.asarray(arr[:, 0].mean(), dtype=jnp.float32)
        out[f"{key}_recall"] = jnp.asarray(arr[:, 1].mean(), dtype=jnp.float32)
        out[f"{key}_fmeasure"] = jnp.asarray(arr[:, 2].mean(), dtype=jnp.float32)
    return out
