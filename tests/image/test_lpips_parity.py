"""Numeric LPIPS parity vs the reference ``_LPIPS`` with matched weights.

The reference (`/root/reference/src/torchmetrics/functional/image/lpips.py:258`)
takes its backbones from torchvision (ImageNet weights, not fetchable offline)
but ships its trained NetLinLayer *head* weights in-repo
(``lpips_models/{alex,vgg,squeeze}.pth``). Here we run the reference's actual
forward code with a **stubbed torchvision** providing seeded random-weight
backbones, inject the *same* backbone weights into our Flax ``LPIPSNet`` via
``convert_lpips_torch``, and assert score parity. This pins every semantic the
architecture tests cannot: conv padding, pool placement/ceil-mode, the scaling
layer, the 1e-8 normalize eps, head application, and spatial averaging — with
the real in-repo head checkpoints exercised through the converter.
"""
import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "helpers"))
from lightning_utilities_stub import install_stub  # noqa: E402

install_stub()

import jax.numpy as jnp

from torchmetrics_tpu.models.lpips import LPIPSNet, convert_lpips_torch, lpips_head_params, make_lpips

torch = pytest.importorskip("torch")

REF_SRC = "/root/reference/src"
LPIPS_MODELS_DIR = os.path.join(REF_SRC, "torchmetrics", "functional", "image", "lpips_models")

pytestmark = pytest.mark.skipif(not os.path.isdir(LPIPS_MODELS_DIR), reason="reference checkpoints not mounted")


class _Fire(torch.nn.Module):
    """torchvision Fire module layout (squeeze/expand1x1/expand3x3)."""

    def __init__(self, inp: int, sq: int, ex: int) -> None:
        super().__init__()
        self.squeeze = torch.nn.Conv2d(inp, sq, 1)
        self.squeeze_activation = torch.nn.ReLU(inplace=True)
        self.expand1x1 = torch.nn.Conv2d(sq, ex, 1)
        self.expand1x1_activation = torch.nn.ReLU(inplace=True)
        self.expand3x3 = torch.nn.Conv2d(sq, ex, 3, padding=1)
        self.expand3x3_activation = torch.nn.ReLU(inplace=True)

    def forward(self, x):
        x = self.squeeze_activation(self.squeeze(x))
        return torch.cat(
            [self.expand1x1_activation(self.expand1x1(x)), self.expand3x3_activation(self.expand3x3(x))], 1
        )


def _alexnet_features():
    n = torch.nn
    return n.Sequential(
        n.Conv2d(3, 64, 11, 4, 2), n.ReLU(True), n.MaxPool2d(3, 2),
        n.Conv2d(64, 192, 5, padding=2), n.ReLU(True), n.MaxPool2d(3, 2),
        n.Conv2d(192, 384, 3, padding=1), n.ReLU(True),
        n.Conv2d(384, 256, 3, padding=1), n.ReLU(True),
        n.Conv2d(256, 256, 3, padding=1), n.ReLU(True),
    )


def _vgg16_features():
    n = torch.nn
    layers, c_in = [], 3
    for stage, widths in enumerate(((64, 64), (128, 128), (256, 256, 256), (512, 512, 512), (512, 512, 512))):
        if stage > 0:
            layers.append(n.MaxPool2d(2, 2))
        for w in widths:
            layers += [n.Conv2d(c_in, w, 3, padding=1), n.ReLU(True)]
            c_in = w
    layers.append(n.MaxPool2d(2, 2))
    return n.Sequential(*layers)


def _squeezenet_features():
    n = torch.nn
    return n.Sequential(
        n.Conv2d(3, 64, 3, stride=2), n.ReLU(True), n.MaxPool2d(3, 2, ceil_mode=True),
        _Fire(64, 16, 64), _Fire(128, 16, 64), n.MaxPool2d(3, 2, ceil_mode=True),
        _Fire(128, 32, 128), _Fire(256, 32, 128), n.MaxPool2d(3, 2, ceil_mode=True),
        _Fire(256, 48, 192), _Fire(384, 48, 192), _Fire(384, 64, 256), _Fire(512, 64, 256),
    )


def _install_torchvision_stub():
    """Give the reference's ``_get_net`` seeded random-weight backbones."""

    def factory(builder):
        def make(pretrained=None, weights=None):
            torch.manual_seed(7)
            return types.SimpleNamespace(features=builder())

        return make

    import importlib.machinery

    models = types.ModuleType("torchvision.models")
    models.alexnet = factory(_alexnet_features)
    models.vgg16 = factory(_vgg16_features)
    models.squeezenet1_1 = factory(_squeezenet_features)
    models.AlexNet_Weights = types.SimpleNamespace(IMAGENET1K_V1="stub")
    models.VGG16_Weights = types.SimpleNamespace(IMAGENET1K_V1="stub")
    models.SqueezeNet1_1_Weights = types.SimpleNamespace(IMAGENET1K_V1="stub")
    models.__spec__ = importlib.machinery.ModuleSpec("torchvision.models", loader=None)
    tv = types.ModuleType("torchvision")
    tv.models = models
    tv.__version__ = "0.0.0-stub"
    tv.__spec__ = importlib.machinery.ModuleSpec("torchvision", loader=None)
    sys.modules["torchvision"] = tv
    sys.modules["torchvision.models"] = models


@pytest.fixture(scope="module")
def ref_lpips_module():
    sys.path.insert(0, REF_SRC)
    _install_torchvision_stub()
    try:
        from torchmetrics.functional.image import lpips as ref_lpips
        yield ref_lpips
    finally:
        sys.path.remove(REF_SRC)
        sys.modules.pop("torchvision", None)
        sys.modules.pop("torchvision.models", None)


# H=W=37 makes the squeeze trunk's ceil-mode pools keep a partial window
# (pool input 18 -> 9 with ceil vs 8 with floor), so ceil semantics are pinned.
@pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
@pytest.mark.parametrize("size", [37, 64])
def test_lpips_matches_reference_with_matched_weights(ref_lpips_module, net_type, size):
    ref = ref_lpips_module._LPIPS(pretrained=True, net=net_type, eval_mode=True)

    heads_state = torch.load(os.path.join(LPIPS_MODELS_DIR, f"{net_type}.pth"), map_location="cpu")
    params = convert_lpips_torch(ref.net.state_dict(), heads_state, net_type=net_type)

    rng = np.random.default_rng(42)
    img0 = rng.uniform(-1, 1, size=(3, 3, size, size)).astype(np.float32)
    img1 = rng.uniform(-1, 1, size=(3, 3, size, size)).astype(np.float32)

    with torch.no_grad():
        expected = ref(torch.from_numpy(img0), torch.from_numpy(img1)).squeeze().numpy()
    got = np.asarray(LPIPSNet(net_type=net_type).apply(params, jnp.asarray(img0), jnp.asarray(img1)))

    np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-4)


def test_lpips_normalize_flag_matches_reference(ref_lpips_module):
    ref = ref_lpips_module._LPIPS(pretrained=True, net="alex", eval_mode=True)
    heads_state = torch.load(os.path.join(LPIPS_MODELS_DIR, "alex.pth"), map_location="cpu")
    params = convert_lpips_torch(ref.net.state_dict(), heads_state, net_type="alex")

    rng = np.random.default_rng(3)
    img0 = rng.uniform(0, 1, size=(2, 3, 40, 40)).astype(np.float32)
    img1 = rng.uniform(0, 1, size=(2, 3, 40, 40)).astype(np.float32)
    with torch.no_grad():
        expected = ref(torch.from_numpy(img0), torch.from_numpy(img1), normalize=True).squeeze().numpy()
    got = np.asarray(LPIPSNet(net_type="alex").apply(params, jnp.asarray(img0), jnp.asarray(img1), normalize=True))
    np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
def test_vendored_heads_match_reference_checkpoints(net_type):
    """The committed npz is byte-equivalent to converting the .pth in-repo."""
    heads_state = torch.load(os.path.join(LPIPS_MODELS_DIR, f"{net_type}.pth"), map_location="cpu")
    vendored = lpips_head_params(net_type)
    n_lins = len(vendored)
    assert n_lins == (7 if net_type == "squeeze" else 5)
    for i in range(n_lins):
        expected = heads_state[f"lin{i}.model.1.weight"].numpy().transpose(2, 3, 1, 0)
        np.testing.assert_array_equal(np.asarray(vendored[f"lin{i}"]["kernel"]), expected)
        assert vendored[f"lin{i}"]["kernel"].shape[:2] == (1, 1)


@pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
def test_make_lpips_pretrained_heads(net_type):
    _, params, distance = make_lpips(net_type=net_type, pretrained_heads=True)
    x = jnp.zeros((1, 3, 48, 48))
    y = jnp.ones((1, 3, 48, 48)) * 0.5
    d = np.asarray(distance(x, y))
    assert d.shape == (1,) and np.isfinite(d).all() and d[0] >= 0
    assert float(np.asarray(distance(x, x))[0]) == pytest.approx(0.0, abs=1e-6)
