"""Array-level parity for detection option surfaces (VERDICT r3 item 10).

- ``extended_summary`` precision/recall arrays vs the reference legacy
  pure-torch mAP's internal ``_calculate`` (same COCOeval (T,R,K,A,M)
  layout, same default parameter grids);
- ``extended_summary`` IoU matrices vs an independently-written torch IoU
  oracle under the pycocotools convention (score-sorted rows, maxDets[-1]
  truncation);
- ``average="micro"`` vs the legacy implementation run on the same scenes
  with every label collapsed to one class (micro == class-agnostic).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "helpers"))
from lightning_utilities_stub import install_stub as _lu  # noqa: E402
from pycocotools_stub import install_stub as _pc  # noqa: E402
from torchvision_stub import install_stub as _tv  # noqa: E402

_lu()
_pc()
_tv()
sys.path.insert(0, "/root/reference/src")
torch = pytest.importorskip("torch")

from torchmetrics.detection._mean_ap import MeanAveragePrecision as LegacyMAP  # noqa: E402

from torchmetrics_tpu.detection import MeanAveragePrecision  # noqa: E402

KEYS = ["map", "map_50", "map_75", "map_small", "map_medium", "map_large",
        "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large"]


def _random_scene(rng, n_classes=3):
    n_gt = rng.randint(1, 6)
    n_det = rng.randint(1, 8)
    gt_xy = rng.rand(n_gt, 2) * 80
    gt_wh = rng.rand(n_gt, 2) * 40 + 3
    gt = np.concatenate([gt_xy, gt_xy + gt_wh], axis=1)
    det = gt[rng.randint(0, n_gt, n_det)] + rng.randn(n_det, 4) * 2
    det = np.sort(det.reshape(n_det, 2, 2), axis=1).reshape(n_det, 4)
    d = {"boxes": det.astype(np.float32), "scores": rng.rand(n_det).astype(np.float32),
         "labels": rng.randint(0, n_classes, n_det)}
    g = {"boxes": gt.astype(np.float32), "labels": rng.randint(0, n_classes, n_gt)}
    return d, g


def _feed(ours, ref, scenes):
    for d, g in scenes:
        ours.update([d], [g])
        ref.update(
            [{k: torch.tensor(v) for k, v in d.items()}],
            [{k: torch.tensor(v) for k, v in g.items()}],
        )


@pytest.mark.parametrize("seed", [0, 3, 11])
def test_extended_precision_recall_arrays_vs_legacy(seed):
    rng = np.random.RandomState(seed)
    scenes = [_random_scene(rng) for _ in range(4)]
    ours = MeanAveragePrecision(iou_type="bbox", extended_summary=True)
    ref = LegacyMAP(iou_type="bbox")
    _feed(ours, ref, scenes)
    result = ours.compute()
    classes = ref._get_classes()
    ref_prec, ref_rec = ref._calculate(classes)
    np.testing.assert_allclose(
        np.asarray(result["precision"]), ref_prec.numpy(), atol=1e-6,
        err_msg="extended_summary precision (T,R,K,A,M) diverges from legacy reference",
    )
    np.testing.assert_allclose(
        np.asarray(result["recall"]), ref_rec.numpy(), atol=1e-6,
        err_msg="extended_summary recall (T,K,A,M) diverges from legacy reference",
    )


def _torch_box_iou(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Independent IoU oracle (plain clamp formula, no shared code)."""
    ta, tb = torch.tensor(a, dtype=torch.float64), torch.tensor(b, dtype=torch.float64)
    lt = torch.maximum(ta[:, None, :2], tb[None, :, :2])
    rb = torch.minimum(ta[:, None, 2:], tb[None, :, 2:])
    wh = (rb - lt).clamp(min=0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (ta[:, 2] - ta[:, 0]) * (ta[:, 3] - ta[:, 1])
    area_b = (tb[:, 2] - tb[:, 0]) * (tb[:, 3] - tb[:, 1])
    return (inter / (area_a[:, None] + area_b[None, :] - inter)).numpy()


def test_extended_ious_score_sorted_vs_torch_oracle():
    rng = np.random.RandomState(5)
    scenes = [_random_scene(rng) for _ in range(3)]
    ours = MeanAveragePrecision(iou_type="bbox", extended_summary=True)
    for d, g in scenes:
        ours.update([d], [g])
    result = ours.compute()
    ious = result["ious"]
    assert len(ious) > 0
    checked = 0
    for (img_idx, cls), mat in ious.items():
        d, g = scenes[img_idx]
        d_sel = d["labels"] == cls
        g_sel = g["labels"] == cls
        boxes_d = d["boxes"][d_sel]
        scores_d = d["scores"][d_sel]
        # pycocotools convention: rows in score order, maxDets[-1] cap
        order = np.argsort(-scores_d, kind="mergesort")[:100]
        expect = _torch_box_iou(boxes_d[order], g["boxes"][g_sel])
        got = np.asarray(mat)
        assert got.shape == expect.shape, (img_idx, cls, got.shape, expect.shape)
        if expect.size:
            np.testing.assert_allclose(got, expect, atol=1e-5)
            checked += 1
    assert checked > 0


# The legacy reference's `_find_best_gt_match` removes ignored
# (out-of-area-range) GTs from matching entirely (`_mean_ap.py:640-642`),
# while real pycocotools lets a detection match an ignored GT and become
# ignored itself instead of counting as FP (our behavior; pinned by
# tests/detection/test_cocoeval_goldens.py). Area-range keys can therefore
# legitimately diverge from the legacy oracle and are excluded here.
NON_AREA_KEYS = [k for k in KEYS if not k.endswith(("small", "medium", "large"))]


@pytest.mark.parametrize("seed", [1, 7])
def test_micro_average_vs_legacy_class_agnostic(seed):
    """micro == class-agnostic: the legacy reference has no micro mode, but
    relabelling every box to one class makes macro == micro by definition.
    Also asserts micro equals OUR macro on the relabelled inputs for every
    key (the defining identity, free of legacy's area-ignore quirk)."""
    rng = np.random.RandomState(seed)
    scenes = [_random_scene(rng, n_classes=4) for _ in range(4)]
    ours = MeanAveragePrecision(iou_type="bbox", average="micro")
    relabel = MeanAveragePrecision(iou_type="bbox")
    ref = LegacyMAP(iou_type="bbox")
    for d, g in scenes:
        ours.update([d], [g])
        d0 = dict(d, labels=np.zeros_like(d["labels"]))
        g0 = dict(g, labels=np.zeros_like(g["labels"]))
        relabel.update([d0], [g0])
        ref.update(
            [{k: torch.tensor(v) for k, v in d0.items()}],
            [{k: torch.tensor(v) for k, v in g0.items()}],
        )
    r_ours = ours.compute()
    r_rel = relabel.compute()
    r_ref = ref.compute()
    for k in KEYS:
        a, b = float(r_ours[k]), float(r_rel[k])
        assert np.isclose(a, b, atol=1e-6), f"{k} micro!=class-agnostic: {a} vs {b}"
    for k in NON_AREA_KEYS:
        a, b = float(r_ours[k]), float(r_ref[k])
        assert np.isclose(a, b, atol=1e-6), f"{k}: ours={a} legacy={b}"
