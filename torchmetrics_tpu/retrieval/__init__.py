"""Retrieval metrics (L4). Parity: reference ``src/torchmetrics/retrieval/``."""
from .base import RetrievalMetric
from .metrics import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalRPrecision,
)
from .precision_recall_curve import RetrievalPrecisionRecallCurve, RetrievalRecallAtFixedPrecision

__all__ = [
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMetric",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
]
