"""Modular clustering metrics — cat list states of raw labels/embeddings.

Parity targets: reference ``clustering/*.py`` (all store raw label or data
lists with ``"cat"`` reduction and evaluate once at ``compute``). The
label-pair metrics need the full epoch's labels (cluster ids are only
comparable within one labeling), so raw storage is the correct state design
in both frameworks; the evaluation itself is one vectorized XLA call.
"""
from typing import Any

import jax
import jax.numpy as jnp

from ..functional.clustering import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    calinski_harabasz_score,
    completeness_score,
    davies_bouldin_score,
    dunn_index,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from ..metric import Metric
from ..utils.data import dim_zero_cat

Array = jax.Array


class _LabelClusteringMetric(Metric):
    """Base for metrics over (preds, target) label vectors."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    # update is a trace-safe append (in-graph all_gather syncs the cat
    # states); only compute is eager — label spaces are data-dependent
    jittable = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._compute_jittable = False
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        self.preds.append(jnp.asarray(preds).reshape(-1))
        self.target.append(jnp.asarray(target).reshape(-1))

    def _evaluate(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError

    def compute(self) -> Array:
        return self._evaluate(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class MutualInfoScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/mutual_info_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MutualInfoScore
        >>> metric = MutualInfoScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0986
    """

    plot_lower_bound = 0.0

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return mutual_info_score(preds, target)


class AdjustedMutualInfoScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/adjusted_mutual_info_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import AdjustedMutualInfoScore
        >>> metric = AdjustedMutualInfoScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if average_method not in ("min", "geometric", "arithmetic", "max"):
            raise ValueError(
                "Expected argument `average_method` to be one of `min`, `geometric`, `arithmetic`, `max`,"
                f"but got {average_method}"
            )
        self.average_method = average_method

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return adjusted_mutual_info_score(preds, target, self.average_method)


class NormalizedMutualInfoScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/normalized_mutual_info_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import NormalizedMutualInfoScore
        >>> metric = NormalizedMutualInfoScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if average_method not in ("min", "geometric", "arithmetic", "max"):
            raise ValueError(
                "Expected argument `average_method` to be one of `min`, `geometric`, `arithmetic`, `max`,"
                f"but got {average_method}"
            )
        self.average_method = average_method

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return normalized_mutual_info_score(preds, target, self.average_method)


class RandScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/rand_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RandScore
        >>> metric = RandScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return rand_score(preds, target)


class AdjustedRandScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/adjusted_rand_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import AdjustedRandScore
        >>> metric = AdjustedRandScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = -0.5
    plot_upper_bound = 1.0

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return adjusted_rand_score(preds, target)


class FowlkesMallowsIndex(_LabelClusteringMetric):
    """Parity: reference ``clustering/fowlkes_mallows_index.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import FowlkesMallowsIndex
        >>> metric = FowlkesMallowsIndex()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return fowlkes_mallows_index(preds, target)


class HomogeneityScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/homogeneity_completeness_v_measure.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import HomogeneityScore
        >>> metric = HomogeneityScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return homogeneity_score(preds, target)


class CompletenessScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/homogeneity_completeness_v_measure.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CompletenessScore
        >>> metric = CompletenessScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return completeness_score(preds, target)


class VMeasureScore(_LabelClusteringMetric):
    """Parity: reference ``clustering/homogeneity_completeness_v_measure.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import VMeasureScore
        >>> metric = VMeasureScore()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(beta, (int, float)) and beta > 0):
            raise ValueError(f"Argument `beta` should be a positive float. Got {beta}.")
        self.beta = float(beta)

    def _evaluate(self, preds: Array, target: Array) -> Array:
        return v_measure_score(preds, target, self.beta)


class _EmbeddingClusteringMetric(Metric):
    """Base for metrics over (data, labels) — stores raw embeddings."""

    is_differentiable = True
    full_state_update = True
    jittable = True  # append-only update; compute is eager (see above)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._compute_jittable = False
        self.add_state("data", [], dist_reduce_fx="cat")
        self.add_state("labels", [], dist_reduce_fx="cat")

    def update(self, data: Array, labels: Array) -> None:
        self.data.append(jnp.asarray(data))
        self.labels.append(jnp.asarray(labels).reshape(-1))


class CalinskiHarabaszScore(_EmbeddingClusteringMetric):
    """Parity: reference ``clustering/calinski_harabasz_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CalinskiHarabaszScore
        >>> metric = CalinskiHarabaszScore()
        >>> data = jnp.asarray([[0.0, 0.0], [0.1, 0.2], [2.0, 2.0], [2.1, 1.9], [4.0, 4.1], [3.9, 4.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> metric.update(data, labels)
        >>> round(float(metric.compute()), 4)
        1027.8895
    """

    higher_is_better = True
    plot_lower_bound = 0.0

    def compute(self) -> Array:
        return calinski_harabasz_score(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class DaviesBouldinScore(_EmbeddingClusteringMetric):
    """Parity: reference ``clustering/davies_bouldin_score.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import DaviesBouldinScore
        >>> metric = DaviesBouldinScore()
        >>> data = jnp.asarray([[0.0, 0.0], [0.1, 0.2], [2.0, 2.0], [2.1, 1.9], [4.0, 4.1], [3.9, 4.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> metric.update(data, labels)
        >>> round(float(metric.compute()), 4)
        0.0613
    """

    higher_is_better = False
    plot_lower_bound = 0.0

    def compute(self) -> Array:
        return davies_bouldin_score(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class DunnIndex(_EmbeddingClusteringMetric):
    """Parity: reference ``clustering/dunn_index.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import DunnIndex
        >>> metric = DunnIndex()
        >>> data = jnp.asarray([[0.0, 0.0], [0.1, 0.2], [2.0, 2.0], [2.1, 1.9], [4.0, 4.1], [3.9, 4.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> metric.update(data, labels)
        >>> round(float(metric.compute()), 4)
        24.368
    """

    higher_is_better = True
    plot_lower_bound = 0.0

    def __init__(self, p: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.p = p

    def compute(self) -> Array:
        return dunn_index(dim_zero_cat(self.data), dim_zero_cat(self.labels), self.p)
