"""Property tests for the mergeable sketch states (ISSUE 7).

Covers the documented error bounds against exact cat-state twins, the O(1)
state-size invariant, and the merge contract — associativity / permutation
invariance locally, under every ``SyncPolicy`` route, and through the
ElasticSync checkpoint → merge-on-rejoin path.
"""
import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchmetrics_tpu import (
    ApproxAUROC,
    ApproxCalibrationError,
    ApproxFrequency,
    ApproxQuantile,
)
from torchmetrics_tpu.parallel.elastic import checkpoint_metric, merge_checkpoint
from torchmetrics_tpu.parallel.reduction import (
    SKETCH_REDUCTIONS,
    Reduction,
    SketchReduction,
    resolve_reduction,
)
from torchmetrics_tpu.parallel.strategies import SyncPolicy
from torchmetrics_tpu.parallel.sync import FakeSync, reduce_state_in_graph
from torchmetrics_tpu.sketches import (
    countmin_init,
    countmin_merge,
    countmin_query,
    countmin_update,
    reservoir_init,
    reservoir_merge,
    reservoir_rows,
    reservoir_update,
    tdigest_init,
    tdigest_merge,
    tdigest_quantile,
    tdigest_update,
)


def _state_nbytes(m) -> int:
    total = 0
    for name in m._defaults:
        v = getattr(m, name)
        if isinstance(v, list):
            total += sum(int(x.size) * x.dtype.itemsize for x in v)
        elif hasattr(v, "buffer"):
            total += int(v.buffer.size) * v.buffer.dtype.itemsize
        else:
            total += int(v.size) * v.dtype.itemsize
    return total


# --------------------------------------------------------------- registration
def test_sketch_tags_resolve_to_registered_reductions():
    td = resolve_reduction("tdigest")
    rs = resolve_reduction("reservoir")
    cm = resolve_reduction("countmin")
    assert isinstance(td, SketchReduction) and td.mergeable and td.supports_decay
    assert isinstance(rs, SketchReduction) and rs.mergeable and rs.supports_decay
    assert cm is Reduction.SUM  # count-min merges elementwise: plain SUM alias
    assert td is SKETCH_REDUCTIONS["tdigest"]  # singletons, not per-call copies
    assert pickle.loads(pickle.dumps(td)) is td


def test_unknown_sketch_tag_raises():
    with pytest.raises(ValueError, match="sketch tag"):
        resolve_reduction("hyperloglog")


# ------------------------------------------------- t-digest vs the exact twin
@pytest.mark.parametrize("q", [0.1, 0.5, 0.9])
def test_tdigest_rank_error_within_documented_bound(q):
    rng = np.random.RandomState(3)
    data = rng.lognormal(0.0, 1.0, size=50_000).astype(np.float32)
    approx = ApproxQuantile(q=q, compression=128)
    exact = ApproxQuantile(q=q, compression=128, exact=True)
    for chunk in np.split(data, 10):
        approx.update(jnp.asarray(chunk))
        exact.update(jnp.asarray(chunk))
    est = float(approx.compute())
    # the twin is the oracle: rank the estimate inside the exact sample
    rank = float(np.mean(data <= est))
    assert abs(rank - q) <= approx.error_bound()
    # and the exact twin itself is the true quantile (same estimator)
    assert float(exact.compute()) == pytest.approx(float(np.quantile(data, q)), rel=1e-5)


def test_tdigest_state_bytes_constant_from_1e4_to_1e6():
    rng = np.random.RandomState(7)
    m = ApproxQuantile(q=0.5, compression=128)
    m.update(jnp.asarray(rng.rand(10_000).astype(np.float32)))
    bytes_1e4 = _state_nbytes(m)
    chunk = jnp.asarray(rng.rand(45_000).astype(np.float32))
    for _ in range(22):  # 10_000 + 22 * 45_000 = 1_000_000 observations
        m.update(chunk)
    assert _state_nbytes(m) == bytes_1e4
    assert bytes_1e4 == (m.compression + 1) * 2 * 4  # (C+1, 2) float32, exactly


def test_tdigest_merge_permutation_invariant_bitwise():
    rng = np.random.RandomState(11)
    digests = []
    for r in range(4):
        d = tdigest_init(64)
        d = tdigest_update(d, jnp.asarray(rng.randn(2_000).astype(np.float32) + r))
        digests.append(d)
    stack = jnp.stack(digests)
    merged = tdigest_merge(stack)
    for perm in ([3, 1, 0, 2], [1, 0, 3, 2], [2, 3, 1, 0]):
        np.testing.assert_array_equal(
            np.asarray(tdigest_merge(stack[jnp.asarray(perm)])), np.asarray(merged)
        )


def test_tdigest_two_step_merge_agrees_within_envelope():
    rng = np.random.RandomState(13)
    data = rng.randn(3, 4_000).astype(np.float32)
    parts = [tdigest_update(tdigest_init(128), jnp.asarray(d)) for d in data]
    one_shot = tdigest_merge(jnp.stack(parts))
    two_step = tdigest_merge(jnp.stack([tdigest_merge(jnp.stack(parts[:2])), parts[2]]))
    bound = ApproxQuantile(compression=128).error_bound()
    flat = data.reshape(-1)
    for q in (0.25, 0.5, 0.75):
        for est in (one_shot, two_step):
            rank = float(np.mean(flat <= float(tdigest_quantile(est, q))))
            assert abs(rank - q) <= bound


# ----------------------------------------------------- count-min: bounds
def test_countmin_overestimate_only_and_epsilon_bound():
    rng = np.random.RandomState(17)
    items = (rng.zipf(1.3, size=20_000) % 10_000).astype(np.int32)
    depth, width = 4, 2048
    table = countmin_init(depth, width)
    for chunk in np.split(items, 10):
        table = countmin_update(table, jnp.asarray(chunk), seed=0)
    ids, true_counts = np.unique(items, return_counts=True)
    est = np.asarray(countmin_query(table, jnp.asarray(ids), seed=0))
    assert np.all(est >= true_counts)  # collisions can only ADD
    # ε = e/width excess over the total count, w.p. 1 - e^-depth; with a
    # fixed seed the failure set is deterministic — gate every id
    eps_n = np.e / width * items.size
    assert np.all(est - true_counts <= eps_n)


def test_countmin_merge_is_exact_addition():
    rng = np.random.RandomState(19)
    tables = []
    all_items = []
    for r in range(3):
        items = (rng.zipf(1.5, size=5_000) % 1_000).astype(np.int32)
        all_items.append(items)
        tables.append(countmin_update(countmin_init(4, 1024), jnp.asarray(items), seed=0))
    merged = countmin_merge(jnp.stack(tables))
    direct = countmin_update(
        countmin_init(4, 1024), jnp.asarray(np.concatenate(all_items)), seed=0
    )
    np.testing.assert_array_equal(np.asarray(merged), np.asarray(direct))


# ----------------------------------------------------- reservoir: sampling
def test_reservoir_holds_everything_below_capacity():
    vals = jnp.arange(100, dtype=jnp.float32)
    sk = reservoir_update(reservoir_init(256), vals, seed=0)
    rows, valid = reservoir_rows(sk)
    assert int(jnp.sum(valid)) == 100
    got = np.sort(np.asarray(rows[:, 0])[np.asarray(valid)])
    np.testing.assert_array_equal(got, np.arange(100, dtype=np.float32))


def test_reservoir_sample_mean_unbiased_over_seeds():
    rng = np.random.RandomState(23)
    data = rng.rand(4_096).astype(np.float32)  # true mean 0.5003...
    cap, n_seeds = 256, 24
    means = []
    for seed in range(n_seeds):
        sk = reservoir_init(cap)
        for chunk in np.split(data, 8):
            sk = reservoir_update(sk, jnp.asarray(chunk), seed=seed)
        rows, valid = reservoir_rows(sk)
        means.append(float(jnp.sum(jnp.where(valid, rows[:, 0], 0.0)) / jnp.sum(valid)))
    # mean of per-seed sample means concentrates at the population mean with
    # s.e. ≈ σ/sqrt(cap·seeds); gate 4 standard errors
    se = float(np.std(data)) / np.sqrt(cap * n_seeds)
    assert abs(np.mean(means) - float(np.mean(data))) <= 4 * se


def test_reservoir_merge_permutation_invariant_bitwise():
    rng = np.random.RandomState(29)
    parts = []
    for r in range(4):
        sk = reservoir_init(64)
        sk = reservoir_update(sk, jnp.asarray(rng.rand(300).astype(np.float32)), seed=r)
        parts.append(sk)
    stack = jnp.stack(parts)
    merged = reservoir_merge(stack)
    for perm in ([2, 0, 3, 1], [3, 2, 1, 0]):
        np.testing.assert_array_equal(
            np.asarray(reservoir_merge(stack[jnp.asarray(perm)])), np.asarray(merged)
        )
    # associative: ((a+b)+(c+d)) == (a+b+c+d) bitwise — top-K over a union
    ab = reservoir_merge(stack[:2])
    cd = reservoir_merge(stack[2:])
    np.testing.assert_array_equal(
        np.asarray(reservoir_merge(jnp.stack([ab, cd]))), np.asarray(merged)
    )


def test_reservoir_auroc_within_sampling_error_of_exact_twin():
    rng = np.random.RandomState(31)
    n = 20_000
    target = (rng.rand(n) < 0.4).astype(np.float32)
    preds = np.clip(0.3 * target + 0.7 * rng.rand(n), 0, 1).astype(np.float32)
    approx = ApproxAUROC(capacity=2048)
    exact = ApproxAUROC(capacity=2048, exact=True)
    for p, t in zip(np.split(preds, 10), np.split(target, 10)):
        approx.update(jnp.asarray(p), jnp.asarray(t))
        exact.update(jnp.asarray(p), jnp.asarray(t))
    assert abs(float(approx.compute()) - float(exact.compute())) <= approx.error_bound()


def test_reservoir_ece_within_sampling_error_of_exact_twin():
    rng = np.random.RandomState(37)
    n = 20_000
    preds = rng.rand(n).astype(np.float32)
    target = (rng.rand(n) < preds).astype(np.float32)  # perfectly calibrated
    approx = ApproxCalibrationError(capacity=2048, n_bins=10)
    exact = ApproxCalibrationError(capacity=2048, n_bins=10, exact=True)
    for p, t in zip(np.split(preds, 10), np.split(target, 10)):
        approx.update(jnp.asarray(p), jnp.asarray(t))
        exact.update(jnp.asarray(p), jnp.asarray(t))
    assert abs(float(approx.compute()) - float(exact.compute())) <= approx.error_bound()


# ------------------------------------------------- sync: every policy route
_POLICIES = {
    "default": None,
    "exact": SyncPolicy(exact=True),
    "all_gather": SyncPolicy(gather="all_gather"),
    "psum": SyncPolicy(gather="psum"),
    "quantized": SyncPolicy(gather="all_gather", quantize_bits=8, quantize_threshold=1),
    "reduce_scatter": SyncPolicy(reduce_scatter_threshold=1),
}


def _sketch_ranks(policy, world=2):
    rng = np.random.RandomState(41)
    ms = []
    for _ in range(world):
        kw = {} if policy is None else {"sync_policy": policy}
        ms.append(
            (
                ApproxQuantile(q=0.5, compression=64, **kw),
                ApproxAUROC(capacity=128, **kw),
                ApproxFrequency(track=(1, 2, 3), width=256, **kw),
            )
        )
    for q, a, f in ms:
        vals = rng.rand(500).astype(np.float32)
        labels = (rng.rand(500) < 0.5).astype(np.float32)
        items = (rng.zipf(1.5, size=500) % 100).astype(np.int32)
        q.update(jnp.asarray(vals))
        a.update(jnp.asarray(vals), jnp.asarray(labels))
        f.update(jnp.asarray(items))
    return ms


@pytest.mark.parametrize("name", sorted(_POLICIES))
def test_sketch_states_sync_bitwise_on_every_policy_route(name):
    """After an eager sync, every rank holds the SAME merged sketch — the
    n-way merge rides the callable-reduction path of whichever route the
    policy selects (sketch leaves are never quantized or scattered)."""
    policy = _POLICIES[name]
    ms = _sketch_ranks(policy)
    for col in range(3):
        ranks = [ms[r][col] for r in range(len(ms))]
        expected = ranks[0].merge_states([m._tensor_state() for m in ranks])
        group = [m.metric_state for m in ranks]
        for r, m in enumerate(ranks):
            m.sync(sync_backend=FakeSync(group, r))
        states = [m.metric_state for m in ranks]
        for key in states[0]:
            ref = np.asarray(states[0][key])
            np.testing.assert_array_equal(np.asarray(states[1][key]), ref)
            np.testing.assert_array_equal(np.asarray(expected[key]), ref)


def test_sketch_leaf_reduces_in_graph_via_vmap_collective():
    """The in-graph route: a tdigest leaf in a vmapped ``reduce_state_in_graph``
    merges to the same digest on every replica, identical to a host-side
    ``tdigest_merge`` of the per-replica stack."""
    rng = np.random.RandomState(43)
    parts = [
        tdigest_update(tdigest_init(64), jnp.asarray(rng.randn(400).astype(np.float32)))
        for _ in range(4)
    ]
    stack = jnp.stack(parts)
    red = resolve_reduction("tdigest")
    out = jax.vmap(
        lambda s: reduce_state_in_graph(s, {"digest": red}, "dp"), axis_name="dp"
    )({"digest": stack})["digest"]
    expected = np.asarray(tdigest_merge(stack))
    for r in range(4):
        np.testing.assert_array_equal(np.asarray(out[r]), expected)


# -------------------------------------- elastic: checkpoint → merge-on-rejoin
def test_sketch_checkpoint_merge_on_rejoin_matches_direct_merge():
    rng = np.random.RandomState(47)
    a = ApproxQuantile(q=0.5, compression=64)
    b = ApproxQuantile(q=0.5, compression=64)
    da, db = rng.randn(2, 1_000).astype(np.float32)
    a.update(jnp.asarray(da))
    b.update(jnp.asarray(db))
    expected = a.merge_states([a._tensor_state(), b._tensor_state()])
    blob = checkpoint_metric(b)  # the preempted rank hands off its state...
    merge_checkpoint(a, blob)  # ...and folds back into the surviving peer
    np.testing.assert_array_equal(np.asarray(a.digest), np.asarray(expected["digest"]))
    # the rejoined estimate stays inside the documented envelope on the union
    both = np.concatenate([da, db])
    rank = float(np.mean(both <= float(a.compute())))
    assert abs(rank - 0.5) <= a.error_bound()


def test_sketch_metric_survives_elastic_drop_and_rejoin():
    """ChaosSync drop → degraded partial result with honest coverage;
    rejoin → full-coverage result bitwise equal to the fault-free run."""
    from torchmetrics_tpu.parallel import ChaosSchedule, ElasticSync, chaos_group

    rng = np.random.RandomState(53)
    data = rng.rand(2, 800).astype(np.float32)

    def _ranks():
        ms = [ApproxQuantile(q=0.5, compression=64) for _ in range(2)]
        for r, m in enumerate(ms):
            m.update(jnp.asarray(data[r]))
        return ms

    ref = _ranks()
    ref[0]._sync_backend = FakeSync([m.metric_state for m in ref], 0)
    fault_free = float(ref[0].compute())

    ms = _ranks()
    sched = ChaosSchedule({0: [("drop", 1)], 1: [("rejoin", 1)]})
    backs = chaos_group([m.metric_state for m in ms], sched)
    for r, m in enumerate(ms):
        m._sync_backend = ElasticSync(backs[r], policy=SyncPolicy(retry_attempts=1))
    ctrl = backs[0].controller

    ctrl.advance()  # round 0: rank 1 absent — degraded, coverage 1/2
    degraded = float(ms[0].compute())
    cov = ms[0].coverage
    assert cov is not None and cov.ranks_present == 1 and cov.ranks_expected == 2
    # rank 0 alone: its own data's median, within the sketch envelope
    rank0 = float(np.mean(data[0] <= degraded))
    assert abs(rank0 - 0.5) <= ms[0].error_bound()

    ctrl.advance()  # round 1: rank 1 rejoins — full coverage, bitwise result
    ms[0]._computed = None
    rejoined = float(ms[0].compute())
    cov = ms[0].coverage
    assert cov is not None and cov.fraction == 1.0
    assert rejoined == fault_free
