"""Task / averaging enums.

Parity: reference ``src/torchmetrics/utilities/enums.py:56-154``.
"""
from enum import Enum


class EnumStr(str, Enum):
    """String enum with case-insensitive lookup."""

    @classmethod
    def from_str(cls, value, source: str = "input"):
        try:
            return cls(value.lower().replace("-", "_")) if isinstance(value, str) else cls(value)
        except ValueError:
            valid = [e.value for e in cls]
            raise ValueError(f"Invalid {source} value {value!r}. Expected one of {valid}.") from None

    def __str__(self) -> str:
        return self.value


class ClassificationTask(EnumStr):
    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    BINARY = "binary"
    MULTICLASS = "multiclass"


class AverageMethod(EnumStr):
    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class DataType(EnumStr):
    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"
