"""P.862 utterance-level time alignment battery (round 5).

The reference backend performs utterance splitting + per-utterance
alignment + bad-interval realignment via the wrapped ITU C library
(`/root/reference/src/torchmetrics/functional/audio/pesq.py:81-84`); this
battery pins the first-party implementation of those three components:

- piecewise-constant delay across utterances must cost ~nothing (the
  VERDICT r4 acceptance bound: within 0.1 MOS of the unshifted score);
- a delay jump INSIDE one utterance must be recovered by recursive
  sub-splitting (the old global alignment scored it ~1.8);
- a held-out degradation family (hard clipping) is asserted only against
  loose bounds + monotonicity, never regenerated goldens — the
  calibration is fitted to the two ITU anchors, so at least one family
  must stay outside the fit's reach (ADVICE r4).
"""
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu.functional.audio import perceptual_evaluation_speech_quality

FS = 16000


def _speechish(seed: int, n: int) -> np.ndarray:
    """Formant-filtered, pitch-modulated pulse train — speech-shaped
    spectrum (glottal-like source, 500/1500/2500 Hz formants), faded edges
    so silent-gap insertion is artifact-free."""
    rng = np.random.RandomState(seed)
    t = np.arange(n) / FS
    f0 = 120 + 30 * np.sin(2 * np.pi * 2.1 * t)
    src = np.sign(np.sin(2 * np.pi * np.cumsum(f0) / FS)) * (0.6 + 0.4 * np.sin(2 * np.pi * 3.7 * t))
    x = src + 0.3 * rng.randn(n)
    spec = np.fft.rfft(x)
    fr = np.fft.rfftfreq(n, 1 / FS)
    formants = (
        np.exp(-(((fr - 500) / 400) ** 2))
        + 0.5 * np.exp(-(((fr - 1500) / 500) ** 2))
        + 0.25 * np.exp(-(((fr - 2500) / 600) ** 2))
    )
    w = np.fft.irfft(spec * formants, n)
    r = int(0.01 * FS)
    w[:r] *= np.linspace(0, 1, r)
    w[-r:] *= np.linspace(1, 0, r)
    return w.astype(np.float32)


def _pesq(deg, ref, fs=FS, mode="wb"):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return float(perceptual_evaluation_speech_quality(jnp.asarray(deg), jnp.asarray(ref), fs, mode))


GAP = int(0.35 * FS)


def _two_utterances(d1: int, d2: int) -> np.ndarray:
    """Two 1 s utterances in silence, each at its own delay."""
    u1, u2 = _speechish(0, FS), _speechish(1, FS)
    x = np.zeros(3 * GAP + 2 * FS, np.float32)
    x[GAP + d1 : GAP + d1 + FS] = u1
    x[2 * GAP + FS + d2 : 2 * GAP + FS + d2 + FS] = u2
    return x


@pytest.mark.parametrize(("d1", "d2"), [(120, -80), (400, -400), (800, 300), (0, 640)])
def test_piecewise_delay_within_tenth_mos(d1, d2):
    """Utterances shifted by DIFFERENT amounts score within 0.1 MOS of the
    unshifted signal (global alignment can fix at most one delay)."""
    ref = _two_utterances(0, 0)
    base = _pesq(ref, ref)
    shifted = _pesq(_two_utterances(d1, d2), ref)
    assert abs(shifted - base) <= 0.1, (shifted, base)


def test_uniform_delay_still_aligned():
    """A single global delay (the old path's only competence) still scores
    at the ceiling."""
    ref = _two_utterances(0, 0)
    assert abs(_pesq(_two_utterances(250, 250), ref) - _pesq(ref, ref)) <= 0.05


def test_mid_utterance_delay_jump_recovered():
    """A 40 ms delay jump INSIDE one utterance: recursive sub-splitting must
    recover all but the genuine splice artifact (global alignment scored
    this construction ~1.8)."""
    u = _speechish(0, 2 * FS)
    n = 2 * GAP + 2 * FS + 1600
    ref = np.zeros(n, np.float32)
    ref[GAP : GAP + 2 * FS] = u
    deg = np.zeros(n, np.float32)
    half = FS
    deg[GAP : GAP + half] = u[:half]
    deg[GAP + half + 640 : GAP + half + 640 + half] = u[half:]
    score = _pesq(deg, ref)
    assert score >= 4.3, score
    assert _pesq(ref, ref) - score <= 0.35  # residual = the real 40 ms skip


def test_clipping_family_held_out_loose_bounds():
    """Held-out degradation family (ADVICE r4): hard clipping is asserted
    only against loose bounds and monotonicity — never pinned to a
    regenerated golden — so at least one family stays outside the
    two-anchor calibration fit and keeps providing independent signal."""
    ref = _two_utterances(0, 0)
    peak = np.abs(ref).max()
    scores = []
    for frac in (0.5, 0.2, 0.05):
        deg = np.clip(ref, -frac * peak, frac * peak)
        scores.append(_pesq(deg, ref))
    ceiling = _pesq(ref, ref)
    # loose sanity: clipping hurts, harder clipping hurts more, never below floor
    assert all(1.0 <= s < ceiling - 0.1 for s in scores), scores
    assert scores[0] > scores[1] > scores[2], scores


def test_polarity_and_scale_invariance_of_alignment():
    """Level alignment + envelope correlation must tolerate gain changes;
    the alignment must not lock onto an anticorrelated lag."""
    ref = _two_utterances(0, 0)
    assert abs(_pesq(0.25 * ref, ref) - _pesq(ref, ref)) <= 0.05
