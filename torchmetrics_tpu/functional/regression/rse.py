"""Relative squared error.

Parity: reference ``src/torchmetrics/functional/regression/rse.py``.
"""
import jax
import jax.numpy as jnp

from .r2 import _r2_score_update

Array = jax.Array


def _relative_squared_error_compute(
    sum_squared_obs: Array, sum_obs: Array, sum_squared_error: Array, num_obs: Array, squared: bool = True
) -> Array:
    epsilon = 1.17e-06
    rse = sum_squared_error / jnp.clip(sum_squared_obs - sum_obs * sum_obs / num_obs, min=epsilon)
    if not squared:
        rse = jnp.sqrt(rse)
    return jnp.mean(rse)


def relative_squared_error(preds: Array, target: Array, num_outputs: int = 1, squared: bool = True) -> Array:
    """Parity: reference ``rse.py:42``."""
    sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target, num_outputs)
    return _relative_squared_error_compute(sum_squared_obs, sum_obs, rss, num_obs, squared)
