"""Reduction tags for metric states.

The key architectural invariant (see SURVEY.md §1): a metric state leaf carries
a reduction tag telling the distributed layer how replicas merge. Parity with
reference ``Metric.add_state``'s ``dist_reduce_fx`` mapping
(``src/torchmetrics/metric.py:252-261``), but as a first-class enum so the
in-graph collective (``lax.psum``/``pmax``/``pmin``/``all_gather``) can be
chosen per tag — O(state) traffic instead of the reference's O(world·state)
gather-then-reduce (``utilities/distributed.py:97``).
"""
from enum import Enum
from typing import Callable, Optional, Union


class Reduction(str, Enum):
    SUM = "sum"
    MEAN = "mean"
    MAX = "max"
    MIN = "min"
    CAT = "cat"
    NONE = "none"  # state is not synced automatically (custom merge in compute)

    def __str__(self) -> str:
        return self.value


#: Reductions that act elementwise on fixed-shape states. Leaves sharing a
#: ``(Reduction, dtype)`` pair can be flattened into one buffer and reduced by
#: a single collective (bucketing), bitwise-identically to per-leaf reduction.
ELEMENTWISE_REDUCTIONS = frozenset({Reduction.SUM, Reduction.MEAN, Reduction.MAX, Reduction.MIN})

ReduceFx = Union[str, Reduction, Callable, None]


def resolve_reduction(fx: ReduceFx) -> Union[Reduction, Callable]:
    """Map user-facing ``dist_reduce_fx`` values to a Reduction tag."""
    if fx is None:
        return Reduction.NONE
    if isinstance(fx, Reduction):
        return fx
    if isinstance(fx, str):
        try:
            return Reduction(fx)
        except ValueError:
            raise ValueError(
                f"`dist_reduce_fx` must be one of {[r.value for r in Reduction]} or a callable, got {fx!r}"
            ) from None
    if callable(fx):
        return fx
    raise ValueError(f"`dist_reduce_fx` must be a string, callable or None, got {fx!r}")
