"""SQuAD v1.1 evaluation: Exact Match + token F1 on normalized answers.

Parity target: reference ``functional/text/squad.py`` (official SQuAD
normalization: lowercase, strip punctuation, drop articles, squash spaces).
"""
import re
import string
from collections import Counter
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

SINGLE_PRED_TYPE = Dict[str, Any]
PREDS_TYPE = Union[SINGLE_PRED_TYPE, List[SINGLE_PRED_TYPE]]
SINGLE_TARGET_TYPE = Dict[str, Any]
TARGETS_TYPE = Union[SINGLE_TARGET_TYPE, List[SINGLE_TARGET_TYPE]]


def _normalize_text(s: str) -> str:
    s = s.lower()
    s = "".join(ch for ch in s if ch not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def _get_tokens(s: str) -> List[str]:
    return _normalize_text(s).split() if s else []


def _compute_f1_score(pred: str, target: str) -> float:
    pred_tokens, tgt_tokens = _get_tokens(pred), _get_tokens(target)
    common = Counter(pred_tokens) & Counter(tgt_tokens)
    num_same = sum(common.values())
    if len(pred_tokens) == 0 or len(tgt_tokens) == 0:
        return float(pred_tokens == tgt_tokens)
    if num_same == 0:
        return 0.0
    precision = num_same / len(pred_tokens)
    recall = num_same / len(tgt_tokens)
    return 2 * precision * recall / (precision + recall)


def _compute_exact_match(pred: str, target: str) -> float:
    return float(_normalize_text(pred) == _normalize_text(target))


def _squad_input_check(preds: PREDS_TYPE, targets: TARGETS_TYPE) -> Tuple[Dict[str, str], List[Dict[str, Any]]]:
    """Flatten SQuAD-format dicts to {id: prediction} + answer records."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]
    preds_dict = {}
    for p in preds:
        if "prediction_text" not in p or "id" not in p:
            raise KeyError("Expected keys in a single prediction are 'prediction_text' and 'id'.")
        preds_dict[p["id"]] = p["prediction_text"]
    target_list = []
    for t in targets:
        if "answers" not in t or "id" not in t:
            raise KeyError("Expected keys in a single target are 'answers' and 'id'.")
        if "text" not in t["answers"]:
            raise KeyError("Expected keys in a 'answers' are 'text'.")
        target_list.append({"id": t["id"], "answers": list(t["answers"]["text"])})
    return preds_dict, target_list


def _squad_update(preds_dict: Dict[str, str], target_list: List[Dict[str, Any]]) -> Tuple[Array, Array, Array]:
    f1 = exact = 0.0
    total = 0
    for rec in target_list:
        total += 1
        pred = preds_dict.get(rec["id"], "")
        answers = rec["answers"] or [""]
        exact += max(_compute_exact_match(pred, a) for a in answers)
        f1 += max(_compute_f1_score(pred, a) for a in answers)
    return jnp.asarray(f1), jnp.asarray(exact), jnp.asarray(float(total))


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    return {
        "exact_match": 100.0 * exact_match / jnp.maximum(total, 1.0),
        "f1": 100.0 * f1 / jnp.maximum(total, 1.0),
    }


def squad(preds: PREDS_TYPE, target: TARGETS_TYPE) -> Dict[str, Array]:
    """SQuAD EM/F1 (percent). Parity: reference ``squad.py:195``."""
    preds_dict, target_list = _squad_input_check(preds, target)
    f1, exact, total = _squad_update(preds_dict, target_list)
    return _squad_compute(f1, exact, total)
