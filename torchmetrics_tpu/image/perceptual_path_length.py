"""Perceptual path length.

Parity: reference
``src/torchmetrics/functional/image/perceptual_path_length.py:27``
(``GeneratorType`` protocol, latent interpolation lerp/slerp, LPIPS distance
between epsilon-jittered latent pairs).
"""
from typing import Any, Callable, Optional

import jax

from ..functional.image.perceptual_path_length import perceptual_path_length
from ..metric import Metric

Array = jax.Array


class PerceptualPathLength(Metric):
    """Class wrapper over :func:`perceptual_path_length`."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jittable = False

    def __init__(self, distance_fn: Callable, num_samples: int = 10_000, conditional: bool = False,
                 batch_size: int = 128, interpolation_method: str = "lerp", epsilon: float = 1e-4,
                 resize: Optional[int] = 64, lower_discard: Optional[float] = 0.01,
                 upper_discard: Optional[float] = 0.99, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.distance_fn = distance_fn
        self.num_samples = num_samples
        self.conditional = conditional
        self.batch_size = batch_size
        self.interpolation_method = interpolation_method
        self.epsilon = epsilon
        self.resize = resize
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        self._generator = None

    def update(self, generator: Any) -> None:
        self._generator = generator

    def compute(self):
        if self._generator is None:
            raise RuntimeError("No generator has been provided via `update`.")
        return perceptual_path_length(
            self._generator, self.distance_fn, self.num_samples, self.conditional, self.batch_size,
            self.interpolation_method, self.epsilon, self.resize, self.lower_discard, self.upper_discard,
        )
