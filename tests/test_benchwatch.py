"""Bench-trajectory regression gate (``tools/benchwatch``).

Covers round extraction across every committed BENCH_r*.json shape (parsed
dict, recoverable truncated tail, timed-out round), the direction-aware
IQR tolerance gate, the min-observation skip, baseline re-anchoring, and —
as an integration check — that the repo's own committed trajectory passes.
"""
import json
import os

from tools import benchwatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round(tmp_path, n, parsed=None, tail="", rc=0):
    doc = {"n": n, "cmd": "bench", "rc": rc, "tail": tail, "parsed": parsed}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(doc))


def _payload(headline, **extras):
    return {
        "value": headline,
        "extra": {k: {"value": v} for k, v in extras.items()},
    }


def test_load_rounds_parsed_and_fragment_and_dead(tmp_path):
    _round(tmp_path, 1, parsed=_payload(100.0, cfg=10.0))
    _round(tmp_path, 2, rc=124, tail="")  # timed-out round: skipped
    # front-truncated payload: only fragments + the methodology runs list
    _round(tmp_path, 3, tail='53, "cfg": {"value": 12.5, "unit": "x"}, '
           '"methodology": {"headline_runs": [90.0, 110.0, 105.0]}}')
    rounds = benchwatch.load_rounds(str(tmp_path))
    assert [r["n"] for r in rounds] == [1, 3]
    assert rounds[0]["source"] == "parsed"
    assert rounds[0]["values"] == {"headline": 100.0, "cfg": 10.0}
    assert rounds[1]["source"] == "tail-fragment"
    assert rounds[1]["values"]["cfg"] == 12.5
    # headline refit as the median of the recovered runs
    assert rounds[1]["values"]["headline"] == 105.0


def test_step_overhead_pct_extracted_lower_better(tmp_path):
    parsed = {"value": 50.0, "extra": {"step_overhead": {"pct": 2.5}}}
    _round(tmp_path, 1, parsed=parsed)
    (r,) = benchwatch.load_rounds(str(tmp_path))
    assert r["values"]["step_overhead_pct"] == 2.5


def test_gate_passes_within_tolerance(tmp_path):
    for n, v in enumerate([100.0, 110.0, 95.0], start=1):
        _round(tmp_path, n, parsed=_payload(v))
    res = benchwatch.check(str(tmp_path), baseline_path=str(tmp_path / "anchor.json"))
    assert res["ok"] is True
    assert res["configs"]["headline"]["status"] == "pass"


def test_gate_fails_on_headline_regression(tmp_path):
    for n, v in enumerate([100.0, 102.0, 98.0, 40.0], start=1):
        _round(tmp_path, n, parsed=_payload(v))
    res = benchwatch.check(str(tmp_path), baseline_path=str(tmp_path / "anchor.json"))
    assert res["ok"] is False
    verdict = res["configs"]["headline"]
    assert verdict["status"] == "fail"
    assert verdict["direction"] == "higher_better"
    assert verdict["latest"] == 40.0


def test_gate_direction_aware_for_overhead(tmp_path):
    # overhead pct going UP is the regression
    for n, pct in enumerate([1.0, 1.1, 0.9, 5.0], start=1):
        _round(tmp_path, n, parsed={"value": 100.0,
                                    "extra": {"step_overhead": {"pct": pct}}})
    res = benchwatch.check(str(tmp_path), baseline_path=str(tmp_path / "anchor.json"))
    assert res["configs"]["step_overhead_pct"]["status"] == "fail"
    assert res["configs"]["headline"]["status"] == "pass"


def test_noisy_series_widens_tolerance_via_iqr(tmp_path):
    # prior spread is huge: a 35% dip must ride inside the IQR-aware band
    # (a fixed 25% floor alone would reject it)
    for n, v in enumerate([60.0, 140.0, 100.0, 65.0], start=1):
        _round(tmp_path, n, parsed=_payload(v))
    res = benchwatch.check(str(tmp_path), baseline_path=str(tmp_path / "anchor.json"))
    verdict = res["configs"]["headline"]
    assert verdict["tolerance"] > 0.25
    assert verdict["status"] == "pass"


def test_thin_history_skipped_not_gated(tmp_path):
    # one prior round is not a median — report skipped, never fail
    for n, v in enumerate([100.0, 10.0], start=1):
        _round(tmp_path, n, parsed=_payload(v))
    res = benchwatch.check(str(tmp_path), baseline_path=str(tmp_path / "anchor.json"))
    assert res["ok"] is True
    assert res["configs"]["headline"]["status"] == "skipped"


def test_baseline_reanchors_reference(tmp_path):
    for n, v in enumerate([100.0, 102.0, 98.0, 40.0], start=1):
        _round(tmp_path, n, parsed=_payload(v))
    anchor = str(tmp_path / "anchor.json")
    assert benchwatch.check(str(tmp_path), baseline_path=anchor)["ok"] is False
    doc = benchwatch.write_baseline(str(tmp_path), anchor)
    assert doc["values"]["headline"] == 40.0
    # after the intentional re-anchor the same trajectory passes
    res = benchwatch.check(str(tmp_path), baseline_path=anchor)
    assert res["ok"] is True
    assert res["configs"]["headline"]["anchored"] is True


def test_committed_trajectory_passes():
    # the repo's own BENCH_r*.json history is the contract bench.py --smoke
    # enforces; it must hold, and the headline must be actively gated
    res = benchwatch.check(REPO)
    assert res["ok"] is True, res
    assert res["rounds_seen"] >= 3
    assert res["configs"]["headline"]["status"] == "pass"
    assert res["configs"]["headline"]["observations"] >= 3


# -------------------------------------------------- explicit round exclusion
def test_scan_rounds_excludes_partial_fixture_with_reason(tmp_path):
    # BENCH_PARTIAL.json is a raw bench payload committed without the
    # n/rc/parsed envelope; it must be excluded by name, with a reason,
    # not parsed as a round (its "value" field would poison the series)
    _round(tmp_path, 1, parsed=_payload(100.0))
    (tmp_path / "BENCH_PARTIAL.json").write_text(
        json.dumps({"metric": "throughput", "value": 406.89, "extra": {}})
    )
    rounds, skipped = benchwatch.scan_rounds(str(tmp_path))
    assert [r["n"] for r in rounds] == [1]
    (sk,) = skipped
    assert sk["path"] == "BENCH_PARTIAL.json"
    assert "envelope" in sk["reason"]


def test_scan_rounds_excludes_failed_rc_with_reason(tmp_path):
    _round(tmp_path, 1, parsed=_payload(100.0))
    # a timed-out round: rc=124 — excluded even though its tail might hold
    # fragments (a dead run's numbers are not trajectory evidence)
    _round(tmp_path, 2, rc=124, tail='{"value": 3.0}')
    _round(tmp_path, 3, parsed=_payload(99.0))
    rounds, skipped = benchwatch.scan_rounds(str(tmp_path))
    assert [r["n"] for r in rounds] == [1, 3]
    (sk,) = skipped
    assert sk["path"] == "BENCH_r02.json"
    assert "rc=124" in sk["reason"]


def test_check_reports_skipped_rounds(tmp_path):
    _round(tmp_path, 1, parsed=_payload(100.0))
    _round(tmp_path, 2, rc=1, tail="")
    (tmp_path / "BENCH_PARTIAL.json").write_text("{}")
    (tmp_path / "BENCH_r03.json").write_text("{ not json")
    res = benchwatch.check(str(tmp_path), baseline_path=str(tmp_path / "anchor.json"))
    reasons = {s["path"]: s["reason"] for s in res["skipped_rounds"]}
    assert set(reasons) == {"BENCH_PARTIAL.json", "BENCH_r02.json", "BENCH_r03.json"}
    assert "rc=1" in reasons["BENCH_r02.json"]
    assert "unreadable" in reasons["BENCH_r03.json"]
    assert res["rounds_seen"] == 1


def test_committed_partial_fixture_is_skipped_not_parsed():
    # the repo really does commit a BENCH_PARTIAL.json; the live check must
    # list it (and the rc=124 round) under skipped_rounds
    res = benchwatch.check(REPO)
    skipped_paths = {s["path"] for s in res["skipped_rounds"]}
    assert "BENCH_PARTIAL.json" in skipped_paths
    assert "BENCH_r04.json" in skipped_paths  # the committed timed-out round
    assert all("reason" in s and s["reason"] for s in res["skipped_rounds"])
