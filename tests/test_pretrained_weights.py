"""Canonical-weights pipeline: offline round-trip + online certification.

Two layers:

1. Offline (always runs, zero skips): the full ``tools/fetch_weights.py``
   pipeline — its OWN ``fetch_fid``/``fetch_lpips`` code paths with a
   stubbed download, the filename-hash checksum pin, convert → npz-cache →
   loader → extractor — exercised with RANDOM-weight torch mirrors standing
   in for the downloaded checkpoints and asserted numerically against
   them. The only step not executed offline is the network transfer
   itself.
2. ``-m weights`` (DESELECTED from default runs by tests/conftest.py, run
   explicitly after ``tools/fetch_weights.py``): certifies the CANONICAL
   artifacts — FID/KID int-feature ctors resolve, LPIPS pretrained
   backbones load, CLIP resolves through the transformers cache.
"""
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu.models import pretrained as PT


def _cache_has(name: str) -> bool:
    return os.path.exists(os.path.join(PT.weights_dir(), name))


def _mirror_fid_net():
    """Seed-0 torch FID-Inception mirror (tests/image oracle)."""
    torch = pytest.importorskip("torch")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "image"))
    try:
        from test_inception_parity import TFIDInception
    finally:
        sys.path.pop(0)
    torch.manual_seed(0)
    return TFIDInception().eval()


def _assert_extractor_matches(net) -> None:
    """The cached-weights extractor must reproduce the torch mirror's
    2048-d features on seed-0 images."""
    import torch

    extract = PT.fid_inception_extractor(2048)
    assert extract is not None
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (2, 3, 96, 96)).astype(np.float32)
    ours = np.asarray(extract(jnp.asarray(imgs)))
    with torch.no_grad():
        theirs = net(torch.tensor(imgs))[2048].numpy()
    np.testing.assert_allclose(ours, theirs, atol=5e-3, rtol=1e-3)


_ALEX_CFG = ((3, 64, 11), (64, 192, 5), (192, 384, 3), (384, 256, 3), (256, 256, 3))


def _alex_state_np() -> dict:
    """Seed-0 random torchvision-layout alex trunk state dict (numpy)."""
    rng = np.random.RandomState(0)
    state = {}
    for i, (cin, cout, k) in enumerate(_ALEX_CFG):
        state[f"features.{i}.weight"] = rng.randn(cout, cin, k, k).astype(np.float32) * 0.01
        state[f"features.{i}.bias"] = rng.randn(cout).astype(np.float32) * 0.01
    return state


def test_flatten_unflatten_roundtrip():
    tree = {"params": {"a": np.ones((2, 2)), "b": {"c": np.zeros(3)}}, "batch_stats": {"m": np.asarray(1.0)}}
    flat = PT.flatten_pytree(tree)
    assert set(flat) == {"params/a", "params/b/c", "batch_stats/m"}
    back = PT.unflatten_pytree(flat)
    np.testing.assert_array_equal(back["params"]["b"]["c"], tree["params"]["b"]["c"])


def test_fid_pipeline_offline_with_mirror_checkpoint(tmp_path, monkeypatch):
    """convert -> npz cache -> loader -> extractor matches the torch mirror
    the state dict came from (random weights; same path the real
    checkpoint takes through tools/fetch_weights.py)."""
    from torchmetrics_tpu.models.inception import convert_torch_state_dict

    net = _mirror_fid_net()
    state = {k: v.numpy() for k, v in net.state_dict().items()}
    variables = convert_torch_state_dict(state)

    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    np.savez_compressed(os.path.join(str(tmp_path), PT.FID_NPZ), **PT.flatten_pytree(variables))

    _assert_extractor_matches(net)

    # the int-feature FID ctor now resolves through the cache
    from torchmetrics_tpu import FrechetInceptionDistance

    imgs = np.random.RandomState(0).randint(0, 256, (2, 3, 96, 96)).astype(np.float32)
    fid = FrechetInceptionDistance(feature=2048)
    fid.update(jnp.asarray(imgs), real=True)
    fid.update(jnp.asarray(imgs), real=False)
    assert float(fid.compute()) == pytest.approx(0.0, abs=1e-2)


def test_fid_int_feature_message_names_fetch_tool(tmp_path, monkeypatch):
    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))  # empty cache
    from torchmetrics_tpu import FrechetInceptionDistance, InceptionScore

    with pytest.raises(ModuleNotFoundError, match="fetch_weights"):
        FrechetInceptionDistance(feature=2048)
    with pytest.raises(ModuleNotFoundError, match="fetch_weights"):
        InceptionScore()  # default feature='logits_unbiased' resolves via cache too


def test_inception_score_resolves_from_cache(tmp_path, monkeypatch):
    from torchmetrics_tpu.models.inception import convert_torch_state_dict

    net = _mirror_fid_net()
    variables = convert_torch_state_dict({k: v.numpy() for k, v in net.state_dict().items()})
    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    np.savez_compressed(os.path.join(str(tmp_path), PT.FID_NPZ), **PT.flatten_pytree(variables))

    from torchmetrics_tpu import InceptionScore

    isc = InceptionScore(splits=2)  # 'logits_unbiased' string tap via cache
    imgs = np.random.RandomState(0).randint(0, 256, (8, 3, 96, 96)).astype(np.float32)
    isc.update(jnp.asarray(imgs))
    mean, std = isc.compute()
    assert np.isfinite(float(mean)) and float(mean) >= 1.0


def test_lpips_class_resolves_from_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    _write_mirror_alex_cache(str(tmp_path))
    from torchmetrics_tpu.image.lpip import LearnedPerceptualImagePatchSimilarity

    metric = LearnedPerceptualImagePatchSimilarity(net_type="alex")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1)
    metric.update(x, x)
    assert float(metric.compute()) == pytest.approx(0.0, abs=1e-6)


def test_ppl_string_simnet_resolves_from_cache(tmp_path, monkeypatch):
    """Reference-parity sim_net strings for PPL: resolve via the weights
    cache, raise with fetch-tool guidance otherwise."""
    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    from torchmetrics_tpu.image.perceptual_path_length import PerceptualPathLength

    with pytest.raises(ModuleNotFoundError, match="fetch_weights"):
        PerceptualPathLength(distance_fn="alex", num_samples=4, batch_size=2)
    with pytest.raises(ValueError, match="one of"):
        PerceptualPathLength(distance_fn="resnet")
    _write_mirror_alex_cache(str(tmp_path))
    ppl = PerceptualPathLength(distance_fn="alex", num_samples=4, batch_size=2, resize=None)
    assert callable(ppl.distance_fn)


def test_fid_invalid_tap_rejected_up_front(tmp_path, monkeypatch):
    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    from torchmetrics_tpu import FrechetInceptionDistance, InceptionScore

    with pytest.raises(ValueError, match="must be one of"):
        FrechetInceptionDistance(feature=1024)
    with pytest.raises(ValueError, match="must be one of"):
        InceptionScore(feature="logits_unbiassed")


def _write_mirror_alex_cache(cache_dir: str) -> dict:
    """Random torchvision-layout alex state dict -> converted npz in the
    cache, exactly as tools/fetch_weights.py would; returns the state."""
    from torchmetrics_tpu.models.lpips import convert_lpips_torch, lpips_head_params

    state = _alex_state_np()
    inner = dict(convert_lpips_torch(state, {}, net_type="alex")["params"])
    inner.update(lpips_head_params("alex"))
    np.savez_compressed(
        os.path.join(cache_dir, PT.LPIPS_NPZ.format(net="alex")),
        **PT.flatten_pytree({"params": inner}),
    )
    return state


def test_lpips_pipeline_offline_with_mirror_backbone(tmp_path, monkeypatch):
    """A random torchvision-layout alex state dict flows through the tool's
    convert+cache path and make_lpips(backbone='pretrained') loads it."""
    from torchmetrics_tpu.models.lpips import make_lpips

    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    state = _write_mirror_alex_cache(str(tmp_path))
    rng = np.random.RandomState(3)
    _, loaded, distance = make_lpips("alex", backbone="pretrained")
    kern = np.asarray(loaded["params"]["net"]["conv0"]["kernel"])
    np.testing.assert_allclose(kern, state["features.0.weight"].transpose(2, 3, 1, 0))
    x = jnp.asarray(rng.rand(1, 3, 64, 64).astype(np.float32) * 2 - 1)
    assert float(distance(x, x)[0]) == pytest.approx(0.0, abs=1e-6)


def test_lpips_pretrained_requires_cache(tmp_path, monkeypatch):
    from torchmetrics_tpu.models.lpips import make_lpips

    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    with pytest.raises(FileNotFoundError, match="fetch_weights"):
        make_lpips("alex", backbone="pretrained")


# ------------------------------------------------------------- fetch tool
import functools


@functools.lru_cache(maxsize=1)
def _import_fetch_tool():
    """Load tools/fetch_weights.py once per session (its top level prepends
    the repo to sys.path — re-executing per test would accumulate entries)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools", "fetch_weights.py")
    spec = importlib.util.spec_from_file_location("fetch_weights_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fetch_tool_fid_end_to_end_with_stubbed_download(tmp_path, monkeypatch):
    """tools/fetch_weights.py's OWN fetch_fid path (torch.load -> convert ->
    npz cache) run against a synthetic checkpoint, asserted numerically
    against the torch mirror — the only step left untested offline is the
    network transfer inside _download."""
    torch = pytest.importorskip("torch")
    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    fw = _import_fetch_tool()
    net = _mirror_fid_net()
    pth = tmp_path / "synthetic-fid.pth"
    torch.save(net.state_dict(), str(pth))
    monkeypatch.setattr(fw, "_download", lambda url: str(pth))
    fw.fetch_fid()
    _assert_extractor_matches(net)


def test_fetch_tool_lpips_end_to_end_with_stubbed_download(tmp_path, monkeypatch):
    """fetch_lpips' own path: torchvision-layout .pth (incl. classifier
    tensors, exercising the features.-filter) -> convert -> cache -> the
    pretrained LPIPS backbone loads with the exact converted kernels."""
    torch = pytest.importorskip("torch")
    from torchmetrics_tpu.models.lpips import make_lpips

    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path))
    fw = _import_fetch_tool()
    state = {k: torch.tensor(v) for k, v in _alex_state_np().items()}
    state["classifier.1.weight"] = torch.tensor(np.random.RandomState(9).randn(10, 256).astype(np.float32))  # must be filtered out
    pth = tmp_path / "synthetic-alex.pth"
    torch.save(state, str(pth))
    monkeypatch.setattr(fw, "TORCHVISION_URLS", {"alex": "stub://alex"})
    monkeypatch.setattr(fw, "_download", lambda url: str(pth))
    fw.fetch_lpips()

    _, loaded, distance = make_lpips("alex", backbone="pretrained")
    kern = np.asarray(loaded["params"]["net"]["conv0"]["kernel"])
    np.testing.assert_allclose(kern, state["features.0.weight"].numpy().transpose(2, 3, 1, 0))
    x = jnp.asarray(np.random.RandomState(3).rand(1, 3, 64, 64).astype(np.float32) * 2 - 1)
    assert float(distance(x, x)[0]) == pytest.approx(0.0, abs=1e-6)


def test_fetch_tool_checksum_pin(tmp_path, monkeypatch):
    """_download's filename-hash pin: a file whose sha256 matches its
    embedded 8-hex pin verifies; a mismatching pin raises and removes the
    corrupt file (file:// URLs keep the transfer itself local)."""
    import hashlib

    monkeypatch.setenv("TM_TPU_WEIGHTS_DIR", str(tmp_path / "cache"))
    fw = _import_fetch_tool()
    payload = b"synthetic checkpoint bytes"
    digest = hashlib.sha256(payload).hexdigest()
    good = tmp_path / f"weights-{digest[:8]}.pth"
    good.write_bytes(payload)
    dest = fw._download(good.as_uri())
    assert os.path.exists(dest)

    bad = tmp_path / "weights-deadbeef.pth"
    bad.write_bytes(payload)
    with pytest.raises(RuntimeError, match="checksum mismatch"):
        fw._download(bad.as_uri())
    assert not os.path.exists(os.path.join(str(tmp_path / "cache"), bad.name))


# ---------------------------------------------------------------- canonical
@pytest.mark.weights
@pytest.mark.skipif(not _cache_has(PT.FID_NPZ), reason="canonical FID weights not fetched")
def test_canonical_fid_weights():
    from torchmetrics_tpu import FrechetInceptionDistance

    fid = FrechetInceptionDistance(feature=2048)
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randint(0, 256, (4, 3, 128, 128)).astype(np.float32))
    fid.update(imgs, real=True)
    fid.update(imgs, real=False)
    assert float(fid.compute()) == pytest.approx(0.0, abs=1e-2)
    shifted = jnp.clip(imgs + 40.0, 0, 255)
    fid.reset()
    fid.update(imgs, real=True)
    fid.update(shifted, real=False)
    assert float(fid.compute()) > 0.0


@pytest.mark.weights
@pytest.mark.parametrize("net", ["alex", "vgg", "squeeze"])
def test_canonical_lpips_backbones(net):
    if not _cache_has(PT.LPIPS_NPZ.format(net=net)):
        pytest.skip(f"canonical {net} LPIPS weights not fetched")
    from torchmetrics_tpu.models.lpips import make_lpips

    _, _, distance = make_lpips(net, backbone="pretrained")
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(1, 3, 64, 64).astype(np.float32) * 2 - 1)
    noisy = jnp.clip(x + 0.3 * jnp.asarray(rng.randn(1, 3, 64, 64).astype(np.float32)), -1, 1)
    assert float(distance(x, x)[0]) == pytest.approx(0.0, abs=1e-6)
    assert float(distance(x, noisy)[0]) > 0.01  # trained nets penalize noise


@pytest.mark.weights
def test_canonical_clip():
    transformers = pytest.importorskip("transformers")
    try:  # resolves from the local HF cache only — no network at test time
        transformers.FlaxCLIPModel.from_pretrained(
            "openai/clip-vit-base-patch16", local_files_only=True
        )
    except Exception:
        pytest.skip("canonical CLIP weights not in the transformers cache")
    from torchmetrics_tpu.multimodal import CLIPScore

    metric = CLIPScore(model_name_or_path="openai/clip-vit-base-patch16")
    img = np.random.RandomState(0).rand(3, 224, 224).astype(np.float32)
    metric.update([img], ["a photo of random noise"])
    assert np.isfinite(float(metric.compute()))
