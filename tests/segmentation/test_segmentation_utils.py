"""Segmentation morphology toolbox vs scipy.ndimage oracles.

Mirrors the reference's strategy of checking ``functional/segmentation/utils``
against scipy (``tests/unittests`` use scipy.ndimage as the oracle)."""
import numpy as np

import jax.numpy as jnp
import pytest
from scipy import ndimage

from torchmetrics_tpu.functional.segmentation import (
    binary_dilation,
    binary_erosion,
    distance_transform,
    generate_binary_structure,
    get_neighbour_tables,
    mask_edges,
    surface_distance,
    table_contour_length,
    table_surface_area,
)


@pytest.mark.parametrize("rank", [2, 3])
@pytest.mark.parametrize("connectivity", [1, 2, 3])
def test_generate_binary_structure(rank, connectivity):
    ours = np.asarray(generate_binary_structure(rank, connectivity))
    theirs = ndimage.generate_binary_structure(rank, connectivity)
    assert (ours == theirs).all()


@pytest.mark.parametrize("connectivity", [1, 2])
def test_binary_erosion_dilation_vs_scipy(connectivity):
    rng = np.random.RandomState(0)
    img = (rng.rand(1, 1, 17, 23) > 0.4).astype(np.int32)
    st = generate_binary_structure(2, connectivity)
    ours = np.asarray(binary_erosion(img, st))[0, 0]
    theirs = ndimage.binary_erosion(img[0, 0], np.asarray(st)).astype(np.int32)
    assert (ours == theirs).all()
    ours_d = np.asarray(binary_dilation(img, st))[0, 0]
    theirs_d = ndimage.binary_dilation(img[0, 0], np.asarray(st)).astype(np.int32)
    assert (ours_d == theirs_d).all()


@pytest.mark.parametrize("metric", ["euclidean", "chessboard", "taxicab"])
@pytest.mark.parametrize("sampling", [(1.0, 1.0), (2.0, 0.5)])
def test_distance_transform_vs_scipy(metric, sampling):
    rng = np.random.RandomState(1)
    img = (rng.rand(19, 26) > 0.3).astype(np.int32)
    img[0, 0] = 0  # ensure background exists
    ours = np.asarray(distance_transform(img, sampling=sampling, metric=metric))
    if metric == "euclidean":
        theirs = ndimage.distance_transform_edt(img, sampling=sampling)
    else:
        if sampling != (1.0, 1.0):
            pytest.skip("scipy cdt has no sampling")
        theirs = ndimage.distance_transform_cdt(
            img, metric="chessboard" if metric == "chessboard" else "taxicab"
        )
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_mask_edges_and_surface_distance():
    a = np.zeros((20, 20), np.int32)
    a[5:15, 5:15] = 1
    b = np.zeros((20, 20), np.int32)
    b[6:16, 4:14] = 1
    ea, eb = mask_edges(a, b, crop=False)
    # edge = mask minus eroded mask
    exp_a = a - ndimage.binary_erosion(a, ndimage.generate_binary_structure(2, 1)).astype(np.int32)
    assert (np.asarray(ea).astype(np.int32) == exp_a).all()
    d = np.asarray(surface_distance(np.asarray(ea).astype(np.int32), np.asarray(eb).astype(np.int32)))
    assert d.shape[0] == int(exp_a.sum())
    assert (d >= 0).all() and np.isfinite(d).all()
    # crop=True pads each spatial dim by one (reference keeps the frame)
    ea_c, eb_c = mask_edges(a, b, crop=True)
    assert ea_c.shape == (22, 22)
    assert int(np.asarray(ea_c).sum()) == int(exp_a.sum())


def test_mask_edges_spacing_four_tuple():
    a = np.zeros((12, 12), np.int32)
    a[3:9, 3:9] = 1
    ep, et, ap_, at_ = mask_edges(a, a, crop=False, spacing=(1.0, 1.0))
    # neighbour-code conv output is (H-1, W-1) for a 2x2 valid conv
    assert ep.shape == (11, 11)
    # contour of a 6x6 pixel square through cell midpoints: 4 straight sides
    # of 5 units plus 4 diagonal corner cuts of length sqrt(2)/2 each
    assert np.isclose(float(np.asarray(ap_).sum()), 20.0 + 4 * np.sqrt(0.5), atol=1e-5)
    # empty masks with crop: zero 4-tuple
    z = np.zeros((12, 12), np.int32)
    out = mask_edges(z, z, crop=True, spacing=(1.0, 1.0))
    assert len(out) == 4 and not np.asarray(out[0]).any()


def test_contour_table_square():
    # a filled rectangle's contour length from the neighbour-code table should
    # approximate its perimeter
    table, kernel = table_contour_length((1.0, 1.0))
    assert table.shape == (16,)
    assert np.asarray(table)[0] == 0 and np.asarray(table)[15] == 0
    # straight-edge codes measure 1 pixel of contour
    assert np.isclose(np.asarray(table)[3], 1.0)  # vertical edge through cell
    assert np.isclose(np.asarray(table)[5], 1.0)  # horizontal edge


def test_surface_area_table_flat_plane():
    table, kernel = table_surface_area((1.0, 1.0, 1.0))
    t = np.asarray(table)
    assert t.shape == (256,)
    assert t[0] == 0 and t[255] == 0
    # flat plane: top 4 corners inside, bottom 4 outside -> area 1 per cell
    code_top = sum(1 << (7 - k) for k in range(8) if ((k >> 2) & 1) == 0)
    assert np.isclose(t[code_top], 1.0, atol=1e-6)
    # table must be symmetric under inside/outside complement
    assert np.allclose(t, t[::-1], atol=1e-6)


def test_distance_transform_no_background():
    img = np.ones((5, 5), np.int32)
    out = np.asarray(distance_transform(img))
    assert np.isinf(out).all()


def test_parity_vs_reference_torch():
    """binary_erosion + distance_transform (all 3 metrics, with sampling)
    against the reference's torch implementations on random masks."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "helpers"))
    from lightning_utilities_stub import install_stub

    install_stub()
    sys.path.insert(0, "/root/reference/src")
    try:
        import torch
        import torchmetrics.functional.segmentation.utils as RU
    except ImportError:
        pytest.skip("reference not importable")
    finally:
        sys.path.remove("/root/reference/src")

    import torchmetrics_tpu.functional.segmentation.utils as OU

    rng = np.random.RandomState(0)
    for trial in range(4):
        mask = rng.rand(24, 24) > 0.4
        ref = RU.binary_erosion(torch.tensor(mask[None, None].astype(np.float32))).numpy()[0, 0]
        got = np.asarray(OU.binary_erosion(jnp.asarray(mask[None, None].astype(np.int32))))[0, 0]
        np.testing.assert_array_equal(got.astype(bool), ref.astype(bool), err_msg=f"erosion {trial}")
        for metric in ("euclidean", "chessboard", "taxicab"):
            ref = RU.distance_transform(torch.tensor(mask.astype(np.float32)), metric=metric).numpy()
            got = np.asarray(OU.distance_transform(jnp.asarray(mask.astype(np.float32)), metric=metric))
            np.testing.assert_allclose(got, ref, atol=1e-4, err_msg=f"dt {metric} {trial}")
        ref = RU.distance_transform(
            torch.tensor(mask.astype(np.float32)), sampling=[2, 1], metric="euclidean").numpy()
        got = np.asarray(OU.distance_transform(
            jnp.asarray(mask.astype(np.float32)), sampling=[2, 1], metric="euclidean"))
        np.testing.assert_allclose(got, ref, atol=1e-4, err_msg=f"dt sampling {trial}")
