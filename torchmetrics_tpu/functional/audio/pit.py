"""Permutation Invariant Training (PIT) metric wrapper.

Parity target: reference ``functional/audio/pit.py`` — exhaustive
permutation search (``:68``) or scipy Hungarian on the speaker-pair metric
matrix (``:42-62``, CPU transfer).

TPU-native: the (spk x spk) pair-metric matrix is ONE batched call of the
underlying metric (broadcast over speaker pairs); the exhaustive search
evaluates all spk! permutations by indexing that matrix (no re-computation,
no Python loop over the batch). Hungarian (for spk > 3) runs on host over
the small matrix — same boundary the reference crosses.
"""
from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _pair_metric_matrix(preds: Array, target: Array, metric_func: Callable, **kwargs: Any) -> Array:
    """(..., spk_pred, spk_target) metric of every speaker pair in one call."""
    spk = preds.shape[-2]
    p = jnp.repeat(preds[..., :, None, :], spk, axis=-2)  # (..., sp, st, T)
    t = jnp.repeat(target[..., None, :, :], spk, axis=-3)
    return metric_func(p, t, **kwargs)


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """Best metric value + permutation per sample. Parity: ``pit.py:permutation_invariant_training``."""
    if preds.shape[:2] != target.shape[:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ("max", "min"):
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ("speaker-wise", "permutation-wise"):
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk = target.shape[1]
    perms = list(permutations(range(spk)))

    if mode == "speaker-wise":
        matrix = _pair_metric_matrix(preds, target, metric_func, **kwargs)  # (B, sp, st)
        if spk > 3:
            # Hungarian on host: optimal without enumerating spk! options.
            # First-party C++ Jonker-Volgenant (``_native``); scipy fallback.
            from ... import _native

            if _native.NATIVE_AVAILABLE:
                linear_sum_assignment = _native.linear_sum_assignment
            else:
                from scipy.optimize import linear_sum_assignment

            mat_np = np.asarray(matrix)
            best_perm = np.empty((mat_np.shape[0], spk), dtype=np.int64)
            best_metric = np.empty(mat_np.shape[0])
            for b in range(mat_np.shape[0]):
                sign = -1.0 if eval_func == "max" else 1.0
                rows, cols = linear_sum_assignment(sign * mat_np[b])
                best_perm[b] = cols
                best_metric[b] = mat_np[b, rows, cols].mean()
            return jnp.asarray(best_metric), jnp.asarray(best_perm)
        # exhaustive: gather each permutation's diagonal from the matrix
        perm_arr = jnp.asarray(perms)  # (P, spk)
        rows = jnp.arange(spk)
        per_perm = jnp.stack(
            [jnp.mean(matrix[..., rows, perm_arr[p]], axis=-1) for p in range(len(perms))], axis=-1
        )  # (B, P)
    else:
        per_perm_vals = []
        for perm in perms:
            permuted = target[:, jnp.asarray(perm), ...]
            per_perm_vals.append(metric_func(preds, permuted, **kwargs))
        per_perm = jnp.stack(per_perm_vals, axis=-1)  # (B, P)

    best_idx = jnp.argmax(per_perm, axis=-1) if eval_func == "max" else jnp.argmin(per_perm, axis=-1)
    best_metric = jnp.take_along_axis(per_perm, best_idx[..., None], axis=-1)[..., 0]
    best_perm = jnp.asarray(perms)[best_idx]
    return best_metric, best_perm


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Rearrange speakers according to per-sample permutations. Parity: ``pit.py:pit_permutate``."""
    return jnp.take_along_axis(preds, perm[..., None], axis=1)
