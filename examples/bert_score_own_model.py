"""BASELINE config 5 — BERTScore with a user-provided encoder + ROUGE.

Mirrors the reference's ``examples/bert_score-own_model.py``: any callable
that maps token batches to embeddings works as the encoder — no HF download
needed. ROUGE runs host-side (strings never touch the device)."""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # in-repo run

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.bert import bert_score_from_embeddings
from torchmetrics_tpu.functional.text.rouge import rouge_score


def _toy_tokenize(texts: List[str], max_len: int = 16):
    ids = np.zeros((len(texts), max_len), np.int32)
    mask = np.zeros((len(texts), max_len), np.float32)
    for i, t in enumerate(texts):
        toks = [hash(w) % 1000 for w in t.split()][:max_len]
        ids[i, : len(toks)] = toks
        mask[i, : len(toks)] = 1
    return ids, mask


def main() -> None:
    preds = ["the quick brown fox jumps", "hello world"]
    target = ["a quick brown fox leaps", "hello there world"]

    # toy embedding table stands in for a real encoder
    table = jax.random.normal(jax.random.PRNGKey(0), (1000, 32))
    p_ids, p_mask = _toy_tokenize(preds)
    t_ids, t_mask = _toy_tokenize(target)
    score = bert_score_from_embeddings(
        table[p_ids], jnp.asarray(p_mask), table[t_ids], jnp.asarray(t_mask)
    )
    print({k: np.asarray(v).round(3).tolist() for k, v in score.items()})

    rouge: Dict = rouge_score(preds, target)
    print({k: round(float(v), 3) for k, v in rouge.items() if k.endswith("fmeasure")})


if __name__ == "__main__":
    main()
