"""Functional retrieval kernels (L3).

Single-query public API with reference-parity signatures
(``functional/retrieval/__init__.py``); all maths delegate to the batched
padded kernels in ``_ops.py``.
"""
from typing import Optional, Tuple

import jax

from ._ops import (
    _single,
    batched_auroc,
    batched_average_precision,
    batched_fall_out,
    batched_hit_rate,
    batched_ndcg,
    batched_precision,
    batched_precision_recall_curve,
    batched_r_precision,
    batched_recall,
    batched_reciprocal_rank,
    _check_retrieval_functional_inputs,
)

Array = jax.Array


def _check_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


def retrieval_average_precision(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Parity: reference ``functional/retrieval/average_precision.py:22``."""
    _check_top_k(top_k)
    return _single(batched_average_precision, preds, target, top_k=top_k)


def retrieval_reciprocal_rank(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Parity: reference ``functional/retrieval/reciprocal_rank.py:22``."""
    _check_top_k(top_k)
    return _single(batched_reciprocal_rank, preds, target, top_k=top_k)


def retrieval_precision(
    preds: Array, target: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """Parity: reference ``functional/retrieval/precision.py:21``."""
    _check_top_k(top_k)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    return _single(batched_precision, preds, target, top_k=top_k, adaptive_k=adaptive_k)


def retrieval_recall(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Parity: reference ``functional/retrieval/recall.py:22``."""
    _check_top_k(top_k)
    return _single(batched_recall, preds, target, top_k=top_k)


def retrieval_fall_out(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Parity: reference ``functional/retrieval/fall_out.py:22``."""
    _check_top_k(top_k)
    return _single(batched_fall_out, preds, target, top_k=top_k)


def retrieval_hit_rate(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Parity: reference ``functional/retrieval/hit_rate.py:22``."""
    _check_top_k(top_k)
    return _single(batched_hit_rate, preds, target, top_k=top_k)


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """Parity: reference ``functional/retrieval/r_precision.py:20``."""
    return _single(batched_r_precision, preds, target)


def retrieval_normalized_dcg(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Parity: reference ``functional/retrieval/ndcg.py:71`` (ignore-ties)."""
    _check_top_k(top_k)
    return _single(batched_ndcg, preds, target, allow_non_binary_target=True, top_k=top_k)


def retrieval_auroc(
    preds: Array, target: Array, top_k: Optional[int] = None, max_fpr: Optional[float] = None
) -> Array:
    """Parity: reference ``functional/retrieval/auroc.py:22``."""
    _check_top_k(top_k)
    if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
        raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
    return _single(batched_auroc, preds, target, top_k=top_k, max_fpr=max_fpr)


def retrieval_precision_recall_curve(
    preds: Array, target: Array, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Parity: reference ``functional/retrieval/precision_recall_curve.py:24``."""
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    p, t = _check_retrieval_functional_inputs(preds, target)
    if max_k is None:
        max_k = p.shape[-1]
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")
    import jax.numpy as jnp

    mask = jnp.ones_like(p, dtype=bool)
    prec, rec, ks = batched_precision_recall_curve(p[None], t[None], mask[None], max_k, adaptive_k)
    return prec[0], rec[0], ks


__all__ = [
    "retrieval_auroc",
    "retrieval_average_precision",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_precision_recall_curve",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",
]
