"""Generate runnable Example blocks for metric class docstrings.

For every class in CLASS_SNIPPETS that lacks a ``>>>`` example, run its
snippet in a mini-REPL (each line compiled in 'single' mode so expression
values print exactly as doctest expects), capture the real outputs, and
insert an ``Example:`` section at the end of the class docstring in the
source file. Deterministic inputs only — no RNG — so the captured outputs
are stable across runs and platforms (doctests run on CPU via conftest).

Run from the repo root:  python tools/gen_doctests.py [--check]
"""
import contextlib
import io
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# force CPU before any jax backend init (an accelerator plugin can override
# the env var, so the config update is required too)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

PRELUDE = [
    "import jax.numpy as jnp",
]

# ---------------------------------------------------------------- templates

def agg(name, final):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}()",
        "metric.update(jnp.asarray([1.0, 2.0, 3.0]))",
        "metric.update(jnp.asarray([4.0]))",
        final,
    ]


def mc(name, ctor, final="round(float(metric.compute()), 4)"):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        "preds = jnp.asarray([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.6, 0.1]])",
        "target = jnp.asarray([0, 1, 2, 0])",
        "metric.update(preds, target)",
        final,
    ]


def binary(name, ctor, final="round(float(metric.compute()), 4)"):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        "preds = jnp.asarray([0.1, 0.8, 0.6, 0.3, 0.9, 0.4])",
        "target = jnp.asarray([0, 1, 1, 0, 1, 0])",
        "metric.update(preds, target)",
        final,
    ]


def ml(name, ctor="num_labels=3", final="round(float(metric.compute()), 4)"):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        "preds = jnp.asarray([[0.9, 0.1, 0.6], [0.2, 0.8, 0.3], [0.7, 0.4, 0.9]])",
        "target = jnp.asarray([[1, 0, 1], [0, 1, 0], [1, 0, 1]])",
        "metric.update(preds, target)",
        final,
    ]


def reg(name, ctor="", final="round(float(metric.compute()), 4)", positive=False):
    p = "[0.5, 1.5, 2.5, 4.0]" if positive else "[0.5, -1.5, 2.5, -4.0]"
    t = "[0.8, 1.0, 3.0, 3.5]" if positive else "[0.8, -1.0, 3.0, -3.5]"
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        f"metric.update(jnp.asarray({p}), jnp.asarray({t}))",
        final,
    ]


def img(name, ctor="", size=16, channels=3, pair=True, final="round(float(metric.compute()), 4)"):
    lines = [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        f"preds = jnp.tile(jnp.linspace(0.1, 0.9, {size}), (2, {channels}, {size}, 1))",
    ]
    if pair:
        lines += [
            "target = preds * 0.9 + 0.05",
            "metric.update(preds, target)",
        ]
    else:
        lines += ["metric.update(preds)"]
    lines.append(final)
    return lines


def audio(name, ctor="", final="round(float(metric.compute()), 4)", t=1600):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        f"t = jnp.linspace(0.0, 100.0, {t})",
        "target = jnp.sin(t)",
        "preds = target + 0.1 * jnp.cos(3.0 * t)",
        "metric.update(preds, target)",
        final,
    ]


def cluster_ex(name):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}()",
        "metric.update(jnp.asarray([0, 0, 1, 1, 2, 2]), jnp.asarray([1, 1, 0, 0, 2, 2]))",
        "round(float(metric.compute()), 4)",
    ]


def cluster_in(name):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}()",
        "data = jnp.asarray([[0.0, 0.0], [0.1, 0.2], [2.0, 2.0], [2.1, 1.9], [4.0, 4.1], [3.9, 4.0]])",
        "labels = jnp.asarray([0, 0, 1, 1, 2, 2])",
        "metric.update(data, labels)",
        "round(float(metric.compute()), 4)",
    ]


def nominal(name, ctor=""):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        "metric.update(jnp.asarray([0, 1, 2, 0, 1, 2, 0, 1]), jnp.asarray([0, 1, 2, 1, 1, 2, 0, 0]))",
        "round(float(metric.compute()), 4)",
    ]


def retrieval(name, ctor="", final="round(float(metric.compute()), 4)"):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        "preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])",
        "target = jnp.asarray([1, 0, 1, 0, 0, 1])",
        "indexes = jnp.asarray([0, 0, 0, 1, 1, 1])",
        "metric.update(preds, target, indexes=indexes)",
        final,
    ]


def text_pair(name, ctor="", final="round(float(metric.compute()), 4)"):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        'metric.update(["the cat sat on the mat"], ["the cat sat on a mat"])',
        final,
    ]


def text_corpus(name, ctor="", final="round(float(metric.compute()), 4)"):
    return [
        f"from torchmetrics_tpu import {name}",
        f"metric = {name}({ctor})",
        'metric.update(["the cat is on the mat"], [["there is a cat on the mat", "the cat is on the mat"]])',
        final,
    ]


CLASS_SNIPPETS = {}

for n, fin in [
    ("SumMetric", "float(metric.compute())"),
    ("MeanMetric", "float(metric.compute())"),
    ("MaxMetric", "float(metric.compute())"),
    ("MinMetric", "float(metric.compute())"),
    ("CatMetric", "metric.compute().tolist()"),
    ("RunningMean", "float(metric.compute())"),
    ("RunningSum", "float(metric.compute())"),
]:
    CLASS_SNIPPETS[n] = agg(n, fin)

MC3 = 'task="multiclass", num_classes=3'
for n, ctor in [
    ("Accuracy", MC3), ("Precision", MC3), ("Recall", MC3),
    ("F1Score", MC3), ("FBetaScore", MC3 + ", beta=0.5"), ("Specificity", MC3),
    ("CohenKappa", MC3), ("MatthewsCorrCoef", MC3), ("JaccardIndex", MC3),
    ("HammingDistance", MC3), ("CalibrationError", MC3), ("AUROC", MC3),
    ("AveragePrecision", MC3), ("HingeLoss", MC3),
]:
    CLASS_SNIPPETS[n] = mc(n, ctor)
CLASS_SNIPPETS["Dice"] = mc("Dice", "num_classes=3")
CLASS_SNIPPETS["StatScores"] = mc("StatScores", MC3, final="metric.compute().tolist()")
CLASS_SNIPPETS["ConfusionMatrix"] = mc("ConfusionMatrix", MC3, final="metric.compute().tolist()")
CLASS_SNIPPETS["ROC"] = binary(
    "ROC", 'task="binary", thresholds=5',
    final="[[round(float(x), 4) for x in v] for v in metric.compute()]",
)
CLASS_SNIPPETS["PrecisionRecallCurve"] = binary(
    "PrecisionRecallCurve", 'task="binary", thresholds=5',
    final="[[round(float(x), 4) for x in v] for v in metric.compute()]",
)
for n, kw in [
    ("PrecisionAtFixedRecall", "min_recall=0.5"),
    ("RecallAtFixedPrecision", "min_precision=0.5"),
    ("SensitivityAtSpecificity", "min_specificity=0.5"),
    ("SpecificityAtSensitivity", "min_sensitivity=0.5"),
]:
    CLASS_SNIPPETS[n] = binary(
        n, f'task="binary", {kw}',
        final="tuple(round(float(v), 4) for v in metric.compute())",
    )
CLASS_SNIPPETS["ExactMatch"] = [
    "from torchmetrics_tpu import ExactMatch",
    'metric = ExactMatch(task="multiclass", num_classes=3)',
    "preds = jnp.asarray([[0, 1, 2], [2, 1, 0]])",
    "target = jnp.asarray([[0, 1, 2], [2, 1, 1]])",
    "metric.update(preds, target)",
    "round(float(metric.compute()), 4)",
]
CLASS_SNIPPETS["BinaryFairness"] = [
    "from torchmetrics_tpu import BinaryFairness",
    "metric = BinaryFairness(num_groups=2)",
    "preds = jnp.asarray([0.9, 0.2, 0.8, 0.3, 0.6, 0.7])",
    "target = jnp.asarray([1, 0, 1, 0, 1, 1])",
    "groups = jnp.asarray([0, 0, 0, 1, 1, 1])",
    "metric.update(preds, target, groups)",
    "{k: round(float(v), 4) for k, v in sorted(metric.compute().items())}",
]
CLASS_SNIPPETS["BinaryGroupStatRates"] = [
    "from torchmetrics_tpu import BinaryGroupStatRates",
    "metric = BinaryGroupStatRates(num_groups=2)",
    "preds = jnp.asarray([0.9, 0.2, 0.8, 0.3, 0.6, 0.7])",
    "target = jnp.asarray([1, 0, 1, 0, 1, 1])",
    "groups = jnp.asarray([0, 0, 0, 1, 1, 1])",
    "metric.update(preds, target, groups)",
    "{k: [round(float(x), 4) for x in v] for k, v in sorted(metric.compute().items())}",
]
for n in ["MultilabelCoverageError", "MultilabelRankingAveragePrecision", "MultilabelRankingLoss"]:
    CLASS_SNIPPETS[n] = ml(n)

for n, ctor, positive in [
    ("MeanAbsoluteError", "", False), ("MeanSquaredLogError", "", True),
    ("LogCoshError", "", False), ("MeanAbsolutePercentageError", "", True),
    ("SymmetricMeanAbsolutePercentageError", "", True),
    ("WeightedMeanAbsolutePercentageError", "", True),
    ("ConcordanceCorrCoef", "", False), ("ExplainedVariance", "", False),
    ("R2Score", "", False), ("SpearmanCorrCoef", "", False),
    ("KendallRankCorrCoef", "", False), ("RelativeSquaredError", "", False),
    ("TweedieDevianceScore", "power=1.5", True), ("CriticalSuccessIndex", "threshold=1.0", True),
    ("MinkowskiDistance", "p=3.0", False),
]:
    CLASS_SNIPPETS[n] = reg(n, ctor, positive=positive)
CLASS_SNIPPETS["KLDivergence"] = [
    "from torchmetrics_tpu import KLDivergence",
    "metric = KLDivergence()",
    "p = jnp.asarray([[0.2, 0.3, 0.5], [0.1, 0.6, 0.3]])",
    "q = jnp.asarray([[0.3, 0.3, 0.4], [0.2, 0.5, 0.3]])",
    "metric.update(p, q)",
    "round(float(metric.compute()), 4)",
]
CLASS_SNIPPETS["CosineSimilarity"] = [
    "from torchmetrics_tpu import CosineSimilarity",
    "metric = CosineSimilarity()",
    "metric.update(jnp.asarray([[1.0, 2.0, 3.0]]), jnp.asarray([[1.0, 2.0, 2.0]]))",
    "round(float(metric.compute()), 4)",
]

for n, kw in [
    ("ErrorRelativeGlobalDimensionlessSynthesis", {}),
    ("RelativeAverageSpectralError", {}),
    ("RootMeanSquaredErrorUsingSlidingWindow", {}),
    ("SpectralAngleMapper", {}),
    ("SpectralDistortionIndex", {}),
    ("UniversalImageQualityIndex", {}),
    ("StructuralSimilarityIndexMeasure", {}),
]:
    CLASS_SNIPPETS[n] = img(n, **kw)
# SCC needs real 2-D high-frequency content: on a linear ramp the laplacian
# response is ~0 and the score would be platform-dependent conv noise
CLASS_SNIPPETS["SpatialCorrelationCoefficient"] = [
    "from torchmetrics_tpu import SpatialCorrelationCoefficient",
    "metric = SpatialCorrelationCoefficient()",
    "wave = jnp.sin(jnp.linspace(0.0, 9.0, 24))",
    "preds = jnp.tile(wave[:, None] * wave[None, :], (2, 3, 1, 1)) * 0.4 + 0.5",
    "target = preds * 0.9 + 0.03",
    "metric.update(preds, target)",
    "round(float(metric.compute()), 4)",
]
CLASS_SNIPPETS["MultiScaleStructuralSimilarityIndexMeasure"] = img(
    "MultiScaleStructuralSimilarityIndexMeasure", ctor="kernel_size=3", size=48)
CLASS_SNIPPETS["VisualInformationFidelity"] = img("VisualInformationFidelity", size=48)
CLASS_SNIPPETS["PeakSignalNoiseRatioWithBlockedEffect"] = img(
    "PeakSignalNoiseRatioWithBlockedEffect", size=16, channels=1)
CLASS_SNIPPETS["TotalVariation"] = img("TotalVariation", pair=False)
for n in ["SpatialDistortionIndex", "QualityWithNoReference"]:
    # ms must be >= 16x16: UQI's 11x11 window needs that much support, and
    # window_size=7 must stay below the ms dims (reference d_s.py:175)
    CLASS_SNIPPETS[n] = [
        f"from torchmetrics_tpu import {n}",
        f"metric = {n}()",
        "preds = jnp.tile(jnp.sin(jnp.linspace(0.0, 6.0, 32)) * 0.4 + 0.5, (1, 3, 32, 1))",
        "ms = jnp.tile(jnp.sin(jnp.linspace(0.0, 6.0, 16)) * 0.4 + 0.5, (1, 3, 16, 1))",
        "pan = preds * 0.95",
        'metric.update(preds, {"ms": ms, "pan": pan})',
        "round(float(metric.compute()), 4)",
    ]

for n in ["SignalNoiseRatio", "ScaleInvariantSignalNoiseRatio",
          "ScaleInvariantSignalDistortionRatio", "SignalDistortionRatio"]:
    CLASS_SNIPPETS[n] = audio(n)
CLASS_SNIPPETS["SourceAggregatedSignalDistortionRatio"] = [
    "from torchmetrics_tpu import SourceAggregatedSignalDistortionRatio",
    "metric = SourceAggregatedSignalDistortionRatio()",
    "t = jnp.linspace(0.0, 100.0, 800)",
    "target = jnp.stack([jnp.sin(t), jnp.cos(t)])[None]",
    "preds = target + 0.1",
    "metric.update(preds, target)",
    "round(float(metric.compute()), 4)",
]
CLASS_SNIPPETS["ComplexScaleInvariantSignalNoiseRatio"] = [
    "from torchmetrics_tpu import ComplexScaleInvariantSignalNoiseRatio",
    "metric = ComplexScaleInvariantSignalNoiseRatio()",
    "t = jnp.linspace(0.0, 6.0, 65 * 10 * 2)",
    "target = jnp.sin(t).reshape(1, 65, 10, 2)",
    "preds = target * 0.8 + 0.05",
    "metric.update(preds, target)",
    "round(float(metric.compute()), 4)",
]
CLASS_SNIPPETS["PermutationInvariantTraining"] = [
    "from torchmetrics_tpu import PermutationInvariantTraining",
    "from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio",
    "metric = PermutationInvariantTraining(scale_invariant_signal_noise_ratio)",
    "t = jnp.linspace(0.0, 100.0, 400)",
    "target = jnp.stack([jnp.sin(t), jnp.cos(t)])[None]",
    "preds = target[:, ::-1, :] + 0.05",
    "metric.update(preds, target)",
    "round(float(metric.compute()), 4)",
]
CLASS_SNIPPETS["PerceptualEvaluationSpeechQuality"] = audio(
    "PerceptualEvaluationSpeechQuality", ctor='fs=8000, mode="nb", implementation="native"', t=4096)
CLASS_SNIPPETS["ShortTimeObjectiveIntelligibility"] = audio(
    "ShortTimeObjectiveIntelligibility", ctor="fs=8000", t=4096)
CLASS_SNIPPETS["SpeechReverberationModulationEnergyRatio"] = [
    "from torchmetrics_tpu import SpeechReverberationModulationEnergyRatio",
    "metric = SpeechReverberationModulationEnergyRatio(fs=8000)",
    "t = jnp.linspace(0.0, 400.0, 4096)",
    "metric.update(jnp.sin(t) * (1 + 0.5 * jnp.sin(0.05 * t)))",
    "round(float(metric.compute()), 4)",
]

for n in ["AdjustedMutualInfoScore", "AdjustedRandScore", "CompletenessScore",
          "FowlkesMallowsIndex", "HomogeneityScore", "MutualInfoScore",
          "NormalizedMutualInfoScore", "RandScore", "VMeasureScore"]:
    CLASS_SNIPPETS[n] = cluster_ex(n)
for n in ["CalinskiHarabaszScore", "DaviesBouldinScore", "DunnIndex"]:
    CLASS_SNIPPETS[n] = cluster_in(n)

for n in ["PearsonsContingencyCoefficient", "TheilsU", "TschuprowsT"]:
    CLASS_SNIPPETS[n] = nominal(n, "num_classes=3")
CLASS_SNIPPETS["FleissKappa"] = [
    "from torchmetrics_tpu import FleissKappa",
    'metric = FleissKappa(mode="counts")',
    "ratings = jnp.asarray([[3, 1], [2, 2], [4, 0], [1, 3], [0, 4]])",
    "metric.update(ratings)",
    "round(float(metric.compute()), 4)",
]

for n, ctor in [
    ("RetrievalAUROC", ""), ("RetrievalFallOut", ""), ("RetrievalHitRate", ""),
    ("RetrievalMAP", ""), ("RetrievalNormalizedDCG", ""), ("RetrievalPrecision", "top_k=2"),
    ("RetrievalRPrecision", ""), ("RetrievalRecall", "top_k=2"),
]:
    CLASS_SNIPPETS[n] = retrieval(n, ctor)
CLASS_SNIPPETS["RetrievalPrecisionRecallCurve"] = retrieval(
    "RetrievalPrecisionRecallCurve", "max_k=2",
    final="[[round(float(x), 4) for x in v] for v in metric.compute()]",
)
CLASS_SNIPPETS["RetrievalRecallAtFixedPrecision"] = retrieval(
    "RetrievalRecallAtFixedPrecision", "min_precision=0.5",
    final="tuple(round(float(v), 4) for v in metric.compute())",
)

for n in ["CharErrorRate", "MatchErrorRate", "WordErrorRate", "WordInfoLost",
          "WordInfoPreserved", "TranslationEditRate", "ExtendedEditDistance", "CHRFScore"]:
    CLASS_SNIPPETS[n] = text_pair(n)
CLASS_SNIPPETS["EditDistance"] = [
    "from torchmetrics_tpu import EditDistance",
    "metric = EditDistance()",
    'metric.update(["kitten"], ["sitting"])',
    "float(metric.compute())",
]
for n in ["BLEUScore", "SacreBLEUScore"]:
    CLASS_SNIPPETS[n] = text_corpus(n)
CLASS_SNIPPETS["ROUGEScore"] = [
    "from torchmetrics_tpu import ROUGEScore",
    "metric = ROUGEScore()",
    'metric.update(["the cat is on the mat"], ["there is a cat on the mat"])',
    'round(float(metric.compute()["rouge1_fmeasure"]), 4)',
]
CLASS_SNIPPETS["SQuAD"] = [
    "from torchmetrics_tpu import SQuAD",
    "metric = SQuAD()",
    'preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]',
    'target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]',
    "metric.update(preds, target)",
    "{k: float(v) for k, v in sorted(metric.compute().items())}",
]
CLASS_SNIPPETS["Perplexity"] = [
    "from torchmetrics_tpu import Perplexity",
    "metric = Perplexity()",
    "logits = jnp.log(jnp.asarray([[[0.7, 0.2, 0.1], [0.2, 0.7, 0.1]]]))",
    "tokens = jnp.asarray([[0, 1]])",
    "metric.update(logits, tokens)",
    "round(float(metric.compute()), 4)",
]

_IOU_KEYS = {
    "IntersectionOverUnion": "iou",
    "GeneralizedIntersectionOverUnion": "giou",
    "DistanceIntersectionOverUnion": "diou",
    "CompleteIntersectionOverUnion": "ciou",
}
for n in _IOU_KEYS:
    CLASS_SNIPPETS[n] = [
        f"from torchmetrics_tpu import {n}",
        f"metric = {n}()",
        'preds = [{"boxes": jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), "scores": jnp.asarray([0.9]), "labels": jnp.asarray([0])}]',
        'target = [{"boxes": jnp.asarray([[12.0, 8.0, 58.0, 62.0]]), "labels": jnp.asarray([0])}]',
        "metric.update(preds, target)",
        f'round(float(metric.compute()["{_IOU_KEYS[n]}"]), 4)',
    ]
CLASS_SNIPPETS["MeanAveragePrecision"] = [
    "from torchmetrics_tpu import MeanAveragePrecision",
    "metric = MeanAveragePrecision()",
    'preds = [{"boxes": jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), "scores": jnp.asarray([0.9]), "labels": jnp.asarray([0])}]',
    'target = [{"boxes": jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), "labels": jnp.asarray([0])}]',
    "metric.update(preds, target)",
    'round(float(metric.compute()["map"]), 4)',
]
for n in ["PanopticQuality", "ModifiedPanopticQuality"]:
    CLASS_SNIPPETS[n] = [
        f"from torchmetrics_tpu import {n}",
        f"metric = {n}(things={{0}}, stuffs={{1}})",
        "img = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])",
        "metric.update(img[None], img[None])",
        "round(float(metric.compute()), 4)",
    ]

CLASS_SNIPPETS["MinMaxMetric"] = [
    "from torchmetrics_tpu import MeanSquaredError, MinMaxMetric",
    "metric = MinMaxMetric(MeanSquaredError())",
    "_ = metric(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 3.0]))",
    "_ = metric(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 2.0]))",
    "{k: round(float(v), 4) for k, v in sorted(metric.compute().items())}",
]
CLASS_SNIPPETS["MultioutputWrapper"] = [
    "from torchmetrics_tpu import MeanSquaredError, MultioutputWrapper",
    "metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)",
    "metric.update(jnp.asarray([[1.0, 5.0], [2.0, 6.0]]), jnp.asarray([[1.0, 4.0], [2.0, 8.0]]))",
    "jnp.round(metric.compute(), 4).tolist()",
]
CLASS_SNIPPETS["MultitaskWrapper"] = [
    "from torchmetrics_tpu import MeanSquaredError, MultitaskWrapper",
    "from torchmetrics_tpu.classification import BinaryAccuracy",
    'metric = MultitaskWrapper({"reg": MeanSquaredError(), "cls": BinaryAccuracy()})',
    'preds = {"reg": jnp.asarray([1.0, 2.0]), "cls": jnp.asarray([0.9, 0.2])}',
    'target = {"reg": jnp.asarray([1.0, 3.0]), "cls": jnp.asarray([1, 0])}',
    "metric.update(preds, target)",
    "{k: round(float(v), 4) for k, v in sorted(metric.compute().items())}",
]
CLASS_SNIPPETS["Running"] = [
    "from torchmetrics_tpu import Running, SumMetric",
    "metric = Running(SumMetric(), window=2)",
    "_ = metric(jnp.asarray([1.0]))",
    "_ = metric(jnp.asarray([2.0]))",
    "_ = metric(jnp.asarray([3.0]))",
    "float(metric.compute())",
]
CLASS_SNIPPETS["ClasswiseWrapper"] = [
    "from torchmetrics_tpu import ClasswiseWrapper",
    "from torchmetrics_tpu.classification import MulticlassAccuracy",
    'metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average="none"))',
    "metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1]]), jnp.asarray([0, 2]))",
    "{k: round(float(v), 4) for k, v in sorted(metric.compute().items())}",
]


# ------------------------------------------------------------------ engine

def run_snippet(lines):
    """Execute lines REPL-style; return [(line, output_str), ...]."""
    ns = {}
    for line in PRELUDE:
        exec(compile(line, "<doctest-gen>", "exec"), ns)
    results = []
    for line in lines:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            code = compile(line, "<doctest-gen>", "single")
            exec(code, ns)
        results.append((line, buf.getvalue()))
    return results


def format_example(results, indent):
    out = [f"{indent}Example:"]
    pad = indent + "    "
    out.append(f"{pad}>>> import jax.numpy as jnp")
    for line, output in results:
        out.append(f"{pad}>>> {line}")
        for ol in output.rstrip("\n").splitlines():
            out.append(f"{pad}{ol}")
    return "\n".join(out) + "\n"


def insert_example(cls, example_text):
    import inspect

    path = inspect.getsourcefile(cls)
    src = open(path).read()
    pat = re.compile(
        rf'(class {cls.__name__}\([^)]*\):\n)((?:    plot = .*\n)?)(    """)(.*?)("""\n)', re.S
    )
    m = pat.search(src)
    indent = "    "
    if m:
        body = m.group(4)
        if ">>>" in body:
            return False, path
        closing = m.group(5)
        sep = "\n" if body.endswith("\n") else "\n\n"
        # keep the closing quotes on their own line after the example
        new_body = body.rstrip() + "\n\n" + example_text + indent
        new = src[: m.start()] + m.group(1) + m.group(2) + m.group(3) + new_body + closing + src[m.end():]
    else:
        pat2 = re.compile(rf"(class {cls.__name__}\([^)]*\):\n)")
        m2 = pat2.search(src)
        if m2:
            # class without a docstring: add one holding just the example
            doc = f'    """{cls.__name__}.\n\n{example_text}    """\n'
            new = src[: m2.end()] + doc + src[m2.end():]
        else:
            # factory-made class (e.g. _make_facade): append a __doc__ patch
            block = example_text.replace('"""', r'\"\"\"')
            new = (
                src.rstrip("\n")
                + f'\n\n{cls.__name__}.__doc__ = ({cls.__name__}.__doc__ or "") + """\n\n{block}"""\n'
            )
    open(path, "w").write(new)
    return True, path


def main():
    import torchmetrics_tpu as M

    changed = []
    failed = []
    for name, lines in sorted(CLASS_SNIPPETS.items()):
        cls = getattr(M, name)
        if ">>>" in (cls.__doc__ or ""):
            continue
        try:
            results = run_snippet(lines)
        except Exception as err:  # noqa: BLE001
            failed.append((name, f"{type(err).__name__}: {err}"))
            continue
        example = format_example(results, "    ")
        ok, path = insert_example(cls, example)
        if ok:
            changed.append((name, path))
    print(f"inserted {len(changed)} examples")
    for name, err in failed:
        print(f"FAILED {name}: {err}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
