"""Task-dispatch facade base.

Parity: reference ``src/torchmetrics/classification/base.py:19``
(``_ClassificationTaskWrapper``): user-facing names (``Accuracy``, ...) are
facades whose ``__new__`` returns the Binary/Multiclass/Multilabel class
based on ``task=``.
"""
from typing import Any

from ..metric import Metric


class _ClassificationTaskWrapper(Metric):
    """Base for facades; never instantiated itself."""

    def __new__(cls, *args: Any, **kwargs: Any) -> "Metric":
        raise NotImplementedError(f"`{cls.__name__}` must be subclassed with a task-dispatching __new__.")

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError(f"{self.__class__.__name__} metric does not exist for the chosen task.")

    def compute(self) -> None:
        raise NotImplementedError(f"{self.__class__.__name__} metric does not exist for the chosen task.")
