"""R2Score & ExplainedVariance classes.

Parity: reference ``src/torchmetrics/regression/{r2,explained_variance}.py``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from ..functional.regression.explained_variance import (
    _explained_variance_compute,
    _explained_variance_update,
)
from ..functional.regression.r2 import _r2_score_compute, _r2_score_update
from ..metric import Metric

Array = jax.Array


class R2Score(Metric):
    """R2Score.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import R2Score
        >>> metric = R2Score()
        >>> metric.update(jnp.asarray([0.5, -1.5, 2.5, -4.0]), jnp.asarray([0.8, -1.0, 3.0, -3.5]))
        >>> round(float(metric.compute()), 4)
        0.9631
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_upper_bound = 1.0

    def __init__(self, num_outputs: int = 1, adjusted: int = 0, multioutput: str = "uniform_average",
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed}")
        self.multioutput = multioutput
        self.add_state("sum_squared_error", jnp.zeros((num_outputs,)).squeeze(), dist_reduce_fx="sum")
        self.add_state("sum_error", jnp.zeros((num_outputs,)).squeeze(), dist_reduce_fx="sum")
        self.add_state("residual", jnp.zeros((num_outputs,)).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target, self.num_outputs)
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + rss
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )


class ExplainedVariance(Metric):
    """ExplainedVariance.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ExplainedVariance
        >>> metric = ExplainedVariance()
        >>> metric.update(jnp.asarray([0.5, -1.5, 2.5, -4.0]), jnp.asarray([0.8, -1.0, 3.0, -3.5]))
        >>> round(float(metric.compute()), 4)
        0.9987
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_upper_bound = 1.0

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed}")
        self.multioutput = multioutput
        self.add_state("sum_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_target", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_obs", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(
            preds, target
        )
        self.n_obs = self.n_obs + n_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + sum_squared_target

    def compute(self) -> Array:
        return _explained_variance_compute(
            self.n_obs, self.sum_error, self.sum_squared_error, self.sum_target, self.sum_squared_target,
            self.multioutput,
        )
