"""Speech-transcription error rates: WER / CER / MER / WIL / WIP.

Parity targets: reference ``functional/text/{wer,cer,mer,wil,wip}.py`` —
host-side Levenshtein on word/char tokens, sum states, ratio computes.
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from .helper import _as_list, edit_distance_fast

Array = jax.Array


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    errors, total = 0, 0
    for pred, tgt in zip(_as_list(preds), _as_list(target)):
        errors += edit_distance_fast(pred.split(), tgt.split())
        total += len(tgt.split())
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WER = word edits / reference words. Parity: ``wer.py:66``."""
    return _wer_compute(*_wer_update(preds, target))


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    errors, total = 0, 0
    for pred, tgt in zip(_as_list(preds), _as_list(target)):
        errors += edit_distance_fast(list(pred), list(tgt))
        total += len(tgt)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """CER = char edits / reference chars. Parity: ``cer.py:66``."""
    errors, total = _cer_update(preds, target)
    return errors / total


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    errors, total = 0, 0
    for pred, tgt in zip(_as_list(preds), _as_list(target)):
        p, t = pred.split(), tgt.split()
        errors += edit_distance_fast(p, t)
        total += max(len(p), len(t))
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """MER = edits / max-length alignment. Parity: ``mer.py:67``."""
    errors, total = _mer_update(preds, target)
    return errors / total


def _wil_wip_update(
    preds: Union[str, List[str]], target: Union[str, List[str]]
) -> Tuple[Array, Array, Array]:
    """Returns (errors - total, target_words, pred_words); the first term's
    square ratio gives WIP (reference ``wil.py:22-55`` convention)."""
    errors, total, t_total, p_total = 0, 0, 0, 0
    for pred, tgt in zip(_as_list(preds), _as_list(target)):
        p, t = pred.split(), tgt.split()
        errors += edit_distance_fast(p, t)
        t_total += len(t)
        p_total += len(p)
        total += max(len(p), len(t))
    return (
        jnp.asarray(float(errors - total)),
        jnp.asarray(float(t_total)),
        jnp.asarray(float(p_total)),
    )


def word_information_lost(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WIL = 1 - WIP. Parity: ``wil.py:72``."""
    errors, t_total, p_total = _wil_wip_update(preds, target)
    return 1.0 - (errors / t_total) * (errors / p_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WIP = (hits/ref_words)(hits/hyp_words). Parity: ``wip.py:71``."""
    errors, t_total, p_total = _wil_wip_update(preds, target)
    return (errors / t_total) * (errors / p_total)
