"""Wrapper metrics (L5 composition)."""
from .abstract import WrapperMetric
from .bootstrapping import BootStrapper
from .classwise import ClasswiseWrapper
from .feature_share import FeatureShare, NetworkCache
from .minmax import MinMaxMetric
from .multioutput import MultioutputWrapper
from .multitask import MultitaskWrapper
from .running import Running
from .tracker import MetricTracker

__all__ = [
    "WrapperMetric",
    "BootStrapper",
    "ClasswiseWrapper",
    "FeatureShare",
    "NetworkCache",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "Running",
    "MetricTracker",
]
