"""Padded geometric cat-state buffers.

List/``cat`` states historically stored one device array per ``update`` and
re-concatenated the whole list at compute/sync time — every jitted consumer
specialized on the running total length (O(n) retraces across an n-step run)
and every observation copied O(n) elements. ``CatBuffer`` replaces the list
with a ``(buffer, count)`` pair: ``buffer`` has power-of-two row capacity
(doubling on overflow, so only O(log n) distinct shapes ever exist) and
appends are in-place ``lax.dynamic_update_slice`` writes into a donated
buffer — O(1) amortized. The valid prefix is ``buffer[:count]``; rows at or
past ``count`` are garbage and must be masked by every reader.

Append/grow kernels go through the process-global executable cache
(``metric._global_jit``), so the number of cat-path executables for an
n-append run is O(log n) (one per capacity) and steady-state appends are
pure cache hits. ``count`` rides into the kernels as a weak-typed ``int32``
scalar, so it never causes a retrace.

Snapshots are copy-on-write: ``snapshot()`` aliases the device buffer and
marks both sides unowned; the next append first copies, so a cached snapshot
(``Metric._cache``, forward full-state restore) is never clobbered by buffer
donation.
"""
from typing import Any, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

MIN_CAPACITY = 8


def default_eval_mesh(devices: Optional[Sequence[Any]] = None) -> Any:
    """The 1-D eval mesh sharded cat state lives on: every visible device on
    one ``'batch'`` axis (SNIPPETS §1 pattern). Pass ``devices`` to build a
    sub-mesh (elastic survivors, reshard targets)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    return jax.sharding.Mesh(np.array(devs), ("batch",))


def batch_sharding(mesh: Any) -> Any:
    """``NamedSharding(mesh, P('batch'))`` — rows partitioned on the leading
    axis, trailing dims replicated."""
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("batch"))


class CatLayoutError(TypeError):
    """An increment is incompatible with the padded buffer's row layout.

    Raised when the trailing (non-concatenated) dimensions of an increment
    differ from the buffer's; the owning metric degrades that state to the
    list layout, which tolerates ragged increments until concat time.
    """


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length() if n > 1 else 1


def _capacity_for(rows: int) -> int:
    return max(_next_pow2(rows), MIN_CAPACITY)


# public aliases: the pow2 shape-stability trick is shared infrastructure —
# tenant slots (multitenant.py) pad to the same geometric capacities as cat
# rows, so churn within capacity never changes a traced shape
next_pow2 = _next_pow2
capacity_for = _capacity_for


def _row_form(inc: Any) -> Array:
    """Increment as (rows,) + trailing — scalars become a single row,
    matching ``dim_zero_cat``'s ``atleast_1d`` semantics."""
    arr = inc if isinstance(inc, jax.Array) else jnp.asarray(inc)
    return arr[None] if arr.ndim == 0 else arr


def _jit(key: Any, fn: Any, donate: bool = False) -> Any:
    from .metric import _global_jit  # deferred: metric.py imports this module

    return _global_jit(key, fn, donate_state=donate)


def _append_kernel(buf: Array, inc: Array, count: Array) -> Tuple[Array, Array]:
    """(new_buf, new_count). ``count`` rides as a DEVICE scalar and the
    increment is folded in on-device, so a steady-state append issues zero
    host→device transfers (strict_mode transfer_guard clean)."""
    start = (count,) + (0,) * (buf.ndim - 1)
    return lax.dynamic_update_slice(buf, inc, start), count + inc.shape[0]


def _make_grow_append(new_capacity: int) -> Any:
    def grow_append(buf: Array, inc: Array, count: Array) -> Tuple[Array, Array]:
        pad = jnp.zeros((new_capacity - buf.shape[0],) + buf.shape[1:], buf.dtype)
        grown = jnp.concatenate([buf, pad], axis=0)
        return _append_kernel(grown, inc, count)

    return grow_append


class CatBuffer:
    """Growable padded cat state: ``(buffer, count)`` with pow2 capacity.

    Mutation rebinds ``buffer``/``count`` on the *same* object, so aliases
    held by compute groups and the incremental hash cache stay current.
    Equality compares the valid prefix (a list/tuple compares as its
    concatenation); hashing is by identity, as for lists.
    """

    __slots__ = ("buffer", "count", "_count_dev", "_owns")

    def __init__(self, buffer: Array, count: int, owns: bool = True) -> None:
        self.buffer = buffer
        self.count = int(count)
        # device mirror of `count`, fed to the append kernels so steady-state
        # appends never transfer a host scalar; created lazily on first append
        self._count_dev: Optional[Array] = None
        self._owns = owns

    # ------------------------------------------------------------- creation

    @classmethod
    def allocate(cls, first_inc: Any) -> "CatBuffer":
        inc = _row_form(first_inc)
        cap = _capacity_for(inc.shape[0])
        buf = cls(jnp.zeros((cap,) + inc.shape[1:], inc.dtype), 0)
        buf.append(inc)
        return buf

    @classmethod
    def from_increments(cls, increments: Sequence[Any]) -> "CatBuffer":
        rows = [_row_form(e) for e in increments]
        trailings = {r.shape[1:] for r in rows}
        if len(trailings) > 1:
            raise CatLayoutError(f"ragged increment trailing shapes {sorted(trailings)}")
        return cls.allocate(rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0))

    # ------------------------------------------------------------ properties

    @property
    def capacity(self) -> int:
        return self.buffer.shape[0]

    @property
    def dtype(self) -> Any:
        return self.buffer.dtype

    @property
    def trailing(self) -> Tuple[int, ...]:
        return self.buffer.shape[1:]

    # -------------------------------------------------------------- mutation

    def append(self, inc: Any) -> None:
        """In-place append of one increment (O(1) amortized device writes)."""
        inc = _row_form(inc)
        if inc.shape[1:] != self.trailing:
            raise CatLayoutError(
                f"increment trailing shape {inc.shape[1:]} != buffer trailing {self.trailing}"
            )
        if inc.dtype != self.dtype:
            promoted = jnp.promote_types(self.dtype, inc.dtype)
            if promoted != self.dtype:
                # rare dtype widening: eager cast of the whole buffer
                self.buffer = self.buffer.astype(promoted)
                self._owns = True
            if promoted != inc.dtype:
                inc = inc.astype(promoted)
        rows = inc.shape[0]
        if rows == 0:
            return
        needed = self.count + rows
        count = self._count_dev
        if count is None:
            count = jnp.asarray(self.count, jnp.int32)
        if needed > self.capacity:
            new_cap = _capacity_for(needed)
            # no donation: the old capacity can't back the larger output
            # buffer anyway, and XLA warns on unusable donations
            fn = _jit(
                ("catbuf_grow_append", self.capacity, new_cap, inc.shape, str(inc.dtype)),
                _make_grow_append(new_cap),
            )
            self.buffer, self._count_dev = fn(self.buffer, inc, count)
        else:
            if not self._owns:
                # copy-on-write: a snapshot aliases this buffer, so the
                # donating append must not clobber it
                self.buffer = jnp.array(self.buffer, copy=True)
            fn = _jit(
                ("catbuf_append", self.capacity, inc.shape, str(inc.dtype)),
                _append_kernel,
                donate=True,
            )
            self.buffer, self._count_dev = fn(self.buffer, inc, count)
        self._owns = True
        self.count = needed

    def extend(self, increments: Iterable[Any]) -> None:
        for inc in increments:
            self.append(inc)

    # --------------------------------------------------------------- reading

    def materialize(self) -> Array:
        """Masked valid slice ``buffer[:count]`` (never the raw buffer)."""
        return self.buffer[: self.count]

    def rows(self, start: int, stop: int) -> Array:
        """Rows ``[start, stop)`` of the valid region; ``stop`` is clamped to
        ``count`` so capacity padding never leaks into a sync payload."""
        return self.buffer[start : min(stop, self.count)]

    def snapshot(self) -> "CatBuffer":
        """Cheap O(1) copy sharing the device buffer; the next append on
        either side copies first (copy-on-write)."""
        self._owns = False
        out = CatBuffer(self.buffer, self.count, owns=False)
        out._count_dev = self._count_dev  # device scalars are immutable
        return out

    def astype(self, dtype: Any) -> "CatBuffer":
        return CatBuffer(self.buffer.astype(dtype), self.count)

    def to_device(self, device: Any) -> "CatBuffer":
        return CatBuffer(jax.device_put(self.buffer, device), self.count)

    # ------------------------------------------------------------- protocols

    def __len__(self) -> int:
        return self.count

    def __iter__(self) -> Iterator[Array]:
        for i in range(self.count):
            yield self.buffer[i]

    def __eq__(self, other: Any) -> Any:
        if other is self:
            return True
        if isinstance(other, CatBuffer):
            if self.count != other.count or self.trailing != other.trailing:
                return False
            if self.count == 0:
                return True
            return bool(jnp.all(self.materialize() == other.materialize()))
        if isinstance(other, (list, tuple)):
            if len(other) == 0:
                return self.count == 0
            try:
                cat = jnp.concatenate([_row_form(e) for e in other], axis=0)
            except Exception:
                return NotImplemented
            if cat.shape != (self.count,) + self.trailing:
                return False
            return bool(jnp.all(self.materialize() == cat))
        return NotImplemented

    __hash__ = object.__hash__

    def __repr__(self) -> str:
        return (
            f"CatBuffer(count={self.count}, capacity={self.capacity}, "
            f"trailing={self.trailing}, dtype={self.dtype})"
        )

    # ------------------------------------------------- pickle / deepcopy

    def __getstate__(self) -> Tuple[Any, int]:
        return np.asarray(self.materialize()), self.count

    def __setstate__(self, state: Tuple[Any, int]) -> None:
        valid, count = state
        cap = _capacity_for(max(count, 1))
        arr = np.zeros((cap,) + valid.shape[1:], valid.dtype)
        arr[:count] = valid
        self.buffer = jnp.asarray(arr)
        self.count = int(count)
        self._count_dev = None
        self._owns = True

    def __deepcopy__(self, memo: dict) -> "CatBuffer":
        # device arrays are immutable; an owned alias is a faithful deep copy
        new = CatBuffer(self.buffer, self.count, owns=True)
        new._count_dev = self._count_dev
        self._owns = False
        new._owns = False
        memo[id(self)] = new
        return new


def _make_sharded_append(n_shards: int, chunk: int, rows: int, sharding: Any) -> Any:
    """Donating append kernel for the sharded layout.

    The increment is padded to ``n_shards * chunk`` rows, reshaped to one
    ``chunk``-row slab per shard, and written at each shard's own valid
    count with a vmapped ``dynamic_update_slice`` — under the sharding
    constraint each device writes only the slab it owns. Rows past a
    shard's valid share land past its count (the CatBuffer garbage
    invariant), so uneven splits need no masking. ``chunk``/``rows`` are
    static per executable key; the per-shard valid row counts derived from
    them bake in as constants.
    """
    valid = np.clip(rows - np.arange(n_shards) * chunk, 0, chunk).astype(np.int32)

    def sharded_append(buf: Array, inc: Array, counts: Array) -> Tuple[Array, Array]:
        pad = n_shards * chunk - rows
        if pad:
            inc = jnp.concatenate(
                [inc, jnp.zeros((pad,) + inc.shape[1:], inc.dtype)], axis=0
            )
        slabs = inc.reshape((n_shards, chunk) + inc.shape[1:])

        def upd(buf_s: Array, slab: Array, cnt: Array) -> Array:
            start = (cnt,) + (0,) * (slab.ndim - 1)
            return lax.dynamic_update_slice(buf_s, slab, start)

        new = jax.vmap(upd)(buf, slabs, counts)
        new = lax.with_sharding_constraint(new, sharding)
        return new, counts + jnp.asarray(valid)

    return sharded_append


def _make_sharded_grow_append(new_capacity: int, *args: Any) -> Any:
    inner = _make_sharded_append(*args)

    def grow_append(buf: Array, inc: Array, counts: Array) -> Tuple[Array, Array]:
        pad = jnp.zeros(
            (buf.shape[0], new_capacity - buf.shape[1]) + buf.shape[2:], buf.dtype
        )
        return inner(jnp.concatenate([buf, pad], axis=1), inc, counts)

    return grow_append


class ShardedCatBuffer(CatBuffer):
    """Cat state resident under ``NamedSharding(P('batch'))`` on the eval mesh.

    The buffer is ``(n_shards, capacity) + trailing`` with the shard axis
    partitioned across the mesh — each device owns ``capacity`` rows of
    padding-backed storage, so resident cat-state bytes per device scale as
    ``total / n_shards`` instead of ``total``. Appends split each increment
    into one slab per shard and write all slabs in a single donated kernel;
    per-shard valid counts ride as an ordinary ``(n_shards,)`` int32 leaf
    (host-mirrored, like ``CatBuffer.count``).

    Reading: the valid rows are the per-shard prefixes in shard-major order
    — NOT append order. Every exact consumer of cat state (AUROC, PR-curve,
    rank correlations, retrieval grouping) is row-order-invariant, which is
    what makes the layout sound. ``dim_zero_cat``/``padded_cat`` REFUSE to
    densify this type outside :func:`sharded_oracle`
    (``utils/data.py``); distributed reads go through
    :mod:`torchmetrics_tpu.parallel.sharded_compute`.

    Pickling stores the materialized valid rows only; ``__setstate__``
    rebuilds balanced shards on the *current* default mesh — a checkpoint
    taken on one mesh rejoins a differently-sized mesh resharded (see
    ``sharded_compute.reshard`` for the in-memory plan).
    """

    __slots__ = ("counts", "_counts_dev", "mesh", "owner")

    def __init__(
        self,
        buffer: Array,
        counts: Any,
        mesh: Any = None,
        owns: bool = True,
        owner: Optional[str] = None,
    ) -> None:
        counts = np.asarray(counts, np.int32)
        super().__init__(buffer, int(counts.sum()), owns=owns)
        self.counts = counts
        self._counts_dev: Optional[Array] = None
        self.mesh = mesh if mesh is not None else default_eval_mesh()
        self.owner = owner

    # ------------------------------------------------------------- creation

    @classmethod
    def allocate(
        cls,
        first_inc: Any,
        mesh: Any = None,
        owner: Optional[str] = None,
    ) -> "ShardedCatBuffer":
        inc = _row_form(first_inc)
        mesh = mesh if mesh is not None else default_eval_mesh()
        n_shards = mesh.devices.size
        cap = _capacity_for(-(-max(inc.shape[0], 1) // n_shards))
        buf = jax.device_put(
            jnp.zeros((n_shards, cap) + inc.shape[1:], inc.dtype), batch_sharding(mesh)
        )
        out = cls(buf, np.zeros(n_shards, np.int32), mesh=mesh, owner=owner)
        out.append(inc)
        return out

    @classmethod
    def from_increments(
        cls,
        increments: Sequence[Any],
        mesh: Any = None,
        owner: Optional[str] = None,
    ) -> "ShardedCatBuffer":
        rows = [_row_form(e) for e in increments]
        trailings = {r.shape[1:] for r in rows}
        if len(trailings) > 1:
            raise CatLayoutError(f"ragged increment trailing shapes {sorted(trailings)}")
        first = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)
        return cls.allocate(first, mesh=mesh, owner=owner)

    @classmethod
    def from_rows(
        cls,
        rows: Any,
        mesh: Any = None,
        owner: Optional[str] = None,
    ) -> "ShardedCatBuffer":
        """Balanced sharded buffer over an already-dense rows array (sync
        re-materialization, checkpoint restore)."""
        return cls.allocate(_row_form(rows), mesh=mesh, owner=owner)

    # ------------------------------------------------------------ properties

    @property
    def n_shards(self) -> int:
        return self.buffer.shape[0]

    @property
    def capacity(self) -> int:
        """Per-shard row capacity (the grow/garbage contract is per shard)."""
        return self.buffer.shape[1]

    @property
    def trailing(self) -> Tuple[int, ...]:
        return self.buffer.shape[2:]

    def per_device_nbytes(self) -> dict:
        """Resident buffer bytes per device (the HBM-scaling observable)."""
        out: dict = {}
        for shard in self.buffer.addressable_shards:
            d = shard.device
            out[d] = out.get(d, 0) + shard.data.size * shard.data.dtype.itemsize
        return out

    # -------------------------------------------------------------- mutation

    def append(self, inc: Any) -> None:
        inc = _row_form(inc)
        if inc.shape[1:] != self.trailing:
            raise CatLayoutError(
                f"increment trailing shape {inc.shape[1:]} != buffer trailing {self.trailing}"
            )
        if inc.dtype != self.dtype:
            promoted = jnp.promote_types(self.dtype, inc.dtype)
            if promoted != self.dtype:
                self.buffer = jax.device_put(
                    self.buffer.astype(promoted), batch_sharding(self.mesh)
                )
                self._owns = True
            if promoted != inc.dtype:
                inc = inc.astype(promoted)
        rows = inc.shape[0]
        if rows == 0:
            return
        n = self.n_shards
        chunk = -(-rows // n)  # ceil: shard s takes rows [s*chunk, (s+1)*chunk)
        counts = self._counts_dev
        if counts is None:
            counts = jnp.asarray(self.counts)
        sharding = batch_sharding(self.mesh)
        mesh_key = tuple(d.id for d in self.mesh.devices.flat)
        key_tail = (self.capacity, n, chunk, inc.shape, str(inc.dtype), mesh_key)
        if int(self.counts.max()) + chunk > self.capacity:
            new_cap = _capacity_for(int(self.counts.max()) + chunk)
            fn = _jit(
                ("sharded_catbuf_grow_append", new_cap) + key_tail,
                _make_sharded_grow_append(new_cap, n, chunk, rows, sharding),
            )
            self.buffer, self._counts_dev = fn(self.buffer, inc, counts)
        else:
            if not self._owns:
                self.buffer = jax.device_put(
                    jnp.array(self.buffer, copy=True), sharding
                )
            fn = _jit(
                ("sharded_catbuf_append",) + key_tail,
                _make_sharded_append(n, chunk, rows, sharding),
                donate=True,
            )
            self.buffer, self._counts_dev = fn(self.buffer, inc, counts)
        self._owns = True
        self.counts = self.counts + np.clip(
            rows - np.arange(n) * chunk, 0, chunk
        ).astype(np.int32)
        self.count = int(self.counts.sum())

    # --------------------------------------------------------------- reading

    def materialize(self) -> Array:
        """Densify to the valid rows in shard-major order.

        This is the ORACLE/wire read: it replicates the full state onto one
        device. API-level densify (``dim_zero_cat``/``padded_cat``) refuses
        sharded buffers outside :func:`~torchmetrics_tpu.utils.data.sharded_oracle`;
        compute paths go through ``parallel.sharded_compute`` instead.
        """
        if self.count == 0:
            return jnp.zeros((0,) + self.trailing, self.dtype)
        parts = [
            self.buffer[s, : int(c)] for s, c in enumerate(self.counts) if int(c)
        ]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)

    def rows(self, start: int, stop: int) -> Array:
        return self.materialize()[start : min(stop, self.count)]

    def padded_wire(self) -> Tuple[Array, int]:
        """Dense pow2-padded ``(buffer, count)`` view for the DCN sync wire
        (``sync_cat_padded``): a host gather materializes bytes regardless
        of layout, so the wire format stays layout-independent."""
        rows = self.materialize()
        cap = _capacity_for(max(self.count, 1))
        pad = jnp.zeros((cap - rows.shape[0],) + self.trailing, self.dtype)
        return jnp.concatenate([rows, pad], axis=0), self.count

    def snapshot(self) -> "ShardedCatBuffer":
        self._owns = False
        out = ShardedCatBuffer(
            self.buffer, self.counts.copy(), mesh=self.mesh, owns=False, owner=self.owner
        )
        out._counts_dev = self._counts_dev  # device arrays are immutable
        return out

    def astype(self, dtype: Any) -> "ShardedCatBuffer":
        buf = jax.device_put(self.buffer.astype(dtype), batch_sharding(self.mesh))
        return ShardedCatBuffer(buf, self.counts.copy(), mesh=self.mesh, owner=self.owner)

    def to_device(self, device: Any) -> "ShardedCatBuffer":
        # placement IS the mesh for this layout; a single-device move would
        # silently un-shard the state, so it is a no-op by contract
        return self

    # ------------------------------------------------------------- protocols

    def __eq__(self, other: Any) -> Any:
        if other is self:
            return True
        if isinstance(other, ShardedCatBuffer):
            if self.count != other.count or self.trailing != other.trailing:
                return False
            if self.count == 0:
                return True
            # host-side compare: the two buffers may live on different meshes
            # (e.g. before/after reshard), and jnp refuses mixed device sets.
            # reshard() preserves the shard-major row stream, so elementwise
            # equality is the right check even across meshes.
            return bool(
                np.array_equal(
                    np.asarray(self.materialize()), np.asarray(other.materialize())
                )
            )
        if isinstance(other, (CatBuffer, list, tuple)):
            # cross-layout comparison is row-ORDER-INSENSITIVE: shard-major
            # materialization permutes append order, and every sharded
            # consumer is order-invariant by contract
            if isinstance(other, CatBuffer):
                cat = other.materialize()
            else:
                if len(other) == 0:
                    return self.count == 0
                try:
                    cat = jnp.concatenate([_row_form(e) for e in other], axis=0)
                except Exception:
                    return NotImplemented
            mine = self.materialize()
            if cat.shape != mine.shape:
                return False
            if self.count == 0:
                return True
            flat_a = np.asarray(mine).reshape(self.count, -1)
            flat_b = np.asarray(cat).reshape(self.count, -1)
            order_a = np.lexsort(flat_a.T[::-1])
            order_b = np.lexsort(flat_b.T[::-1])
            return bool(np.array_equal(flat_a[order_a], flat_b[order_b]))
        return NotImplemented

    __hash__ = object.__hash__

    def __repr__(self) -> str:
        return (
            f"ShardedCatBuffer(count={self.count}, shards={self.n_shards}, "
            f"capacity/shard={self.capacity}, trailing={self.trailing}, "
            f"dtype={self.dtype})"
        )

    # ------------------------------------------------- pickle / deepcopy

    def __getstate__(self) -> Tuple[Any, int, Optional[str]]:
        return np.asarray(self.materialize()), self.count, self.owner

    def __setstate__(self, state: Tuple[Any, ...]) -> None:
        valid, count, owner = state
        mesh = default_eval_mesh()
        n = mesh.devices.size
        chunk = -(-max(int(count), 1) // n)
        cap = _capacity_for(chunk)
        arr = np.zeros((n, cap) + valid.shape[1:], valid.dtype)
        counts = np.clip(int(count) - np.arange(n) * chunk, 0, chunk).astype(np.int32)
        # balanced ceil-chunk per shard, shard-major: restore IS the reshard
        # plan for a checkpoint crossing onto a differently-sized mesh
        for s in range(n):
            lo = s * chunk
            arr[s, : counts[s]] = valid[lo : lo + counts[s]]
        self.buffer = jax.device_put(jnp.asarray(arr), batch_sharding(mesh))
        self.counts = counts
        self.count = int(count)
        self._count_dev = None
        self._counts_dev = None
        self._owns = True
        self.mesh = mesh
        self.owner = owner

    def __deepcopy__(self, memo: dict) -> "ShardedCatBuffer":
        new = ShardedCatBuffer(
            self.buffer, self.counts.copy(), mesh=self.mesh, owns=False, owner=self.owner
        )
        new._counts_dev = self._counts_dev
        self._owns = False
        memo[id(self)] = new
        return new


def cat_rows(value: Any, template: Optional[Array] = None) -> Array:
    """Concatenated valid rows of a cat state in any layout.

    Accepts a ``CatBuffer`` (masked slice), a list/tuple of increments, or an
    already-concatenated array. An empty list yields a 0-row array shaped
    like ``template`` (or ``(0,)`` float32 without one).
    """
    if isinstance(value, CatBuffer):
        return value.materialize()
    if isinstance(value, (list, tuple)):
        if not value:
            if template is not None:
                return jnp.zeros((0,) + template.shape[1:], template.dtype)
            return jnp.zeros((0,), jnp.float32)
        return jnp.concatenate([_row_form(e) for e in value], axis=0)
    arr = jnp.asarray(value)
    return arr[None] if arr.ndim == 0 else arr
