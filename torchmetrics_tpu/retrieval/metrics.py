"""Modular retrieval metrics.

Parity targets: reference ``retrieval/{average_precision,reciprocal_rank,
precision,recall,fall_out,hit_rate,ndcg,r_precision,auroc}.py`` — each a thin
``_metric`` override of :class:`RetrievalMetric`; here each supplies the
batched padded kernel instead (one XLA call for all queries).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..functional.retrieval._ops import (
    batched_auroc,
    batched_average_precision,
    batched_fall_out,
    batched_hit_rate,
    batched_ndcg,
    batched_precision,
    batched_r_precision,
    batched_recall,
    batched_reciprocal_rank,
)
from .base import RetrievalMetric

Array = jax.Array


def _check_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


class RetrievalMAP(RetrievalMetric):
    """Mean Average Precision. Parity: reference ``retrieval/average_precision.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalMAP
        >>> metric = RetrievalMAP()
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        0.75
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        self.top_k = top_k

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_average_precision(preds, target, mask, self.top_k)


class RetrievalMRR(RetrievalMetric):
    """Mean Reciprocal Rank. Parity: reference ``retrieval/reciprocal_rank.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> metric = RetrievalMRR()
        >>> metric.update(jnp.asarray([0.2, 0.6, 0.3, 0.9]), jnp.asarray([0, 1, 0, 1]),
        ...               indexes=jnp.asarray([0, 0, 1, 1]))
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        self.top_k = top_k

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_reciprocal_rank(preds, target, mask, self.top_k)


class RetrievalPrecision(RetrievalMetric):
    """Precision@k. Parity: reference ``retrieval/precision.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalPrecision
        >>> metric = RetrievalPrecision(top_k=2)
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        0.75
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, adaptive_k: bool = False,
                 aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_precision(preds, target, mask, self.top_k, self.adaptive_k)


class RetrievalRecall(RetrievalMetric):
    """Recall@k. Parity: reference ``retrieval/recall.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalRecall
        >>> metric = RetrievalRecall(top_k=2)
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        self.top_k = top_k

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_recall(preds, target, mask, self.top_k)


class RetrievalFallOut(RetrievalMetric):
    """Fall-out@k (lower is better). Parity: reference ``retrieval/fall_out.py:30``.

    The empty-query condition inverts: a query is "empty" when it has no
    NEGATIVE targets (reference ``fall_out.py:116-155``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalFallOut
        >>> metric = RetrievalFallOut()
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        1.0
    """

    higher_is_better = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "pos", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        self.top_k = top_k

    def _empty_mask(self, target: Array, mask: Array) -> Array:
        neg = (1.0 - target.astype(jnp.float32)) * mask
        return jnp.sum(neg, axis=-1) == 0

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_fall_out(preds, target, mask, self.top_k)


class RetrievalHitRate(RetrievalMetric):
    """HitRate@k. Parity: reference ``retrieval/hit_rate.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalHitRate
        >>> metric = RetrievalHitRate()
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        1.0
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        self.top_k = top_k

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_hit_rate(preds, target, mask, self.top_k)


class RetrievalNormalizedDCG(RetrievalMetric):
    """nDCG@k with graded relevance. Parity: reference ``retrieval/ndcg.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalNormalizedDCG
        >>> metric = RetrievalNormalizedDCG()
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        0.8155
    """

    allow_non_binary_target = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        self.top_k = top_k

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_ndcg(preds, target, mask, self.top_k)


class RetrievalRPrecision(RetrievalMetric):
    """R-Precision. Parity: reference ``retrieval/r_precision.py:27``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalRPrecision
        >>> metric = RetrievalRPrecision()
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        0.5
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_r_precision(preds, target, mask)


class RetrievalAUROC(RetrievalMetric):
    """Per-query AUROC. Parity: reference ``retrieval/auroc.py:28``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import RetrievalAUROC
        >>> metric = RetrievalAUROC()
        >>> preds = jnp.asarray([0.9, 0.3, 0.6, 0.1, 0.8, 0.5])
        >>> target = jnp.asarray([1, 0, 1, 0, 0, 1])
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1])
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        0.75
    """

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, max_fpr: Optional[float] = None,
                 aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _check_top_k(top_k)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.top_k = top_k
        self.max_fpr = max_fpr

    def _batched_scores(self, preds: Array, target: Array, mask: Array) -> Array:
        return batched_auroc(preds, target, mask, self.top_k, self.max_fpr)
