// tm_native — host-side native kernels for torchmetrics_tpu.
//
// TPU-native replacement for the reference's third-party native backends
// (SURVEY.md §2.9): pycocotools' C RLE codec/IoU (reference
// detection/mean_ap.py:50-71), scipy's linear_sum_assignment used by PIT
// (reference functional/audio/pit.py:42-62), and the pure-Python Levenshtein
// DP (reference functional/text/helper.py). Device math stays in JAX; these
// are the string/assignment/RLE host paths that never touch the TPU.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>
#include <limits>
#include <cmath>

extern "C" {

// ---------------------------------------------------------------------------
// Levenshtein
// ---------------------------------------------------------------------------

// Unit-cost edit distance between int64 token sequences.
int64_t tm_edit_distance(const int64_t* a, int64_t la, const int64_t* b, int64_t lb) {
    if (la == 0) return lb;
    if (lb == 0) return la;
    std::vector<int64_t> prev(lb + 1), cur(lb + 1);
    for (int64_t j = 0; j <= lb; ++j) prev[j] = j;
    for (int64_t i = 1; i <= la; ++i) {
        cur[0] = i;
        const int64_t ai = a[i - 1];
        for (int64_t j = 1; j <= lb; ++j) {
            const int64_t sub = prev[j - 1] + (ai != b[j - 1]);
            const int64_t del = prev[j] + 1;
            const int64_t ins = cur[j - 1] + 1;
            cur[j] = std::min(sub, std::min(del, ins));
        }
        std::swap(prev, cur);
    }
    return prev[lb];
}

// Edit distance decomposed into (substitutions, deletions, insertions, hits)
// via full DP + backtrace, pred->tgt edits. out must hold 4 int64.
void tm_edit_distance_counts(const int64_t* pred, int64_t m, const int64_t* tgt, int64_t n,
                             int64_t* out) {
    std::vector<int32_t> dp((m + 1) * (n + 1));
    const int64_t W = n + 1;
    for (int64_t i = 0; i <= m; ++i) dp[i * W] = (int32_t)i;
    for (int64_t j = 0; j <= n; ++j) dp[j] = (int32_t)j;
    for (int64_t i = 1; i <= m; ++i) {
        const int64_t pi = pred[i - 1];
        for (int64_t j = 1; j <= n; ++j) {
            const int32_t sub = dp[(i - 1) * W + (j - 1)] + (pi != tgt[j - 1]);
            const int32_t del = dp[(i - 1) * W + j] + 1;
            const int32_t ins = dp[i * W + (j - 1)] + 1;
            dp[i * W + j] = std::min(sub, std::min(del, ins));
        }
    }
    int64_t s = 0, d = 0, ins_c = 0, hits = 0;
    int64_t i = m, j = n;
    while (i > 0 || j > 0) {
        if (i > 0 && j > 0 &&
            dp[i * W + j] == dp[(i - 1) * W + (j - 1)] + (pred[i - 1] != tgt[j - 1])) {
            if (pred[i - 1] == tgt[j - 1]) ++hits; else ++s;
            --i; --j;
        } else if (i > 0 && dp[i * W + j] == dp[(i - 1) * W + j] + 1) {
            ++d; --i;
        } else {
            ++ins_c; --j;
        }
    }
    out[0] = s; out[1] = d; out[2] = ins_c; out[3] = hits;
}

// Batched edit distance over packed sequences: offsets are prefix sums
// (len B+1); out[b] = distance(pred_b, tgt_b).
void tm_edit_distance_batch(const int64_t* preds, const int64_t* pred_off,
                            const int64_t* tgts, const int64_t* tgt_off,
                            int64_t batch, int64_t* out) {
    for (int64_t b = 0; b < batch; ++b) {
        out[b] = tm_edit_distance(preds + pred_off[b], pred_off[b + 1] - pred_off[b],
                                  tgts + tgt_off[b], tgt_off[b + 1] - tgt_off[b]);
    }
}

// Batched counts variant: out is (batch, 4) row-major [S, D, I, H].
void tm_edit_distance_counts_batch(const int64_t* preds, const int64_t* pred_off,
                                   const int64_t* tgts, const int64_t* tgt_off,
                                   int64_t batch, int64_t* out) {
    for (int64_t b = 0; b < batch; ++b) {
        tm_edit_distance_counts(preds + pred_off[b], pred_off[b + 1] - pred_off[b],
                                tgts + tgt_off[b], tgt_off[b + 1] - tgt_off[b],
                                out + 4 * b);
    }
}

// ---------------------------------------------------------------------------
// Linear sum assignment (Jonker-Volgenant shortest augmenting path, O(n^3)).
// cost is row-major (n rows, m cols), n <= m required. Writes col4row[n].
// Minimizes total cost. Returns 0 on success, -1 on invalid input.
// ---------------------------------------------------------------------------
int tm_linear_sum_assignment(const double* cost, int64_t n, int64_t m, int64_t* col4row) {
    if (n <= 0 || m <= 0 || n > m) return -1;
    const double INF = std::numeric_limits<double>::infinity();
    std::vector<double> u(n, 0.0), v(m, 0.0), shortest(m);
    std::vector<int64_t> row4col(m, -1), path(m, -1);
    std::vector<char> SR(n), SC(m);
    std::vector<int64_t> remaining(m);
    std::fill(col4row, col4row + n, -1);

    for (int64_t curRow = 0; curRow < n; ++curRow) {
        double minVal = 0.0;
        int64_t i = curRow, sink = -1;
        std::fill(SR.begin(), SR.end(), 0);
        std::fill(SC.begin(), SC.end(), 0);
        std::fill(shortest.begin(), shortest.end(), INF);
        int64_t numRemaining = m;
        for (int64_t it = 0; it < m; ++it) remaining[it] = m - it - 1;

        while (sink == -1) {
            int64_t index = -1;
            double lowest = INF;
            SR[i] = 1;
            for (int64_t it = 0; it < numRemaining; ++it) {
                const int64_t j = remaining[it];
                const double r = minVal + cost[i * m + j] - u[i] - v[j];
                if (r < shortest[j]) { path[j] = i; shortest[j] = r; }
                if (shortest[j] < lowest || (shortest[j] == lowest && row4col[j] == -1)) {
                    lowest = shortest[j]; index = it;
                }
            }
            minVal = lowest;
            if (minVal == INF) return -1;  // infeasible
            const int64_t j = remaining[index];
            if (row4col[j] == -1) sink = j; else i = row4col[j];
            SC[j] = 1;
            remaining[index] = remaining[--numRemaining];
        }
        u[curRow] += minVal;
        for (int64_t ii = 0; ii < n; ++ii)
            if (SR[ii] && ii != curRow) u[ii] += minVal - shortest[col4row[ii]];
        for (int64_t jj = 0; jj < m; ++jj)
            if (SC[jj]) v[jj] -= minVal - shortest[jj];
        // augment
        int64_t j = sink;
        while (true) {
            const int64_t ii = path[j];
            row4col[j] = ii;
            std::swap(col4row[ii], j);
            if (ii == curRow) break;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// COCO-compatible RLE (column-major run-length encoding of binary masks).
// counts alternate runs of 0s and 1s, starting with 0s, scanning columns
// first (Fortran order) — byte-compatible with pycocotools' semantics.
// ---------------------------------------------------------------------------

// Encode dense row-major (h, w) uint8 mask. out_counts must hold h*w+1.
// Returns number of runs written.
int64_t tm_rle_encode(const uint8_t* mask, int64_t h, int64_t w, uint32_t* out_counts) {
    int64_t nruns = 0;
    uint8_t prev = 0;
    uint32_t run = 0;
    for (int64_t c = 0; c < w; ++c) {
        for (int64_t r = 0; r < h; ++r) {
            const uint8_t val = mask[r * w + c] ? 1 : 0;
            if (val == prev) { ++run; }
            else { out_counts[nruns++] = run; run = 1; prev = val; }
        }
    }
    out_counts[nruns++] = run;
    return nruns;
}

// Decode RLE into dense row-major (h, w) uint8 mask.
void tm_rle_decode(const uint32_t* counts, int64_t ncounts, int64_t h, int64_t w,
                   uint8_t* out_mask) {
    int64_t pos = 0;  // column-major linear index
    uint8_t val = 0;
    for (int64_t k = 0; k < ncounts; ++k) {
        for (uint32_t t = 0; t < counts[k]; ++t) {
            const int64_t c = pos / h, r = pos % h;
            out_mask[r * w + c] = val;
            ++pos;
        }
        val = 1 - val;
    }
}

uint64_t tm_rle_area(const uint32_t* counts, int64_t ncounts) {
    uint64_t area = 0;
    for (int64_t k = 1; k < ncounts; k += 2) area += counts[k];
    return area;
}

// Intersection of two RLEs (same h*w extent) without decoding.
static uint64_t rle_intersection(const uint32_t* a, int64_t na, const uint32_t* b, int64_t nb) {
    uint64_t inter = 0;
    int64_t ka = 0, kb = 0;
    uint64_t ca = na ? a[0] : 0, cb = nb ? b[0] : 0;  // remaining in current run
    uint8_t va = 0, vb = 0;
    while (ka < na && kb < nb) {
        const uint64_t step = std::min(ca, cb);
        if (va && vb) inter += step;
        ca -= step; cb -= step;
        if (ca == 0) { ++ka; va = 1 - va; if (ka < na) ca = a[ka]; }
        if (cb == 0) { ++kb; vb = 1 - vb; if (kb < nb) cb = b[kb]; }
    }
    return inter;
}

// Pairwise IoU between n_dt and n_gt RLE masks, flattened counts arrays with
// prefix offsets (len n+1). iscrowd is per-gt; crowd IoU = inter/area_dt.
// out is row-major (n_dt, n_gt) double.
void tm_rle_iou(const uint32_t* dt_counts, const int64_t* dt_off, int64_t n_dt,
                const uint32_t* gt_counts, const int64_t* gt_off, int64_t n_gt,
                const uint8_t* iscrowd, double* out) {
    std::vector<uint64_t> dt_area(n_dt), gt_area(n_gt);
    for (int64_t i = 0; i < n_dt; ++i)
        dt_area[i] = tm_rle_area(dt_counts + dt_off[i], dt_off[i + 1] - dt_off[i]);
    for (int64_t j = 0; j < n_gt; ++j)
        gt_area[j] = tm_rle_area(gt_counts + gt_off[j], gt_off[j + 1] - gt_off[j]);
    for (int64_t i = 0; i < n_dt; ++i) {
        for (int64_t j = 0; j < n_gt; ++j) {
            const uint64_t inter = rle_intersection(
                dt_counts + dt_off[i], dt_off[i + 1] - dt_off[i],
                gt_counts + gt_off[j], gt_off[j + 1] - gt_off[j]);
            double denom;
            if (iscrowd && iscrowd[j]) denom = (double)dt_area[i];
            else denom = (double)dt_area[i] + (double)gt_area[j] - (double)inter;
            out[i * n_gt + j] = denom > 0 ? (double)inter / denom : 0.0;
        }
    }
}

// Pairwise box IoU (xyxy), crowd semantics as above. out (n_dt, n_gt).
void tm_box_iou(const double* dt, int64_t n_dt, const double* gt, int64_t n_gt,
                const uint8_t* iscrowd, double* out) {
    for (int64_t i = 0; i < n_dt; ++i) {
        const double ax0 = dt[i * 4], ay0 = dt[i * 4 + 1], ax1 = dt[i * 4 + 2], ay1 = dt[i * 4 + 3];
        const double a_area = std::max(0.0, ax1 - ax0) * std::max(0.0, ay1 - ay0);
        for (int64_t j = 0; j < n_gt; ++j) {
            const double bx0 = gt[j * 4], by0 = gt[j * 4 + 1], bx1 = gt[j * 4 + 2], by1 = gt[j * 4 + 3];
            const double b_area = std::max(0.0, bx1 - bx0) * std::max(0.0, by1 - by0);
            const double iw = std::min(ax1, bx1) - std::max(ax0, bx0);
            const double ih = std::min(ay1, by1) - std::max(ay0, by0);
            const double inter = (iw > 0 && ih > 0) ? iw * ih : 0.0;
            const double denom = (iscrowd && iscrowd[j]) ? a_area : a_area + b_area - inter;
            out[i * n_gt + j] = denom > 0 ? inter / denom : 0.0;
        }
    }
}

// Batched pairwise box IoU over N independent (dt set, gt set) cells with
// flat concatenated storage — one ctypes round-trip for a whole epoch of
// per-(image, class) IoU matrices (the per-call marshalling otherwise
// dominates: ~13us x thousands of calls).
// dt_flat: sum(n_dt) boxes; offsets are element counts (not byte offsets);
// out_flat laid out cell-major with out_off[c] = sum of n_dt*n_gt before c.
void tm_box_iou_batch(const double* dt_flat, const int64_t* dt_off,
                      const double* gt_flat, const int64_t* gt_off,
                      const uint8_t* crowd_flat, int64_t n_cells,
                      double* out_flat, const int64_t* out_off) {
    for (int64_t c = 0; c < n_cells; ++c) {
        const int64_t n_dt = dt_off[c + 1] - dt_off[c];
        const int64_t n_gt = gt_off[c + 1] - gt_off[c];
        tm_box_iou(dt_flat + dt_off[c] * 4, n_dt, gt_flat + gt_off[c] * 4, n_gt,
                   crowd_flat + gt_off[c], out_flat + out_off[c]);
    }
}

// ---------------------------------------------------------------------------
// COCOeval greedy matcher: one (image, class) cell across T IoU thresholds.
// ious: (n_dt, n_gt) row-major; dt sorted by descending score; gt sorted
// ignore-last. Writes dt_matches/gt_matches (T, n_dt)/(T, n_gt) int64 of
// 1-based match ids (0 = unmatched) and dt_ignore (T, n_dt) uint8.
// Mirrors pycocotools COCOeval.evaluateImg semantics.
// ---------------------------------------------------------------------------
void tm_coco_match(const double* ious, int64_t n_dt, int64_t n_gt,
                   const uint8_t* gt_ignore, const uint8_t* gt_crowd,
                   const double* iou_thrs, int64_t T,
                   int64_t* dt_m, int64_t* gt_m, uint8_t* dt_ig) {
    for (int64_t t = 0; t < T; ++t) {
        const double thr = iou_thrs[t];
        int64_t* dtm = dt_m + t * n_dt;
        int64_t* gtm = gt_m + t * n_gt;
        uint8_t* dti = dt_ig + t * n_dt;
        for (int64_t d = 0; d < n_dt; ++d) {
            double iou = std::min(thr, 1.0 - 1e-10);
            int64_t match = -1;
            for (int64_t g = 0; g < n_gt; ++g) {
                if (gtm[g] > 0 && !gt_crowd[g]) continue;        // gt already matched (non-crowd)
                if (match > -1 && !gt_ignore[match] && gt_ignore[g]) break;  // moving to ignored gts: stop
                if (ious[d * n_gt + g] < iou) continue;
                iou = ious[d * n_gt + g];
                match = g;
            }
            if (match == -1) continue;
            dti[d] = gt_ignore[match];
            dtm[d] = match + 1;
            gtm[match] = d + 1;
        }
    }
}

// ---------------------------------------------------------------------------
// Fused COCOeval staging + matching: one call per epoch over (image, class)
// cells, each evaluated across A area ranges and T IoU thresholds. Replaces
// the per-cell Python staging (score argsort, per-area gt ignore-sort,
// matrix reorders) that dominates evaluation once IoU and matching are
// native. Cell c reads the UNordered full matrices:
//   ious_flat[iou_off[c] .. +D*G]  (row-major, detection-major)
//   scores/d_areas at d_off[c] (D), g_areas/crowd at g_off[c] (G)
// and writes, with D2 = min(D, cap):
//   order_flat[d2_off[c] .. +D2]          descending-score dt indices
//   matched/ignored_flat[d2_off[c]*A*T ..] laid out (A, T, D2) per cell
//   npos_flat[c*A .. +A]                  non-ignored gt count per area
// Semantics identical to per-cell tm_coco_match with staged inputs: gts sorted
// ignore-last per area, greedy threshold matching, unmatched dts outside
// the area range ignored.
// ---------------------------------------------------------------------------
void tm_coco_stage_match_batch(
    const double* ious_flat, const int64_t* iou_off,
    const double* scores_flat, const double* d_areas_flat, const int64_t* d_off,
    const double* g_areas_flat, const uint8_t* crowd_flat, const int64_t* g_off,
    int64_t n_cells,
    const double* area_lo, const double* area_hi, int64_t A,
    const double* iou_thrs, int64_t T, int64_t cap,
    const int64_t* d2_off,
    int64_t* order_flat, uint8_t* matched_flat, uint8_t* ignored_flat,
    int64_t* npos_flat) {
    std::vector<int64_t> gidx;
    std::vector<uint8_t> g_ign, gtm, d_ign;
    for (int64_t c = 0; c < n_cells; ++c) {
        const int64_t D = d_off[c + 1] - d_off[c];
        const int64_t G = g_off[c + 1] - g_off[c];
        const int64_t D2 = d2_off[c + 1] - d2_off[c];
        const double* ious = ious_flat + iou_off[c];
        const double* scores = scores_flat + d_off[c];
        const double* d_areas = d_areas_flat + d_off[c];
        const double* g_areas = g_areas_flat + g_off[c];
        const uint8_t* crowd = crowd_flat + g_off[c];
        int64_t* order = order_flat + d2_off[c];

        // descending-score stable order, truncated to cap; NaN scores sort
        // last (np.argsort(-scores) semantics) — mapping NaN to -inf keeps
        // the comparator a strict weak ordering
        std::vector<int64_t> full(D);
        for (int64_t i = 0; i < D; ++i) full[i] = i;
        const auto key = [&](int64_t i) {
            const double s = scores[i];
            return std::isnan(s) ? -std::numeric_limits<double>::infinity() : s;
        };
        std::stable_sort(full.begin(), full.end(),
                         [&](int64_t a, int64_t b) { return key(a) > key(b); });
        for (int64_t i = 0; i < D2; ++i) order[i] = full[i];

        if ((int64_t)gidx.size() < G) { gidx.resize(G); g_ign.resize(G); gtm.resize(G); }
        if ((int64_t)d_ign.size() < D2) d_ign.resize(D2);

        for (int64_t a = 0; a < A; ++a) {
            const double lo = area_lo[a], hi = area_hi[a];
            int64_t npos = 0;
            for (int64_t g = 0; g < G; ++g) {
                g_ign[g] = crowd[g] || g_areas[g] < lo || g_areas[g] > hi;
                if (!g_ign[g]) ++npos;
            }
            npos_flat[c * A + a] = npos;
            for (int64_t g = 0; g < G; ++g) gidx[g] = g;
            std::stable_sort(gidx.begin(), gidx.begin() + G,
                             [&](int64_t x, int64_t y) { return g_ign[x] < g_ign[y]; });
            for (int64_t i = 0; i < D2; ++i) {
                const double ar = d_areas[order[i]];
                d_ign[i] = ar < lo || ar > hi;
            }
            uint8_t* m_base = matched_flat + d2_off[c] * A * T + a * T * D2;
            uint8_t* i_base = ignored_flat + d2_off[c] * A * T + a * T * D2;
            for (int64_t t = 0; t < T; ++t) {
                const double thr = iou_thrs[t];
                uint8_t* dtm = m_base + t * D2;
                uint8_t* dti = i_base + t * D2;
                std::fill(gtm.begin(), gtm.begin() + G, 0);
                for (int64_t d = 0; d < D2; ++d) {
                    const double* iou_row = ious + order[d] * G;
                    double iou = std::min(thr, 1.0 - 1e-10);
                    int64_t match = -1;
                    for (int64_t gi = 0; gi < G; ++gi) {
                        const int64_t g = gidx[gi];
                        if (gtm[gi] && !crowd[g]) continue;
                        if (match > -1 && !g_ign[gidx[match]] && g_ign[g]) break;
                        if (iou_row[g] < iou) continue;
                        iou = iou_row[g];
                        match = gi;
                    }
                    if (match == -1) {
                        dti[d] = d_ign[d];  // unmatched dt outside area range
                        continue;
                    }
                    dti[d] = g_ign[gidx[match]];
                    dtm[d] = 1;
                    gtm[match] = 1;
                }
            }
        }
    }
}

}  // extern "C"
