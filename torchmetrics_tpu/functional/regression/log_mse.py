"""Mean squared log error & log-cosh error.

Parity: reference ``src/torchmetrics/functional/regression/{log_mse,log_cosh}.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape

Array = jax.Array


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    d = jnp.log1p(preds) - jnp.log1p(target)
    return jnp.sum(d * d), jnp.asarray(target.size, dtype=jnp.float32)


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """Parity: reference ``log_mse.py:45``."""
    s, n = _mean_squared_log_error_update(preds, target)
    return s / n


def _stable_log_cosh(x: Array) -> Array:
    # log(cosh(x)) = |x| + log1p(exp(-2|x|)) - log(2); overflow-safe
    ax = jnp.abs(x)
    return ax + jnp.log1p(jnp.exp(-2 * ax)) - jnp.log(2.0)


def _log_cosh_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    return jnp.sum(_stable_log_cosh(preds - target), axis=0), jnp.asarray(preds.shape[0], dtype=jnp.float32)


def log_cosh_error(preds: Array, target: Array, num_outputs: int = 1) -> Array:
    """Parity: reference ``log_cosh.py:55``."""
    s, n = _log_cosh_error_update(preds, target, num_outputs)
    return s / n
