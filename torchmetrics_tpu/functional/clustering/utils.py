"""Shared clustering machinery: contingency matrix, entropy, pair counts, EMI.

Parity target: reference ``functional/clustering/utils.py`` (contingency +
pair counting at :282). TPU-native notes: the contingency matrix is built as
ONE flattened bincount (``R*C`` bins — same trick the classification
confusion-matrix engine uses), and the AMI expected-mutual-information sum
(sklearn does this in Cython) is a fully vectorized (R, C, n_max) tensor
contraction using ``gammaln`` — no scalar loops.
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def check_cluster_labels(preds: Array, target: Array) -> None:
    """Both inputs must be 1-D integer label vectors of equal length."""
    if preds.shape != target.shape or preds.ndim != 1:
        raise ValueError(
            f"Expected 1d integer label tensors of equal shape, got {preds.shape} and {target.shape}"
        )
    for name, x in (("preds", preds), ("target", target)):
        if jnp.issubdtype(x.dtype, jnp.floating):
            raise ValueError(f"Expected integer cluster labels for `{name}`, got {x.dtype}")


def calculate_contingency_matrix(
    preds: Array, target: Array, num_preds: int, num_target: int, eps: Optional[float] = None
) -> Array:
    """Dense (num_preds, num_target) contingency via one flattened bincount."""
    joint = preds.astype(jnp.int32) * num_target + target.astype(jnp.int32)
    mat = jnp.bincount(joint, length=num_preds * num_target).reshape(num_preds, num_target)
    if eps is not None:
        mat = mat.astype(jnp.float32) + eps
    return mat


def _label_counts(contingency: Array) -> Tuple[Array, Array, Array]:
    a = jnp.sum(contingency, axis=1)  # preds marginal
    b = jnp.sum(contingency, axis=0)  # target marginal
    n = jnp.sum(a)
    return a.astype(jnp.float64), b.astype(jnp.float64), n.astype(jnp.float64)


def calculate_entropy(counts: Array) -> Array:
    """Entropy (nats) of a label distribution given bin counts."""
    n = jnp.sum(counts)
    p = counts / jnp.maximum(n, 1.0)
    return -jnp.sum(jnp.where(counts > 0, p * (jnp.log(jnp.maximum(counts, 1.0)) - jnp.log(jnp.maximum(n, 1.0))), 0.0))


def calculate_generalized_mean(x: Array, p: Union[int, str]) -> Array:
    """Power mean. Parity: reference ``utils.py calculate_generalized_mean``."""
    if isinstance(p, str):
        if p == "min":
            return jnp.min(x)
        if p == "geometric":
            return jnp.exp(jnp.mean(jnp.log(jnp.maximum(x, 1e-30))))
        if p == "arithmetic":
            return jnp.mean(x)
        if p == "max":
            return jnp.max(x)
        raise ValueError("'method' must be 'min', 'geometric', 'arithmetic', or 'max'")
    return jnp.mean(jnp.power(x, p)) ** (1.0 / p)


def mutual_info_from_contingency(contingency: Array) -> Array:
    """MI (nats) between the two labelings of a contingency matrix."""
    a, b, n = _label_counts(contingency)
    c = contingency.astype(jnp.float64)
    outer = a[:, None] * b[None, :]
    valid = c > 0
    logterm = jnp.log(jnp.maximum(c, 1.0)) + jnp.log(jnp.maximum(n, 1.0)) - jnp.log(jnp.maximum(outer, 1.0))
    return jnp.sum(jnp.where(valid, (c / jnp.maximum(n, 1.0)) * logterm, 0.0))


def pair_counts(contingency: Array) -> Tuple[Array, Array, Array, Array]:
    """(sum_comb_cells, sum_comb_rows, sum_comb_cols, comb_total) — #same-cluster pairs."""

    def comb2(x):
        return x * (x - 1.0) / 2.0

    a, b, n = _label_counts(contingency)
    c = contingency.astype(jnp.float64)
    return jnp.sum(comb2(c)), jnp.sum(comb2(a)), jnp.sum(comb2(b)), comb2(n)


def expected_mutual_info(contingency: Array) -> Array:
    """Expected MI under the permutation model (sklearn ``expected_mutual_information``).

    Vectorized over an (R, C, n_max) grid: for each cell the hypergeometric
    probability of each feasible co-occurrence count ``nij`` times its MI
    contribution, summed with a feasibility mask. Runs on HOST in numpy
    float64 — the gammaln difference chains cancel catastrophically in
    float32 (JAX x64 is typically disabled), and this is an eager
    once-per-epoch computation.
    """
    import numpy as np
    from scipy.special import gammaln as np_gammaln

    cont = np.asarray(contingency, dtype=np.float64)
    a = cont.sum(axis=1)
    b = cont.sum(axis=0)
    n = cont.sum()
    n_max = int(n)
    nij = np.arange(1, n_max + 1, dtype=np.float64)
    ai = a[:, None, None]
    bj = b[None, :, None]
    nijg = nij[None, None, :]
    lo = np.maximum(ai + bj - n, 1.0)
    hi = np.minimum(ai, bj)
    feasible = (nijg >= lo) & (nijg <= hi)
    with np.errstate(divide="ignore", invalid="ignore"):
        term_mi = (nijg / n) * (np.log(n) + np.log(nijg) - np.log(np.maximum(ai * bj, 1.0)))
        log_p = (
            np_gammaln(ai + 1.0)
            + np_gammaln(bj + 1.0)
            + np_gammaln(n - ai + 1.0)
            + np_gammaln(n - bj + 1.0)
            - np_gammaln(n + 1.0)
            - np_gammaln(nijg + 1.0)
            - np_gammaln(np.maximum(ai - nijg + 1.0, 1.0))
            - np_gammaln(np.maximum(bj - nijg + 1.0, 1.0))
            - np_gammaln(np.maximum(n - ai - bj + nijg + 1.0, 1.0))
        )
        contrib = np.where(feasible, term_mi * np.exp(log_p), 0.0)
    return jnp.asarray(contrib.sum())


def relabel_dense(labels: Array) -> Tuple[Array, int]:
    """Map arbitrary integer labels to 0..K-1 (host-side, eager only)."""
    import numpy as np

    arr = np.asarray(labels)
    uniq, inv = np.unique(arr, return_inverse=True)
    return jnp.asarray(inv), len(uniq)
