"""FID InceptionV3 in Flax (linen).

Parity target: the feature network behind the reference's FID/KID/IS/MiFID —
``NoTrainInceptionV3`` (reference ``image/fid.py:44``) wrapping
torch-fidelity's TF-ported ``FeatureExtractorInceptionV3``. That network
differs from torchvision's InceptionV3 in the FID-critical details, all
reproduced here:

- pool branches of the A/C/E blocks use 3x3 stride-1 average pooling with
  ``count_include_pad=False``;
- the final E block (Mixed_7c) uses **max** pooling in its pool branch;
- the classifier head has 1008 logits (TF class layout);
- input is resized to 299x299 bilinear (no antialias, like
  ``F.interpolate(..., align_corners=False)``) and normalized from [0, 255]
  to [-1, 1].

Feature taps match torch-fidelity's ``features_list``: ``64`` (after first
maxpool), ``192`` (after second maxpool), ``768`` (end of the 17x17 stage),
``2048`` (global avgpool), ``"logits_unbiased"``.

Weights: this offline build cannot download the FID checkpoint; use
:func:`convert_torch_state_dict` to convert a locally-available
torch-fidelity ``pt_inception-2015-12-05`` state_dict, then
``flax_params = load_params(path)``. Random init is fully supported for
architecture tests.
"""
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

Array = jax.Array


class BasicConv2d(nn.Module):
    """Conv → BatchNorm(eps=1e-3, no scale-learn in eval) → ReLU."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        # pin: on TPU the default conv precision is bf16 multiplies; FID
        # features must match the torch extractor at f32 accuracy
        x = nn.Conv(self.features, self.kernel, self.strides, padding=self.padding,
                    use_bias=False, precision=jax.lax.Precision.HIGHEST, name="conv")(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=1e-3, momentum=0.9, name="bn")(x)
        return nn.relu(x)


def _avg_pool_3x3_valid_count(x: Array) -> Array:
    """3x3 stride-1 pad-1 average pool with count_include_pad=False."""
    ones = jnp.ones(x.shape[:-1] + (1,), x.dtype)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1),
                                   [(0, 0), (1, 1), (1, 1), (0, 0)])
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1),
                                   [(0, 0), (1, 1), (1, 1), (0, 0)])
    return summed / counts


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(64, (1, 1), name="branch1x1")(x)
        b5 = BasicConv2d(48, (1, 1), name="branch5x5_1")(x)
        b5 = BasicConv2d(64, (5, 5), padding=((2, 2), (2, 2)), name="branch5x5_2")(b5)
        b3 = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = BasicConv2d(96, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_2")(b3)
        b3 = BasicConv2d(96, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_3")(b3)
        bp = _avg_pool_3x3_valid_count(x)
        bp = BasicConv2d(self.pool_features, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(384, (3, 3), (2, 2), name="branch3x3")(x)
        bd = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(96, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_2")(bd)
        bd = BasicConv2d(96, (3, 3), (2, 2), name="branch3x3dbl_3")(bd)
        bp = nn.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        c7 = self.channels_7x7
        b1 = BasicConv2d(192, (1, 1), name="branch1x1")(x)
        b7 = BasicConv2d(c7, (1, 1), name="branch7x7_1")(x)
        b7 = BasicConv2d(c7, (1, 7), padding=((0, 0), (3, 3)), name="branch7x7_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=((3, 3), (0, 0)), name="branch7x7_3")(b7)
        bd = BasicConv2d(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = BasicConv2d(c7, (7, 1), padding=((3, 3), (0, 0)), name="branch7x7dbl_2")(bd)
        bd = BasicConv2d(c7, (1, 7), padding=((0, 0), (3, 3)), name="branch7x7dbl_3")(bd)
        bd = BasicConv2d(c7, (7, 1), padding=((3, 3), (0, 0)), name="branch7x7dbl_4")(bd)
        bd = BasicConv2d(192, (1, 7), padding=((0, 0), (3, 3)), name="branch7x7dbl_5")(bd)
        bp = _avg_pool_3x3_valid_count(x)
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(192, (1, 1), name="branch3x3_1")(x)
        b3 = BasicConv2d(320, (3, 3), (2, 2), name="branch3x3_2")(b3)
        b7 = BasicConv2d(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = BasicConv2d(192, (1, 7), padding=((0, 0), (3, 3)), name="branch7x7x3_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=((3, 3), (0, 0)), name="branch7x7x3_3")(b7)
        b7 = BasicConv2d(192, (3, 3), (2, 2), name="branch7x7x3_4")(b7)
        bp = nn.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    pool_mode: str  # "avg" (Mixed_7b) or "max" (Mixed_7c, FID variant)

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(320, (1, 1), name="branch1x1")(x)
        b3 = BasicConv2d(384, (1, 1), name="branch3x3_1")(x)
        b3a = BasicConv2d(384, (1, 3), padding=((0, 0), (1, 1)), name="branch3x3_2a")(b3)
        b3b = BasicConv2d(384, (3, 1), padding=((1, 1), (0, 0)), name="branch3x3_2b")(b3)
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        bd = BasicConv2d(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(384, (3, 3), padding=((1, 1), (1, 1)), name="branch3x3dbl_2")(bd)
        bda = BasicConv2d(384, (1, 3), padding=((0, 0), (1, 1)), name="branch3x3dbl_3a")(bd)
        bdb = BasicConv2d(384, (3, 1), padding=((1, 1), (0, 0)), name="branch3x3dbl_3b")(bd)
        bd = jnp.concatenate([bda, bdb], axis=-1)
        if self.pool_mode == "max":
            bp = nn.max_pool(x, (3, 3), (1, 1), padding=((1, 1), (1, 1)))
        else:
            bp = _avg_pool_3x3_valid_count(x)
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class FIDInceptionV3(nn.Module):
    """The torch-fidelity FID feature extractor, NHWC internally.

    ``__call__`` takes (N, 3, H, W) images in [0, 255] (float or uint8) and
    returns a dict of the requested feature taps.
    """

    features_list: Sequence[Any] = (2048,)

    @nn.compact
    def __call__(self, x: Array) -> Dict[Any, Array]:
        x = jnp.asarray(x, jnp.float32)
        # (N, 3, H, W) -> resize -> normalize to [-1, 1] -> NHWC
        n, c, h, w = x.shape
        # antialias=False: torch-fidelity resizes with F.interpolate(bilinear,
        # align_corners=False), which never antialiases — with the default
        # antialias=True, downscaling >299px inputs would diverge from it
        # ambient pin: jax.image.resize lowers to dot_generals (one per
        # spatial dim) that TPU would otherwise run as bf16 — caught by the
        # on-chip suite at 1.2e-2 relative feature error
        with jax.default_matmul_precision("highest"):
            x = jax.image.resize(x, (n, c, 299, 299), jax.image.ResizeMethod.LINEAR, antialias=False)
        x = (x - 128.0) / 128.0
        x = jnp.transpose(x, (0, 2, 3, 1))

        out: Dict[Any, Array] = {}
        x = BasicConv2d(32, (3, 3), (2, 2), name="Conv2d_1a_3x3")(x)
        x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = BasicConv2d(64, (3, 3), padding=((1, 1), (1, 1)), name="Conv2d_2b_3x3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2))
        if 64 in self.features_list:
            out[64] = _gap(x)
        x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2))
        if 192 in self.features_list:
            out[192] = _gap(x)
        x = InceptionA(32, name="Mixed_5b")(x)
        x = InceptionA(64, name="Mixed_5c")(x)
        x = InceptionA(64, name="Mixed_5d")(x)
        x = InceptionB(name="Mixed_6a")(x)
        x = InceptionC(128, name="Mixed_6b")(x)
        x = InceptionC(160, name="Mixed_6c")(x)
        x = InceptionC(160, name="Mixed_6d")(x)
        x = InceptionC(192, name="Mixed_6e")(x)
        if 768 in self.features_list:
            out[768] = _gap(x)
        x = InceptionD(name="Mixed_7a")(x)
        x = InceptionE("avg", name="Mixed_7b")(x)
        x = InceptionE("max", name="Mixed_7c")(x)
        pooled = x.mean(axis=(1, 2))  # global average pool -> (N, 2048)
        if 2048 in self.features_list:
            out[2048] = pooled
        if "logits_unbiased" in self.features_list or 1008 in self.features_list:
            logits = nn.Dense(1008, use_bias=False, precision=jax.lax.Precision.HIGHEST, name="fc")(pooled)
            out["logits_unbiased"] = logits
            if 1008 in self.features_list:
                out[1008] = logits
        return out


def _gap(x: Array) -> Array:
    """torch-fidelity taps 64/192/768 via adaptive avg pool to (1, 1)."""
    return x.mean(axis=(1, 2))


def make_fid_inception(features: Any = 2048, rng_seed: int = 0):
    """Build (module, params, extract_fn) with random init.

    ``extract_fn(imgs)`` maps (N, 3, H, W) [0, 255] images to (N, D)
    features for the single requested tap — directly usable as the
    ``feature=`` callable of FID/KID/IS/MiFID.
    """
    feats = (features,) if not isinstance(features, (tuple, list)) else tuple(features)
    mod = FIDInceptionV3(features_list=feats)
    # init on the host CPU backend: on a remote-attached TPU the eager init
    # chain pays one tunnel round-trip per op (~300 s measured); on CPU it
    # is milliseconds. Pull leaves to numpy so the jitted extract uploads
    # them once at compile time on whatever backend runs it.
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # JAX_PLATFORMS pinned without cpu: init where we run
        cpu = None
    if cpu is not None:
        with jax.default_device(cpu):
            params = mod.init(jax.random.PRNGKey(rng_seed), jnp.zeros((1, 3, 32, 32)))
        params = jax.tree.map(np.asarray, params)
    else:
        params = mod.init(jax.random.PRNGKey(rng_seed), jnp.zeros((1, 3, 32, 32)))

    @jax.jit
    def extract(imgs: Array) -> Array:
        return mod.apply(params, imgs)[feats[0]]

    return mod, params, extract


# ---------------------------------------------------------------------------
# torch -> flax weight conversion
# ---------------------------------------------------------------------------

def convert_torch_state_dict(state_dict: Dict[str, "np.ndarray"]) -> Dict:
    """Convert a torch-fidelity FID-InceptionV3 ``state_dict`` (tensors or
    numpy arrays) into this module's flax params/batch_stats pytree.

    Mapping: ``<block>.conv.weight`` (O, I, kH, kW) → ``params/<block>/conv``
    kernel (kH, kW, I, O); BN ``weight/bias`` → scale/bias params; BN
    ``running_mean/var`` → batch_stats; ``fc.weight`` (O, I) → Dense kernel
    (I, O).
    """
    params: Dict = {}
    batch_stats: Dict = {}

    def _set(tree: Dict, path: Sequence[str], value):
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = jnp.asarray(np.asarray(value))

    for name, tensor in state_dict.items():
        arr = np.asarray(tensor)
        parts = name.split(".")
        if parts[-2:] == ["conv", "weight"]:
            _set(params, parts[:-1] + ["kernel"], arr.transpose(2, 3, 1, 0))
        elif parts[-2] == "bn" and parts[-1] == "weight":
            _set(params, parts[:-1] + ["scale"], arr)
        elif parts[-2] == "bn" and parts[-1] == "bias":
            _set(params, parts[:-1] + ["bias"], arr)
        elif parts[-1] == "running_mean":
            _set(batch_stats, parts[:-1] + ["mean"], arr)
        elif parts[-1] == "running_var":
            _set(batch_stats, parts[:-1] + ["var"], arr)
        elif parts == ["fc", "weight"]:
            _set(params, ["fc", "kernel"], arr.T)
        elif parts == ["fc", "bias"]:
            _set(params, ["fc", "bias"], arr)
    return {"params": params, "batch_stats": batch_stats}
