"""Sharded cat state: buffer layout, distributed kernels, reshard plan.

Covers ISSUE 20: the resident ``NamedSharding`` :class:`ShardedCatBuffer`,
the distributed read paths in ``parallel.sharded_compute`` (bitwise for
sort-based consumers, documented ε for the bucketed-histogram backend), the
refused-densify contract with the ``sharded_oracle()`` escape hatch, and the
reshard plan under elastic preemption/rejoin (uneven counts, empty shards,
larger mesh, double-preemption) with coverage accounting.

Runs on 8 virtual CPU devices (conftest.py forces
``--xla_force_host_platform_device_count=8``).
"""
import copy
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import KendallRankCorrCoef, SpearmanCorrCoef
from torchmetrics_tpu.buffers import CatBuffer, ShardedCatBuffer, default_eval_mesh
from torchmetrics_tpu.classification.auroc import BinaryAUROC
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
)
from torchmetrics_tpu.parallel import sharded_compute as sc
from torchmetrics_tpu.parallel.elastic import (
    ChaosSchedule,
    ElasticSync,
    chaos_group,
    checkpoint_metric,
    merge_checkpoint,
    rejoin_metric,
    reset_elastic_stats,
)
from torchmetrics_tpu.parallel.strategies import SyncPolicy
from torchmetrics_tpu.parallel.sync import FakeSync
from torchmetrics_tpu.retrieval import RetrievalMRR
from torchmetrics_tpu.utils.data import dim_zero_cat, padded_cat, sharded_oracle

WORLD = len(jax.devices())

FAST = SyncPolicy(retry_attempts=2, backoff_base_s=0.001)


def _rand(n, seed=0):
    return np.random.RandomState(seed).rand(n).astype(np.float32)


# ---------------------------------------------------------------------------
# buffer layout
# ---------------------------------------------------------------------------


def test_allocate_shards_across_all_devices():
    buf = ShardedCatBuffer.allocate(jnp.asarray(_rand(100)))
    assert buf.n_shards == WORLD
    assert buf.count == 100
    per_dev = buf.per_device_nbytes()
    assert len(per_dev) == WORLD
    # balanced layout: every device holds the same resident bytes
    assert len(set(per_dev.values())) == 1


def test_append_grow_and_materialize_order_stable():
    data = _rand(1000, seed=1)
    buf = ShardedCatBuffer.allocate(jnp.asarray(data[:64]))
    for i in range(64, 1000, 64):
        buf.append(jnp.asarray(data[i : i + 64]))
    assert buf.count == 1000
    # shard-major materialization is a permutation of the appended rows
    rows = np.sort(np.asarray(buf.materialize()))
    np.testing.assert_array_equal(rows, np.sort(data))
    # and cat_compact reproduces materialize() order bitwise
    np.testing.assert_array_equal(
        np.asarray(sc.cat_compact(buf)), np.asarray(buf.materialize())
    )


def test_uneven_counts_small_append():
    # 3 rows over 8 shards: shards past the third stay empty
    buf = ShardedCatBuffer.allocate(jnp.arange(3, dtype=jnp.float32))
    assert buf.count == 3
    assert int(np.sum(buf.counts == 0)) == WORLD - 3
    np.testing.assert_array_equal(np.asarray(buf.materialize()), np.arange(3.0))


def test_lockstep_appends_align_across_states():
    # preds/target appended in lockstep share per-shard counts, so the
    # shard-major permutation keeps rows aligned
    p = _rand(123, seed=2)
    t = _rand(123, seed=3)
    pb = ShardedCatBuffer.allocate(jnp.asarray(p[:50]))
    tb = ShardedCatBuffer.allocate(jnp.asarray(t[:50]))
    pb.append(jnp.asarray(p[50:]))
    tb.append(jnp.asarray(t[50:]))
    np.testing.assert_array_equal(pb.counts, tb.counts)
    pm, tm_ = np.asarray(pb.materialize()), np.asarray(tb.materialize())
    pairs = {(round(float(a), 6), round(float(b), 6)) for a, b in zip(pm, tm_)}
    expect = {(round(float(a), 6), round(float(b), 6)) for a, b in zip(p, t)}
    assert pairs == expect


def test_snapshot_is_copy_on_write():
    data = _rand(32)
    buf = ShardedCatBuffer.allocate(jnp.asarray(data))
    snap = buf.snapshot()
    before = np.asarray(snap.materialize()).copy()
    buf.append(jnp.asarray(_rand(32, seed=9)))
    assert snap.count == 32 and buf.count == 64
    # the snapshot is insulated from the later append
    np.testing.assert_array_equal(np.asarray(snap.materialize()), before)


def test_pickle_roundtrip_rebalances():
    data = _rand(77, seed=4)
    buf = ShardedCatBuffer.allocate(jnp.asarray(data))
    restored = pickle.loads(pickle.dumps(buf))
    assert isinstance(restored, ShardedCatBuffer)
    assert restored.count == 77
    assert restored == buf
    # balanced ceil-chunk restore
    assert int(restored.counts.max()) - int(restored.counts.min()) <= 10


def test_deepcopy_and_astype():
    buf = ShardedCatBuffer.allocate(jnp.asarray(_rand(16)))
    dup = copy.deepcopy(buf)
    assert dup == buf and dup is not buf
    as64 = buf.astype(jnp.int32)
    assert str(as64.dtype) == "int32"


# ---------------------------------------------------------------------------
# refused densify (satellite: clear NotImplementedError naming the metric)
# ---------------------------------------------------------------------------


def test_dim_zero_cat_refuses_sharded_state():
    m = SpearmanCorrCoef(list_layout="padded", cat_layout="sharded")
    m.update(jnp.asarray(_rand(32)), jnp.asarray(_rand(32, seed=1)))
    with pytest.raises(NotImplementedError, match="SpearmanCorrCoef.preds"):
        dim_zero_cat(m.preds)
    with pytest.raises(NotImplementedError, match="sharded_oracle"):
        padded_cat(m.target)


def test_sharded_oracle_context_allows_densify():
    m = SpearmanCorrCoef(list_layout="padded", cat_layout="sharded")
    m.update(jnp.asarray(_rand(32)), jnp.asarray(_rand(32, seed=1)))
    with sharded_oracle():
        vals, count = padded_cat(m.preds)
    assert count == 32
    # and the context unwinds: the guard re-arms afterwards
    with pytest.raises(NotImplementedError):
        dim_zero_cat(m.preds)


# ---------------------------------------------------------------------------
# metric integration + state metadata
# ---------------------------------------------------------------------------


def test_cat_layout_validation():
    with pytest.raises(ValueError, match="replicated.*sharded|sharded.*replicated"):
        SpearmanCorrCoef(cat_layout="bogus")
    with pytest.raises(ValueError, match="padded"):
        SpearmanCorrCoef(list_layout="list", cat_layout="sharded")


def test_sharded_states_in_treedef_aux():
    rep = SpearmanCorrCoef(list_layout="padded")
    sh = SpearmanCorrCoef(list_layout="padded", cat_layout="sharded")
    for m in (rep, sh):
        m.update(jnp.asarray(_rand(8)), jnp.asarray(_rand(8, seed=1)))
    assert sh._state_view().sharded_states == frozenset({"preds", "target"})
    assert rep._state_view().sharded_states == frozenset()
    # replicated/sharded twins must never share a treedef (or a jit cache line)
    _, td_rep = jax.tree_util.tree_flatten(rep._state_view())
    _, td_sh = jax.tree_util.tree_flatten(sh._state_view())
    assert td_rep != td_sh


def test_state_buffers_are_sharded_buffers():
    m = BinaryPrecisionRecallCurve(list_layout="padded", cat_layout="sharded")
    m.update(jnp.asarray(_rand(64)), jnp.asarray((_rand(64, seed=5) < 0.5).astype(np.int32)))
    assert isinstance(m.preds, ShardedCatBuffer)
    assert isinstance(m.target, ShardedCatBuffer)
    assert m.preds.owner == "BinaryPrecisionRecallCurve.preds"


# ---------------------------------------------------------------------------
# compute parity vs the replicated oracle
# ---------------------------------------------------------------------------


def _twin_update(rep, sh, preds, target, chunks=4):
    n = len(preds)
    step = -(-n // chunks)
    for i in range(0, n, step):
        rep.update(jnp.asarray(preds[i : i + step]), jnp.asarray(target[i : i + step]))
        sh.update(jnp.asarray(preds[i : i + step]), jnp.asarray(target[i : i + step]))


def test_pr_curve_bitwise_parity():
    preds = _rand(500, seed=6)
    target = (_rand(500, seed=7) < 0.4).astype(np.int32)
    rep = BinaryPrecisionRecallCurve(list_layout="padded")
    sh = BinaryPrecisionRecallCurve(list_layout="padded", cat_layout="sharded")
    _twin_update(rep, sh, preds, target)
    for a, b in zip(rep.compute(), sh.compute()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auroc_bitwise_parity():
    preds = _rand(500, seed=8)
    target = (_rand(500, seed=9) < 0.4).astype(np.int32)
    rep = BinaryAUROC(list_layout="padded")
    sh = BinaryAUROC(list_layout="padded", cat_layout="sharded")
    _twin_update(rep, sh, preds, target)
    assert float(rep.compute()) == float(sh.compute())


def test_auroc_ignore_index_parity():
    preds = _rand(300, seed=10)
    target = (_rand(300, seed=11) < 0.4).astype(np.int32)
    target[::5] = -1
    rep = BinaryAUROC(ignore_index=-1, list_layout="padded")
    sh = BinaryAUROC(ignore_index=-1, list_layout="padded", cat_layout="sharded")
    _twin_update(rep, sh, preds, target)
    assert float(rep.compute()) == float(sh.compute())


def test_histogram_auroc_epsilon():
    preds = _rand(2000, seed=12)
    target = (_rand(2000, seed=13) < 0.35).astype(np.int32)
    exact = BinaryAUROC(list_layout="padded")
    hist = BinaryAUROC(hist_bins=8192, list_layout="padded", cat_layout="sharded")
    _twin_update(exact, hist, preds, target)
    # ε = O(1/bins): for uniform scores, well inside 1e-3 at 8192 buckets
    assert abs(float(exact.compute()) - float(hist.compute())) < 1e-3


def test_hist_bins_requires_sharded_layout():
    with pytest.raises(ValueError, match="sharded"):
        BinaryAUROC(hist_bins=4096, list_layout="padded")


def test_rank_correlation_parity():
    preds = _rand(400, seed=14)
    target = preds * 2 + _rand(400, seed=15) * 0.3
    for cls in (SpearmanCorrCoef, KendallRankCorrCoef):
        rep = cls(list_layout="padded")
        sh = cls(list_layout="padded", cat_layout="sharded")
        _twin_update(rep, sh, preds, target)
        ra, rb = rep.compute(), sh.compute()
        ra = ra[0] if isinstance(ra, tuple) else ra
        rb = rb[0] if isinstance(rb, tuple) else rb
        assert abs(float(ra) - float(rb)) < 1e-6


def test_retrieval_parity():
    n = 400
    preds = _rand(n, seed=16)
    target = (_rand(n, seed=17) < 0.3).astype(np.int32)
    idx = np.random.RandomState(18).randint(0, 25, n)
    rep = RetrievalMRR(list_layout="padded")
    sh = RetrievalMRR(list_layout="padded", cat_layout="sharded")
    step = 100
    for i in range(0, n, step):
        rep.update(jnp.asarray(preds[i : i + step]), jnp.asarray(target[i : i + step]),
                   indexes=jnp.asarray(idx[i : i + step]))
        sh.update(jnp.asarray(preds[i : i + step]), jnp.asarray(target[i : i + step]),
                  indexes=jnp.asarray(idx[i : i + step]))
    assert abs(float(rep.compute()) - float(sh.compute())) < 1e-7


def test_sharded_topk_exact():
    data = _rand(999, seed=19)
    buf = ShardedCatBuffer.allocate(jnp.asarray(data))
    got = np.sort(np.asarray(sc.sharded_topk(buf, 25)))[::-1]
    np.testing.assert_allclose(got, np.sort(data)[::-1][:25])


def test_sharded_moments_match_numpy():
    data = _rand(777, seed=20)
    buf = ShardedCatBuffer.allocate(jnp.asarray(data))
    mean, var = sc.sharded_moments(buf)
    assert abs(float(mean) - data.mean()) < 1e-5
    assert abs(float(var) - data.var()) < 1e-5


# ---------------------------------------------------------------------------
# sync: wire stays layout-independent, residency stays sharded
# ---------------------------------------------------------------------------


def test_fake_sync_group_keeps_sharded_residency():
    preds = _rand(200, seed=21)
    target = preds * 3 + _rand(200, seed=22) * 0.1
    # replicated twin group = the oracle
    rep = [SpearmanCorrCoef(list_layout="padded") for _ in range(2)]
    sh = [SpearmanCorrCoef(list_layout="padded", cat_layout="sharded") for _ in range(2)]
    for r, (lo, hi) in enumerate(((0, 100), (100, 200))):
        rep[r].update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
        sh[r].update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    rep[0]._sync_backend = FakeSync([m.metric_state for m in rep], 0)
    sh[0]._sync_backend = FakeSync([m.metric_state for m in sh], 0)
    expect = float(rep[0].compute())
    got = float(sh[0].compute())
    assert abs(got - expect) < 1e-6
    # post-sync state is re-sharded, not densified
    with sh[0].sync_context():
        assert isinstance(sh[0].preds, ShardedCatBuffer)


# ---------------------------------------------------------------------------
# reshard plan edge cases
# ---------------------------------------------------------------------------


def test_reshard_uneven_counts_parity():
    data = _rand(137, seed=23)
    buf = ShardedCatBuffer.allocate(jnp.asarray(data[:9]))
    buf.append(jnp.asarray(data[9:]))
    assert len(set(int(c) for c in buf.counts)) >= 1  # ragged per-shard fill
    out = sc.reshard(buf, devices=jax.devices()[:3])
    assert out.n_shards == 3 and out.count == 137
    assert out == buf  # shard-major row stream preserved


def test_reshard_empty_and_never_updated_shards():
    # 2 rows over 8 shards: 6 shards never held data
    buf = ShardedCatBuffer.allocate(jnp.asarray(_rand(2, seed=24)))
    out = sc.reshard(buf, devices=jax.devices()[:5])
    assert out.count == 2 and out == buf
    # zero-count buffer roundtrip (all shards empty)
    empty = ShardedCatBuffer.allocate(jnp.asarray(_rand(4, seed=25)))
    empty2 = sc.reshard(empty, devices=jax.devices()[:2])
    assert empty2.count == 4 and empty2 == empty


def test_reshard_onto_larger_mesh():
    small = sc.reshard(
        ShardedCatBuffer.allocate(jnp.asarray(_rand(64, seed=26))),
        devices=jax.devices()[:2],
    )
    assert small.n_shards == 2
    big = sc.reshard(small)  # back onto the full default mesh
    assert big.n_shards == WORLD and big == small
    per_dev = big.per_device_nbytes()
    assert len(per_dev) == WORLD


def test_checkpoint_restore_is_reshard_plan():
    m = SpearmanCorrCoef(list_layout="padded", cat_layout="sharded")
    m.update(jnp.asarray(_rand(90, seed=27)), jnp.asarray(_rand(90, seed=28)))
    blob = checkpoint_metric(m)
    # restore onto a 4-device survivor mesh
    r = rejoin_metric(blob, devices=jax.devices()[:4])
    assert isinstance(r.preds, ShardedCatBuffer) and r.preds.n_shards == 4
    assert abs(float(r.compute()) - float(m.compute())) < 1e-6


def test_merge_checkpoint_reshards_onto_survivors():
    a_p, a_t = _rand(70, seed=29), _rand(70, seed=30)
    b_p, b_t = _rand(40, seed=31), _rand(40, seed=32)
    oracle = SpearmanCorrCoef(list_layout="padded")
    oracle.update(jnp.asarray(np.concatenate([a_p, b_p])),
                  jnp.asarray(np.concatenate([a_t, b_t])))
    expect = float(oracle.compute())

    m1 = SpearmanCorrCoef(list_layout="padded", cat_layout="sharded")
    m1.update(jnp.asarray(a_p), jnp.asarray(a_t))
    m2 = SpearmanCorrCoef(list_layout="padded", cat_layout="sharded")
    m2.update(jnp.asarray(b_p), jnp.asarray(b_t))
    recovered = merge_checkpoint(m1, checkpoint_metric(m2), devices=jax.devices()[:6])
    assert recovered == 40
    assert isinstance(m1.preds, ShardedCatBuffer) and m1.preds.n_shards == 6
    assert m1.preds.count == 110
    assert abs(float(m1.compute()) - expect) < 1e-6


# ---------------------------------------------------------------------------
# elastic rounds: preemption → rejoin with coverage accounting
# ---------------------------------------------------------------------------


def _spearman_group(world, n=60):
    ms = [SpearmanCorrCoef(list_layout="padded", cat_layout="sharded") for _ in range(world)]
    datas = []
    for r, m in enumerate(ms):
        p = _rand(n, seed=40 + r)
        t = p * 2 + _rand(n, seed=50 + r) * 0.2
        m.update(jnp.asarray(p), jnp.asarray(t))
        datas.append((p, t))
    return ms, datas


def test_preemption_rejoin_round_recovers_with_coverage():
    world = 2
    reset_elastic_stats()
    ms, datas = _spearman_group(world)
    oracle = SpearmanCorrCoef(list_layout="padded")
    oracle.update(jnp.asarray(np.concatenate([d[0] for d in datas])),
                  jnp.asarray(np.concatenate([d[1] for d in datas])))
    expect = float(oracle.compute())

    blob = checkpoint_metric(ms[1])  # rank 1 checkpoints, then is preempted
    group = [m.metric_state for m in ms]
    backs = chaos_group(group, ChaosSchedule({0: [("drop", 1)]}))
    ms[0]._sync_backend = ElasticSync(backs[0], policy=FAST)
    backs[0].advance_round()
    got = float(ms[0].compute())
    cov = ms[0].coverage
    assert cov.ranks_present == 1 and cov.ranks_expected == 2
    # degraded round: rank 0's own (still sharded) partial result
    local = SpearmanCorrCoef(list_layout="padded")
    local.update(jnp.asarray(datas[0][0]), jnp.asarray(datas[0][1]))
    assert abs(got - float(local.compute())) < 1e-6

    # rejoin: merge the preempted rank's checkpoint over the survivor mesh
    recovered = ms[0]._sync_backend.merge_on_rejoin(ms[0], blob)
    assert recovered == 60
    assert isinstance(ms[0].preds, ShardedCatBuffer)
    ms[0]._sync_backend = None
    ms[0]._computed = None
    assert abs(float(ms[0].compute()) - expect) < 1e-6


def test_double_preemption_during_round():
    world = 4
    reset_elastic_stats()
    ms, datas = _spearman_group(world, n=40)
    blobs = [checkpoint_metric(ms[2]), checkpoint_metric(ms[3])]
    group = [m.metric_state for m in ms]
    backs = chaos_group(group, ChaosSchedule({0: [("drop", 2), ("drop", 3)]}))
    ms[0]._sync_backend = ElasticSync(backs[0], policy=FAST)
    backs[0].advance_round()
    float(ms[0].compute())
    cov = ms[0].coverage
    assert cov.ranks_present == 2 and cov.ranks_expected == 4
    assert cov.fraction == pytest.approx(0.5)

    # both preempted ranks' checkpoints fold back in; the adopted samples are
    # remembered for the next round's contribution
    es = ms[0]._sync_backend
    assert es.merge_on_rejoin(ms[0], blobs[0]) == 40
    assert es.merge_on_rejoin(ms[0], blobs[1]) == 40
    assert es._adopted_contrib == 80
    # rank 0's own rows + both recovered checkpoints (the degraded sync
    # round left rank 1's rows with rank 1 — they return when it rejoins)
    assert ms[0].preds.count == 3 * 40

    oracle = SpearmanCorrCoef(list_layout="padded")
    keep = [datas[0], datas[2], datas[3]]
    oracle.update(jnp.asarray(np.concatenate([d[0] for d in keep])),
                  jnp.asarray(np.concatenate([d[1] for d in keep])))
    ms[0]._sync_backend = None
    ms[0]._computed = None
    assert abs(float(ms[0].compute()) - float(oracle.compute())) < 1e-6
