"""Exporters: Perfetto trace JSON, Prometheus text scrape, JSONL event log.

Three machine-readable views of the telemetry collected by
:mod:`~torchmetrics_tpu.observability.spans` and
:mod:`~torchmetrics_tpu.observability.registry`:

* :func:`to_perfetto` — Chrome/Perfetto ``trace_event`` JSON
  (``{"traceEvents": [...]}`` with ``ph: "X"`` complete events, micro-
  second timestamps). Load at https://ui.perfetto.dev.
* :func:`to_prometheus` — the text exposition format a Prometheus
  scraper expects (``# HELP`` / ``# TYPE`` / samples with labels).
* :class:`JsonlEventLog` — append-only one-JSON-object-per-line log.
  Each write is a single appended line followed by ``flush``; a
  preemption mid-run loses at most the current line and never corrupts
  prior records, so restarted workers keep appending to the same file.
"""
from __future__ import annotations

import json
import os
from typing import IO, Any, Dict, List, Optional

from .registry import Counter, Gauge, Histogram, Registry, REGISTRY
from .spans import Span, collected_spans

__all__ = [
    "to_perfetto",
    "write_perfetto",
    "to_prometheus",
    "JsonlEventLog",
]


def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


def to_perfetto(
    spans: Optional[List[Span]] = None,
    process_name: str = "torchmetrics_tpu",
) -> Dict[str, Any]:
    """Render spans as a Chrome/Perfetto ``trace_event`` document.

    Completed spans become ``ph: "X"`` (complete) events with ``ts``/
    ``dur`` in microseconds; zero-duration records become ``ph: "i"``
    instants. Span nesting is reconstructed by Perfetto from the shared
    ``tid`` timeline, and parent ids ride along in ``args`` for tools
    that want the explicit tree.
    """
    if spans is None:
        spans = collected_spans()
    events: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": os.getpid(),
            "args": {"name": process_name},
        }
    ]
    pid = os.getpid()
    for s in spans:
        if s.t1 is None:
            continue
        args = {k: _json_safe(v) for k, v in s.attrs.items()}
        args["span_id"] = s.span_id
        if s.parent_id:
            args["parent_id"] = s.parent_id
        if s.fenced:
            args["fenced"] = True
        dur_us = (s.t1 - s.t0) * 1e6
        ev: Dict[str, Any] = {
            "name": s.name,
            "pid": pid,
            "tid": s.tid,
            "ts": s.t0 * 1e6,
            "args": args,
        }
        if dur_us <= 0.0:
            ev.update(ph="i", s="t")
        else:
            ev.update(ph="X", dur=dur_us)
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_perfetto(
    path: str,
    spans: Optional[List[Span]] = None,
    process_name: str = "torchmetrics_tpu",
) -> str:
    doc = to_perfetto(spans, process_name=process_name)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    sanitized = "".join(out)
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return sanitized


def _prom_escape(value: str) -> str:
    """Escape a label value per the text exposition format.

    Backslash first (so later escapes aren't doubled), then double-quote
    and newline — the three characters the format reserves.
    """
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_labels(labels) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in labels)
    return "{" + body + "}"


def _prom_value(value: float) -> str:
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def to_prometheus(registry: Optional[Registry] = None, prefix: str = "tmtpu") -> str:
    """Render the registry in the Prometheus text exposition format."""
    if registry is None:
        registry = REGISTRY
    lines: List[str] = []
    for inst in registry.instruments():
        metric = _prom_name(f"{prefix}_{inst.name}")
        if isinstance(inst, Counter):
            lines.append(f"# HELP {metric} {inst.help or inst.name}")
            lines.append(f"# TYPE {metric} counter")
            samples = inst.collect() or [((), 0.0)]
            for labels, value in samples:
                lines.append(f"{metric}{_prom_labels(labels)} {_prom_value(value)}")
        elif isinstance(inst, Gauge):
            lines.append(f"# HELP {metric} {inst.help or inst.name}")
            lines.append(f"# TYPE {metric} gauge")
            samples = inst.collect() or [((), 0.0)]
            for labels, value in samples:
                lines.append(f"{metric}{_prom_labels(labels)} {_prom_value(value)}")
        elif isinstance(inst, Histogram):
            lines.append(f"# HELP {metric} {inst.help or inst.name}")
            lines.append(f"# TYPE {metric} histogram")
            # a registered-but-never-observed histogram still emits one
            # valid unlabeled series (all-zero buckets, zero sum/count)
            samples = inst.collect() or [((), [0] * len(inst.buckets), 0.0, 0)]
            for labels, counts, total_sum, total in samples:
                cumulative = 0
                for le, n in zip(inst.buckets, counts):
                    cumulative += n
                    bucket_labels = tuple(labels) + (("le", repr(float(le))),)
                    lines.append(
                        f"{metric}_bucket{_prom_labels(bucket_labels)} {cumulative}"
                    )
                inf_labels = tuple(labels) + (("le", "+Inf"),)
                lines.append(f"{metric}_bucket{_prom_labels(inf_labels)} {total}")
                lines.append(
                    f"{metric}_sum{_prom_labels(labels)} {_prom_value(total_sum)}"
                )
                lines.append(f"{metric}_count{_prom_labels(labels)} {total}")
    return "\n".join(lines) + "\n"


class JsonlEventLog:
    """Append-only JSONL sink, safe under preemption.

    The file is opened in append mode so a rejoining worker resumes the
    same log; every record is written as one line then flushed, so a
    kill mid-run can truncate at most the final line (readers skip a
    trailing partial line via :meth:`read`).

    ``max_bytes`` arms size-capped rotation for long serve runs: when a
    record would push the active file past the cap, the file is atomically
    renamed to ``<path>.1`` (one backup generation, so disk stays bounded
    at roughly twice the cap) and the record starts a fresh file. Records
    are never split across the boundary, and rotation preserves the
    torn-trailing-line guarantee — a partial line torn by a preemption
    rides along into the rotated file, where :meth:`read` still skips it.
    """

    def __init__(self, path: str, max_bytes: Optional[int] = None) -> None:
        self.path = path
        self.max_bytes = max_bytes
        self._fh: Optional[IO[str]] = None

    @property
    def rotated_path(self) -> str:
        return self.path + ".1"

    def _ensure_open(self) -> IO[str]:
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "a")
        return self._fh

    def _maybe_rotate(self, incoming_len: int) -> None:
        if not self.max_bytes:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size and size + incoming_len > self.max_bytes:
            self.close()
            os.replace(self.path, self.rotated_path)

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps({k: _json_safe(v) for k, v in record.items()}) + "\n"
        self._maybe_rotate(len(line))
        fh = self._ensure_open()
        fh.write(line)
        fh.flush()

    def write_span(self, span: Span) -> None:
        self.write(
            {
                "type": "span",
                "name": span.name,
                "t0": span.t0,
                "dur_s": span.duration_s,
                "span_id": span.span_id,
                "parent_id": span.parent_id,
                **span.attrs,
            }
        )

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self._fh.close()

    def __enter__(self) -> "JsonlEventLog":
        self._ensure_open()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    @staticmethod
    def read(path: str, include_rotated: bool = True) -> List[Dict[str, Any]]:
        """Parse a JSONL log, tolerating a truncated final line.

        With ``include_rotated`` (the default) a ``<path>.1`` backup left
        by :attr:`max_bytes` rotation is read first, so the caller sees
        the logical log in order; a line torn by a preemption — whether
        it now sits at the end of the backup or of the active file — is
        skipped, never merged across the boundary.
        """
        records: List[Dict[str, Any]] = []
        paths = [path + ".1", path] if include_rotated else [path]
        for p in paths:
            if not os.path.exists(p):
                continue
            with open(p) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # partial trailing line from a preemption
        return records
