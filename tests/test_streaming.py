"""Buffered streaming updates: stage K steps, flush one scanned executable.

Equivalence suite for the streaming tentpole — the buffered path must be
BITWISE-identical to eager per-step updates (the flush scans the exact
per-step update body sequentially; no reassociation), across:

- MEAN / SUM / cat (list-append) state reductions;
- short final windows (``valid`` masking, shared executable);
- forced flush on every state observation: compute, sync, reset, pickling,
  ``metric_state`` access, an interleaved eager ``update()``;
- compute groups (flush writes through the shared group state dict) and
  donation safety across update/flush/reset cycles;
- dispatch economics: K staged steps cost ONE executable-cache dispatch.
"""
import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchmetrics_tpu.metric as M
from torchmetrics_tpu import (
    BufferedMetric,
    BufferedMetricCollection,
    CatMetric,
    MeanMetric,
    SumMetric,
)
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassF1Score,
)
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.parallel.sync import FakeSync
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

N_CLS = 5


def _batches(steps=11, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.rand(batch).astype(np.float32)) for _ in range(steps)]


def _cls_data(steps=9, batch=16, seed=0):
    preds = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(seed), (steps, batch, N_CLS)), axis=-1
    )
    target = jax.random.randint(jax.random.PRNGKey(seed + 1), (steps, batch), 0, N_CLS)
    return preds, target


def _assert_state_bitwise(a, b):
    sa, sb = a.metric_state, b.metric_state
    assert set(sa) == set(sb)
    for k in sa:
        va, vb = sa[k], sb[k]
        if isinstance(va, (list, tuple)):
            assert len(va) == len(vb), k
            for xa, xb in zip(va, vb):
                np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb), err_msg=k)
        else:
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=k)


# ------------------------------------------------------------- equivalence
@pytest.mark.parametrize(
    "factory",
    [MeanMetric, SumMetric, lambda: CatMetric(nan_strategy="disable")],
    ids=["mean", "sum", "cat"],
)
@pytest.mark.parametrize("window", [1, 4, 32], ids=["K1", "K4", "K32"])
def test_buffered_bitwise_identical_to_eager(factory, window):
    # 11 steps at K=4 exercises two full windows + a short 3-step flush;
    # K=1 is the degenerate flush-per-step cadence; K=32 a single short window
    data = _batches()
    eager, buffered = factory(), factory().buffered(window=window)
    for x in data:
        eager.update(x)
        buffered.update(x)
    _assert_state_bitwise(eager, buffered)
    np.testing.assert_array_equal(
        np.asarray(eager.compute()), np.asarray(buffered.compute())
    )
    assert buffered.update_count == eager.update_count


def test_short_final_window_single_step():
    eager, buffered = SumMetric(), SumMetric().buffered(window=8)
    eager.update(jnp.asarray([1.0, 2.0]))
    buffered.update(jnp.asarray([1.0, 2.0]))
    assert buffered.pending == 1
    assert float(buffered.compute()) == float(eager.compute())
    assert buffered.pending == 0


# ---------------------------------------------------------- forced flushes
def test_compute_forces_flush():
    buffered = MeanMetric().buffered(window=8)
    for x in _batches(steps=3):
        buffered.update(x)
    assert buffered.pending == 3
    buffered.compute()
    assert buffered.pending == 0


def test_reset_forces_flush_then_clears():
    m = SumMetric()
    buffered = m.buffered(window=8)
    buffered.update(jnp.asarray([5.0]))
    buffered.reset()
    assert buffered.pending == 0
    assert float(m.value) == 0.0
    # post-reset staging still works (donated buffers were not resurrected)
    buffered.update(jnp.asarray([2.0]))
    assert float(buffered.compute()) == 2.0


def test_metric_state_access_forces_flush():
    m = SumMetric()
    buffered = m.buffered(window=8)
    buffered.update(jnp.asarray([4.0]))
    # observation on the WRAPPED metric, not the handle: the _flush_pending
    # hook in metric.py must drain the ring first
    assert float(m.metric_state["value"]) == 4.0
    assert buffered.pending == 0


def test_interleaved_eager_update_preserves_order():
    data = _batches(steps=6)
    eager, m = MeanMetric(), MeanMetric()
    buffered = m.buffered(window=8)
    for x in data[:3]:
        eager.update(x)
        buffered.update(x)
    # a direct eager update on the wrapped metric flushes staged work first
    eager.update(data[3])
    m.update(data[3])
    assert buffered.pending == 0
    for x in data[4:]:
        eager.update(x)
        buffered.update(x)
    _assert_state_bitwise(eager, buffered)


def test_pickle_forces_flush_and_roundtrips():
    data = _batches(steps=5)
    eager, buffered = SumMetric(), SumMetric().buffered(window=8)
    for x in data:
        eager.update(x)
        buffered.update(x)
    assert buffered.pending == 5
    clone = pickle.loads(pickle.dumps(buffered))
    assert isinstance(clone, BufferedMetric)
    assert clone.window == 8 and clone.pending == 0
    np.testing.assert_array_equal(
        np.asarray(clone.compute()), np.asarray(eager.compute())
    )


def test_sync_forces_flush():
    preds, target = _cls_data(steps=2)
    world = 2
    ranks = [
        MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False)
        for _ in range(world)
    ]
    handles = [m.buffered(window=8) for m in ranks]
    for r, h in enumerate(handles):
        h.update(preds[r], target[r])
        assert h.pending == 1
    # metric_state in the group build forces each rank's flush
    group = [m.metric_state for m in ranks]
    assert all(h.pending == 0 for h in handles)
    for r, m in enumerate(ranks):
        m.sync(sync_backend=FakeSync(group, r))
    expected = float(
        jnp.sum(jnp.argmax(preds[:world], axis=-1) == target[:world])
        / (world * target.shape[1])
    )
    assert float(ranks[0].compute()) == expected


def test_sync_while_staged_via_handle():
    m = MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False)
    preds, target = _cls_data(steps=1)
    h = m.buffered(window=8)
    h.update(preds[0], target[0])
    h.sync(sync_backend=FakeSync([m.metric_state], 0))
    assert h.pending == 0
    with pytest.raises(TorchMetricsUserError):
        h.update(preds[0], target[0])  # synced metric refuses updates
    h.unsync()
    h.update(preds[0], target[0])
    h.compute()


# --------------------------------------------------------------- signatures
def test_signature_change_forces_flush():
    eager, buffered = SumMetric(), SumMetric().buffered(window=8)
    a, b = jnp.asarray([1.0, 2.0, 3.0]), jnp.asarray([10.0])
    for x in (a, a, b, a):  # shape change at step 3 drains the (a, a) window
        eager.update(x)
        buffered.update(x)
    assert buffered.pending == 1  # the trailing `a` only
    _assert_state_bitwise(eager, buffered)


def test_python_scalar_inputs_stage():
    eager, buffered = SumMetric(), SumMetric().buffered(window=4)
    for v in (1.5, 2.5, 3.5):
        eager.update(v)
        buffered.update(v)
    np.testing.assert_array_equal(
        np.asarray(eager.compute()), np.asarray(buffered.compute())
    )


# ---------------------------------------------------------------- dispatch
def test_k_staged_steps_cost_one_dispatch():
    buffered = SumMetric().buffered(window=8)
    buffered.update(jnp.asarray([0.0]))  # warm the flush executable
    buffered.compute()
    data = _batches(steps=8, seed=3)
    before = M.executable_cache_stats()["dispatches"]
    for x in data:
        buffered.update(x)
    assert M.executable_cache_stats()["dispatches"] - before == 1
    assert buffered.pending == 0


def test_equal_config_buffered_metrics_share_flush_executable():
    a = SumMetric().buffered(window=4)
    for x in _batches(steps=4, seed=4):
        a.update(x)
    miss_before = M.executable_cache_stats()["misses"]
    b = SumMetric().buffered(window=4)
    for x in _batches(steps=4, seed=5):
        b.update(x)
    assert M.executable_cache_stats()["misses"] - miss_before == 0


# -------------------------------------------------------------- collections
def test_buffered_collection_bitwise_identical_with_groups():
    preds, target = _cls_data()

    def mk():
        return MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False),
                "f1": MulticlassF1Score(num_classes=N_CLS, average="macro", validate_args=False),
                "auroc": MulticlassAUROC(num_classes=N_CLS, thresholds=16, validate_args=False),
            }
        )

    eager, coll = mk(), mk()
    buffered = coll.buffered(window=4)
    for i in range(preds.shape[0]):
        eager.update(preds[i], target[i])
        buffered.update(preds[i], target[i])
    assert any(len(g) > 1 for g in coll.compute_groups.values())  # acc+f1 merged
    ev, bv = eager.compute(), buffered.compute()
    for k in ev:
        np.testing.assert_array_equal(np.asarray(ev[k]), np.asarray(bv[k]), err_msg=k)
    # group members observe the flush through the shared state dict
    for members in coll._groups.values():
        rep = coll._metrics[members[0]]
        for name in members[1:]:
            assert coll._metrics[name].__dict__["_state"] is rep.__dict__["_state"]


def test_buffered_collection_window_dispatches():
    preds, target = _cls_data()
    coll = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=N_CLS, average="macro", validate_args=False),
        }
    )
    buffered = coll.buffered(window=4)
    buffered.update(preds[0], target[0])  # eager group discovery
    for i in range(1, 5):  # warm the flush executable (one full window)
        buffered.update(preds[i], target[i])
    before = M.executable_cache_stats()["dispatches"]
    for i in range(5, 9):  # 4 staged steps -> exactly one scanned flush
        buffered.update(preds[i], target[i])
    assert M.executable_cache_stats()["dispatches"] - before == 1
    assert buffered.pending == 0


def test_buffered_collection_reset_and_observation():
    preds, target = _cls_data()
    coll = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=N_CLS, average="macro", validate_args=False),
        }
    )
    buffered = coll.buffered(window=8)
    for i in range(3):
        buffered.update(preds[i], target[i])
    assert buffered.pending == 2  # step 0 was the eager discovery update
    # observation through the COLLECTION (items() walks member state) flushes
    dict(coll.items())
    assert buffered.pending == 0
    buffered.update(preds[3], target[3])
    coll.reset()
    assert buffered.pending == 0
    # post-reset: stage a fresh epoch and match an eager twin bitwise
    eager = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=N_CLS, average="macro", validate_args=False),
        }
    )
    for i in range(4):
        eager.update(preds[i], target[i])
        buffered.update(preds[i], target[i])
    ev, bv = eager.compute(), buffered.compute()
    for k in ev:
        np.testing.assert_array_equal(np.asarray(ev[k]), np.asarray(bv[k]), err_msg=k)


def test_buffered_collection_pickle_roundtrip():
    preds, target = _cls_data()
    coll = MetricCollection(
        {"acc": MulticlassAccuracy(num_classes=N_CLS, average="micro", validate_args=False)}
    )
    buffered = coll.buffered(window=4)
    for i in range(3):
        buffered.update(preds[i], target[i])
    clone = pickle.loads(pickle.dumps(buffered))
    assert isinstance(clone, BufferedMetricCollection)
    assert clone.pending == 0 and clone.window == 4
    np.testing.assert_array_equal(
        np.asarray(clone.compute()["acc"]), np.asarray(coll.compute()["acc"])
    )


# ---------------------------------------------------------- donation safety
def test_donation_safety_across_cycles():
    data = _batches(steps=12, seed=7)
    eager, m = MeanMetric(), MeanMetric()
    buffered = m.buffered(window=4)
    for cycle in range(3):  # update -> flush -> compute -> reset, repeatedly
        for x in data[cycle * 4 : cycle * 4 + 4]:
            eager.update(x)
            buffered.update(x)
        np.testing.assert_array_equal(
            np.asarray(eager.compute()), np.asarray(buffered.compute())
        )
        eager.reset()
        buffered.reset()
    # defaults must have survived three rounds of donated flushes
    buffered.update(jnp.asarray([1.0]))
    assert float(buffered.compute()) == 1.0


def test_forward_flushes_and_returns_batch_value():
    data = _batches(steps=4, seed=9)
    eager, m = MeanMetric(), MeanMetric()
    buffered = m.buffered(window=8)
    for x in data[:3]:
        eager.update(x)
        buffered.update(x)
    expected_batch = eager.forward(data[3])
    got_batch = buffered.forward(data[3])
    assert buffered.pending == 0
    np.testing.assert_array_equal(np.asarray(expected_batch), np.asarray(got_batch))
    _assert_state_bitwise(eager, buffered)


# ---------------------------------------------------------------- validation
@pytest.mark.parametrize("window", [0, -1, 2.5, True], ids=["zero", "neg", "float", "bool"])
def test_invalid_window_raises(window):
    with pytest.raises(ValueError):
        SumMetric().buffered(window=window)


def test_non_jittable_metric_raises():
    m = CatMetric(nan_strategy="ignore")  # dynamic-shape filter: _use_jit=False
    with pytest.raises(TorchMetricsUserError):
        m.buffered(window=4)


def test_rebuffering_flushes_prior_handle():
    m = SumMetric()
    first = m.buffered(window=8)
    first.update(jnp.asarray([3.0]))
    second = m.buffered(window=4)
    assert first.pending == 0  # drained when the new handle took over
    second.update(jnp.asarray([4.0]))
    assert float(second.compute()) == 7.0
