"""SDR (BSS-eval style) + SA-SDR.

Parity targets: reference ``functional/audio/sdr.py:28-200`` (FFT
autocorrelation → symmetric Toeplitz system → solve for the optimal
distortion filter → coherence → dB) and ``:242``
(source-aggregated SI-SDR).

TPU note: the Toeplitz solve is a batched (filter_length x filter_length)
dense ``jnp.linalg.solve`` — static shape, maps to the MXU; the FFTs are
power-of-two rffts.
"""
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .snr import _EPS, _check_same_shape

Array = jax.Array


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix from its first row, batched. Parity: ``sdr.py:28``."""
    v_len = vector.shape[-1]
    idx = jnp.abs(jnp.arange(v_len)[:, None] - jnp.arange(v_len)[None, :])
    return vector[..., idx]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int):
    """FFT-based autocorrelation of target + crosscorrelation with preds.

    Parity: ``sdr.py:57-86``.
    """
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR with optimal length-L distortion filter. Parity: ``sdr.py:105``.

    ``use_cg_iter`` is accepted for API parity; the dense Toeplitz solve is
    always used (XLA batches it onto the MXU, so CG offers no win here).
    """
    _check_same_shape(preds, target)
    # the Toeplitz solve is precision-sensitive (the reference recommends
    # float64 for torch); low-precision inputs compute in f32 here
    preds = preds.astype(jnp.promote_types(preds.dtype, jnp.float32))
    target = target.astype(preds.dtype)
    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)
    target = target / jnp.maximum(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-6)
    preds = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)
    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)
    r = _symmetric_toeplitz(r_0)
    # the LU solve's internal dot_generals follow the ambient matmul
    # precision; without this pin TPU lowers them to bf16 and the
    # distortion ratio drifts at the 1e-3 level
    with jax.default_matmul_precision("highest"):
        sol = jnp.linalg.solve(r, b[..., None])[..., 0]
    coh = jnp.sum(b * sol, axis=-1)
    ratio = coh / jnp.maximum(1.0 - coh, 1e-12)
    return 10.0 * jnp.log10(jnp.maximum(ratio, 1e-12))


def source_aggregated_signal_distortion_ratio(
    preds: Array, target: Array, scale_invariant: bool = True, zero_mean: bool = False
) -> Array:
    """SA-SDR over (..., spk, time). Parity: ``sdr.py:242``."""
    _check_same_shape(preds, target)
    if preds.ndim < 2:
        raise RuntimeError(f"The preds and target should have the shape (..., spk, time), but {preds.shape} found")
    # f16 sums of squares over the time axis overflow; accumulate in f32
    preds = preds.astype(jnp.promote_types(preds.dtype, jnp.float32))
    target = target.astype(preds.dtype)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    if scale_invariant:
        alpha = (jnp.sum(jnp.sum(preds * target, axis=-1, keepdims=True), axis=-2, keepdims=True) + _EPS) / (
            jnp.sum(jnp.sum(target**2, axis=-1, keepdims=True), axis=-2, keepdims=True) + _EPS
        )
        target = alpha * target
    distortion = target - preds
    val = (jnp.sum(jnp.sum(target**2, axis=-1), axis=-1) + _EPS) / (
        jnp.sum(jnp.sum(distortion**2, axis=-1), axis=-1) + _EPS
    )
    return 10.0 * jnp.log10(val)
