"""Internal plumbing: one-shot functional metrics over the stat-scores engine.

The reference repeats the validate→format→update→reduce pipeline verbatim in
every consumer file (~1000 LoC each); here it is written once and
parameterized by the reduce function — less code, identical semantics, and
each public wrapper stays a single jittable call.
"""
from typing import Callable, Optional

import jax

from .stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)

Array = jax.Array


def _binary_stat_metric(
    preds: Array,
    target: Array,
    reduce_fn: Callable,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, multidim_average)
    return reduce_fn(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def _multiclass_stat_metric(
    preds: Array,
    target: Array,
    reduce_fn: Callable,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    tp, fp, tn, fn = _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, multidim_average, ignore_index
    )
    return reduce_fn(tp, fp, tn, fn, average=average, multidim_average=multidim_average, top_k=top_k)


def _multilabel_stat_metric(
    preds: Array,
    target: Array,
    reduce_fn: Callable,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, multidim_average)
    return reduce_fn(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)
