"""Running — sliding window over the last ``window`` update states.

Parity: reference ``src/torchmetrics/wrappers/running.py:27`` (update :106,
compute :126): keeps per-update batch-state snapshots; compute merges the
window's states and runs the base compute.
"""
from collections import deque
from copy import deepcopy
from typing import Any

import jax

from ..metric import Metric, _squeeze_if_scalar
from .abstract import WrapperMetric

Array = jax.Array


class Running(WrapperMetric):
    """Running.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import Running, SumMetric
        >>> metric = Running(SumMetric(), window=2)
        >>> _ = metric(jnp.asarray([1.0]))
        >>> _ = metric(jnp.asarray([2.0]))
        >>> _ = metric(jnp.asarray([3.0]))
        >>> float(metric.compute())
        5.0
    """
    def __init__(self, base_metric: Metric, window: int = 5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        if base_metric.full_state_update:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self._window_states: deque = deque(maxlen=window)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Compute this batch's state from defaults and push onto the window."""
        m = self.base_metric
        batch_state = m.update_state(m.init_state(), *args, **kwargs)
        self._window_states.append(batch_state)

    def _merged_window_state(self):
        states = list(self._window_states)
        if not states:
            return self.base_metric.init_state()
        if len(states) == 1:
            return states[0]
        return self.base_metric.merge_states(states)

    def compute(self) -> Any:
        return self.base_metric.compute_state(self._merged_window_state())

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self.update(*args, **kwargs)
        return self.base_metric.compute_state(self._window_states[-1])

    def reset(self) -> None:
        super().reset()
        self._window_states.clear()
        self.base_metric.reset()
