"""Chunked (long-context) BERTScore must match the dense kernel exactly,
and checkpoint save/restore must round-trip metric state."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.bert import (
    bert_score_from_embeddings,
    bert_score_from_embeddings_chunked,
)


@pytest.mark.parametrize("lp,lt,chunk", [(7, 13, 4), (16, 100, 32), (5, 5, 8)])
def test_chunked_matches_dense(lp, lt, chunk):
    rng = np.random.RandomState(lp * lt)
    b, d = 3, 16
    pe = jnp.asarray(rng.randn(b, lp, d), jnp.float32)
    te = jnp.asarray(rng.randn(b, lt, d), jnp.float32)
    pm = jnp.asarray(rng.rand(b, lp) > 0.2, jnp.float32)
    tm = jnp.asarray(rng.rand(b, lt) > 0.2, jnp.float32)
    p_idf = jnp.asarray(rng.rand(b, lp), jnp.float32)
    t_idf = jnp.asarray(rng.rand(b, lt), jnp.float32)

    dense = bert_score_from_embeddings(pe, pm, te, tm, p_idf, t_idf)
    chunked = jax.jit(
        lambda *a: bert_score_from_embeddings_chunked(*a, chunk_size=chunk)
    )(pe, pm, te, tm, p_idf, t_idf)
    for k in ("precision", "recall", "f1"):
        np.testing.assert_allclose(np.asarray(dense[k]), np.asarray(chunked[k]), atol=1e-5, err_msg=k)


def test_checkpoint_round_trip(tmp_path):
    import torchmetrics_tpu as tm
    from torchmetrics_tpu.utils.checkpoint import restore_metric_state, save_metric_state

    m = tm.classification.MulticlassAccuracy(num_classes=4)
    m.update(jnp.asarray([0, 1, 2, 3]), jnp.asarray([0, 1, 1, 3]))
    expected = float(m.compute())
    path = save_metric_state(str(tmp_path / "acc_state"), m)
    fresh = tm.classification.MulticlassAccuracy(num_classes=4)
    restore_metric_state(path, fresh)
    assert float(fresh.compute()) == expected

    # collection + a cat-list state metric
    coll = tm.MetricCollection({"acc": tm.classification.MulticlassAccuracy(num_classes=4),
                                "cat": tm.CatMetric()})
    coll["acc"].update(jnp.asarray([0, 1]), jnp.asarray([0, 0]))
    coll["cat"].update(jnp.asarray([1.0, 2.0]))
    coll["cat"].update(jnp.asarray([3.0]))
    path2 = save_metric_state(str(tmp_path / "coll_state"), coll)
    coll2 = tm.MetricCollection({"acc": tm.classification.MulticlassAccuracy(num_classes=4),
                                 "cat": tm.CatMetric()})
    restore_metric_state(path2, coll2)
    np.testing.assert_allclose(np.asarray(coll2["cat"].compute()), [1.0, 2.0, 3.0])
    assert float(coll2["acc"].compute()) == float(coll["acc"].compute())
