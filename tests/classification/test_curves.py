"""Curve engine (PR-curve / ROC / AUROC / AP) vs sklearn.

Parity model: reference ``tests/unittests/classification/test_auroc.py`` etc.
"""
import numpy as np
import pytest
from sklearn import metrics as skm

import jax.numpy as jnp

from tests.conftest import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES
from tests.helpers.testers import MetricTester

from torchmetrics_tpu.classification import (
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryPrecisionRecallCurve,
    BinaryROC,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MultilabelAUROC,
)

seed = np.random.RandomState(11)
BIN_PROBS = seed.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
BIN_TARGET = seed.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
MC_PROBS = seed.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32)
MC_PROBS /= MC_PROBS.sum(-1, keepdims=True)
MC_TARGET = seed.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
NUM_LABELS = 4
ML_PROBS = seed.rand(NUM_BATCHES, BATCH_SIZE, NUM_LABELS).astype(np.float32)
ML_TARGET = seed.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_LABELS))


class TestBinaryCurves(MetricTester):
    def test_auroc_exact(self):
        self.run_class_metric_test(
            BIN_PROBS, BIN_TARGET, BinaryAUROC, lambda p, t: skm.roc_auc_score(t, p),
            metric_args={"thresholds": None}, ddp=True, check_batch=True,
        )

    def test_auroc_binned_close(self):
        m = BinaryAUROC(thresholds=500)
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
        ref = skm.roc_auc_score(BIN_TARGET.reshape(-1), BIN_PROBS.reshape(-1))
        assert abs(float(m.compute()) - ref) < 5e-3

    def test_auroc_binned_shard_map(self):
        # binned state is sum-reducible → psum path
        self.atol = 5e-3
        self.rtol = 5e-3
        self.run_shard_map_test(
            BIN_PROBS, BIN_TARGET, BinaryAUROC, lambda p, t: skm.roc_auc_score(t, p),
            metric_args={"thresholds": 500},
        )
        self.atol = self.rtol = 1e-5

    def test_average_precision_exact(self):
        self.run_class_metric_test(
            BIN_PROBS, BIN_TARGET, BinaryAveragePrecision,
            lambda p, t: skm.average_precision_score(t, p), check_batch=True,
        )

    def test_pr_curve_exact(self):
        m = BinaryPrecisionRecallCurve()
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
        prec, rec, thr = m.compute()
        sp, sr, st = skm.precision_recall_curve(BIN_TARGET.reshape(-1), BIN_PROBS.reshape(-1))
        np.testing.assert_allclose(np.asarray(prec), sp, atol=1e-6)
        np.testing.assert_allclose(np.asarray(rec), sr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(thr), st, atol=1e-6)

    def test_roc_exact(self):
        m = BinaryROC()
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(BIN_TARGET[i]))
        fpr, tpr, _ = m.compute()
        sf, st, _ = skm.roc_curve(BIN_TARGET.reshape(-1), BIN_PROBS.reshape(-1), drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sf, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), st, atol=1e-6)

    def test_ignore_index(self):
        t2 = BIN_TARGET.copy()
        t2[:, :4] = -1
        m = BinaryAUROC(ignore_index=-1)
        for i in range(NUM_BATCHES):
            m.update(jnp.asarray(BIN_PROBS[i]), jnp.asarray(t2[i]))
        valid = t2.reshape(-1) != -1
        ref = skm.roc_auc_score(t2.reshape(-1)[valid], BIN_PROBS.reshape(-1)[valid])
        np.testing.assert_allclose(float(m.compute()), ref, atol=1e-6)


class TestMulticlassCurves(MetricTester):
    def test_auroc(self):
        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassAUROC,
            lambda p, t: skm.roc_auc_score(t, p, multi_class="ovr", average="macro", labels=range(NUM_CLASSES)),
            metric_args={"num_classes": NUM_CLASSES}, check_batch=False,
        )

    def test_average_precision(self):
        def sk(p, t):
            oh = np.eye(NUM_CLASSES)[t]
            return np.mean([skm.average_precision_score(oh[:, c], p[:, c]) for c in range(NUM_CLASSES)])

        self.run_class_metric_test(
            MC_PROBS, MC_TARGET, MulticlassAveragePrecision, sk,
            metric_args={"num_classes": NUM_CLASSES}, check_batch=False,
        )


class TestMultilabelCurves(MetricTester):
    def test_auroc(self):
        def sk(p, t):
            return skm.roc_auc_score(t.reshape(-1, NUM_LABELS), p.reshape(-1, NUM_LABELS), average="macro")

        self.run_class_metric_test(
            ML_PROBS, ML_TARGET, MultilabelAUROC, sk,
            metric_args={"num_labels": NUM_LABELS}, check_batch=False,
        )
