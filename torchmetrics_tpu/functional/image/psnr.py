"""Peak signal-to-noise ratio.

Parity: reference ``src/torchmetrics/functional/image/psnr.py`` (154 LoC).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape

Array = jax.Array


def _psnr_update(
    preds: Array, target: Array, dim: Optional[Union[int, Tuple[int, ...]]] = None
) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    diff = preds - target
    if dim is None:
        sum_squared_error = jnp.sum(diff * diff)
        num_obs = jnp.asarray(target.size, dtype=jnp.float32)
    else:
        sum_squared_error = jnp.sum(diff * diff, axis=dim)
        num_obs = jnp.asarray(
            jnp.prod(jnp.asarray([target.shape[d] for d in (dim if isinstance(dim, tuple) else (dim,))])),
            dtype=jnp.float32,
        )
        num_obs = jnp.broadcast_to(num_obs, sum_squared_error.shape)
    return sum_squared_error, num_obs


def _psnr_compute(
    sum_squared_error: Array,
    num_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: str = "elementwise_mean",
) -> Array:
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / num_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(base))
    if reduction == "elementwise_mean":
        return jnp.mean(psnr_vals)
    if reduction == "sum":
        return jnp.sum(psnr_vals)
    return psnr_vals


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    base: float = 10.0,
    reduction: str = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """Parity: reference ``psnr.py:92``."""
    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is set.")
        data_range = jnp.max(target) - jnp.min(target)
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = jnp.asarray(data_range[1] - data_range[0])
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, num_obs = _psnr_update(preds, target, dim)
    return _psnr_compute(sum_squared_error, num_obs, data_range, base, reduction)
