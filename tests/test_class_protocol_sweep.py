"""Universal protocol sweep over EVERY root-exported metric class.

The reference's ``MetricTester`` enforces per-metric protocol invariants
(``tests/unittests/_helpers/testers.py:126-204``): constructability, pickle
round-trip, ``clone()`` independence, constancy of the metadata flags, and
empty ``state_dict`` by default. This sweep applies those invariants to the
whole L6 surface at once, so adding a class that breaks the core protocol
fails CI even before a domain test exists for it.
"""
import inspect
import pickle

import numpy as np
import pytest

import jax.numpy as jnp

import torchmetrics_tpu as M
import torchmetrics_tpu.classification as MC
from torchmetrics_tpu.metric import Metric

# default values for common required constructor params
COMMON = {
    "num_classes": 5,
    "num_labels": 4,
    "num_groups": 2,
    "num_outputs": 2,
    "fs": 8000,
    "mode": "nb",
    "task": "multiclass",
    "min_recall": 0.5,
    "min_precision": 0.5,
    "min_specificity": 0.5,
    "min_sensitivity": 0.5,
    "p": 2.0,
}


def _dummy_feature_net(imgs):
    return jnp.mean(jnp.asarray(imgs, jnp.float32).reshape(imgs.shape[0], -1), axis=-1, keepdims=True) * jnp.ones((1, 8))


def _dummy_distance(a, b):
    return jnp.mean((jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32)) ** 2, axis=tuple(range(1, a.ndim)))


def _dummy_logits_net(imgs):
    return jnp.ones((imgs.shape[0], 10)) / 10


# lazy factories: each entry constructs its own helper metrics so one bad
# constructor can't poison every parametrized case
EXTRA = {
    "FrechetInceptionDistance": lambda: {"feature": _dummy_feature_net},
    "KernelInceptionDistance": lambda: {"feature": _dummy_feature_net, "subset_size": 4},
    "MemorizationInformedFrechetInceptionDistance": lambda: {"feature": _dummy_feature_net},
    "InceptionScore": lambda: {"feature": _dummy_logits_net},
    "LearnedPerceptualImagePatchSimilarity": lambda: {"net_type": _dummy_distance},
    "PerceptualPathLength": lambda: {"distance_fn": _dummy_distance},
    "PermutationInvariantTraining": lambda: {"metric_func": _dummy_distance},
    "MetricCollection": lambda: {"metrics": {"mse": M.MeanSquaredError()}},
    "MetricTracker": lambda: {"metric": M.MeanSquaredError()},
    "MinMaxMetric": lambda: {"base_metric": M.MeanSquaredError()},
    "MultioutputWrapper": lambda: {"base_metric": M.MeanSquaredError(), "num_outputs": 2},
    "MultitaskWrapper": lambda: {"task_metrics": {"t": M.MeanSquaredError()}},
    "Running": lambda: {"base_metric": M.SumMetric(), "window": 3},
    "BootStrapper": lambda: {"base_metric": M.MeanSquaredError(), "num_bootstraps": 3},
    "ClasswiseWrapper": lambda: {"metric": MC.MulticlassAccuracy(num_classes=5, average="none")},
    "ModifiedPanopticQuality": lambda: {"things": {0, 1}, "stuffs": {2}},
    "PanopticQuality": lambda: {"things": {0, 1}, "stuffs": {2}},
    "MinkowskiDistance": lambda: {"p": 2.0},
    "Dice": lambda: {"num_classes": 5},
    "FeatureShare": lambda: {"metrics": [M.MeanSquaredError()]},
}


def _build(name):
    obj = getattr(M, name)
    extra = EXTRA.get(name)
    if extra is not None:
        return obj(**extra())
    target = obj.__new__ if obj.__new__ is not object.__new__ else obj.__init__
    try:
        sig = inspect.signature(target)
    except (ValueError, TypeError):
        return obj()
    kwargs = {}
    params = list(sig.parameters.values())[1:]
    for p in params:
        if p.default is not inspect.Parameter.empty or p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            continue
        if p.name in COMMON:
            kwargs[p.name] = COMMON[p.name]
        else:
            pytest.skip(f"{name}: no default for required arg {p.name!r}")
    if kwargs.get("task") == "multiclass" and any(p.name == "num_classes" for p in params):
        kwargs["num_classes"] = COMMON["num_classes"]  # task facades default it to None
    return obj(**kwargs)


CLASS_NAMES = sorted(n for n in M.__all__ if isinstance(getattr(M, n), type))


@pytest.mark.parametrize("name", CLASS_NAMES)
def test_class_protocol(name):
    try:
        m = _build(name)
    except OSError:
        # embedding-network metrics (CLIP*) fetch pretrained weights at
        # construction; offline this is a connection failure, mirroring the
        # reference's skip_on_connection_issues test wrapper
        pytest.skip(f"{name}: pretrained weights unavailable offline")
    if not isinstance(m, Metric):
        pytest.skip(f"{name} is not a Metric subclass")

    # metadata flags exist and are locked (reference metric.py:715-726)
    for flag in ("is_differentiable", "higher_is_better", "full_state_update"):
        assert hasattr(m, flag), f"{name} missing {flag}"
    with pytest.raises(Exception):
        m.is_differentiable = True

    # empty state_dict by default (states are non-persistent, metric.py:834)
    assert dict(m.state_dict()) == {}, f"{name} leaks states into state_dict"

    # pickle round-trip preserves class and state names
    m2 = pickle.loads(pickle.dumps(m))
    assert type(m2) is type(m)
    assert list(m2.metric_state.keys()) == list(m.metric_state.keys())

    # clone() is deep: mutating the clone's state leaves the original intact
    c = m.clone()
    assert type(c) is type(m)
    assert list(c.metric_state.keys()) == list(m.metric_state.keys())

    # reset() leaves states at defaults and is idempotent
    m.reset()
    state_a = {k: v for k, v in m.metric_state.items()}
    m.reset()
    for k, v in m.metric_state.items():
        a, b = state_a[k], v
        if isinstance(a, list):
            assert isinstance(b, list) and len(a) == len(b)
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
