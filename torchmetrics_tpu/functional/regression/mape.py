"""Mean absolute percentage error (+ symmetric & weighted variants).

Parity: reference ``src/torchmetrics/functional/regression/{mape,symmetric_mape,
wmape}.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape

Array = jax.Array
_EPS = 1.17e-06  # matches reference epsilon (torch.finfo(float32).eps scale)


def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = _EPS
) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), min=epsilon)
    return jnp.sum(abs_per_error), jnp.asarray(target.size, dtype=jnp.float32)


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Parity: reference ``mape.py:51``."""
    s, n = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(s, n)


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = _EPS
) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    abs_per_error = 2 * jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), min=epsilon)
    return jnp.sum(abs_per_error), jnp.asarray(target.size, dtype=jnp.float32)


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Parity: reference ``symmetric_mape.py:51``."""
    s, n = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return s / n


def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    return jnp.sum(jnp.abs(preds - target)), jnp.sum(jnp.abs(target))


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Parity: reference ``wmape.py:48``."""
    num, denom = _weighted_mean_absolute_percentage_error_update(preds, target)
    return num / jnp.clip(denom, min=_EPS)
