"""MeanAveragePrecision (COCO mAP / mAR).

Parity target: reference ``detection/mean_ap.py`` (states ``:442-450``, args
``:375``, compute ``:513-590``, stats order from COCOeval ``summarize``).
The reference shells out to the pycocotools C extension; this build owns the
COCO protocol in ``functional/detection/coco_eval.py`` (numpy host core,
JAX-kernel IoU available for large batches, optional C++ fast path).

States are ragged per-image arrays kept as host list states
(``dist_reduce_fx=None`` in the reference; object-gather across processes).
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .. import _native
from ..functional.detection.coco_eval import (
    DEFAULT_IOU_THRESHOLDS,
    DEFAULT_MAX_DETS,
    DEFAULT_REC_THRESHOLDS,
    evaluate_detections,
    summarize,
)
from ..metric import Metric
from .iou import _input_validator


def _validate_iou_type_arg(iou_type: Union[str, Tuple[str, ...]]) -> Tuple[str, ...]:
    allowed = ("bbox", "segm")
    if isinstance(iou_type, str):
        iou_type = (iou_type,)
    if any(tp not in allowed for tp in iou_type):
        raise ValueError(f"Expected argument `iou_type` to be one of {allowed} or a list of, but got {iou_type}")
    return tuple(iou_type)


class MeanAveragePrecision(Metric):
    """COCO-protocol mean average precision / recall for object detection.

    Accepts ``preds``/``target`` as lists of per-image dicts (``boxes``,
    ``scores``, ``labels``, optional ``masks``/``iscrowd``/``area``), exactly
    like the reference (``detection/mean_ap.py:92-148``). Output dict keys:
    ``map, map_50, map_75, map_{small,medium,large}, mar_{maxdets...},
    mar_{small,medium,large}, map_per_class, mar_<last>_per_class, classes``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanAveragePrecision
        >>> metric = MeanAveragePrecision()
        >>> preds = [{"boxes": jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), "scores": jnp.asarray([0.9]), "labels": jnp.asarray([0])}]
        >>> target = [{"boxes": jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), "labels": jnp.asarray([0])}]
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()["map"]), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = True
    jittable = False  # ragged host states; IoU kernels vectorized internally

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: Union[str, Tuple[str, ...]] = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        extended_summary: bool = False,
        average: str = "macro",
        backend: str = "native",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if box_format not in ("xyxy", "xywh", "cxcywh"):
            raise ValueError(f"Expected argument `box_format` to be one of ('xyxy', 'xywh', 'cxcywh') but got {box_format}")
        self.box_format = box_format
        self.iou_type = _validate_iou_type_arg(iou_type)
        if iou_thresholds is not None and not isinstance(iou_thresholds, (list, tuple)):
            raise ValueError(f"Expected argument `iou_thresholds` to either be `None` or a list of floats but got {iou_thresholds}")
        if rec_thresholds is not None and not isinstance(rec_thresholds, (list, tuple)):
            raise ValueError(f"Expected argument `rec_thresholds` to either be `None` or a list of floats but got {rec_thresholds}")
        if max_detection_thresholds is not None and not isinstance(max_detection_thresholds, (list, tuple)):
            raise ValueError(f"Expected argument `max_detection_thresholds` to either be `None` or a list of ints but got {max_detection_thresholds}")
        self.iou_thresholds = list(iou_thresholds) if iou_thresholds is not None else DEFAULT_IOU_THRESHOLDS.tolist()
        self.rec_thresholds = list(rec_thresholds) if rec_thresholds is not None else DEFAULT_REC_THRESHOLDS.tolist()
        self.max_detection_thresholds = sorted(
            max_detection_thresholds if max_detection_thresholds is not None else DEFAULT_MAX_DETS
        )
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(extended_summary, bool):
            raise ValueError("Expected argument `extended_summary` to be a boolean")
        self.extended_summary = extended_summary
        if average not in ("macro", "micro"):
            raise ValueError(f"Expected argument `average` to be one of ('macro', 'micro') but got {average}")
        self.average = average
        if backend not in ("native", "cpp"):
            raise ValueError(f"Expected argument `backend` to be one of ('native', 'cpp') but got {backend}")
        self.backend = backend  # "native" numpy/JAX core; "cpp" compiled fast path
        self._compute_jittable = False

        self.add_state("detection_box", [], dist_reduce_fx=None)
        self.add_state("detection_mask", [], dist_reduce_fx=None)
        self.add_state("detection_scores", [], dist_reduce_fx=None)
        self.add_state("detection_labels", [], dist_reduce_fx=None)
        self.add_state("groundtruth_box", [], dist_reduce_fx=None)
        self.add_state("groundtruth_mask", [], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", [], dist_reduce_fx=None)
        self.add_state("groundtruth_crowds", [], dist_reduce_fx=None)
        self.add_state("groundtruth_area", [], dist_reduce_fx=None)

    def update(self, preds: List[Dict[str, Any]], target: List[Dict[str, Any]]) -> None:
        """Append per-image detections/groundtruths; parity ``mean_ap.py:470``."""
        for tp in self.iou_type:
            _input_validator(preds, target, iou_type=tp)
        for item in preds:
            self.detection_box.append(self._boxes_xyxy(item) if "bbox" in self.iou_type else np.zeros((0, 4)))
            self.detection_mask.append(self._masks(item) if "segm" in self.iou_type else None)
            self.detection_scores.append(np.asarray(item["scores"], np.float64).reshape(-1))
            self.detection_labels.append(np.asarray(item["labels"]).reshape(-1).astype(np.int64))
        for item in target:
            self.groundtruth_box.append(self._boxes_xyxy(item) if "bbox" in self.iou_type else np.zeros((0, 4)))
            self.groundtruth_mask.append(self._masks(item) if "segm" in self.iou_type else None)
            labels = np.asarray(item["labels"]).reshape(-1).astype(np.int64)
            self.groundtruth_labels.append(labels)
            crowds = np.asarray(item.get("iscrowd", np.zeros(len(labels)))).reshape(-1).astype(np.int64)
            self.groundtruth_crowds.append(crowds)
            area = np.asarray(item.get("area", np.zeros(0, np.float64))).reshape(-1).astype(np.float64)
            self.groundtruth_area.append(area)

    def _boxes_xyxy(self, item: Dict[str, Any]) -> np.ndarray:
        boxes = np.asarray(item["boxes"], np.float64)
        if boxes.size == 0:
            return np.zeros((0, 4), np.float64)
        boxes = boxes.reshape(-1, 4)
        # convert in float64 numpy: routing through 32-bit JAX here could
        # flip a borderline IoU exactly at an evaluation threshold
        if self.box_format == "xywh":
            x, y, w, h = boxes.T
            boxes = np.stack([x, y, x + w, y + h], axis=1)
        elif self.box_format == "cxcywh":
            cx, cy, w, h = boxes.T
            boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
        return boxes

    @staticmethod
    def _masks(item: Dict[str, Any]):
        """Dense (N, H, W) boolean masks, or COCO RLE dicts kept as-is.

        The reference accepts RLE-encoded masks (``detection/mean_ap.py``
        update path gathers RLE tuples); here RLEs stay encoded end-to-end —
        pairwise IoU runs directly on run-lengths in the native kernel
        (``_native.rle_iou``), never decoding to dense.
        """
        masks = item["masks"]
        if isinstance(masks, (list, tuple)) and len(masks) and isinstance(masks[0], dict):
            out = []
            for m in masks:
                counts = m["counts"]
                if isinstance(counts, (bytes, str)):  # pycocotools compressed form
                    counts = _native.rle_from_coco_string(counts)
                out.append({"size": tuple(m["size"]), "counts": np.asarray(counts, np.uint32)})
            return out
        masks = np.asarray(masks)
        if masks.size == 0:
            return np.zeros((0, 1, 1), bool)
        return masks.astype(bool)

    def _get_classes(self) -> List[int]:
        classes = set()
        for lab in self.detection_labels:
            classes.update(np.asarray(lab).tolist())
        for lab in self.groundtruth_labels:
            classes.update(np.asarray(lab).tolist())
        return sorted(int(c) for c in classes)

    def compute(self) -> Dict[str, Any]:
        result: Dict[str, Any] = {}
        n_img = len(self.detection_labels)
        for i_type in self.iou_type:
            prefix = "" if len(self.iou_type) == 1 else f"{i_type}_"
            dets, gts = [], []
            for i in range(n_img):
                d = {"scores": self.detection_scores[i], "labels": self.detection_labels[i]}
                g = {
                    "labels": self.groundtruth_labels[i],
                    "iscrowd": self.groundtruth_crowds[i],
                    "area": self.groundtruth_area[i],
                }
                if i_type == "bbox":
                    d["boxes"] = self.detection_box[i]
                    g["boxes"] = self.groundtruth_box[i]
                else:
                    d["masks"] = self.detection_mask[i]
                    g["masks"] = self.groundtruth_mask[i]
                dets.append(d)
                gts.append(g)

            ev = evaluate_detections(
                dets,
                gts,
                iou_type=i_type,
                iou_thresholds=np.asarray(self.iou_thresholds),
                rec_thresholds=np.asarray(self.rec_thresholds),
                max_dets=self.max_detection_thresholds,
                class_agnostic=self.average == "micro",
            )
            summ = summarize(ev)
            for key in ("map", "map_50", "map_75", "map_small", "map_medium", "map_large",
                        "mar_small", "mar_medium", "mar_large"):
                result[f"{prefix}{key}"] = jnp.asarray(summ[key], jnp.float32)
            for md in self.max_detection_thresholds:
                result[f"{prefix}mar_{md}"] = jnp.asarray(summ[f"mar_{md}"], jnp.float32)

            if self.extended_summary:
                result[f"{prefix}ious"] = {
                    k: jnp.asarray(v, jnp.float32) for k, v in ev["ious"].items()
                }
                result[f"{prefix}precision"] = jnp.asarray(ev["precision"], jnp.float32)
                result[f"{prefix}recall"] = jnp.asarray(ev["recall"], jnp.float32)
                result[f"{prefix}scores"] = jnp.asarray(ev["scores"], jnp.float32)

            last_md = self.max_detection_thresholds[-1]
            if self.class_metrics:
                if self.average == "micro":
                    # per-class numbers require a macro pass (reference :555-560)
                    ev = evaluate_detections(
                        dets, gts, iou_type=i_type,
                        iou_thresholds=np.asarray(self.iou_thresholds),
                        rec_thresholds=np.asarray(self.rec_thresholds),
                        max_dets=self.max_detection_thresholds,
                        class_agnostic=False,
                    )
                    summ = summarize(ev)
                result[f"{prefix}map_per_class"] = jnp.asarray(summ["map_per_class"], jnp.float32)
                result[f"{prefix}mar_{last_md}_per_class"] = jnp.asarray(summ["mar_per_class"], jnp.float32)
            else:
                result[f"{prefix}map_per_class"] = jnp.asarray([-1.0], jnp.float32)
                result[f"{prefix}mar_{last_md}_per_class"] = jnp.asarray([-1.0], jnp.float32)
        result["classes"] = jnp.asarray(self._get_classes(), jnp.int32)
        return result
