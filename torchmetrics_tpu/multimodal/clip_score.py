"""Modular CLIPScore.

Parity: reference ``multimodal/clip_score.py`` (303 LoC): ``score``/
``n_samples`` sum states (``:130-131``), compute = clamp(score/n, min=0)
(``:261-263``).
"""
from typing import Any, Tuple, Union

import jax.numpy as jnp

from ..functional.multimodal.clip_score import _DEFAULT_MODEL, _clip_score_update, _resolve_model
from ..metric import Metric


class CLIPScore(Metric):
    """CLIP image/text (or image/image, text/text) alignment score.

    Parity: reference ``multimodal/clip_score.py`` — score is
    ``max(100 * cosine, 0)`` averaged over pairs. ``model_name_or_path``
    takes a HF name (resolved via transformers' Flax CLIP) or an injected
    ``(model, processor)`` pair for offline use: ``model`` exposes
    ``get_image_features`` / ``get_text_features``, ``processor`` maps
    images/text to arrays.

    Example (tiny injected model):
        >>> import numpy as np
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CLIPScore
        >>> emb = np.abs(np.random.RandomState(7).randn(100, 4)).astype(np.float32)
        >>> class TinyClip:
        ...     def get_image_features(self, pixel_values):
        ...         flat = pixel_values.reshape(pixel_values.shape[0], -1)
        ...         return jnp.stack([flat.mean(1), flat.std(1), flat.min(1), flat.max(1)], axis=1)
        ...     def get_text_features(self, input_ids, attention_mask):
        ...         e = jnp.asarray(emb)[input_ids]
        ...         m = attention_mask[..., None]
        ...         return (e * m).sum(1) / m.sum(1)
        >>> class TinyProcessor:
        ...     def __call__(self, text=None, images=None, return_tensors="np", padding=True):
        ...         if images is not None:
        ...             return {"pixel_values": np.stack([np.asarray(i, np.float32) for i in images])}
        ...         ids = np.zeros((len(text), 4), dtype=np.int32)
        ...         mask = np.zeros((len(text), 4), dtype=np.int32)
        ...         for i, t in enumerate(text):
        ...             toks = [sum(map(ord, w)) % 100 for w in t.split()][:4]
        ...             ids[i, :len(toks)] = toks
        ...             mask[i, :len(toks)] = 1
        ...         return {"input_ids": ids, "attention_mask": mask}
        >>> metric = CLIPScore(model_name_or_path=(TinyClip(), TinyProcessor()))
        >>> imgs = [np.random.RandomState(2).rand(3, 16, 16).astype(np.float32)]
        >>> metric.update(imgs, ["a photo of a cat"])
        >>> round(float(metric.compute()), 1)
        97.2
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0
    feature_network = "model"
    jittable = False  # host tokenizer/processor in update

    def __init__(
        self,
        model_name_or_path: Union[str, Tuple[Any, Any]] = _DEFAULT_MODEL,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model, self.processor = _resolve_model(model_name_or_path, "CLIPScore")
        self.add_state("score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, source, target) -> None:
        """Accumulate 100*cosine similarity over (source, target) pairs."""
        score_sum, n = _clip_score_update(source, target, self.model, self.processor)
        self.score = self.score + score_sum
        self.n_samples = self.n_samples + n

    def compute(self):
        return jnp.maximum(self.score / self.n_samples, 0.0)
