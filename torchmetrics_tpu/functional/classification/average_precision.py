"""Average precision (area under the PR curve, step interpolation).

Parity: reference
``src/torchmetrics/functional/classification/average_precision.py``.
"""
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ...utils.compute import _safe_divide
from .precision_recall_curve import (
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_update,
    Thresholds,
)

Array = jax.Array


def _ap_from_curve(precision: Array, recall: Array) -> Array:
    # recall is decreasing toward 0 along the curve order; curves are 1D
    # (binary / exact-mode per class) or (C, T+1) in binned mode — slice the
    # threshold axis, not the class axis (reference ``:50-53``)
    return -jnp.sum(jnp.diff(recall, axis=-1) * precision[..., :-1], axis=-1)


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]], thresholds: Optional[Array]
) -> Array:
    """Parity: reference ``average_precision.py:45``."""
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds)
    return _ap_from_curve(precision, recall)


def _binary_average_precision_exact(preds: Array, target: Array) -> Array:
    """Exact-mode binary AP with the no-positives nan guard.

    The reference's recall is 0/0 -> nan with no positive samples; our curve
    substitutes the modern-sklearn "recall = 1" convention, so the guard is
    explicit. ``target`` must already be ignore-filtered (values in {0, 1}).
    The single shared helper keeps the functional and class layers from
    drifting (binned mode deliberately returns 0 instead — _safe_divide).
    """
    ap = _binary_average_precision_compute((preds, target), None)
    return jnp.where(jnp.sum(target == 1) > 0, ap, jnp.nan)


def binary_average_precision(
    preds: Array, target: Array, thresholds: Thresholds = None, ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Parity: reference ``average_precision.py:77``.

    With no positive samples the reference's recall is 0/0 and the result is
    ``nan``; reproduced explicitly here since our curve substitutes the
    modern-sklearn "recall = 1" convention.
    """
    preds, target, thr, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        return _binary_average_precision_exact(preds, target)
    # binned mode: the reference's _safe_divide gives recall 0 with no
    # positives, so the result is 0, not nan — reproduced for parity
    state = _binary_precision_recall_curve_update(preds, target, thr, mask)
    return _binary_average_precision_compute(state, thr)


def _reduce_average_precision(precision, recall, average: Optional[str] = "macro", weights=None,
                              exclude_empty: bool = False) -> Array:
    if isinstance(precision, (list, tuple)):
        scores = jnp.stack([_ap_from_curve(p, r) for p, r in zip(precision, recall)])
    else:
        scores = _ap_from_curve(precision, recall)
    if exclude_empty and weights is not None:
        # EXACT mode only: classes with no positive samples have undefined
        # AP (the reference's recall is 0/0 -> nan) and are excluded from
        # macro/weighted averages (reference ``average_precision.py:56-66``).
        # In BINNED mode the reference's ``_safe_divide`` yields recall 0,
        # so empty classes contribute AP 0 and stay IN the average — that
        # asymmetry is reproduced deliberately. jnp.where keeps it jit-safe.
        scores = jnp.where(weights > 0, jnp.nan_to_num(scores, nan=0.0), jnp.nan)
    else:
        scores = jnp.nan_to_num(scores, nan=0.0)
    if average in (None, "none"):
        return scores
    valid = ~jnp.isnan(scores)
    s0 = jnp.where(valid, scores, 0.0)
    if average == "macro":
        # all-nan (no class has positives) -> nan, the reference's mean of
        # an empty tensor — NOT 0.0 (nan is load-bearing for e.g. Tracker)
        n_valid = jnp.sum(valid)
        return jnp.where(n_valid > 0, jnp.sum(s0) / jnp.maximum(n_valid, 1), jnp.nan)
    if average == "weighted":
        w = jnp.where(valid, weights, 0.0)
        w = _safe_divide(w, jnp.sum(w))
        return jnp.sum(s0 * w)
    raise ValueError(f"Received invalid `average` {average}")


def multiclass_average_precision(
    preds: Array, target: Array, num_classes: int, average: Optional[str] = "macro",
    thresholds: Thresholds = None, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Parity: reference ``average_precision.py:178``."""
    preds, target, thr, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        precision, recall, _ = _multiclass_precision_recall_curve_compute((preds, target), num_classes, None)
        support = jnp.sum(jax.nn.one_hot(target, num_classes), axis=0)
        return _reduce_average_precision(precision, recall, average, weights=support, exclude_empty=True)
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thr, mask)
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thr)
    support = (state[0, :, 1, 1] + state[0, :, 1, 0]).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=support)


def multilabel_average_precision(
    preds: Array, target: Array, num_labels: int, average: Optional[str] = "macro",
    thresholds: Thresholds = None, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Parity: reference ``average_precision.py:275``."""
    if average == "micro":
        # Route through the MULTILABEL format first (sigmoid-if-logits before
        # ignore-masking), then flatten to the binary compute — the reference
        # order (``average_precision.py:291-301``). Delegating to
        # binary_average_precision would let an out-of-[0,1] pred at an
        # *ignored* position flip the logit-detection decision differently.
        preds_f, target_f, thr, mask = _multilabel_precision_recall_curve_format(
            preds, target, num_labels, thresholds, ignore_index
        )
        if thr is None:
            p, t = preds_f.reshape(-1), target_f.reshape(-1)
            if mask is not None:
                m = mask.reshape(-1)
                p, t = p[m], t[m]
            return _binary_average_precision_exact(p, t)
        state = _multilabel_precision_recall_curve_update(preds_f, target_f, num_labels, thr, mask)
        return _binary_average_precision_compute(state.sum(axis=1), thr)
    preds_f, target_f, thr, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thr is None:
        precision, recall, _ = _multilabel_precision_recall_curve_compute(
            (preds_f, target_f), num_labels, None, ignore_index
        )
        support = jnp.sum(target_f == 1, axis=0).astype(jnp.float32)
        return _reduce_average_precision(precision, recall, average, weights=support, exclude_empty=True)
    state = _multilabel_precision_recall_curve_update(preds_f, target_f, num_labels, thr, mask)
    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thr)
    support = (state[0, :, 1, 1] + state[0, :, 1, 0]).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=support)


def average_precision(
    preds: Array, target: Array, task: str, thresholds: Thresholds = None, num_classes: Optional[int] = None,
    num_labels: Optional[int] = None, average: Optional[str] = "macro", ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher. Parity: reference ``average_precision.py:380``."""
    from ...utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index,
                                            validate_args)
    if not isinstance(num_labels, int):
        raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)}` was passed.")
    return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
