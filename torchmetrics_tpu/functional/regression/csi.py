"""Critical success index.

Parity: reference ``src/torchmetrics/functional/regression/csi.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape
from ...utils.compute import _safe_divide

Array = jax.Array


def _critical_success_index_update(
    preds: Array, target: Array, threshold: float, keep_sequence_dim=None
) -> Tuple[Array, Array, Array]:
    _check_same_shape(preds, target)
    p = preds >= threshold
    t = target >= threshold
    axis = None if keep_sequence_dim is None else tuple(i for i in range(preds.ndim) if i != keep_sequence_dim)
    hits = jnp.sum(p & t, axis=axis)
    misses = jnp.sum(~p & t, axis=axis)
    false_alarms = jnp.sum(p & ~t, axis=axis)
    return hits, misses, false_alarms


def _critical_success_index_compute(hits: Array, misses: Array, false_alarms: Array) -> Array:
    return _safe_divide(hits, hits + misses + false_alarms)


def critical_success_index(
    preds: Array, target: Array, threshold: float, keep_sequence_dim=None
) -> Array:
    """Parity: reference ``csi.py:62``."""
    hits, misses, false_alarms = _critical_success_index_update(preds, target, threshold, keep_sequence_dim)
    return _critical_success_index_compute(hits, misses, false_alarms)
