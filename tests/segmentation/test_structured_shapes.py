"""Structured-shape families for the segmentation morphology toolbox.

The existing parity tests use iid-noise masks; morphology and distance
transforms behave differently on coherent geometry — smooth boundaries
(disk), double boundaries (ring), sub-structure-size features (1-px lines),
interior holes (cavity), and anisotropic spacing (ellipse) — where the EDT's
exactness over long straight runs and erosion's treatment of thin structures
actually show. Every case is asserted against scipy.ndimage on identical
masks; the shifted-disk surface-distance case additionally pins the
geometrically-known answer.
"""
import numpy as np
import pytest
from scipy import ndimage

import jax.numpy as jnp

from torchmetrics_tpu.functional.segmentation.utils import (
    binary_dilation,
    binary_erosion,
    distance_transform,
    generate_binary_structure,
    mask_edges,
    surface_distance,
)

H, W = 48, 64
_yy, _xx = np.mgrid[0:H, 0:W]


def _disk():
    return (((_yy - 24) ** 2 + (_xx - 30) ** 2) <= 15**2).astype(np.int32)


def _ring():
    r2 = (_yy - 24) ** 2 + (_xx - 30) ** 2
    return ((r2 <= 18**2) & (r2 >= 10**2)).astype(np.int32)


def _thin_lines():
    m = np.zeros((H, W), np.int32)
    m[10, 5:55] = 1                      # 1-px horizontal line
    for i in range(30):                  # 1-px diagonal
        m[14 + i, 8 + i] = 1
    m[30:33, 40] = 1                     # 3-px vertical stub
    return m


def _cavity():
    blob = (((_yy - 24) ** 2 / 1.4 + (_xx - 32) ** 2 / 2.2) <= 14**2).astype(np.int32)
    hole = (((_yy - 24) ** 2 + (_xx - 36) ** 2) <= 5**2)
    blob[hole] = 0
    return blob


def _ellipse():
    return ((((_yy - 24) / 18.0) ** 2 + ((_xx - 30) / 9.0) ** 2) <= 1.0).astype(np.int32)


SHAPES = [("disk", _disk), ("ring", _ring), ("thin-lines", _thin_lines),
          ("cavity", _cavity), ("ellipse", _ellipse)]
IDS = [s[0] for s in SHAPES]


@pytest.mark.parametrize(("name", "gen"), SHAPES, ids=IDS)
@pytest.mark.parametrize("connectivity", [1, 2])
def test_morphology_on_structured_shapes(name, gen, connectivity):
    img = gen()
    st = generate_binary_structure(2, connectivity)
    ours_e = np.asarray(binary_erosion(img[None, None], st))[0, 0]
    ref_e = ndimage.binary_erosion(img, np.asarray(st)).astype(np.int32)
    np.testing.assert_array_equal(ours_e, ref_e, err_msg=f"{name} erosion")
    ours_d = np.asarray(binary_dilation(img[None, None], st))[0, 0]
    ref_d = ndimage.binary_dilation(img, np.asarray(st)).astype(np.int32)
    np.testing.assert_array_equal(ours_d, ref_d, err_msg=f"{name} dilation")
    if name == "thin-lines" and connectivity == 1:
        # 1-px structures must vanish entirely under erosion
        assert ours_e[10, 5:55].sum() == 0


@pytest.mark.parametrize(("name", "gen"), SHAPES, ids=IDS)
@pytest.mark.parametrize("sampling", [(1.0, 1.0), (2.0, 0.5)])
def test_euclidean_edt_on_structured_shapes(name, gen, sampling):
    img = gen()
    ours = np.asarray(distance_transform(img, sampling=sampling, metric="euclidean"))
    ref = ndimage.distance_transform_edt(img, sampling=sampling)
    np.testing.assert_allclose(ours, ref, atol=1e-4, err_msg=name)


@pytest.mark.parametrize(("name", "gen"), SHAPES, ids=IDS)
def test_chessboard_taxicab_edt_on_structured_shapes(name, gen):
    img = gen()
    for metric in ("chessboard", "taxicab"):
        ours = np.asarray(distance_transform(img, metric=metric))
        ref = ndimage.distance_transform_cdt(img, metric=metric)
        np.testing.assert_allclose(ours, ref, atol=1e-5, err_msg=f"{name}/{metric}")


def test_shifted_disk_surface_distance_geometry():
    """A disk shifted by 3 px: every boundary point of the shifted disk is
    within 3 px of the original boundary, and the mean surface distance is
    strictly positive but well below the shift."""
    a = _disk()
    b = np.roll(a, 3, axis=1)
    ea, eb = (np.asarray(x).astype(bool) for x in mask_edges(jnp.asarray(a), jnp.asarray(b))[:2])
    d = np.asarray(surface_distance(jnp.asarray(eb.astype(np.int32)), jnp.asarray(ea.astype(np.int32))))
    assert d.max() <= 3.0 + 1e-6
    assert 0.0 < d.mean() < 3.0
    # symmetric direction agrees with scipy-derived oracle: distances from
    # shifted edge to original edge via scipy's EDT of the inverted edge mask
    ref_field = ndimage.distance_transform_edt(~ea)
    np.testing.assert_allclose(d, ref_field[eb], atol=1e-4)  # row-major gather on both sides


def test_ring_inner_and_outer_boundaries_in_edges():
    """mask_edges on the ring must mark BOTH boundaries (an interior hole is
    still a boundary): scipy oracle = ring minus its erosion."""
    r = _ring()
    er, _ = mask_edges(jnp.asarray(r), jnp.asarray(r), crop=False)[:2]
    ref = r - ndimage.binary_erosion(r, ndimage.generate_binary_structure(2, 1)).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(er).astype(np.int32), ref)
