"""ROUGEScore, EditDistance, SQuAD, BERTScore, InfoLM metric classes.

Parity targets: reference ``text/{rouge,edit,squad,bert,infolm}.py``.
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..functional.text.bert import bert_score
from ..functional.text.edit import _edit_distance_single
from ..functional.text.infolm import _ALLOWED_INFORMATION_MEASURE, infolm
from ..functional.text.rouge import ALLOWED_ACCUMULATE, ALLOWED_ROUGE_KEYS, _rouge_score_update
from ..functional.text.squad import PREDS_TYPE, TARGETS_TYPE, _squad_compute, _squad_input_check, _squad_update
from ..utils.data import cat_state_or_empty, dim_zero_cat
from .asr import _HostTextMetric

Array = jax.Array


class ROUGEScore(_HostTextMetric):
    """Parity: reference ``text/rouge.py:ROUGEScore`` (236 LoC).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ROUGEScore
        >>> metric = ROUGEScore()
        >>> metric.update(["the cat is on the mat"], ["there is a cat on the mat"])
        >>> round(float(metric.compute()["rouge1_fmeasure"]), 4)
        0.7692
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, use_stemmer: bool = False, normalizer: Optional[Callable] = None,
                 tokenizer: Optional[Callable] = None, accumulate: str = "best",
                 rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(rouge_keys, str):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {ALLOWED_ROUGE_KEYS}")
        if accumulate not in ALLOWED_ACCUMULATE:
            raise ValueError(f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE}")
        self.rouge_keys = rouge_keys
        self.accumulate = accumulate
        self.stemmer = None
        if use_stemmer:
            try:
                import nltk.stem.porter

                self.stemmer = nltk.stem.porter.PorterStemmer()
            except ImportError as err:
                raise ModuleNotFoundError("Stemmer requires that `nltk` is installed.") from err
        for key in rouge_keys:
            slug = key.replace(".", "_")
            self.add_state(f"{slug}_triplets", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]],
               target: Union[str, Sequence[str], Sequence[Sequence[str]]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [target] if isinstance(target, str) else list(target)
        results = _rouge_score_update(preds_, target_, self.rouge_keys, self.accumulate, self.stemmer)
        for key, triplets in results.items():
            getattr(self, f"{key}_triplets").append(jnp.asarray(triplets, dtype=jnp.float32).reshape(-1, 3))

    def compute(self) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        for key in self.rouge_keys:
            vals = cat_state_or_empty(getattr(self, f"{key}_triplets")).reshape(-1, 3)
            arr = vals if vals.size else jnp.zeros((1, 3))
            out[f"{key}_precision"] = jnp.mean(arr[:, 0])
            out[f"{key}_recall"] = jnp.mean(arr[:, 1])
            out[f"{key}_fmeasure"] = jnp.mean(arr[:, 2])
        return out


class EditDistance(_HostTextMetric):
    """Parity: reference ``text/edit.py:EditDistance``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import EditDistance
        >>> metric = EditDistance()
        >>> metric.update(["kitten"], ["sitting"])
        >>> float(metric.compute())
        3.0
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(substitution_cost, int) or substitution_cost < 0:
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        if reduction not in ("mean", "sum", "none", None):
            raise ValueError("Expected argument `reduction` to be one of ['mean', 'sum', 'none', None]")
        self.substitution_cost = substitution_cost
        self.reduction = reduction
        if reduction in ("none", None):
            self.add_state("values", [], dist_reduce_fx="cat")
        else:
            self.add_state("edit_scores_list", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [target] if isinstance(target, str) else list(target)
        if len(preds_) != len(target_):
            raise ValueError(
                f"Expected argument `preds` and `target` to have same length, but got {len(preds_)} and {len(target_)}"
            )
        dists = jnp.asarray(
            [_edit_distance_single(p, t, self.substitution_cost) for p, t in zip(preds_, target_)],
            dtype=jnp.float32,
        )
        if self.reduction in ("none", None):
            self.values.append(dists)
        else:
            self.edit_scores_list.append(dists)

    def compute(self) -> Array:
        if self.reduction in ("none", None):
            return cat_state_or_empty(self.values)
        arr = cat_state_or_empty(self.edit_scores_list)
        if self.reduction == "mean":
            return jnp.mean(arr) if arr.size else jnp.asarray(0.0)
        return jnp.sum(arr)


class SQuAD(_HostTextMetric):
    """Parity: reference ``text/squad.py:SQuAD`` (167 LoC).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SQuAD
        >>> metric = SQuAD()
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> metric.update(preds, target)
        >>> {k: float(v) for k, v in sorted(metric.compute().items())}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: PREDS_TYPE, target: TARGETS_TYPE) -> None:
        preds_dict, target_list = _squad_input_check(preds, target)
        f1, exact, total = _squad_update(preds_dict, target_list)
        self.f1_score = self.f1_score + f1
        self.exact_match = self.exact_match + exact
        self.total = self.total + total

    def compute(self) -> Dict[str, Array]:
        return _squad_compute(self.f1_score, self.exact_match, self.total)


class BERTScore(_HostTextMetric):
    """Parity: reference ``text/bert.py:BERTScore`` — stores raw sentence
    pairs (the reference stores tokenized ids, same storage semantics) and
    runs the encoder + greedy matching once at compute.

    Example (user-provided tokenizer + embedding forward, the reference's
    ``user_tokenizer``/``user_forward_fn`` escape hatch; a HF name like
    ``'roberta-large'`` works when transformers weights are available):
        >>> import numpy as np
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import BERTScore
        >>> emb = np.random.RandomState(7).randn(100, 12).astype(np.float32)
        >>> def tok(texts, max_length=None):
        ...     ids = np.zeros((len(texts), 4), dtype=np.int32)
        ...     mask = np.zeros((len(texts), 4), dtype=np.int32)
        ...     for i, t in enumerate(texts):
        ...         toks = [sum(map(ord, w)) % 100 for w in t.split()][:4]
        ...         ids[i, :len(toks)] = toks
        ...         mask[i, :len(toks)] = 1
        ...     return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}
        >>> def fwd(ids, mask):
        ...     return jnp.asarray(emb)[ids]
        >>> bert = BERTScore(user_tokenizer=tok, user_forward_fn=fwd)
        >>> bert.update(["the cat sat"], ["the cat ran"])
        >>> res = bert.compute()
        >>> {k: round(float(res[k]), 4) for k in sorted(res)}
        {'f1': 0.8789, 'precision': 0.7839, 'recall': 1.0}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, model_name_or_path: Optional[str] = None, num_layers: Optional[int] = None,
                 idf: bool = False, lang: str = "en", max_length: int = 512, batch_size: int = 64,
                 user_tokenizer: Any = None, user_forward_fn: Optional[Callable] = None,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.idf = idf
        self.lang = lang
        self.max_length = max_length
        self.batch_size = batch_size
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self._preds: List[str] = []
        self._target: List[str] = []

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        self._update_count += 1
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [target] if isinstance(target, str) else list(target)
        if len(preds_) != len(target_):
            raise ValueError("Number of predicted and reference sentences must be the same!")
        self._preds.extend(preds_)
        self._target.extend(target_)

    def compute(self) -> Dict[str, Array]:
        return bert_score(
            self._preds, self._target,
            model_name_or_path=self.model_name_or_path, num_layers=self.num_layers,
            idf=self.idf, lang=self.lang, max_length=self.max_length,
            batch_size=self.batch_size, user_tokenizer=self.user_tokenizer,
            user_forward_fn=self.user_forward_fn,
        )

    def reset(self) -> None:
        super().reset()
        self._preds, self._target = [], []


class InfoLM(_HostTextMetric):
    """Parity: reference ``text/infolm.py:InfoLM`` (244 LoC).

    Example (user-provided tokenizer + masked-LM logits forward; a HF name
    like ``'bert-base-uncased'`` works when transformers weights are
    available):
        >>> import numpy as np
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import InfoLM
        >>> emb = np.abs(np.random.RandomState(7).randn(100, 4)).astype(np.float32)
        >>> def tok(texts, max_length=None):
        ...     ids = np.zeros((len(texts), 4), dtype=np.int32)
        ...     mask = np.zeros((len(texts), 4), dtype=np.int32)
        ...     for i, t in enumerate(texts):
        ...         toks = [sum(map(ord, w)) % 100 for w in t.split()][:4]
        ...         ids[i, :len(toks)] = toks
        ...         mask[i, :len(toks)] = 1
        ...     return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}
        >>> def fwd(ids, mask):
        ...     return jnp.asarray(emb)[ids] @ jnp.asarray(emb).T
        >>> infolm = InfoLM(user_tokenizer=tok, user_forward_fn=fwd, idf=False)
        >>> infolm.update(["the cat sat"], ["the cat ran"])
        >>> round(float(infolm.compute()), 4)
        0.1659
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, model_name_or_path: str = "bert-base-uncased", temperature: float = 0.25,
                 information_measure: str = "kl_divergence", idf: bool = True,
                 alpha: Optional[float] = None, beta: Optional[float] = None,
                 max_length: Optional[int] = None, batch_size: int = 64,
                 return_sentence_level_score: bool = False,
                 user_tokenizer: Any = None, user_forward_fn: Optional[Callable] = None,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if information_measure not in _ALLOWED_INFORMATION_MEASURE:
            raise ValueError(
                f"Argument `information_measure` is expected to be one of {_ALLOWED_INFORMATION_MEASURE}"
            )
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_sentence_level_score = return_sentence_level_score
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self._preds: List[str] = []
        self._target: List[str] = []

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        self._update_count += 1
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [target] if isinstance(target, str) else list(target)
        self._preds.extend(preds_)
        self._target.extend(target_)

    def compute(self):
        return infolm(
            self._preds, self._target, model_name_or_path=self.model_name_or_path,
            temperature=self.temperature, information_measure=self.information_measure,
            idf=self.idf, alpha=self.alpha, beta=self.beta, max_length=self.max_length,
            batch_size=self.batch_size, return_sentence_level_score=self.return_sentence_level_score,
            user_tokenizer=self.user_tokenizer, user_forward_fn=self.user_forward_fn,
        )

    def reset(self) -> None:
        super().reset()
        self._preds, self._target = [], []
