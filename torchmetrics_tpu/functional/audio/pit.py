"""Permutation Invariant Training (PIT) metric wrapper.

Parity target: reference ``functional/audio/pit.py`` — exhaustive
permutation search (``:68``) or scipy Hungarian on the speaker-pair metric
matrix (``:42-62``, CPU transfer).

TPU-native: the (spk x spk) pair-metric matrix is ONE batched call of the
underlying metric (broadcast over speaker pairs); the exhaustive search
evaluates all spk! permutations by indexing that matrix (no re-computation,
no Python loop over the batch). Hungarian (for spk > 3) runs on host over
the small matrix — same boundary the reference crosses.
"""
from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _pair_metric_matrix(preds: Array, target: Array, metric_func: Callable, **kwargs: Any) -> Array:
    """(..., spk_pred, spk_target) metric of every speaker pair in one call."""
    spk = preds.shape[-2]
    p = jnp.repeat(preds[..., :, None, :], spk, axis=-2)  # (..., sp, st, T)
    t = jnp.repeat(target[..., None, :, :], spk, axis=-3)
    return metric_func(p, t, **kwargs)


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """Best metric value + permutation per sample. Parity: ``pit.py:permutation_invariant_training``."""
    if preds.shape[:2] != target.shape[:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ("max", "min"):
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ("speaker-wise", "permutation-wise"):
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk = target.shape[1]
    perms = list(permutations(range(spk)))

    if mode == "speaker-wise":
        matrix = _pair_metric_matrix(preds, target, metric_func, **kwargs)  # (B, sp, st)
        if spk > 3:
            # Hungarian on host: optimal without enumerating spk! options.
            # First-party C++ Jonker-Volgenant (``_native``); scipy fallback.
            # Wrapped in ``jax.pure_callback`` so the speaker-wise PIT stays
            # usable under jit/shard_map (the solver output shapes are
            # static: one permutation per sample).
            sign = -1.0 if eval_func == "max" else 1.0

            def _solve_host(mat_np: np.ndarray) -> np.ndarray:
                from ... import _native

                if _native.NATIVE_AVAILABLE:
                    linear_sum_assignment = _native.linear_sum_assignment
                else:
                    from scipy.optimize import linear_sum_assignment

                mat_np = np.asarray(mat_np, np.float64)
                cols_out = np.empty((mat_np.shape[0], spk), dtype=np.int32)
                for b in range(mat_np.shape[0]):
                    _rows, cols = linear_sum_assignment(sign * mat_np[b])
                    cols_out[b] = cols
                return cols_out

            if isinstance(matrix, jax.core.Tracer):
                # under jit/shard_map/vmap: host solver via pure_callback
                # (static output shapes — one permutation per sample). Note:
                # runtimes without host-callback support (e.g. the axon dev
                # tunnel) cannot execute this traced path; the eager branch
                # below works everywhere.
                # stop_gradient: the chosen permutation is piecewise-constant
                # in the inputs, so gradients flow (correctly) only through
                # the selected matrix entries below — and pure_callback has
                # no JVP. vmap_method="sequential" keeps update_state_batched
                # (a vmap over steps) working.
                best_perm = jax.pure_callback(
                    _solve_host,
                    jax.ShapeDtypeStruct((matrix.shape[0], spk), jnp.int32),
                    jax.lax.stop_gradient(matrix),
                    vmap_method="sequential",
                )
            else:
                # concrete arrays solve directly on host — some TPU runtimes
                # (axon) do not implement host callbacks even eagerly
                best_perm = jnp.asarray(_solve_host(np.asarray(matrix)))
            # matrix[b, i, best_perm[b, i]] per (sample, speaker)
            chosen = jnp.take_along_axis(matrix, best_perm[..., None], axis=2)[..., 0]
            best_metric = jnp.mean(chosen, axis=-1)
            return best_metric, best_perm
        # exhaustive: gather each permutation's diagonal from the matrix
        perm_arr = jnp.asarray(perms)  # (P, spk)
        rows = jnp.arange(spk)
        per_perm = jnp.stack(
            [jnp.mean(matrix[..., rows, perm_arr[p]], axis=-1) for p in range(len(perms))], axis=-1
        )  # (B, P)
    else:
        per_perm_vals = []
        for perm in perms:
            permuted = target[:, jnp.asarray(perm), ...]
            per_perm_vals.append(metric_func(preds, permuted, **kwargs))
        per_perm = jnp.stack(per_perm_vals, axis=-1)  # (B, P)

    best_idx = jnp.argmax(per_perm, axis=-1) if eval_func == "max" else jnp.argmin(per_perm, axis=-1)
    best_metric = jnp.take_along_axis(per_perm, best_idx[..., None], axis=-1)[..., 0]
    best_perm = jnp.asarray(perms)[best_idx]
    return best_metric, best_perm


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Rearrange speakers according to per-sample permutations. Parity: ``pit.py:pit_permutate``."""
    return jnp.take_along_axis(preds, perm[..., None], axis=1)
