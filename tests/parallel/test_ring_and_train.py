"""Ring attention, expert all-to-all, and the dp x pp x tp train template.

Covers the SURVEY.md §2.10 additions that the reference does not have:
sequence/context parallelism and composition of metric updates with a fully
sharded training step, on the 8-device simulated CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tests.helpers.testers import _shard_map

shard_map = _shard_map()

from torchmetrics_tpu.parallel import (
    demo_param_shardings,
    expert_all_to_all,
    init_demo_params,
    make_demo_train_step,
    ring_attention,
)

rng = np.random.RandomState(0)


def _mesh1d(name):
    return Mesh(np.array(jax.devices("cpu")[:8]).reshape(8), (name,))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full_attention(causal):
    B, T, D = 2, 64, 16
    q = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    mesh = _mesh1d("sp")
    ra = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
            mesh=mesh, in_specs=(P(None, "sp", None),) * 3, out_specs=P(None, "sp", None),
        )
    )
    out = ra(q, k, v)
    s = jnp.einsum("btd,bsd->bts", q, k) * (D**-0.5)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -jnp.inf)
    ref = jnp.einsum("bts,bsd->btd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_bf16():
    """bf16 inputs (the TPU compute dtype) accumulate in f32 and return bf16."""
    B, T, D = 2, 64, 16
    q = jnp.asarray(rng.randn(B, T, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, T, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, T, D), jnp.bfloat16)
    mesh = _mesh1d("sp")
    ra = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp"),
            mesh=mesh, in_specs=(P(None, "sp", None),) * 3, out_specs=P(None, "sp", None),
        )
    )
    out = ra(q, k, v)
    assert out.dtype == jnp.bfloat16
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("btd,bsd->bts", qf, kf) * (D**-0.5)
    ref = jnp.einsum("bts,bsd->btd", jax.nn.softmax(s, axis=-1), vf)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref), atol=0.05)


def test_ring_attention_differentiable():
    B, T, D = 1, 32, 8
    q = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    mesh = _mesh1d("sp")

    def loss_ring(q, k, v):
        f = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp"),
            mesh=mesh, in_specs=(P(None, "sp", None),) * 3, out_specs=P(None, "sp", None),
        )
        return jnp.sum(f(q, k, v) ** 2)

    def loss_full(q, k, v):
        s = jnp.einsum("btd,bsd->bts", q, k) * (D**-0.5)
        return jnp.sum(jnp.einsum("bts,bsd->btd", jax.nn.softmax(s, axis=-1), v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_expert_all_to_all_dispatch_semantics():
    """One all_to_all = blockwise transpose (dispatch); two = identity (combine)."""
    mesh = _mesh1d("ep")
    # global (shards, groups, d): shard s holds groups destined for each expert
    x = jnp.asarray(rng.randn(8, 8, 6).astype(np.float32))

    def once(x):
        return expert_all_to_all(x, "ep", split_axis=1, concat_axis=1)

    f1 = jax.jit(shard_map(once, mesh=mesh, in_specs=(P("ep", None, None),),
                               out_specs=P("ep", None, None)))
    f2 = jax.jit(shard_map(lambda x: once(once(x)), mesh=mesh,
                               in_specs=(P("ep", None, None),), out_specs=P("ep", None, None)))
    # dispatch: expert e receives group e from every source shard
    np.testing.assert_allclose(np.asarray(f1(x)), np.asarray(x.transpose(1, 0, 2)), atol=0)
    # combine inverts dispatch
    np.testing.assert_allclose(np.asarray(f2(x)), np.asarray(x), atol=0)


def test_demo_train_step_converges_and_feeds_metrics():
    """Full train step (pp=2 x dp=2 x tp=2, ep on tp) with in-loop metrics."""
    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.text.perplexity import Perplexity

    mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(2, 2, 2), ("pp", "dp", "tp"))
    vocab, d_model, d_hidden = 32, 16, 32
    params = init_demo_params(jax.random.PRNGKey(0), vocab, d_model, d_hidden, pp=2, tp=2)
    sh = demo_param_shardings(mesh)
    params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    step = make_demo_train_step(mesh, microbatches=2, lr=1.0)

    B, T = 8, 8
    tokens = jax.device_put(
        jnp.asarray(rng.randint(0, vocab, (B, T))), NamedSharding(mesh, P("dp", None))
    )
    targets = jax.device_put(
        jnp.asarray(rng.randint(0, vocab, (B, T))), NamedSharding(mesh, P("dp", None))
    )

    acc = MulticlassAccuracy(num_classes=vocab, average="micro")
    ppl = Perplexity()
    acc_state, ppl_state = acc.init_state(), ppl.init_state()

    @jax.jit
    def metrics_update(acc_state, ppl_state, logits, targets):
        # metric updates run under GSPMD on the sharded logits — no
        # host gather; states come out replicated
        a = acc.update_state(acc_state, logits.reshape(-1, vocab), targets.reshape(-1))
        p = ppl.update_state(ppl_state, logits, targets)
        return a, p

    losses = []
    for _ in range(40):
        params, loss, logits = step(params, tokens, targets)
        acc_state, ppl_state = metrics_update(acc_state, ppl_state, logits, targets)
        losses.append(float(loss))

    assert losses[-1] < losses[0] - 0.5, losses[::8]
    final_acc = float(acc.compute_state(acc_state))
    final_ppl = float(ppl.compute_state(ppl_state))
    assert 0.0 <= final_acc <= 1.0
    assert np.isfinite(final_ppl) and final_ppl > 1.0
    # training on fixed data: late-epoch accuracy must beat early epochs
    fresh = acc.update_state(acc.init_state(), logits.reshape(-1, vocab), targets.reshape(-1))
    assert float(acc.compute_state(fresh)) > 0.5
