"""PrecisionRecallCurve metric classes — the stateful Engine B.

Parity: reference ``src/torchmetrics/classification/precision_recall_curve.py``.
Two state modes (reference ``functional/.../precision_recall_curve.py:190``):
``thresholds=None`` → exact (raw preds/target ``cat`` list states);
``thresholds=int/list/array`` → binned fixed-shape confusion state with
``"sum"`` reduction (the TPU-native default recommendation).
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ..functional.classification.precision_recall_curve import (
    Thresholds,
    _adjust_threshold_arg,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_update,
)
from ..metric import Metric
from ..parallel.sharded_compute import cat_compact, padded_or_sharded_cat
from ..utils.enums import ClassificationTask
from .base import _ClassificationTaskWrapper

Array = jax.Array


class BinaryPrecisionRecallCurve(Metric):
    """Parity: reference ``classification/precision_recall_curve.py:40``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    _signature_key = "binary_prc"

    def _engine_signature(self):
        thr = self.thresholds
        # np conversion, not iteration: indexing a concrete array inside a
        # jit trace lifts the elements to tracers
        import numpy as np

        thr_key = None if thr is None else tuple(np.asarray(thr, dtype=np.float64).tolist())
        return (self._signature_key, getattr(self, "num_classes", None),
                getattr(self, "num_labels", None), thr_key, self.ignore_index)

    def __init__(self, thresholds: Thresholds = None, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        thr = _adjust_threshold_arg(thresholds)
        self.thresholds = thr
        if thr is None:
            self._compute_jittable = False
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            if ignore_index is not None:
                self.add_state("valid", [], dist_reduce_fx="cat")
        else:
            self.add_state("confmat", jnp.zeros((thr.shape[0], 2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        p, t, _, mask = _binary_precision_recall_curve_format(preds, target, None, self.ignore_index)
        if self.thresholds is None:
            self.preds.append(p)
            self.target.append(t)
            if self.ignore_index is not None:
                self.valid.append(mask)
        else:
            self.confmat = self.confmat + _binary_precision_recall_curve_update(p, t, self.thresholds, mask)

    def _exact_state(self) -> Tuple[Array, Array]:
        # padded layout: the state is a (buffer, count) pair; the cat read
        # slices off the invalid tail before the exact-length kernel sees it.
        # Sharded layout reads through cat_compact (shard-major compaction on
        # the mesh) — same row order as the replicated materialization, so the
        # downstream sort-based curve is bitwise-identical either way.
        preds, _ = padded_or_sharded_cat(self.preds)
        target, _ = padded_or_sharded_cat(self.target)
        if self.ignore_index is not None:
            # astype(bool): sync transports may return the mask as 0/1 ints,
            # and integer `preds[keep]` would gather rows instead of masking
            keep = cat_compact(self.valid).astype(bool)
            preds, target = preds[keep], target[keep]
        return preds, target

    def compute(self) -> Tuple[Array, Array, Array]:
        if self.thresholds is None:
            return _binary_precision_recall_curve_compute(self._exact_state(), None)
        return _binary_precision_recall_curve_compute(self.confmat, self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from ..utils.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve((curve[1], curve[0], curve[2]), score=score, ax=ax,
                          label_names=("Recall", "Precision"), name=type(self).__name__)


class MulticlassPrecisionRecallCurve(Metric):
    """Parity: reference ``classification/precision_recall_curve.py:185``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    _signature_key = "multiclass_prc"
    _engine_signature = BinaryPrecisionRecallCurve._engine_signature

    def __init__(self, num_classes: int, thresholds: Thresholds = None, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        thr = _adjust_threshold_arg(thresholds)
        self.thresholds = thr
        if thr is None:
            self._compute_jittable = False
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            if ignore_index is not None:
                self.add_state("valid", [], dist_reduce_fx="cat")
        else:
            self.add_state("confmat", jnp.zeros((thr.shape[0], num_classes, 2, 2), dtype=jnp.int32),
                           dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        p, t, _, mask = _multiclass_precision_recall_curve_format(preds, target, self.num_classes, None,
                                                                  self.ignore_index)
        if self.thresholds is None:
            self.preds.append(p)
            self.target.append(t)
            if self.ignore_index is not None:
                self.valid.append(mask)
        else:
            self.confmat = self.confmat + _multiclass_precision_recall_curve_update(
                p, t, self.num_classes, self.thresholds, mask
            )

    def _exact_state(self) -> Tuple[Array, Array]:
        preds, _ = padded_or_sharded_cat(self.preds)
        target, _ = padded_or_sharded_cat(self.target)
        if self.ignore_index is not None:
            keep = cat_compact(self.valid).astype(bool)
            preds, target = preds[keep], target[keep]
        return preds, target

    def compute(self):
        if self.thresholds is None:
            return _multiclass_precision_recall_curve_compute(self._exact_state(), self.num_classes, None)
        return _multiclass_precision_recall_curve_compute(self.confmat, self.num_classes, self.thresholds)

    plot = BinaryPrecisionRecallCurve.plot


class MultilabelPrecisionRecallCurve(Metric):
    """Parity: reference ``classification/precision_recall_curve.py:327``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    _signature_key = "multilabel_prc"
    _engine_signature = BinaryPrecisionRecallCurve._engine_signature

    def __init__(self, num_labels: int, thresholds: Thresholds = None, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        thr = _adjust_threshold_arg(thresholds)
        self.thresholds = thr
        if thr is None:
            self._compute_jittable = False
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
        else:
            self.add_state("confmat", jnp.zeros((thr.shape[0], num_labels, 2, 2), dtype=jnp.int32),
                           dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        p, t, _, mask = _multilabel_precision_recall_curve_format(preds, target, self.num_labels, None, None)
        if self.thresholds is None:
            self.preds.append(p)
            self.target.append(jnp.asarray(target).reshape(-1, self.num_labels))
        else:
            if self.ignore_index is not None:
                mask = jnp.asarray(target).reshape(-1, self.num_labels) != self.ignore_index
            self.confmat = self.confmat + _multilabel_precision_recall_curve_update(
                p, t, self.num_labels, self.thresholds, mask
            )

    def _exact_state(self) -> Tuple[Array, Array]:
        return padded_or_sharded_cat(self.preds)[0], padded_or_sharded_cat(self.target)[0]

    def compute(self):
        if self.thresholds is None:
            return _multilabel_precision_recall_curve_compute(
                self._exact_state(), self.num_labels, None, self.ignore_index
            )
        return _multilabel_precision_recall_curve_compute(self.confmat, self.num_labels, self.thresholds)

    plot = BinaryPrecisionRecallCurve.plot


BinaryPrecisionRecallCurve._signature_base = BinaryPrecisionRecallCurve
MulticlassPrecisionRecallCurve._signature_base = MulticlassPrecisionRecallCurve
MultilabelPrecisionRecallCurve._signature_base = MultilabelPrecisionRecallCurve


class PrecisionRecallCurve(_ClassificationTaskWrapper):
    """Task facade. Parity: reference ``classification/precision_recall_curve.py:472``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PrecisionRecallCurve
        >>> metric = PrecisionRecallCurve(task="binary", thresholds=5)
        >>> preds = jnp.asarray([0.1, 0.8, 0.6, 0.3, 0.9, 0.4])
        >>> target = jnp.asarray([0, 1, 1, 0, 1, 0])
        >>> metric.update(preds, target)
        >>> [[round(float(x), 4) for x in v] for v in metric.compute()]
        [[0.5, 0.6, 1.0, 1.0, 0.0, 1.0], [1.0, 1.0, 1.0, 0.6667, 0.0, 0.0], [0.0, 0.25, 0.5, 0.75, 1.0]]
    """

    def __new__(cls, task: str, thresholds: Thresholds = None, num_classes: Optional[int] = None,
                num_labels: Optional[int] = None, ignore_index: Optional[int] = None,
                validate_args: bool = True, **kwargs: Any) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionRecallCurve(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
            return MulticlassPrecisionRecallCurve(num_classes, **kwargs)
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)}` was passed.")
        return MultilabelPrecisionRecallCurve(num_labels, **kwargs)
