"""Mean absolute error.

Parity: reference ``src/torchmetrics/functional/regression/mae.py``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array, num_outputs: int = 1) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    sum_abs_error = jnp.sum(jnp.abs((preds - target).astype(jnp.float32)), axis=0)
    return sum_abs_error, jnp.asarray(preds.shape[0], dtype=jnp.float32)


def _mean_absolute_error_compute(sum_abs_error: Array, total: Array) -> Array:
    return sum_abs_error / total


def mean_absolute_error(preds: Array, target: Array, num_outputs: int = 1) -> Array:
    """Parity: reference ``mae.py:46``."""
    sum_abs_error, total = _mean_absolute_error_update(preds, target, num_outputs)
    return _mean_absolute_error_compute(sum_abs_error, total)
