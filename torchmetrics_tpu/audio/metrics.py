"""Modular audio metrics — mean-of-values sum states.

Parity targets: reference ``audio/{snr,sdr,pit,pesq,stoi,srmr}.py`` — every
class keeps ``sum_<metric>`` + ``total`` sum states (mean at compute), the
exact state design of the reference's audio domain.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..functional.audio.pesq import perceptual_evaluation_speech_quality
from ..functional.audio.pit import permutation_invariant_training
from ..functional.audio.srmr import speech_reverberation_modulation_energy_ratio
from ..functional.audio.stoi import short_time_objective_intelligibility
from ..functional.audio.sdr import (
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from ..functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from ..metric import Metric

Array = jax.Array


class _MeanAudioMetric(Metric):
    """Accumulate sum + count of per-sample values."""

    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_value", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _values(self, *args: Any, **kwargs: Any) -> Array:
        raise NotImplementedError

    def update(self, preds: Array, target: Array) -> None:
        values = self._values(preds, target)
        self.sum_value = self.sum_value + jnp.sum(values)
        self.total = self.total + values.size

    def compute(self) -> Array:
        return self.sum_value / self.total


class SignalNoiseRatio(_MeanAudioMetric):
    """Parity: reference ``audio/snr.py:SignalNoiseRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> metric = SignalNoiseRatio()
        >>> metric.update(jnp.asarray([3.0, -0.5, 2.0, 7.0]), jnp.asarray([3.0, -0.5, 2.0, 8.0]))
        >>> print(f"{float(metric.compute()):.4f}")
        18.8790
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return signal_noise_ratio(preds, target, self.zero_mean)


class ScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """Parity: reference ``audio/snr.py:ScaleInvariantSignalNoiseRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ScaleInvariantSignalNoiseRatio
        >>> metric = ScaleInvariantSignalNoiseRatio()
        >>> t = jnp.linspace(0.0, 100.0, 1600)
        >>> target = jnp.sin(t)
        >>> preds = target + 0.1 * jnp.cos(3.0 * t)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        20.0177
    """

    is_differentiable = True
    higher_is_better = True

    def _values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_noise_ratio(preds, target)


class ComplexScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """Parity: reference ``audio/snr.py:ComplexScaleInvariantSignalNoiseRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ComplexScaleInvariantSignalNoiseRatio
        >>> metric = ComplexScaleInvariantSignalNoiseRatio()
        >>> t = jnp.linspace(0.0, 6.0, 65 * 10 * 2)
        >>> target = jnp.sin(t).reshape(1, 65, 10, 2)
        >>> preds = target * 0.8 + 0.05
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        21.2661
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return complex_scale_invariant_signal_noise_ratio(preds, target, self.zero_mean)


class SignalDistortionRatio(_MeanAudioMetric):
    """Parity: reference ``audio/sdr.py:SignalDistortionRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SignalDistortionRatio
        >>> metric = SignalDistortionRatio()
        >>> t = jnp.linspace(0.0, 100.0, 1600)
        >>> target = jnp.sin(t)
        >>> preds = target + 0.1 * jnp.cos(3.0 * t)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 3)  # 3 digits: the 4th varies per backend
        20.396
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, use_cg_iter: Any = None, filter_length: int = 512, zero_mean: bool = False,
                 load_diag: Any = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def _values(self, preds: Array, target: Array) -> Array:
        return signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )


class ScaleInvariantSignalDistortionRatio(_MeanAudioMetric):
    """Parity: reference ``audio/sdr.py:ScaleInvariantSignalDistortionRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ScaleInvariantSignalDistortionRatio
        >>> metric = ScaleInvariantSignalDistortionRatio()
        >>> t = jnp.linspace(0.0, 100.0, 1600)
        >>> target = jnp.sin(t)
        >>> preds = target + 0.1 * jnp.cos(3.0 * t)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        20.0176
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_distortion_ratio(preds, target, self.zero_mean)


class SourceAggregatedSignalDistortionRatio(_MeanAudioMetric):
    """Parity: reference ``audio/sdr.py:SourceAggregatedSignalDistortionRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SourceAggregatedSignalDistortionRatio
        >>> metric = SourceAggregatedSignalDistortionRatio()
        >>> t = jnp.linspace(0.0, 100.0, 800)
        >>> target = jnp.stack([jnp.sin(t), jnp.cos(t)])[None]
        >>> preds = target + 0.1
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        16.9873
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.scale_invariant = scale_invariant
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return source_aggregated_signal_distortion_ratio(preds, target, self.scale_invariant, self.zero_mean)


class PermutationInvariantTraining(_MeanAudioMetric):
    """Parity: reference ``audio/pit.py:PermutationInvariantTraining`` (164 LoC).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PermutationInvariantTraining
        >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
        >>> metric = PermutationInvariantTraining(scale_invariant_signal_noise_ratio)
        >>> t = jnp.linspace(0.0, 100.0, 400)
        >>> target = jnp.stack([jnp.sin(t), jnp.cos(t)])[None]
        >>> preds = target[:, ::-1, :] + 0.05
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 3)  # 3 digits: the 4th varies per backend
        92.247
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, metric_func: Callable, mode: str = "speaker-wise", eval_func: str = "max",
                 **kwargs: Any) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in list(kwargs)
            if k in ("compute_on_cpu", "dist_sync_on_step", "sync_on_compute", "compute_with_cache",
                     "sync_backend", "jit")
        }
        super().__init__(**base_kwargs)
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.metric_kwargs = kwargs  # remaining kwargs forwarded to metric_func

    def _values(self, preds: Array, target: Array) -> Array:
        best_metric, _ = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.metric_kwargs
        )
        return best_metric


class PerceptualEvaluationSpeechQuality(_MeanAudioMetric):
    """Parity: reference ``audio/pesq.py``.

    The reference gates on the third-party ITU C backend; this build ships a
    first-party P.862-structured implementation
    (``functional/audio/pesq.py``) and works out of the box — the ITU C
    backend is still preferred automatically when installed
    (``implementation="auto"``).

    Example (tones inside the narrow-band 300-3100 Hz telephone band — the
    P.862 input filter removes anything below it; the computation is pinned
    to the CPU device so the golden stays exact on accelerator backends,
    whose fused FFT/filterbank arithmetic differs in the last digit):
        >>> import jax
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PerceptualEvaluationSpeechQuality
        >>> metric = PerceptualEvaluationSpeechQuality(fs=8000, mode="nb", implementation="native")
        >>> with jax.default_device(jax.devices("cpu")[0]):
        ...     t = jnp.arange(8000) / 8000.0
        ...     target = jnp.sin(2 * jnp.pi * 440.0 * t)
        ...     preds = target + 0.1 * jnp.sin(2 * jnp.pi * 1320.0 * t)
        ...     metric.update(preds, target)
        ...     value = metric.compute()
        >>> round(float(value), 2)
        2.95
    """

    is_differentiable = False
    higher_is_better = True
    jittable = False
    plot_lower_bound = -0.5
    plot_upper_bound = 4.5

    def __init__(self, fs: int, mode: str, n_processes: int = 1,
                 implementation: str = "auto", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        if implementation not in ("auto", "itu", "native"):
            raise ValueError(
                f"Expected argument `implementation` in ('auto','itu','native'), got {implementation}"
            )
        self.fs = fs
        self.mode = mode
        self.n_processes = n_processes
        self.implementation = implementation

    def _values(self, preds: Array, target: Array) -> Array:
        return perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode,
                                                    n_processes=self.n_processes,
                                                    implementation=self.implementation)


class ShortTimeObjectiveIntelligibility(_MeanAudioMetric):
    """Parity: reference ``audio/stoi.py``. First-party implementation
    (``functional/audio/stoi.py``) — no pystoi dependency.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ShortTimeObjectiveIntelligibility
        >>> metric = ShortTimeObjectiveIntelligibility(fs=8000)
        >>> t = jnp.linspace(0.0, 100.0, 4096)
        >>> target = jnp.sin(t)
        >>> preds = target + 0.1 * jnp.cos(3.0 * t)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.793
    """

    is_differentiable = False
    higher_is_better = True
    jittable = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.extended = extended

    def _values(self, preds: Array, target: Array) -> Array:
        return short_time_objective_intelligibility(preds, target, self.fs, self.extended)


class SpeechReverberationModulationEnergyRatio(_MeanAudioMetric):
    """Parity: reference ``audio/srmr.py``. First-party implementation
    (``functional/audio/srmr.py``) — no gammatone/torchaudio dependency.

    Example (pinned to the CPU device so the 4-digit golden stays exact on
    accelerator backends, whose filterbank arithmetic differs in the final
    digit):
        >>> import jax
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SpeechReverberationModulationEnergyRatio
        >>> metric = SpeechReverberationModulationEnergyRatio(fs=8000)
        >>> with jax.default_device(jax.devices("cpu")[0]):
        ...     t = jnp.linspace(0.0, 400.0, 4096)
        ...     metric.update(jnp.sin(t) * (1 + 0.5 * jnp.sin(0.05 * t)))
        ...     value = metric.compute()
        >>> round(float(value), 4)
        77.1469
    """

    is_differentiable = False
    higher_is_better = True
    jittable = False

    def __init__(
        self,
        fs: int,
        n_cochlear_filters: int = 23,
        low_freq: float = 125.0,
        min_cf: float = 4.0,
        max_cf: Optional[float] = None,
        norm: bool = False,
        fast: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.n_cochlear_filters = n_cochlear_filters
        self.low_freq = low_freq
        self.min_cf = min_cf
        self.max_cf = max_cf
        self.norm = norm
        self.fast = fast

    def update(self, preds: Array) -> None:  # SRMR is reference-free
        values = speech_reverberation_modulation_energy_ratio(
            preds, self.fs, n_cochlear_filters=self.n_cochlear_filters,
            low_freq=self.low_freq, min_cf=self.min_cf, max_cf=self.max_cf,
            norm=self.norm, fast=self.fast,
        )
        self.sum_value = self.sum_value + jnp.sum(values)
        self.total = self.total + values.size

    def _values(self, preds: Array, target: Array) -> Array:  # pragma: no cover
        raise NotImplementedError
