"""SARIF 2.1.0 export so CI and editors consume tpulint findings natively.

One ``run`` per invocation; every rule in the catalog is declared on the
driver with its severity tier as ``defaultConfiguration.level``; new
violations become ``results``, waived/baselined ones are emitted as
suppressed results (``suppressions``) so SARIF viewers show the full audit
trail without failing the build on them.
"""
from __future__ import annotations

from typing import Dict, List

from .rules import ALL_RULES, RULE_SEVERITY, RULE_TITLES, Violation

SARIF_SCHEMA = "https://docs.oasis-open.org/sarif/sarif/v2.1.0/errata01/os/schemas/sarif-schema-2.1.0.json"
SARIF_VERSION = "2.1.0"

_LEVELS = {"error": "error", "warn": "warning"}


def _result(v: Violation) -> Dict:
    out: Dict = {
        "ruleId": v.rule,
        "level": _LEVELS.get(RULE_SEVERITY.get(v.rule, "error"), "error"),
        "message": {"text": f"{v.message} [{v.symbol}]"},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": v.path.replace("\\", "/")},
                    "region": {"startLine": max(1, v.line), "startColumn": max(1, v.col + 1)},
                }
            }
        ],
    }
    if v.waived:
        out["suppressions"] = [
            {"kind": "inSource", "justification": v.waive_reason or "waived"}
        ]
    elif v.baselined:
        out["suppressions"] = [{"kind": "external", "justification": "baselined"}]
    return out


def to_sarif(result) -> Dict:
    """Convert a :class:`tools.tpulint.LintResult` to a SARIF 2.1.0 dict."""
    rules: List[Dict] = [
        {
            "id": rule,
            "name": rule,
            "shortDescription": {"text": RULE_TITLES.get(rule, rule)},
            "defaultConfiguration": {"level": _LEVELS.get(RULE_SEVERITY.get(rule, "error"), "error")},
        }
        for rule in ALL_RULES
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpulint",
                        "informationUri": "docs/static_analysis.md",
                        "rules": rules,
                    }
                },
                "results": [_result(v) for v in result.violations],
            }
        ],
    }
