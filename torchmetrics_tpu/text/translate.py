"""Translation-quality metric classes: BLEU, SacreBLEU, CHRF, TER, EED.

Parity targets: reference ``text/{bleu,sacre_bleu,chrf,ter,eed}.py`` — host
tokenization/counting; device sum states (count vectors), ratio computes.
"""
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..functional.text.bleu import _bleu_counts, _bleu_score_compute
from ..functional.text.chrf import _chrf_update, _fscore_from_counts
from ..functional.text.eed import _eed_update
from ..functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from ..functional.text.ter import _TercomTokenizer, _ter_update
from ..utils.data import dim_zero_cat
from .asr import _HostTextMetric

Array = jax.Array


class BLEUScore(_HostTextMetric):
    """Parity: reference ``text/bleu.py:BLEUScore`` (157 LoC).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import BLEUScore
        >>> metric = BLEUScore()
        >>> metric.update(["the cat is on the mat"], [["there is a cat on the mat", "the cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, n_gram: int = 4, smooth: bool = False,
                 weights: Optional[Sequence[float]] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights or [1.0 / n_gram] * n_gram
        self.add_state("preds_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    def _tokenizer(self):
        return lambda line: line.split()

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        num, den, plen, tlen = _bleu_counts(preds_, target_, self.n_gram, self._tokenizer())
        self.numerator = self.numerator + jnp.asarray(num)
        self.denominator = self.denominator + jnp.asarray(den)
        self.preds_len = self.preds_len + float(plen)
        self.target_len = self.target_len + float(tlen)

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator,
            self.n_gram, self.weights, self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """Parity: reference ``text/sacre_bleu.py:SacreBLEUScore``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> metric.update(["the cat is on the mat"], [["there is a cat on the mat", "the cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    def __init__(self, n_gram: int = 4, smooth: bool = False, tokenize: str = "13a",
                 lowercase: bool = False, weights: Optional[Sequence[float]] = None,
                 **kwargs: Any) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        self._sacre_tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    def _tokenizer(self):
        return self._sacre_tokenizer


class CHRFScore(_HostTextMetric):
    """Parity: reference ``text/chrf.py:CHRFScore`` — flat count-vector states.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CHRFScore
        >>> metric = CHRFScore()
        >>> metric.update(["the cat sat on the mat"], ["the cat sat on a mat"])
        >>> round(float(metric.compute()), 4)
        0.7198
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, n_char_order: int = 6, n_word_order: int = 2, beta: float = 2.0,
                 lowercase: bool = False, whitespace: bool = False,
                 return_sentence_level_score: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        k = n_char_order + n_word_order
        self.add_state("matching", jnp.zeros(k), dist_reduce_fx="sum")
        self.add_state("pred_total", jnp.zeros(k), dist_reduce_fx="sum")
        self.add_state("ref_total", jnp.zeros(k), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf", [], dist_reduce_fx="cat")

    def update(self, preds: Sequence[str], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        scores = [] if self.return_sentence_level_score else None
        m, p, r = _chrf_update(
            preds_, list(target), self.n_char_order, self.n_word_order,
            self.beta, self.lowercase, self.whitespace, scores,
        )
        self.matching = self.matching + jnp.asarray(m)
        self.pred_total = self.pred_total + jnp.asarray(p)
        self.ref_total = self.ref_total + jnp.asarray(r)
        if self.return_sentence_level_score:
            self.sentence_chrf.append(jnp.asarray(scores, dtype=jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _fscore_from_counts(self.matching, self.pred_total, self.ref_total, self.beta)
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_chrf)
        return score


class TranslationEditRate(_HostTextMetric):
    """Parity: reference ``text/ter.py:TranslationEditRate``.

    .. note::
        Tokenization is memoized: the metric's ``_TercomTokenizer`` keeps a
        per-instance **LRU** of tokenized sentences, capped at
        ``_MEMO_CAP = 4096`` entries (``functional/text/ter.py``): cache
        hits refresh an entry's recency and overflow evicts the
        least-recently-used entry, so repeated references stay cached while
        a long low-repetition stream cannot grow the memo past the cap. The
        memo persists across ``update()`` and ``reset()`` calls for the
        lifetime of the metric object — worst-case host memory is therefore
        bounded by 4096 cached sentences, not by epoch length (at a typical
        ~200 bytes per tokenized sentence that is well under 1 MB per metric
        instance; long-document inputs scale it linearly with sentence
        length) — and is NOT part of the metric state: it is excluded from
        ``state_dict()`` and distributed sync (it only serves to skip
        re-tokenizing repeated references).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import TranslationEditRate
        >>> metric = TranslationEditRate()
        >>> metric.update(["the cat sat on the mat"], ["the cat sat on a mat"])
        >>> round(float(metric.compute()), 4)
        0.1667
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, normalize: bool = False, no_punctuation: bool = False,
                 lowercase: bool = True, asian_support: bool = False,
                 return_sentence_level_score: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score
        self.add_state("total_num_edits", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        scores = [] if self.return_sentence_level_score else None
        edits, tgt_len = _ter_update(preds_, list(target), self.tokenizer, scores)
        self.total_num_edits = self.total_num_edits + edits
        self.total_tgt_length = self.total_tgt_length + tgt_len
        if self.return_sentence_level_score:
            self.sentence_ter.append(jnp.asarray(scores, dtype=jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        # tercom conventions: 0 edits -> 0; edits with no reference mass -> 1
        safe = self.total_num_edits / jnp.maximum(self.total_tgt_length, 1e-12)
        score = jnp.where(
            self.total_tgt_length > 0,
            safe,
            # nan tgt_length (empty-reference-list sample) falls to 0.0 here,
            # matching the reference's score branches
            jnp.where((self.total_tgt_length == 0) & (self.total_num_edits > 0), 1.0, 0.0),
        )
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_ter)
        return score


class ExtendedEditDistance(_HostTextMetric):
    """Parity: reference ``text/eed.py:ExtendedEditDistance``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ExtendedEditDistance
        >>> metric = ExtendedEditDistance()
        >>> metric.update(["the cat sat on the mat"], ["the cat sat on a mat"])
        >>> round(float(metric.compute()), 4)
        0.1452
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, language: str = "en", return_sentence_level_score: bool = False,
                 alpha: float = 2.0, rho: float = 0.3, deletion: float = 0.2,
                 insertion: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        for name, val in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(val, (int, float)) or val < 0:
                raise ValueError(f"Parameter `{name}` is expected to be a non-negative number.")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha, self.rho, self.deletion, self.insertion = alpha, rho, deletion, insertion
        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        scores = _eed_update(preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion)
        self.sentence_eed.append(jnp.asarray(scores, dtype=jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        all_scores = dim_zero_cat(self.sentence_eed)
        mean = jnp.mean(all_scores) if all_scores.size else jnp.asarray(0.0)
        if self.return_sentence_level_score:
            return mean, all_scores
        return mean
