"""Batched padded-query retrieval kernels — the TPU-native core.

The reference computes every retrieval metric one query at a time with a
Python loop over ``torch.split`` groups (``retrieval/base.py:146-183``).
On TPU that shape-varying loop is poison for XLA; instead every kernel here
operates on a dense padded batch ``(Q, L)`` (queries x max-docs) with a
validity ``mask``, so an epoch's worth of per-query scores is ONE fused XLA
program (sort + cumsum + reductions on the VPU, no host round-trips).

Single-query functional wrappers (``retrieval_average_precision`` et al.)
reshape to ``(1, L)`` and index out the scalar — same kernels, same numerics.

Parity targets: reference ``functional/retrieval/*.py`` (average_precision.py:22,
reciprocal_rank.py:22, precision.py:21, recall.py:22, fall_out.py:22,
hit_rate.py:22, ndcg.py:71, r_precision.py:20, auroc.py:22,
precision_recall_curve.py:24).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def sort_by_preds(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array, Array]:
    """Per-row sort by descending prediction; padded entries go last.

    Returns (preds_sorted, target_sorted, mask_sorted), each (Q, L).
    """
    key = jnp.where(mask, -preds, jnp.inf)
    order = jnp.argsort(key, axis=-1, stable=True)
    p = jnp.take_along_axis(preds, order, axis=-1)
    t = jnp.take_along_axis(target, order, axis=-1)
    m = jnp.take_along_axis(mask, order, axis=-1)
    return p, t, m


def _ranks(mask_sorted: Array) -> Array:
    """1-based rank positions, (Q, L) broadcast."""
    length = mask_sorted.shape[-1]
    return jnp.arange(1, length + 1, dtype=jnp.float32)[None, :]


def _within_k(mask_sorted: Array, top_k: Optional[int]) -> Array:
    """Boolean (Q, L): doc is valid and ranked within top_k."""
    ranks = _ranks(mask_sorted)
    sel = mask_sorted
    if top_k is not None:
        sel = sel & (ranks <= float(top_k))
    return sel


def batched_average_precision(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """AP per query, (Q,). Mean over hit positions of (#hits so far / rank)."""
    _, t, m = sort_by_preds(preds, target, mask)
    t = t.astype(jnp.float32) * m
    sel = _within_k(m, top_k)
    hits = t * sel
    prec = jnp.cumsum(hits, axis=-1) / _ranks(m)
    n_hits = jnp.sum(hits, axis=-1)
    ap = jnp.sum(prec * hits, axis=-1) / jnp.maximum(n_hits, 1.0)
    return jnp.where(n_hits > 0, ap, 0.0)


def batched_reciprocal_rank(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """1/rank of the first relevant doc within top_k; 0 if none. (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    sel = _within_k(m, top_k)
    hits = t.astype(jnp.float32) * sel
    return jnp.max(hits / _ranks(m), axis=-1)


def batched_precision(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """Fraction of top-k docs that are relevant. (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    n_docs = jnp.sum(m.astype(jnp.float32), axis=-1)
    k = jnp.full_like(n_docs, float(top_k)) if top_k is not None else n_docs
    if adaptive_k or top_k is None:
        k = jnp.minimum(k, n_docs)
    sel = m & (_ranks(m) <= k[:, None])
    hits = jnp.sum(t.astype(jnp.float32) * sel, axis=-1)
    return hits / jnp.maximum(k, 1.0)


def batched_recall(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Fraction of all relevant docs retrieved in the top-k. (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    t = t.astype(jnp.float32) * m
    sel = _within_k(m, top_k)
    n_pos = jnp.sum(t, axis=-1)
    hits = jnp.sum(t * sel, axis=-1)
    return jnp.where(n_pos > 0, hits / jnp.maximum(n_pos, 1.0), 0.0)


def batched_fall_out(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Fraction of all NON-relevant docs retrieved in the top-k. (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    neg = (1.0 - t.astype(jnp.float32)) * m
    sel = _within_k(m, top_k)
    n_neg = jnp.sum(neg, axis=-1)
    hits = jnp.sum(neg * sel, axis=-1)
    return jnp.where(n_neg > 0, hits / jnp.maximum(n_neg, 1.0), 0.0)


def batched_hit_rate(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """1.0 if any relevant doc in the top-k else 0.0. (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    sel = _within_k(m, top_k)
    return (jnp.sum(t.astype(jnp.float32) * sel, axis=-1) > 0).astype(jnp.float32)


def batched_r_precision(preds: Array, target: Array, mask: Array) -> Array:
    """Precision at rank R where R = #relevant docs of the query. (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    t = t.astype(jnp.float32) * m
    n_pos = jnp.sum(t, axis=-1)
    sel = m & (_ranks(m) <= n_pos[:, None])
    hits = jnp.sum(t * sel, axis=-1)
    return jnp.where(n_pos > 0, hits / jnp.maximum(n_pos, 1.0), 0.0)


def batched_ndcg(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Normalized DCG with linear gain and log2 discount (sklearn-style,
    ignore-ties variant of reference ``functional/retrieval/ndcg.py:45``). (Q,).
    Supports graded (non-binary, non-negative) relevance."""
    _, g, m = sort_by_preds(preds, target, mask)
    g = g.astype(jnp.float32) * m
    ranks = _ranks(m)
    disc = 1.0 / jnp.log2(ranks + 1.0)
    sel = _within_k(m, top_k)
    dcg = jnp.sum(g * disc * sel, axis=-1)
    # ideal ordering: sort gains descending within the valid docs
    ideal = jnp.sort(jnp.where(mask, target.astype(jnp.float32), -jnp.inf), axis=-1)[:, ::-1]
    ideal = jnp.where(jnp.isfinite(ideal), ideal, 0.0)
    idcg = jnp.sum(ideal * disc * sel, axis=-1)
    return jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-12), 0.0)


def batched_auroc(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None, max_fpr: Optional[float] = None
) -> Array:
    """Per-query binary AUROC over the top-k docs (trapezoidal over the exact
    ROC; McClish-standardized partial AUC when ``max_fpr``). (Q,)."""
    _, t, m = sort_by_preds(preds, target, mask)
    sel = _within_k(m, top_k)
    t = t.astype(jnp.float32)
    pos = t * sel
    neg = (1.0 - t) * sel
    n_pos = jnp.sum(pos, axis=-1, keepdims=True)
    n_neg = jnp.sum(neg, axis=-1, keepdims=True)
    tpr = jnp.cumsum(pos, axis=-1) / jnp.maximum(n_pos, 1.0)
    fpr = jnp.cumsum(neg, axis=-1) / jnp.maximum(n_neg, 1.0)
    tpr0 = jnp.concatenate([jnp.zeros_like(tpr[:, :1]), tpr], axis=-1)
    fpr0 = jnp.concatenate([jnp.zeros_like(fpr[:, :1]), fpr], axis=-1)
    if max_fpr is None:
        auc = jnp.sum((fpr0[:, 1:] - fpr0[:, :-1]) * (tpr0[:, 1:] + tpr0[:, :-1]) * 0.5, axis=-1)
    else:
        # clip each trapezoid segment at fpr = max_fpr (linear interpolation)
        x0, x1 = fpr0[:, :-1], fpr0[:, 1:]
        y0, y1 = tpr0[:, :-1], tpr0[:, 1:]
        cx1 = jnp.minimum(x1, max_fpr)
        frac = jnp.where(x1 > x0, (cx1 - x0) / jnp.maximum(x1 - x0, 1e-12), 0.0)
        cy1 = y0 + frac * (y1 - y0)
        seg = jnp.where(x0 < max_fpr, (cx1 - x0) * (y0 + cy1) * 0.5, 0.0)
        pauc = jnp.sum(seg, axis=-1)
        min_area = 0.5 * max_fpr * max_fpr
        max_area = max_fpr
        auc = 0.5 * (1.0 + (pauc - min_area) / (max_area - min_area))
    valid = (n_pos[:, 0] > 0) & (n_neg[:, 0] > 0)
    return jnp.where(valid, auc, 0.0)


def batched_precision_recall_curve(
    preds: Array, target: Array, mask: Array, max_k: int, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Per-query precision@k / recall@k for k = 1..max_k.

    Returns (precision (Q, max_k), recall (Q, max_k), top_k (max_k,)).
    With ``adaptive_k`` the denominator of precision@k is min(k, n_docs).
    """
    _, t, m = sort_by_preds(preds, target, mask)
    t = t.astype(jnp.float32) * m
    length = t.shape[-1]
    n_pos = jnp.sum(t, axis=-1, keepdims=True)
    rel_cum = jnp.cumsum(t, axis=-1)  # (Q, L)
    ks = jnp.arange(1, max_k + 1, dtype=jnp.int32)
    idx = jnp.minimum(ks - 1, length - 1)
    rel_at_k = rel_cum[:, idx]  # (Q, max_k)
    denom = ks.astype(jnp.float32)[None, :]
    if adaptive_k:
        n_docs = jnp.sum(m.astype(jnp.float32), axis=-1, keepdims=True)
        denom = jnp.minimum(denom, jnp.maximum(n_docs, 1.0))
    precision = rel_at_k / denom
    recall = jnp.where(n_pos > 0, rel_at_k / jnp.maximum(n_pos, 1.0), 0.0)
    return precision, recall, ks


def _check_retrieval_functional_inputs(preds: Array, target: Array, allow_non_binary_target: bool = False):
    """Parity: reference ``utilities/checks.py`` retrieval functional checks."""
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if jnp.issubdtype(target.dtype, jnp.floating) and not allow_non_binary_target:
        raise ValueError("`target` must be a tensor of booleans or integers")
    return preds.reshape(-1).astype(jnp.float32), target.reshape(-1)


def _single(fn, preds: Array, target: Array, allow_non_binary_target: bool = False, **kwargs) -> Array:
    p, t = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target)
    mask = jnp.ones_like(p, dtype=bool)
    return fn(p[None, :], t[None, :], mask[None, :], **kwargs)[0]
