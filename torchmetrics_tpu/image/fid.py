"""Frechet inception distance — streaming feature moments, never stores features.

Parity: reference ``src/torchmetrics/image/fid.py`` (436 LoC): running sum +
outer-product cov-sum + count for real/fake features (all ``"sum"``-reduce,
``image/fid.py:324-348``), ``_compute_fid`` via matrix sqrt (:159).

TPU-first: the feature extractor is injectable (any callable mapping a (N, C,
H, W) image batch to (N, D) features — e.g. a Flax module's apply). The
reference's ``NoTrainInceptionV3`` (``image/fid.py:44``) depends on
torch-fidelity's downloaded weights; in this offline build, pass
``feature=<callable>``; an integer selects the FID-Inception architecture and
raises with guidance when pretrained weights are unavailable.

The matrix sqrt uses the symmetric-eigh trick: tr(sqrtm(S1 S2)) =
sum(sqrt(eig(S1^{1/2} S2 S1^{1/2}))) — stable and XLA-friendly.
"""
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp

from ..metric import Metric

Array = jax.Array


# covariance/sqrtm matmuls must not lower to bf16 multiplies on TPU —
# FID is a trace of eigenvalues of matmul products, so bf16 noise in the
# products shifts the headline value at the 1e-2 level
_HI = jax.lax.Precision.HIGHEST


def _sqrtm_psd(mat: Array) -> Array:
    """Symmetric PSD matrix square root via eigendecomposition."""
    vals, vecs = jnp.linalg.eigh(mat)
    vals = jnp.clip(vals, min=0.0)
    return jnp.matmul(vecs * jnp.sqrt(vals)[None, :], vecs.T, precision=_HI)


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """Parity: reference ``image/fid.py:159``."""
    diff = mu1 - mu2
    s1h = _sqrtm_psd(sigma1)
    covmean_sq = jnp.matmul(jnp.matmul(s1h, sigma2, precision=_HI), s1h, precision=_HI)
    vals = jnp.clip(jnp.linalg.eigvalsh(covmean_sq), min=0.0)
    tr_covmean = jnp.sum(jnp.sqrt(vals))
    return jnp.dot(diff, diff, precision=_HI) + jnp.trace(sigma1) + jnp.trace(sigma2) - 2.0 * tr_covmean


def _resolve_feature_extractor(feature: Union[int, str, Callable], metric_name: str) -> Callable:
    if callable(feature):
        return feature
    if isinstance(feature, (int, str)):  # tap id: 64/192/768/2048 or 'logits_unbiased'
        valid = (64, 192, 768, 2048, 1008, "logits_unbiased")
        if feature not in valid:
            raise ValueError(
                f"Input to argument `feature` must be one of {valid}, but got {feature!r}"
            )
        from ..models.pretrained import fid_inception_extractor, weights_dir

        extractor = fid_inception_extractor(feature)
        if extractor is not None:
            return extractor
        raise ModuleNotFoundError(
            f"Metric `{metric_name}` with `feature={feature!r}` requires the pretrained FID-InceptionV3 weights, "
            f"which were not found in the weights cache ({weights_dir()}). On a machine with network access run "
            "`python tools/fetch_weights.py fid` once (download + checksum + convert; the reference "
            "auto-downloads the same torch-fidelity checkpoint at construction). Alternatively pass any "
            "callable mapping (N, C, H, W) images to (N, D) features as `feature=`."
        )
    raise TypeError(f"Got unknown input to argument `feature`: {feature}")


class FrechetInceptionDistance(Metric):
    """Frechet distance between real/fake feature distributions.

    Parity: reference ``image/fid.py:182``. States are streaming moments
    (sum, outer-product sum, count — all ``"sum"``-reducible; features are
    never stored), the InceptionV3-fid extractor is a Flax module, and
    ``feature`` also accepts any callable ``(N,C,H,W) -> (N,D)`` so the
    metric runs offline / with custom embeddings.

    Example (custom feature callable; real Inception features need the
    converted checkpoint, see ``torchmetrics_tpu.models.inception``):
        >>> import numpy as np
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import FrechetInceptionDistance
        >>> def feat(imgs):
        ...     flat = imgs.reshape(imgs.shape[0], -1).astype(jnp.float32)
        ...     return jnp.stack([flat.mean(axis=1), flat.std(axis=1)], axis=1)
        >>> fid = FrechetInceptionDistance(feature=feat, normalize=True)
        >>> real = jnp.asarray(np.random.RandomState(0).rand(8, 3, 16, 16), jnp.float32)
        >>> fake = jnp.asarray(np.random.RandomState(1).rand(8, 3, 16, 16) * 0.5, jnp.float32)
        >>> fid.update(real, real=True)
        >>> fid.update(fake, real=False)
        >>> round(float(fid.compute()), 2)
        0.08
    """

    higher_is_better = False
    is_differentiable = False
    full_state_update = False
    plot_lower_bound = 0.0
    feature_network = "inception"
    jittable = False

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception = _resolve_feature_extractor(feature, "FrechetInceptionDistance")
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize

        # lazily sized on first update (feature dim known after first extract)
        self._num_features: int = -1
        self._states_added = False

    def _ensure_states(self, d: int) -> None:
        if self._states_added:
            return
        self._num_features = d
        self.add_state("real_features_sum", jnp.zeros((d,), dtype=jnp.float64 if False else jnp.float32),
                       dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", jnp.zeros((d, d), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", jnp.zeros((d,), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", jnp.zeros((d, d), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", jnp.asarray(0.0), dist_reduce_fx="sum")
        self._states_added = True

    def update(self, imgs: Array, real: bool) -> None:
        """Parity: reference ``image/fid.py:332``."""
        features = jnp.asarray(self.inception(imgs)).astype(jnp.float32)
        self._ensure_states(features.shape[-1])
        f_sum = jnp.sum(features, axis=0)
        f_cov = jnp.matmul(features.T, features, precision=_HI)
        n = jnp.asarray(features.shape[0], dtype=jnp.float32)
        if real:
            self.real_features_sum = self.real_features_sum + f_sum
            self.real_features_cov_sum = self.real_features_cov_sum + f_cov
            self.real_features_num_samples = self.real_features_num_samples + n
        else:
            self.fake_features_sum = self.fake_features_sum + f_sum
            self.fake_features_cov_sum = self.fake_features_cov_sum + f_cov
            self.fake_features_num_samples = self.fake_features_num_samples + n

    def compute(self) -> Array:
        """Parity: reference ``image/fid.py:350-360``."""
        n_r = self.real_features_num_samples
        n_f = self.fake_features_num_samples
        mean_real = self.real_features_sum / n_r
        mean_fake = self.fake_features_sum / n_f
        cov_real = (self.real_features_cov_sum - n_r * jnp.outer(mean_real, mean_real)) / (n_r - 1)
        cov_fake = (self.fake_features_cov_sum - n_f * jnp.outer(mean_fake, mean_fake)) / (n_f - 1)
        return _compute_fid(mean_real, cov_real, mean_fake, cov_fake)

    def reset(self) -> None:
        if not self._states_added:
            return
        if not self.reset_real_features:
            saved = (
                self.real_features_sum,
                self.real_features_cov_sum,
                self.real_features_num_samples,
            )
            super().reset()
            self.real_features_sum, self.real_features_cov_sum, self.real_features_num_samples = saved
        else:
            super().reset()
