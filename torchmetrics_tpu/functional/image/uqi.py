"""Universal image quality index (UQI).

Parity: reference ``src/torchmetrics/functional/image/uqi.py`` — SSIM with
C1 = C2 = 0 computed with a gaussian window.
"""
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape
from .helper import depthwise_conv2d, gaussian_kernel_2d, reflect_pad_2d

Array = jax.Array


def _uqi_update(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
) -> Array:
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)

    channel = preds.shape[1]
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2
    preds_p = reflect_pad_2d(preds, pad_h, pad_w)
    target_p = reflect_pad_2d(target, pad_h, pad_w)
    kernel = gaussian_kernel_2d(channel, kernel_size, sigma)

    n = preds.shape[0]
    # Center by the global per-image means before filtering: the
    # E[x^2]-E[x]^2 form cancels catastrophically on near-constant windows
    # (conv float noise ~3*eps of the mean power becomes the whole variance
    # estimate, which the eps-guarded ratio amplifies to arbitrary scores).
    # On centered data the products are O(|x-m|^2), so the absolute error is
    # proportional to the *variance* scale, not the mean-power scale — for
    # constant images the sigma terms come out ~eps^2, reproducing the
    # reference's exact-0 windows through its own formula with no special
    # casing (docs/migrating_from_torchmetrics.md).
    mean_p = jnp.mean(preds, axis=(1, 2, 3), keepdims=True)
    mean_t = jnp.mean(target, axis=(1, 2, 3), keepdims=True)
    dp = preds_p - mean_p
    dt = target_p - mean_t
    input_list = jnp.concatenate([dp, dt, dp * dp, dt * dt, dp * dt], axis=0)
    outputs = depthwise_conv2d(input_list, kernel)
    mu_dp = outputs[:n]
    mu_dt = outputs[n : 2 * n]
    mu_pred = mu_dp + mean_p
    mu_target = mu_dt + mean_t
    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target
    # variances clamped at 0, matching reference ``uqi.py:106-107``
    sigma_pred_sq = jnp.maximum(outputs[2 * n : 3 * n] - mu_dp**2, 0.0)
    sigma_target_sq = jnp.maximum(outputs[3 * n : 4 * n] - mu_dt**2, 0.0)
    sigma_pred_target = outputs[4 * n :] - mu_dp * mu_dt

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq
    eps = jnp.finfo(jnp.float32).eps
    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower + eps)
    uqi_idx = uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w] if pad_h and pad_w else uqi_idx
    return jnp.mean(uqi_idx.reshape(n, -1), axis=-1)


def _uqi_reduce(vals: Array, reduction: Optional[str]) -> Array:
    if reduction == "elementwise_mean":
        return jnp.mean(vals)
    if reduction == "sum":
        return jnp.sum(vals)
    return vals


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Parity: reference ``uqi.py:122``."""
    vals = _uqi_update(preds, target, kernel_size, sigma)
    return _uqi_reduce(vals, reduction)
