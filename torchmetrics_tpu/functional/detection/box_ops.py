"""Pairwise bounding-box overlap kernels (IoU / GIoU / DIoU / CIoU) in JAX.

Parity targets: reference ``functional/detection/{iou,giou,diou,ciou}.py``
(which delegate to torchvision ``box_iou`` / ``generalized_box_iou`` /
``distance_box_iou`` / ``complete_box_iou``). Here the variants are a single
vectorized XLA kernel family over ``(N, 4)`` / ``(M, 4)`` corner boxes —
jit/vmap-friendly, static-shaped, no torchvision.
"""
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-7  # matches torchvision's eps in distance/complete IoU


def box_convert(boxes: Array, in_fmt: str = "xyxy", out_fmt: str = "xyxy") -> Array:
    """Convert ``(N, 4)`` boxes between ``xyxy`` / ``xywh`` / ``cxcywh``."""
    if in_fmt == out_fmt:
        return boxes
    if in_fmt == "xywh":
        x, y, w, h = jnp.split(boxes, 4, axis=-1)
        xyxy = jnp.concatenate([x, y, x + w, y + h], axis=-1)
    elif in_fmt == "cxcywh":
        cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
        xyxy = jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    elif in_fmt == "xyxy":
        xyxy = boxes
    else:
        raise ValueError(f"Unsupported box format {in_fmt!r}")
    if out_fmt == "xyxy":
        return xyxy
    x1, y1, x2, y2 = jnp.split(xyxy, 4, axis=-1)
    if out_fmt == "xywh":
        return jnp.concatenate([x1, y1, x2 - x1, y2 - y1], axis=-1)
    if out_fmt == "cxcywh":
        return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], axis=-1)
    raise ValueError(f"Unsupported box format {out_fmt!r}")


def box_area(boxes: Array) -> Array:
    """Area of ``(N, 4)`` xyxy boxes."""
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _pairwise_inter_union(preds: Array, target: Array):
    lt = jnp.maximum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.minimum(preds[:, None, 2:], target[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(preds)[:, None] + box_area(target)[None, :] - inter
    return inter, union


def box_iou_matrix(preds: Array, target: Array) -> Array:
    """Pairwise IoU matrix ``(N, M)``; torchvision ``box_iou`` semantics."""
    inter, union = _pairwise_inter_union(preds, target)
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


def box_giou_matrix(preds: Array, target: Array) -> Array:
    """Pairwise Generalized IoU: ``iou - (C - union) / C`` over enclosing box C."""
    inter, union = _pairwise_inter_union(preds, target)
    iou = inter / (union + _EPS)
    lt = jnp.minimum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.maximum(preds[:, None, 2:], target[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    enclose = wh[..., 0] * wh[..., 1]
    return iou - (enclose - union) / (enclose + _EPS)


def _center_dist_terms(preds: Array, target: Array):
    iou = box_iou_matrix(preds, target)
    # squared diagonal of the smallest enclosing box
    lt = jnp.minimum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.maximum(preds[:, None, 2:], target[None, :, 2:])
    wh = rb - lt
    diag2 = wh[..., 0] ** 2 + wh[..., 1] ** 2 + _EPS
    cp = (preds[:, :2] + preds[:, 2:]) / 2
    ct = (target[:, :2] + target[:, 2:]) / 2
    d = cp[:, None, :] - ct[None, :, :]
    rho2 = d[..., 0] ** 2 + d[..., 1] ** 2
    return iou, rho2 / diag2


def box_diou_matrix(preds: Array, target: Array) -> Array:
    """Pairwise Distance IoU: ``iou - rho^2 / c^2``."""
    iou, penalty = _center_dist_terms(preds, target)
    return iou - penalty


def box_ciou_matrix(preds: Array, target: Array) -> Array:
    """Pairwise Complete IoU: DIoU minus the aspect-ratio consistency term."""
    iou, penalty = _center_dist_terms(preds, target)
    wp = preds[:, 2] - preds[:, 0]
    hp = preds[:, 3] - preds[:, 1]
    wt = target[:, 2] - target[:, 0]
    ht = target[:, 3] - target[:, 1]
    v = (4.0 / (jnp.pi**2)) * (
        jnp.arctan(wt / (ht + _EPS))[None, :] - jnp.arctan(wp / (hp + _EPS))[:, None]
    ) ** 2
    alpha = jax.lax.stop_gradient(v / (1.0 - iou + v + _EPS))
    return iou - penalty - alpha * v


_MATRIX_FNS = {
    "iou": box_iou_matrix,
    "giou": box_giou_matrix,
    "diou": box_diou_matrix,
    "ciou": box_ciou_matrix,
}


def _variant_update(
    variant: str, preds: Array, target: Array, iou_threshold: Optional[float], replacement_val: float = 0.0
) -> Array:
    """Matrix with sub-threshold entries replaced; parity ``_iou_update`` et al."""
    mat = _MATRIX_FNS[variant](jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32))
    if iou_threshold is not None:
        mat = jnp.where(mat < iou_threshold, replacement_val, mat)
    return mat


def _variant_compute(mat: Array, aggregate: bool = True) -> Array:
    if not aggregate:
        return mat
    return jnp.mean(jnp.diagonal(mat)) if mat.size > 0 else jnp.asarray(0.0)


def _make_public(variant: str, doc_name: str):
    def fn(
        preds: Array,
        target: Array,
        iou_threshold: Optional[float] = None,
        replacement_val: float = 0.0,
        aggregate: bool = True,
    ) -> Array:
        mat = _variant_update(variant, preds, target, iou_threshold, replacement_val)
        return _variant_compute(mat, aggregate)

    fn.__name__ = doc_name
    fn.__doc__ = (
        f"Compute {variant.upper()} between two sets of ``(N, 4)`` xyxy boxes.\n\n"
        "With ``aggregate=True`` (default) returns the mean of the matrix\n"
        "diagonal (matched pairs); otherwise the full pairwise matrix.\n"
        f"Parity: reference ``functional/detection/{variant}.py``."
    )
    return fn


intersection_over_union = _make_public("iou", "intersection_over_union")
generalized_intersection_over_union = _make_public("giou", "generalized_intersection_over_union")
distance_intersection_over_union = _make_public("diou", "distance_intersection_over_union")
complete_intersection_over_union = _make_public("ciou", "complete_intersection_over_union")
