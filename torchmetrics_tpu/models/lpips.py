"""LPIPS network in Flax.

Parity target: reference ``functional/image/lpips.py:258`` (``_LPIPS``):
vendored AlexNet/VGG16 backbones with 5 feature taps, per-tap channel-unit
normalization, squared difference, 1x1 ``NetLinLayer`` heads, spatial mean,
sum over taps. The reference ships head weights in-repo (``lpips_models/
{alex,vgg,squeeze}.pth``) and takes backbones from torchvision.

Offline build: the architecture + weight converter live here; pretrained
tensors (torch ``state_dict``) convert via :func:`convert_lpips_torch` when
available locally. Random init exercises the full pipeline for tests.
"""
import warnings
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

Array = jax.Array

# input scaling constants from the LPIPS reference implementation
_SHIFT = (-0.030, -0.088, -0.188)
_SCALE = (0.458, 0.448, 0.450)

_ALEX_CFG = ((64, 11, 4, 2), (192, 5, 1, 2), (384, 3, 1, 1), (256, 3, 1, 1), (256, 3, 1, 1))
# VGG16 conv plan: taps after relu1_2, relu2_2, relu3_3, relu4_3, relu5_3
_VGG_PLAN = ((64, 64), (128, 128), (256, 256, 256), (512, 512, 512), (512, 512, 512))
# SqueezeNet-1.1 Fire plan: (squeeze, expand) channel pairs for the 8 Fire
# modules (features[3,4,6,7,9,10,11,12] in torchvision numbering). Taps per
# reference ``lpips.py:74`` feature_ranges — after the stem relu and after
# Fire modules #2,#4,#5,#6,#7,#8 (1-based; fire_i 1,3,4,5,6,7 below) —
# 7 taps, channels 64/128/256/384/384/512/512.
_SQUEEZE_FIRES = ((16, 64), (16, 64), (32, 128), (32, 128), (48, 192), (48, 192), (64, 256), (64, 256))


# pin: LPIPS parity vs the reference requires f32 conv multiplies on TPU
# (the default lowers to bf16, ~1e-3 relative noise per layer)
_HI = jax.lax.Precision.HIGHEST


class AlexFeatures(nn.Module):
    """AlexNet feature trunk with taps after each of the 5 relu stages."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps = []
        for i, (feats, k, s, p) in enumerate(_ALEX_CFG):
            if i in (1, 2):  # maxpool precedes conv2 and conv3
                x = nn.max_pool(x, (3, 3), (2, 2))
            x = nn.Conv(feats, (k, k), (s, s), padding=((p, p), (p, p)), precision=_HI, name=f"conv{i}")(x)
            x = nn.relu(x)
            taps.append(x)
        return tuple(taps)


class VGG16Features(nn.Module):
    """VGG16 trunk with taps after the last relu of each of the 5 stages."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps = []
        idx = 0
        for stage, widths in enumerate(_VGG_PLAN):
            if stage > 0:
                x = nn.max_pool(x, (2, 2), (2, 2))
            for w in widths:
                x = nn.Conv(w, (3, 3), padding=((1, 1), (1, 1)), precision=_HI, name=f"conv{idx}")(x)
                x = nn.relu(x)
                idx += 1
            taps.append(x)
        return tuple(taps)


def _ceil_max_pool(x: Array, window: int = 3, stride: int = 2) -> Array:
    """Max pool with torch ``ceil_mode=True`` semantics (pad right/bottom with
    -inf so the last partial window is kept). Shapes are static under trace."""
    h, w = x.shape[1], x.shape[2]
    pad_h = (-(h - window)) % stride if h > window else 0
    pad_w = (-(w - window)) % stride if w > window else 0
    if pad_h or pad_w:
        x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)), constant_values=-jnp.inf)
    return nn.max_pool(x, (window, window), (stride, stride))


class SqueezeFeatures(nn.Module):
    """SqueezeNet-1.1 feature trunk with the reference's 7 LPIPS taps.

    Conv order (and hence :func:`convert_lpips_torch` kernel order) matches
    the torchvision ``squeezenet1_1().features`` state dict: the stem conv,
    then per Fire module squeeze → expand1x1 → expand3x3
    (reference ``functional/image/lpips.py:65-102``).
    """

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps = []
        idx = 0

        def conv(x, feats, k, stride=1, pad=0):
            nonlocal idx
            y = nn.Conv(feats, (k, k), (stride, stride), padding=((pad, pad), (pad, pad)), precision=_HI, name=f"conv{idx}")(x)
            idx += 1
            return y

        x = nn.relu(conv(x, 64, 3, stride=2))  # features[0:2]
        taps.append(x)  # relu1
        for fire_i, (sq, ex) in enumerate(_SQUEEZE_FIRES):
            if fire_i in (0, 2, 4):  # maxpools at features[2]/[5]/[8] precede these fires
                x = _ceil_max_pool(x)
            s = nn.relu(conv(x, sq, 1))
            e1 = nn.relu(conv(s, ex, 1))
            e3 = nn.relu(conv(s, ex, 3, pad=1))
            x = jnp.concatenate([e1, e3], axis=-1)
            # reference feature_ranges end at features[4,7,9,10,11,12] — the
            # 2nd,4th,5th,6th,7th,8th Fire modules (0-based fire_i below)
            if fire_i in (1, 3, 4, 5, 6, 7):
                taps.append(x)
        return tuple(taps)


def _unit_normalize(x: Array, eps: float = 1e-8) -> Array:
    # eps inside the sqrt, matching reference ``lpips.py:215`` (_normalize_tensor)
    return x / jnp.sqrt(eps + jnp.sum(x**2, axis=-1, keepdims=True))


_TRUNKS = {"alex": AlexFeatures, "vgg": VGG16Features, "squeeze": SqueezeFeatures}


class LPIPSNet(nn.Module):
    """Full LPIPS distance network. Input: two (N, 3, H, W) images in [-1, 1]."""

    net_type: str = "alex"  # "alex" | "vgg" | "squeeze"

    @nn.compact
    def __call__(self, img0: Array, img1: Array, normalize: bool = False) -> Array:
        if normalize:  # [0, 1] -> [-1, 1] (reference `normalize` flag)
            img0 = 2 * img0 - 1
            img1 = 2 * img1 - 1
        shift = jnp.asarray(_SHIFT).reshape(1, 3, 1, 1)
        scale = jnp.asarray(_SCALE).reshape(1, 3, 1, 1)
        img0 = jnp.transpose((img0 - shift) / scale, (0, 2, 3, 1))
        img1 = jnp.transpose((img1 - shift) / scale, (0, 2, 3, 1))
        trunk = _TRUNKS[self.net_type](name="net")
        f0 = trunk(img0)
        f1 = trunk(img1)
        total = 0.0
        for i, (a, b) in enumerate(zip(f0, f1)):
            d = (_unit_normalize(a) - _unit_normalize(b)) ** 2
            w = nn.Conv(1, (1, 1), use_bias=False, precision=_HI, name=f"lin{i}")(d)  # NetLinLayer
            total = total + w.mean(axis=(1, 2))[:, 0]  # spatial average
        return total


def lpips_head_params(net_type: str = "alex") -> Dict:
    """The reference's trained NetLinLayer head weights, vendored.

    Converted once from the checkpoints the reference ships in-repo
    (``/root/reference/src/torchmetrics/functional/image/lpips_models/
    {alex,vgg,squeeze}.pth``) via :func:`convert_lpips_torch` and stored as
    ``lpips_heads.npz`` next to this module. Returns ``{"lin<i>": {"kernel":
    (1, 1, C_i, 1)}}`` ready to merge over an :func:`LPIPSNet.init` pytree.
    """
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lpips_heads.npz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"vendored LPIPS head weights not found at {path}; regenerate with tools/convert_lpips_heads.py"
        )
    with np.load(path) as data:
        heads = {}
        prefix = f"{net_type}/"
        for key in data.files:
            if key.startswith(prefix):
                heads[key[len(prefix):]] = {"kernel": jnp.asarray(data[key])}
    if not heads:
        raise KeyError(f"no heads for net_type={net_type!r} in {path}")
    return heads


def make_lpips(net_type: str = "alex", rng_seed: int = 0, pretrained_heads: bool = True,
               backbone: str = "auto"):
    """(module, params, distance_fn); ``distance_fn(x, y)`` maps two
    (N, 3, H, W) [-1, 1] image batches to (N,) distances — directly usable as
    the ``net_type=`` callable of ``LearnedPerceptualImagePatchSimilarity``.

    ``backbone``: ``"auto"`` loads the converted canonical torchvision
    weights from the cache when ``tools/fetch_weights.py lpips`` has run
    (reference-comparable distances) and falls back to random init with a
    warning otherwise; ``"pretrained"`` requires the cache; ``"random"``
    never consults it. ``pretrained_heads=True`` overlays the reference's
    trained NetLinLayer weights from :func:`lpips_head_params` (random
    backbones only; the cached artifact already contains the heads).
    """
    if backbone not in ("auto", "pretrained", "random"):
        raise ValueError(f"`backbone` must be 'auto', 'pretrained' or 'random', got {backbone!r}")
    mod = LPIPSNet(net_type=net_type)
    params = None
    if backbone in ("auto", "pretrained"):
        from .pretrained import lpips_params, weights_dir

        loaded = lpips_params(net_type)
        if loaded is not None:
            params = jax.tree.map(jnp.asarray, loaded)
        elif backbone == "pretrained":
            raise FileNotFoundError(
                f"make_lpips(backbone='pretrained'): no converted {net_type!r} backbone in the weights "
                f"cache ({weights_dir()}); run `python tools/fetch_weights.py lpips` on a networked machine."
            )
    if params is None:
        params = mod.init(jax.random.PRNGKey(rng_seed), jnp.zeros((1, 3, 64, 64)), jnp.zeros((1, 3, 64, 64)))
        if pretrained_heads:
            warnings.warn(
                "make_lpips: trained LPIPS heads are overlaid on a RANDOM-init backbone;"
                " distances are self-consistent but not comparable to reference LPIPS."
                " Run `python tools/fetch_weights.py lpips` once (networked) to cache the"
                " canonical torchvision backbone weights.",
                UserWarning,
                stacklevel=2,
            )
            inner = dict(params["params"])
            inner.update(lpips_head_params(net_type))
            params = {"params": inner}

    @jax.jit
    def distance(x: Array, y: Array) -> Array:
        return mod.apply(params, x, y)

    return mod, params, distance


def resolve_pretrained_distance(net_or_fn, metric_name: str, arg_name: str):
    """Shared string→pretrained-LPIPS resolution for metric ctors.

    Callables pass through; 'alex'/'vgg'/'squeeze' load the converted
    canonical backbone from the weights cache, raising one consistent
    fetch-tool-guidance error when it is absent."""
    if callable(net_or_fn):
        return net_or_fn
    if isinstance(net_or_fn, str):
        valid = ("vgg", "alex", "squeeze")
        if net_or_fn not in valid:
            raise ValueError(f"Argument `{arg_name}` must be one of {valid} or a callable, but got {net_or_fn!r}.")
        from .pretrained import weights_dir

        try:
            _, _, distance = make_lpips(net_or_fn, backbone="pretrained")
        except FileNotFoundError:
            raise ModuleNotFoundError(
                f"{metric_name} with the pretrained `{net_or_fn}` LPIPS net requires the converted "
                f"torchvision weights, which were not found in the weights cache ({weights_dir()}). On a "
                "machine with network access run `python tools/fetch_weights.py lpips` once, or pass a "
                f"callable `(img1, img2) -> distances` as `{arg_name}`."
            ) from None
        return distance
    raise ValueError(f"Argument `{arg_name}` must be a string preset or a callable")


_EXPECTED_CONVS = {"alex": 5, "vgg": 13, "squeeze": 1 + 3 * len(_SQUEEZE_FIRES)}


def convert_lpips_torch(backbone_state: Dict, heads_state: Dict, net_type: str = "alex") -> Dict:
    """Convert torchvision backbone + reference in-repo head weights
    (``lpips_models/{alex,vgg,squeeze}.pth``) to this module's params pytree.

    Backbone conv ``weight`` (O, I, kH, kW) → kernel (kH, kW, I, O) in state
    -dict order (which matches the trunk modules' conv numbering); head
    entries ``lin<k>.model.1.weight`` (1, C, 1, 1) → ``lin<k>`` kernel
    (5 heads for alex/vgg, 7 for squeeze). ``net_type`` validates that the
    backbone's conv count matches the corresponding trunk plan.
    """
    params: Dict = {"net": {}}
    conv_idx = 0
    items = [(k, v) for k, v in backbone_state.items() if k.endswith("weight") and np.asarray(v).ndim == 4]
    expected = _EXPECTED_CONVS.get(net_type)
    if expected is not None and len(items) != expected:
        raise ValueError(
            f"backbone_state has {len(items)} conv kernels but the {net_type!r} trunk expects {expected}"
        )
    for (k, v) in items:
        arr = np.asarray(v)
        params["net"][f"conv{conv_idx}"] = {"kernel": jnp.asarray(arr.transpose(2, 3, 1, 0))}
        bias_key = k[: -len("weight")] + "bias"
        if bias_key in backbone_state:
            params["net"][f"conv{conv_idx}"]["bias"] = jnp.asarray(np.asarray(backbone_state[bias_key]))
        conv_idx += 1
    for k, v in heads_state.items():
        if "weight" not in k:
            continue
        lin = k.split(".")[0]  # "lin0".."lin4"
        arr = np.asarray(v)  # (1, C, 1, 1)
        params[lin] = {"kernel": jnp.asarray(arr.transpose(2, 3, 1, 0))}
    return {"params": params}
