"""Modular audio metrics — mean-of-values sum states.

Parity targets: reference ``audio/{snr,sdr,pit,pesq,stoi,srmr}.py`` — every
class keeps ``sum_<metric>`` + ``total`` sum states (mean at compute), the
exact state design of the reference's audio domain.
"""
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..functional.audio.pesq import perceptual_evaluation_speech_quality
from ..functional.audio.pit import permutation_invariant_training
from ..functional.audio.srmr import speech_reverberation_modulation_energy_ratio
from ..functional.audio.stoi import short_time_objective_intelligibility
from ..functional.audio.sdr import (
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from ..functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from ..metric import Metric

Array = jax.Array


class _MeanAudioMetric(Metric):
    """Accumulate sum + count of per-sample values."""

    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_value", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _values(self, *args: Any, **kwargs: Any) -> Array:
        raise NotImplementedError

    def update(self, preds: Array, target: Array) -> None:
        values = self._values(preds, target)
        self.sum_value = self.sum_value + jnp.sum(values)
        self.total = self.total + values.size

    def compute(self) -> Array:
        return self.sum_value / self.total


class SignalNoiseRatio(_MeanAudioMetric):
    """Parity: reference ``audio/snr.py:SignalNoiseRatio``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> metric = SignalNoiseRatio()
        >>> metric.update(jnp.asarray([3.0, -0.5, 2.0, 7.0]), jnp.asarray([3.0, -0.5, 2.0, 8.0]))
        >>> print(f"{float(metric.compute()):.4f}")
        18.8790
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return signal_noise_ratio(preds, target, self.zero_mean)


class ScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """Parity: reference ``audio/snr.py:ScaleInvariantSignalNoiseRatio``."""

    is_differentiable = True
    higher_is_better = True

    def _values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_noise_ratio(preds, target)


class ComplexScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """Parity: reference ``audio/snr.py:ComplexScaleInvariantSignalNoiseRatio``."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return complex_scale_invariant_signal_noise_ratio(preds, target, self.zero_mean)


class SignalDistortionRatio(_MeanAudioMetric):
    """Parity: reference ``audio/sdr.py:SignalDistortionRatio``."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, use_cg_iter: Any = None, filter_length: int = 512, zero_mean: bool = False,
                 load_diag: Any = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def _values(self, preds: Array, target: Array) -> Array:
        return signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )


class ScaleInvariantSignalDistortionRatio(_MeanAudioMetric):
    """Parity: reference ``audio/sdr.py:ScaleInvariantSignalDistortionRatio``."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_distortion_ratio(preds, target, self.zero_mean)


class SourceAggregatedSignalDistortionRatio(_MeanAudioMetric):
    """Parity: reference ``audio/sdr.py:SourceAggregatedSignalDistortionRatio``."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.scale_invariant = scale_invariant
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return source_aggregated_signal_distortion_ratio(preds, target, self.scale_invariant, self.zero_mean)


class PermutationInvariantTraining(_MeanAudioMetric):
    """Parity: reference ``audio/pit.py:PermutationInvariantTraining`` (164 LoC)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, metric_func: Callable, mode: str = "speaker-wise", eval_func: str = "max",
                 **kwargs: Any) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in list(kwargs)
            if k in ("compute_on_cpu", "dist_sync_on_step", "sync_on_compute", "compute_with_cache",
                     "sync_backend", "jit")
        }
        super().__init__(**base_kwargs)
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.metric_kwargs = kwargs  # remaining kwargs forwarded to metric_func

    def _values(self, preds: Array, target: Array) -> Array:
        best_metric, _ = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.metric_kwargs
        )
        return best_metric


class PerceptualEvaluationSpeechQuality(_MeanAudioMetric):
    """Parity: reference ``audio/pesq.py``.

    The reference gates on the third-party ITU C backend; this build ships a
    first-party P.862-structured implementation
    (``functional/audio/pesq.py``) and works out of the box — the ITU C
    backend is still preferred automatically when installed
    (``implementation="auto"``).
    """

    is_differentiable = False
    higher_is_better = True
    jittable = False
    plot_lower_bound = -0.5
    plot_upper_bound = 4.5

    def __init__(self, fs: int, mode: str, n_processes: int = 1,
                 implementation: str = "auto", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        if implementation not in ("auto", "itu", "native"):
            raise ValueError(
                f"Expected argument `implementation` in ('auto','itu','native'), got {implementation}"
            )
        self.fs = fs
        self.mode = mode
        self.n_processes = n_processes
        self.implementation = implementation

    def _values(self, preds: Array, target: Array) -> Array:
        return perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode,
                                                    n_processes=self.n_processes,
                                                    implementation=self.implementation)


class ShortTimeObjectiveIntelligibility(_MeanAudioMetric):
    """Parity: reference ``audio/stoi.py``. First-party implementation
    (``functional/audio/stoi.py``) — no pystoi dependency."""

    is_differentiable = False
    higher_is_better = True
    jittable = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.extended = extended

    def _values(self, preds: Array, target: Array) -> Array:
        return short_time_objective_intelligibility(preds, target, self.fs, self.extended)


class SpeechReverberationModulationEnergyRatio(_MeanAudioMetric):
    """Parity: reference ``audio/srmr.py``. First-party implementation
    (``functional/audio/srmr.py``) — no gammatone/torchaudio dependency."""

    is_differentiable = False
    higher_is_better = True
    jittable = False

    def __init__(self, fs: int, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.fs = fs

    def update(self, preds: Array) -> None:  # SRMR is reference-free
        values = speech_reverberation_modulation_energy_ratio(preds, self.fs)
        self.sum_value = self.sum_value + jnp.sum(values)
        self.total = self.total + values.size

    def _values(self, preds: Array, target: Array) -> Array:  # pragma: no cover
        raise NotImplementedError
