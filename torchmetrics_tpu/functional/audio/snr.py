"""SNR family: SNR, SI-SNR, C-SI-SNR.

Parity targets: reference ``functional/audio/snr.py`` (SNR :22, SI-SNR :60,
complex C-SI-SNR :90) — pure projection algebra, batched over leading dims.
"""
import jax
import jax.numpy as jnp

Array = jax.Array
_EPS = 1.1920929e-07  # float32 eps, matching torch.finfo(float32).eps


def _check_same_shape(preds: Array, target: Array) -> None:
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR = 10 log10(|target|² / |target - preds|²). Parity: ``snr.py:22``."""
    _check_same_shape(preds, target)
    # f16 sums of squares over the time axis overflow; accumulate in f32
    preds = preds.astype(jnp.promote_types(preds.dtype, jnp.float32))
    target = target.astype(preds.dtype)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    val = (jnp.sum(target**2, axis=-1) + _EPS) / (jnp.sum(noise**2, axis=-1) + _EPS)
    return 10.0 * jnp.log10(val)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR (zero-mean projection). Parity: ``snr.py:60``."""
    return scale_invariant_signal_distortion_ratio(preds, target, zero_mean=True)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR via optimal scaling projection. Parity: ``sdr.py:201``."""
    _check_same_shape(preds, target)
    # f16 sums of squares over the time axis overflow; accumulate in f32
    preds = preds.astype(jnp.promote_types(preds.dtype, jnp.float32))
    target = target.astype(preds.dtype)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + _EPS) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + _EPS
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + _EPS) / (jnp.sum(noise**2, axis=-1) + _EPS)
    return 10.0 * jnp.log10(val)


def complex_scale_invariant_signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """C-SI-SNR over (..., F, T, 2) real-imag spectra. Parity: ``snr.py:90``."""
    if jnp.iscomplexobj(preds):
        preds = jnp.stack([preds.real, preds.imag], axis=-1)
    if jnp.iscomplexobj(target):
        target = jnp.stack([target.real, target.imag], axis=-1)
    if preds.ndim < 3 or preds.shape[-1] != 2 or target.ndim < 3 or target.shape[-1] != 2:
        raise RuntimeError(
            "Predictions and targets are expected to have the shape (..., frequency, time, 2),"
            f" but got {preds.shape} and {target.shape}."
        )
    preds = preds.reshape(preds.shape[:-3] + (-1,))
    target = target.reshape(target.shape[:-3] + (-1,))
    return scale_invariant_signal_distortion_ratio(preds, target, zero_mean=zero_mean)
