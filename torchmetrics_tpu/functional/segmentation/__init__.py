"""Segmentation support toolbox (no public metrics at the reference version —
``functional/segmentation/utils.py`` morphology utilities only, SURVEY.md §2.8)."""
from .utils import (
    binary_dilation,
    binary_erosion,
    check_if_binarized,
    distance_transform,
    generate_binary_structure,
    get_neighbour_tables,
    mask_edges,
    surface_distance,
    table_contour_length,
    table_surface_area,
)

__all__ = [
    "binary_dilation",
    "binary_erosion",
    "check_if_binarized",
    "distance_transform",
    "generate_binary_structure",
    "get_neighbour_tables",
    "mask_edges",
    "surface_distance",
    "table_contour_length",
    "table_surface_area",
]
