"""Direct reference-equivalence sweep: run OUR functional metrics and the
reference TorchMetrics (torch CPU, imported from the read-only mount via the
lightning_utilities stub) on IDENTICAL random inputs and assert closeness.

This is the reference's own primary correctness oracle (SURVEY.md §4 point 1)
applied wholesale — one parametrized case per functional kernel family.
"""
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))
from lightning_utilities_stub import install_stub  # noqa: E402

install_stub()
sys.path.insert(0, "/root/reference/src")
torch = pytest.importorskip("torch")

import torchmetrics.functional as RF  # noqa: E402  (reference)
import torchmetrics.functional.clustering as RFC  # noqa: E402
import torchmetrics.functional.image as RFI  # noqa: E402
import torchmetrics.functional.nominal as RFN  # noqa: E402
import torchmetrics.functional.text as RFT  # noqa: E402

import torchmetrics_tpu.functional as F  # noqa: E402  (ours)

RNG = np.random.RandomState(1234)
N = 128
NC = 5

# shared random inputs
P_BIN = RNG.rand(N).astype(np.float32)
T_BIN = (RNG.rand(N) < P_BIN).astype(np.int64)
P_MC = RNG.rand(N, NC).astype(np.float32)
P_MC /= P_MC.sum(-1, keepdims=True)
T_MC = RNG.randint(0, NC, N)
P_ML = RNG.rand(N, NC).astype(np.float32)
T_ML = (RNG.rand(N, NC) > 0.5).astype(np.int64)
X_REG = RNG.randn(N).astype(np.float32)
Y_REG = (X_REG * 0.8 + RNG.randn(N) * 0.3).astype(np.float32)
X_POS = np.abs(X_REG) + 0.1
Y_POS = np.abs(Y_REG) + 0.1
IMG_A = RNG.rand(2, 3, 32, 32).astype(np.float32)
IMG_B = np.clip(IMG_A + RNG.randn(2, 3, 32, 32).astype(np.float32) * 0.1, 0, 1)
AUD_A = RNG.randn(2, 800).astype(np.float32)
AUD_B = (AUD_A + RNG.randn(2, 800).astype(np.float32) * 0.3).astype(np.float32)


def _t(x):
    return torch.from_numpy(np.asarray(x))


def _j(x):
    return jnp.asarray(x)


CASES = [
    # ---- classification -----------------------------------------------------
    ("binary_accuracy", lambda: F.classification.binary_accuracy(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_accuracy(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("multiclass_accuracy_macro", lambda: F.classification.multiclass_accuracy(_j(P_MC), _j(T_MC), NC),
     lambda: RF.classification.multiclass_accuracy(_t(P_MC), _t(T_MC), NC), 1e-6),
    ("multilabel_f1", lambda: F.classification.multilabel_f1_score(_j(P_ML), _j(T_ML), NC),
     lambda: RF.classification.multilabel_f1_score(_t(P_ML), _t(T_ML), NC), 1e-6),
    ("binary_auroc", lambda: F.classification.binary_auroc(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_auroc(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("multiclass_auroc", lambda: F.classification.multiclass_auroc(_j(P_MC), _j(T_MC), NC),
     lambda: RF.classification.multiclass_auroc(_t(P_MC), _t(T_MC), NC), 1e-6),
    ("binary_average_precision", lambda: F.classification.binary_average_precision(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_average_precision(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("binary_calibration_error", lambda: F.classification.binary_calibration_error(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_calibration_error(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("multiclass_cohen_kappa", lambda: F.classification.multiclass_cohen_kappa(_j(P_MC), _j(T_MC), NC),
     lambda: RF.classification.multiclass_cohen_kappa(_t(P_MC), _t(T_MC), NC), 1e-6),
    ("multiclass_confusion_matrix", lambda: F.classification.multiclass_confusion_matrix(_j(P_MC), _j(T_MC), NC),
     lambda: RF.classification.multiclass_confusion_matrix(_t(P_MC), _t(T_MC), NC), 0),
    ("binary_mcc", lambda: F.classification.binary_matthews_corrcoef(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_matthews_corrcoef(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("binary_hamming", lambda: F.classification.binary_hamming_distance(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_hamming_distance(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("multiclass_jaccard", lambda: F.classification.multiclass_jaccard_index(_j(P_MC), _j(T_MC), NC),
     lambda: RF.classification.multiclass_jaccard_index(_t(P_MC), _t(T_MC), NC), 1e-6),
    ("binary_hinge", lambda: F.classification.binary_hinge_loss(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_hinge_loss(_t(P_BIN), _t(T_BIN)), 1e-5),
    ("binary_specificity", lambda: F.classification.binary_specificity(_j(P_BIN), _j(T_BIN)),
     lambda: RF.classification.binary_specificity(_t(P_BIN), _t(T_BIN)), 1e-6),
    ("multilabel_ranking_ap", lambda: F.classification.multilabel_ranking_average_precision(_j(P_ML), _j(T_ML), NC),
     lambda: RF.classification.multilabel_ranking_average_precision(_t(P_ML), _t(T_ML), NC), 1e-6),
    ("binary_roc", lambda: F.classification.binary_roc(_j(P_BIN), _j(T_BIN), thresholds=20)[1],
     lambda: RF.classification.binary_roc(_t(P_BIN), _t(T_BIN), thresholds=20)[1], 1e-6),
    # ---- regression ---------------------------------------------------------
    ("mse", lambda: F.regression.mean_squared_error(_j(X_REG), _j(Y_REG)),
     lambda: RF.mean_squared_error(_t(X_REG), _t(Y_REG)), 1e-5),
    ("mae", lambda: F.regression.mean_absolute_error(_j(X_REG), _j(Y_REG)),
     lambda: RF.mean_absolute_error(_t(X_REG), _t(Y_REG)), 1e-6),
    ("mape", lambda: F.regression.mean_absolute_percentage_error(_j(X_POS), _j(Y_POS)),
     lambda: RF.mean_absolute_percentage_error(_t(X_POS), _t(Y_POS)), 1e-5),
    ("msle", lambda: F.regression.mean_squared_log_error(_j(X_POS), _j(Y_POS)),
     lambda: RF.mean_squared_log_error(_t(X_POS), _t(Y_POS)), 1e-5),
    ("log_cosh", lambda: F.regression.log_cosh_error(_j(X_REG), _j(Y_REG)),
     lambda: RF.log_cosh_error(_t(X_REG), _t(Y_REG)), 1e-5),
    ("pearson", lambda: F.regression.pearson_corrcoef(_j(X_REG), _j(Y_REG)),
     lambda: RF.pearson_corrcoef(_t(X_REG), _t(Y_REG)), 1e-4),
    ("spearman", lambda: F.regression.spearman_corrcoef(_j(X_REG), _j(Y_REG)),
     lambda: RF.spearman_corrcoef(_t(X_REG), _t(Y_REG)), 1e-4),
    ("kendall", lambda: F.regression.kendall_rank_corrcoef(_j(X_REG), _j(Y_REG)),
     lambda: RF.kendall_rank_corrcoef(_t(X_REG), _t(Y_REG)), 1e-4),
    ("r2", lambda: F.regression.r2_score(_j(X_REG), _j(Y_REG)),
     lambda: RF.r2_score(_t(X_REG), _t(Y_REG)), 1e-4),
    ("explained_variance", lambda: F.regression.explained_variance(_j(X_REG), _j(Y_REG)),
     lambda: RF.explained_variance(_t(X_REG), _t(Y_REG)), 1e-4),
    ("concordance", lambda: F.regression.concordance_corrcoef(_j(X_REG), _j(Y_REG)),
     lambda: RF.concordance_corrcoef(_t(X_REG), _t(Y_REG)), 1e-4),
    ("cosine_similarity", lambda: F.regression.cosine_similarity(_j(X_REG.reshape(8, 16)), _j(Y_REG.reshape(8, 16))),
     lambda: RF.cosine_similarity(_t(X_REG.reshape(8, 16)), _t(Y_REG.reshape(8, 16))), 1e-5),
    ("minkowski", lambda: F.regression.minkowski_distance(_j(X_REG), _j(Y_REG), p=3.0),
     lambda: RF.minkowski_distance(_t(X_REG), _t(Y_REG), p=3.0), 1e-4),
    ("rse", lambda: F.regression.relative_squared_error(_j(X_REG), _j(Y_REG)),
     lambda: RF.relative_squared_error(_t(X_REG), _t(Y_REG)), 1e-4),
    ("smape", lambda: F.regression.symmetric_mean_absolute_percentage_error(_j(X_POS), _j(Y_POS)),
     lambda: RF.symmetric_mean_absolute_percentage_error(_t(X_POS), _t(Y_POS)), 1e-5),
    ("wmape", lambda: F.regression.weighted_mean_absolute_percentage_error(_j(X_POS), _j(Y_POS)),
     lambda: RF.weighted_mean_absolute_percentage_error(_t(X_POS), _t(Y_POS)), 1e-5),
    ("tweedie", lambda: F.regression.tweedie_deviance_score(_j(X_POS), _j(Y_POS), power=1.5),
     lambda: RF.tweedie_deviance_score(_t(X_POS), _t(Y_POS), power=1.5), 1e-4),
    ("csi", lambda: F.regression.critical_success_index(_j(P_BIN), _j(T_BIN.astype(np.float32)), 0.5),
     lambda: RF.critical_success_index(_t(P_BIN), _t(T_BIN.astype(np.float32)), 0.5), 1e-6),
    ("kl_divergence", lambda: F.regression.kl_divergence(_j(P_MC), _j(np.roll(P_MC, 1, 0))),
     lambda: RF.kl_divergence(_t(P_MC), _t(np.roll(P_MC, 1, 0))), 1e-5),
    # ---- image --------------------------------------------------------------
    ("psnr", lambda: F.image.peak_signal_noise_ratio(_j(IMG_B), _j(IMG_A), data_range=1.0),
     lambda: RF.peak_signal_noise_ratio(_t(IMG_B), _t(IMG_A), data_range=1.0), 1e-4),
    ("ssim", lambda: F.image.structural_similarity_index_measure(_j(IMG_B), _j(IMG_A), data_range=1.0),
     lambda: RF.structural_similarity_index_measure(_t(IMG_B), _t(IMG_A), data_range=1.0), 1e-4),
    ("uqi", lambda: F.image.universal_image_quality_index(_j(IMG_B), _j(IMG_A)),
     lambda: RF.universal_image_quality_index(_t(IMG_B), _t(IMG_A)), 1e-4),
    ("sam", lambda: F.image.spectral_angle_mapper(_j(IMG_B), _j(IMG_A)),
     lambda: RF.spectral_angle_mapper(_t(IMG_B), _t(IMG_A)), 1e-4),
    ("ergas", lambda: F.image.error_relative_global_dimensionless_synthesis(_j(IMG_B), _j(IMG_A)),
     lambda: RF.error_relative_global_dimensionless_synthesis(_t(IMG_B), _t(IMG_A)), 1e-3),
    ("rase", lambda: F.image.relative_average_spectral_error(_j(IMG_B), _j(IMG_A)),
     lambda: RF.relative_average_spectral_error(_t(IMG_B), _t(IMG_A)), 1e-3),
    ("scc", lambda: F.image.spatial_correlation_coefficient(_j(IMG_B), _j(IMG_A)),
     lambda: RFI.spatial_correlation_coefficient(_t(IMG_B), _t(IMG_A)), 1e-4),
    ("total_variation", lambda: F.image.total_variation(_j(IMG_A)),
     lambda: RF.total_variation(_t(IMG_A)), 1e-2),
    ("rmse_sw", lambda: F.image.root_mean_squared_error_using_sliding_window(_j(IMG_B), _j(IMG_A)),
     lambda: RF.root_mean_squared_error_using_sliding_window(_t(IMG_B), _t(IMG_A)), 1e-4),
    # ---- audio --------------------------------------------------------------
    ("snr", lambda: F.audio.signal_noise_ratio(_j(AUD_B), _j(AUD_A)),
     lambda: RF.signal_noise_ratio(_t(AUD_B), _t(AUD_A)), 1e-4),
    ("si_snr", lambda: F.audio.scale_invariant_signal_noise_ratio(_j(AUD_B), _j(AUD_A)),
     lambda: RF.scale_invariant_signal_noise_ratio(_t(AUD_B), _t(AUD_A)), 1e-4),
    ("si_sdr", lambda: F.audio.scale_invariant_signal_distortion_ratio(_j(AUD_B), _j(AUD_A)),
     lambda: RF.scale_invariant_signal_distortion_ratio(_t(AUD_B), _t(AUD_A)), 1e-4),
    ("sdr", lambda: F.audio.signal_distortion_ratio(_j(AUD_B), _j(AUD_A)),
     lambda: RF.signal_distortion_ratio(_t(AUD_B), _t(AUD_A)), 1e-2),
    # ---- pairwise -----------------------------------------------------------
    ("pairwise_cosine", lambda: F.pairwise_cosine_similarity(_j(IMG_A.reshape(6, -1))),
     lambda: RF.pairwise_cosine_similarity(_t(IMG_A.reshape(6, -1))), 1e-4),
    ("pairwise_euclidean", lambda: F.pairwise_euclidean_distance(_j(IMG_A.reshape(6, -1))),
     lambda: RF.pairwise_euclidean_distance(_t(IMG_A.reshape(6, -1))), 1e-2),
]

TEXT_CASES = [
    ("bleu", lambda: F.text.bleu_score(["the cat sat on the mat"], [["the cat sat on a mat"]]),
     lambda: RF.bleu_score(["the cat sat on the mat"], [["the cat sat on a mat"]]), 1e-5),
    ("chrf", lambda: F.text.chrf_score(["hello world"], [["hello there world"]]),
     lambda: RF.chrf_score(["hello world"], [["hello there world"]]), 1e-5),
    ("wer", lambda: F.text.word_error_rate(["hello big world"], ["hello world"]),
     lambda: RF.word_error_rate(["hello big world"], ["hello world"]), 1e-6),
    ("cer", lambda: F.text.char_error_rate(["abcd"], ["abxd"]),
     lambda: RF.char_error_rate(["abcd"], ["abxd"]), 1e-6),
    ("mer", lambda: F.text.match_error_rate(["hello big world"], ["hello world"]),
     lambda: RF.match_error_rate(["hello big world"], ["hello world"]), 1e-6),
    ("wil", lambda: F.text.word_information_lost(["hello big world"], ["hello world"]),
     lambda: RF.word_information_lost(["hello big world"], ["hello world"]), 1e-6),
    ("wip", lambda: F.text.word_information_preserved(["hello big world"], ["hello world"]),
     lambda: RF.word_information_preserved(["hello big world"], ["hello world"]), 1e-6),
    ("edit", lambda: F.text.edit_distance(["kitten"], ["sitting"]),
     lambda: RFT.edit_distance(["kitten"], ["sitting"]), 1e-6),
    ("ter", lambda: F.text.translation_edit_rate(["the cat sat"], [["the big cat sat"]]),
     lambda: RF.translation_edit_rate(["the cat sat"], [["the big cat sat"]]), 1e-5),
]


@pytest.mark.parametrize("name,ours,ref,atol", CASES + TEXT_CASES,
                         ids=[c[0] for c in CASES + TEXT_CASES])
def test_reference_parity(name, ours, ref, atol):
    a = np.asarray(ours())
    r = ref()
    b = np.asarray(r.detach().numpy() if hasattr(r, "detach") else r)
    np.testing.assert_allclose(a, b, atol=atol, rtol=1e-4,
                               err_msg=f"{name}: ours={a} reference={b}")


def test_reference_parity_perplexity():
    logits = RNG.randn(2, 10, 7).astype(np.float32)
    tokens = RNG.randint(0, 7, (2, 10))
    ours = float(F.text.perplexity(_j(logits), _j(tokens)))
    ref = float(RF.text.perplexity(_t(logits), _t(tokens)))
    assert np.isclose(ours, ref, rtol=1e-4)


def test_reference_parity_rouge():
    ours = F.text.rouge_score(["the cat sat on the mat"], ["a cat sat on the mat"])
    try:
        ref = RF.text.rouge_score(["the cat sat on the mat"], ["a cat sat on the mat"])
    except Exception:
        pytest.skip("reference rouge needs nltk")
    for k in ("rouge1_fmeasure", "rouge2_fmeasure", "rougeL_fmeasure"):
        assert np.isclose(float(ours[k]), float(ref[k]), atol=1e-5), k


def test_reference_parity_clustering_nominal():
    labels_a = RNG.randint(0, 4, 200)
    labels_b = RNG.randint(0, 4, 200)
    pairs = [
        ("mutual_info", F.clustering.mutual_info_score, RFC.mutual_info_score),
        ("adjusted_rand", F.clustering.adjusted_rand_score, RFC.adjusted_rand_score),
        ("rand", F.clustering.rand_score, RFC.rand_score),
        ("fowlkes_mallows", F.clustering.fowlkes_mallows_index, RFC.fowlkes_mallows_index),
        ("nmi", F.clustering.normalized_mutual_info_score, RFC.normalized_mutual_info_score),
    ]
    for name, ours_fn, ref_fn in pairs:
        o = float(ours_fn(_j(labels_a), _j(labels_b)))
        r = float(ref_fn(_t(labels_a), _t(labels_b)))
        assert np.isclose(o, r, atol=1e-5), (name, o, r)
    o = float(F.nominal.cramers_v(_j(labels_a), _j(labels_b)))
    r = float(RFN.cramers_v(_t(labels_a), _t(labels_b)))
    assert np.isclose(o, r, atol=1e-4), ("cramers_v", o, r)


def test_reference_parity_retrieval():
    preds = RNG.rand(200).astype(np.float32)
    target = (RNG.rand(200) > 0.7).astype(np.int64)
    pairs = [
        ("map", F.retrieval.retrieval_average_precision, RF.retrieval.retrieval_average_precision),
        ("mrr", F.retrieval.retrieval_reciprocal_rank, RF.retrieval.retrieval_reciprocal_rank),
        ("ndcg", F.retrieval.retrieval_normalized_dcg, RF.retrieval.retrieval_normalized_dcg),
        ("fall_out", F.retrieval.retrieval_fall_out, RF.retrieval.retrieval_fall_out),
        ("hit_rate", F.retrieval.retrieval_hit_rate, RF.retrieval.retrieval_hit_rate),
    ]
    for name, ours_fn, ref_fn in pairs:
        # per-query functional form: first query's slice
        o = float(ours_fn(_j(preds[:20]), _j(target[:20])))
        r = float(ref_fn(_t(preds[:20]), _t(target[:20])))
        assert np.isclose(o, r, atol=1e-5), (name, o, r)


def test_reference_parity_retrieval_grouped():
    """Grouped (indexes=) class API against the reference RetrievalMAP/NDCG."""
    import torchmetrics as RT

    import torchmetrics_tpu as tm

    idx = np.repeat(np.arange(10), 20)
    preds = RNG.rand(200).astype(np.float32)
    target = (RNG.rand(200) > 0.7).astype(np.int64)
    for ours_cls, ref_cls in [(tm.RetrievalMAP, RT.RetrievalMAP),
                              (tm.RetrievalNormalizedDCG, RT.RetrievalNormalizedDCG),
                              (tm.RetrievalMRR, RT.RetrievalMRR)]:
        ours = ours_cls()
        ref = ref_cls()
        ours.update(_j(preds), _j(target), indexes=_j(idx))
        ref.update(_t(preds), _t(target), indexes=_t(idx))
        o, r = float(ours.compute()), float(ref.compute())
        assert np.isclose(o, r, atol=1e-5), (ours_cls.__name__, o, r)


def test_reference_parity_squad_eed():
    import torchmetrics.functional.text as RFT

    import torchmetrics_tpu.functional.text as FT

    preds = [{"prediction_text": "the cat sat", "id": "1"},
             {"prediction_text": "a dog", "id": "2"}]
    target = [{"answers": {"answer_start": [0], "text": ["the cat sat on the mat"]}, "id": "1"},
              {"answers": {"answer_start": [0], "text": ["a dog", "the dog"]}, "id": "2"}]
    r = RFT.squad(preds, target)
    o = FT.squad(preds, target)
    for k in ("exact_match", "f1"):
        assert np.isclose(float(o[k]), float(r[k]), atol=1e-4), k

    r2 = float(RFT.extended_edit_distance(["the cat sat down"], ["the big cat sat"]))
    o2 = float(FT.extended_edit_distance(["the cat sat down"], ["the big cat sat"]))
    assert np.isclose(o2, r2, atol=1e-6)


def test_root_export_parity_with_reference():
    """Both root namespaces must be supersets of the reference's ``__all__``.

    Guards the L6 API surface (SURVEY.md §1: ~103 class exports, ~97
    functional exports at ``src/torchmetrics/{,functional/}__init__.py``).
    """
    import ast

    import torchmetrics_tpu as M
    import torchmetrics_tpu.functional as F

    def ref_all(path):
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        return ast.literal_eval(node.value)
        raise AssertionError(f"no __all__ in {path}")

    ref_root = "/root/reference/src/torchmetrics"
    missing_cls = [n for n in ref_all(f"{ref_root}/__init__.py") if not hasattr(M, n)]
    missing_fn = [n for n in ref_all(f"{ref_root}/functional/__init__.py") if not hasattr(F, n)]
    assert not missing_cls, f"missing class exports: {missing_cls}"
    assert not missing_fn, f"missing functional exports: {missing_fn}"


def test_reference_parity_fairness_functionals():
    """demographic_parity / equal_opportunity vs the reference implementations.

    The reference keys results ``DP_{low}_{high}`` with data-dependent group
    ids (``group_fairness.py:184-188``); our jit-friendly design uses static
    ``"DP"``/``"EO"`` keys — values must match.
    """
    rng = np.random.RandomState(7)
    n = 256
    preds = rng.rand(n).astype(np.float32)
    target = rng.randint(0, 2, n)
    groups = rng.randint(0, 3, n)

    import torchmetrics.functional.classification as RFCls

    ref_dp = RFCls.demographic_parity(torch.tensor(preds), torch.tensor(groups))
    our_dp = F.demographic_parity(jnp.asarray(preds), jnp.asarray(groups))
    np.testing.assert_allclose(
        np.asarray(our_dp["DP"]), next(iter(ref_dp.values())).numpy(), atol=1e-6
    )

    ref_eo = RFCls.equal_opportunity(torch.tensor(preds), torch.tensor(target), torch.tensor(groups))
    our_eo = F.equal_opportunity(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(groups))
    np.testing.assert_allclose(
        np.asarray(our_eo["EO"]), next(iter(ref_eo.values())).numpy(), atol=1e-6
    )


def test_functional_lpips_and_ppl_with_callable():
    """The offline-gated image functionals run end-to-end with a callable net."""
    rng = np.random.RandomState(3)

    def l2_distance(a, b):
        return jnp.mean((a - b) ** 2, axis=(1, 2, 3))

    img1 = jnp.asarray(rng.rand(4, 3, 16, 16).astype(np.float32) * 2 - 1)
    img2 = jnp.asarray(rng.rand(4, 3, 16, 16).astype(np.float32) * 2 - 1)
    val = F.learned_perceptual_image_patch_similarity(img1, img2, l2_distance)
    np.testing.assert_allclose(np.asarray(val), np.asarray(l2_distance(img1, img2)).mean(), rtol=1e-6)
    with pytest.raises(ModuleNotFoundError):
        F.learned_perceptual_image_patch_similarity(img1, img2, "alex")

    class Gen:
        z_size = 8

        def sample(self, n):
            return jnp.asarray(rng.rand(n, self.z_size).astype(np.float32))

        def __call__(self, z):
            img = jnp.tile(z[:, :, None, None], (1, 1, 4, 4))[:, :3]
            return img

    # For a generator linear in z and the mean-squared distance, the PPL of a
    # lerp path is analytic: imgs differ by eps*(z2-z1) on the first 3 latent
    # dims, so D/eps^2 = mean((z2-z1)[:3]^2) independent of eps.
    mean, std, dists = F.perceptual_path_length(
        Gen(), l2_distance, num_samples=64, batch_size=32, lower_discard=None, upper_discard=None,
        resize=None, seed=11,
    )
    assert np.isfinite(float(mean)) and np.isfinite(float(std)) and dists.shape[0] == 64
    assert 0 < float(mean) < 10.0  # O(var of uniform latents), NOT inflated by 1/eps^2

    class CondGen(Gen):
        num_classes = 4

        def __call__(self, z, labels):
            return super().__call__(z + labels[:, None])

    mean_c, _, _ = F.perceptual_path_length(
        CondGen(), l2_distance, num_samples=32, batch_size=32, conditional=True,
        lower_discard=None, upper_discard=None, resize=None, seed=11,
    )
    assert np.isfinite(float(mean_c))


def test_ppl_interpolate_matches_reference():
    """Our ``_interpolate`` vs the reference's for all three methods."""
    from torchmetrics.functional.image.perceptual_path_length import _interpolate as ref_interp

    from torchmetrics_tpu.functional.image.perceptual_path_length import _interpolate as our_interp

    rng = np.random.RandomState(5)
    l1 = rng.randn(16, 8).astype(np.float32)
    l2 = rng.randn(16, 8).astype(np.float32)
    # include a collinear pair and a zero pair to exercise the lerp fallback
    l2[0] = 2.0 * l1[0]
    l1[1] = 0.0
    for method in ("lerp", "slerp_any", "slerp_unit"):
        ours = np.asarray(our_interp(jnp.asarray(l1), jnp.asarray(l2), 1e-4, method))
        ref = ref_interp(torch.tensor(l1), torch.tensor(l2), 1e-4, method).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5, err_msg=method)
