"""Buffered streaming updates: stage K steps on device, flush one scanned
executable, overlap with the train step.

BENCH_r05 shows every device config is dispatch/memory-bound (~0.5% of peak
FLOPs): after the fused collection dispatch (PR 1) the remaining per-step
cost is the one-dispatch-per-step cadence itself. This module amortizes it:

- :meth:`Metric.buffered(window=K)` / :meth:`MetricCollection.buffered`
  return a handle whose ``update()`` only *stages* the step's inputs into a
  preallocated ring of K slots (one ring per update signature — shapes,
  dtypes and tree structure of the inputs). Staging is pure host work: the
  batch arrays are already device-resident, so a staged step costs a list
  write, not an XLA dispatch.
- When the ring fills (or any state observation forces it), ``flush()`` runs
  ONE jitted executable: the K staged steps are stacked into ``(K, *shape)``
  batches inside the traced program and a single ``lax.scan`` applies the
  metric's update body once per step — K steps of metric work per dispatch
  instead of K dispatches.
- A short final window rides the SAME executable: the ring is padded to K
  with a repeated staged slot and each scan step is masked with
  ``step_index < valid`` (``jnp.where`` keep/drop on every state leaf — the
  weight-0 padding trick from ``ops/bincount.py``), so partial windows never
  retrace and contribute nothing beyond the ``valid`` staged steps.
- The flush is asynchronous (JAX async dispatch; no ``block_until_ready``)
  and double-buffered: the in-flight executable owns window N's slot arrays
  while the handle immediately begins staging window N+1 into fresh slots,
  overlapping metric work with the train step.

Semantics are bitwise-identical to eager per-step updates: the scan applies
the exact per-step update body sequentially (unlike the associative-merge
``update_state_batched``, which reassociates MEAN sums), and every state
observation — ``compute()``, ``sync()``, ``reset()``, state access,
pickling, an interleaved eager ``update()`` — forces a flush first via the
``_flush_pending`` hooks in ``metric.py``/``collections.py``.

Flush executables live in the process-global cache (``metric._global_jit``):
equal-config metrics (clones, BootStrapper copies) share one compiled flush
program, and ``executable_cache_stats()['dispatches']`` counts one dispatch
per flush — the counter the bench/smoke suites assert on.

See ``docs/streaming_pipeline.md`` for when buffering wins and the verified
dispatch-count math.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .buffers import CatBuffer
from .metric import Metric, StateDict, _filter_kwargs, _global_jit, _jit_safe_inputs
from .observability import spans as _spans
from .observability.registry import REGISTRY as _REGISTRY
from .parallel.elastic import note_overlap_deferred
from .parallel.reduction import Reduction
from .parallel.strategies import begin_sync
from .utils.exceptions import TorchMetricsUserError

__all__ = ["BufferedMetric", "BufferedMetricCollection"]

# wall-clock dispatch latency of the scanned flush, labelled by window size —
# one observation per flush (per-K-steps, not per-step, so always-on is
# cheap). The autotune observer compares this against the staged-step cadence
# when choosing the buffered window K.
_FLUSH_LATENCY = _REGISTRY.histogram(
    "streaming.flush_latency_s", "seconds per scanned flush dispatch"
)


def _input_signature(args: tuple, kwargs: dict) -> tuple:
    """Hashable (structure, shapes, dtypes) key for one staged step.

    Steps with equal signatures can share one ring buffer and one flush
    executable; a signature change forces a flush of the current window
    first, so update ORDER is always preserved across signatures.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            sig.append((leaf.shape, str(leaf.dtype)))
        else:  # python scalars: weak-typed, keyed by type
            sig.append(("scalar", type(leaf).__name__))
    return (treedef, tuple(sig))


def _stack_steps(steps: Tuple[Any, ...]) -> Any:
    """Stack K staged (args, kwargs) pytrees into (K, ...) leaf batches."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *steps)


def _masked_merge(keep: Any, new: StateDict, old: StateDict) -> StateDict:
    """Keep the updated leaf for valid steps, the prior leaf for padding."""
    return {k: jnp.where(keep, v, old[k]) for k, v in new.items()}


class _Ring:
    """Preallocated ring of K staging slots for one update signature.

    Slot rotation is the double buffer: ``take()`` hands the filled slots to
    the (asynchronous) flush executable — which then owns those arrays for
    the lifetime of the in-flight program — and rebinds fresh ``None`` slots
    so window N+1 stages while window N is still executing on device.
    """

    __slots__ = ("window", "slots", "count", "signature")

    def __init__(self, window: int) -> None:
        self.window = window
        self.slots: List[Any] = [None] * window
        self.count = 0
        self.signature: Any = None

    def stage(self, step: Any) -> None:
        self.slots[self.count] = step
        self.count += 1

    @property
    def full(self) -> bool:
        return self.count >= self.window

    def take(self) -> Tuple[Tuple[Any, ...], int]:
        """(K padded steps, valid count); resets for the next window."""
        valid = self.count
        pad = self.slots[valid - 1]  # masked out by step_index < valid
        steps = tuple(self.slots[i] if i < valid else pad for i in range(self.window))
        self.slots = [None] * self.window
        self.count = 0
        self.signature = None
        return steps, valid


def _donation_safe_states(reps, seen: set) -> Dict[str, StateDict]:
    """Per-rep tensor states safe for ``donate_argnums`` (metric.py rules:
    never donate a leaf aliasing ``_defaults`` or appearing twice)."""
    states: Dict[str, StateDict] = {}
    for name, rep in reps:
        st: StateDict = {}
        for k, v in rep._state_view().items():
            if k in rep._list_states:
                continue
            if isinstance(v, jax.Array):
                if v is rep._defaults.get(k) or id(v) in seen:
                    v = jnp.array(v, copy=True)
                seen.add(id(v))
            st[k] = v
        states[name] = st
    return states


class BufferedMetric:
    """Streaming-update handle over a single :class:`Metric`.

    ``update()`` stages; ``flush()`` (or any state observation on the handle
    OR the wrapped metric) applies all staged steps in one scanned XLA
    dispatch. Created via :meth:`Metric.buffered`.

    With ``overlap_sync=True`` each flush additionally gathers the cat-state
    increments the *previous* windows appended, eagerly, right after the
    asynchronous scan dispatch — the host-side DCN gather runs while the
    device is still executing the new window's scan, so sync communication
    hides under compute. Elementwise states (one small bucket) and the final
    window's increments are synced at the :meth:`compute` barrier. Requires
    every rank to drive its handle in lockstep (same flush points), the
    invariant eager multi-host sync already demands.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SumMetric
        >>> buffered = SumMetric().buffered(window=4)
        >>> for i in range(6):  # 4 staged steps flush in ONE dispatch
        ...     buffered.update(jnp.asarray([float(i)]))
        >>> float(buffered.compute())  # forces the short 2-step flush
        15.0
    """

    def __init__(self, metric: Metric, window: int = 32, overlap_sync: bool = False) -> None:
        if not isinstance(window, int) or isinstance(window, bool) or window < 1:
            raise ValueError(f"Expected `window` to be a positive integer, got {window!r}")
        if not getattr(metric, "_use_jit", False):
            raise TorchMetricsUserError(
                f"{type(metric).__name__} is not jit-capable (jittable=False or jit=False); "
                "buffered streaming requires a traceable update body."
            )
        prior = metric.__dict__.get("_stream_buffer")
        if prior is not None and prior is not self:
            prior.flush()
        self.__dict__["_metric"] = metric
        self.__dict__["_window"] = window
        self.__dict__["_ring"] = _Ring(window)
        self.__dict__["_flushing"] = False
        self.__dict__["_overlap"] = bool(overlap_sync)
        # overlapped-sync bookkeeping: per cat list state, the merged
        # (already gathered across ranks) window increments and how many
        # LOCAL rows have been covered by issued gathers
        self.__dict__["_ov_gathered"] = {}
        self.__dict__["_ov_synced_idx"] = {}
        object.__setattr__(metric, "_stream_buffer", self)

    # -- staging --------------------------------------------------------
    @property
    def window(self) -> int:
        return self._window

    @property
    def pending(self) -> int:
        """Number of staged-but-unflushed steps."""
        return self._ring.count

    @property
    def metric(self) -> Metric:
        """The wrapped metric WITHOUT forcing a flush (raw access)."""
        return self.__dict__["_metric"]

    def update(self, *args: Any, **kwargs: Any) -> None:
        m = self.__dict__["_metric"]
        if m._is_synced:
            raise TorchMetricsUserError(
                "The Metric is currently synced; call `unsync()` before `update`."
            )
        args = tuple(m._to_array(a) for a in args)
        kwargs = {k: m._to_array(v) for k, v in kwargs.items()}
        if not _jit_safe_inputs(args, kwargs):
            # host-side inputs can't be staged on device; preserve order by
            # draining the ring first, then run the eager path
            self.flush()
            m.update(*args, **kwargs)
            return
        m._eager_validate(*args, **kwargs)
        ring: _Ring = self._ring
        _sp = (
            _spans.start_span("buffered.stage", metric=type(m).__name__)
            if _spans.ENABLED
            else None
        )
        try:
            sig = _input_signature(args, kwargs)
            if ring.count and ring.signature != sig:
                self.flush()  # new shape/dtype signature: drain the old window
            ring.signature = sig
            ring.stage((args, kwargs))
            m._computed = None
            m._update_count += 1
            if ring.full:
                self.flush()
        finally:
            if _sp is not None:
                _sp.end()

    # -- flush ----------------------------------------------------------
    def _flush_fn(self):
        m = self.__dict__["_metric"]
        window = self._window

        def flush(state: StateDict, valid, steps):
            stacked = _stack_steps(steps)

            def body(carry, step):
                idx, (step_args, step_kwargs) = step
                new_tensors, appends = m._pure_update(carry, step_args, step_kwargs)
                return _masked_merge(idx < valid, new_tensors, carry), appends

            final, appends = lax.scan(body, state, (jnp.arange(window), stacked))
            return final, appends

        return _global_jit(
            ("stream_flush", window, m._executable_cache_key()), flush, donate_state=True
        )

    def flush(self) -> None:
        """Apply every staged step in one scanned dispatch (asynchronous)."""
        ring: _Ring = self._ring
        if ring.count == 0 or self.__dict__["_flushing"]:
            return
        self.__dict__["_flushing"] = True
        _sp = (
            _spans.start_span("buffered.flush", staged=ring.count)
            if _spans.ENABLED
            else None
        )
        _t0 = time.perf_counter()
        try:
            m = self.__dict__["_metric"]
            # snapshot the cat-state row counts the PREVIOUS windows produced
            # before this flush appends more: those rows exist on every rank
            # that reached this flush point, so they are safe to gather while
            # the new window's scan is still executing on device
            pre_counts = (
                {name: len(m._state_view()[name]) for name in self._ov_cat_names()}
                if self.__dict__["_overlap"]
                else None
            )
            steps, valid = ring.take()
            fn = self._flush_fn()
            # the valid count is a host int: ship it with an EXPLICIT
            # device_put (cached per count — steady state always flushes a
            # full window, so this is one constant) rather than an implicit
            # jnp.asarray transfer, which strict_mode()'s transfer guard
            # rightly rejects in the serving loop
            valid_cache = self.__dict__.setdefault("_valid_consts", {})
            valid_dev = valid_cache.get(valid)
            if valid_dev is None:
                valid_dev = jax.device_put(np.int32(valid))
                valid_cache[valid] = valid_dev
            if _sp is None:
                new_tensors, appends = fn(m._donation_safe_tensor_state(), valid_dev, steps)
            else:
                with _spans.trace_span("buffered.scan", valid=int(valid)) as scan_sp:
                    new_tensors, appends = fn(
                        m._donation_safe_tensor_state(), valid_dev, steps
                    )
                    scan_sp.fence(new_tensors)
            state = m._state_view()
            for k, v in new_tensors.items():
                state[k] = v
            # appends leaves are (K, B, ...) scan stacks; rows >= valid are
            # padding garbage — the valid rows land in the cat state in ONE
            # fused device write per state (padded layout) or as per-step
            # increments (list layout), preserving step-major append order
            m._extend_list_states_stacked(appends, valid)
            if pre_counts is not None:
                backend = m.sync_backend
                if backend.is_available() and not m._is_synced:
                    # an overlapped gather is an optimization, not a
                    # correctness point: if a peer stalls here, defer the
                    # rows to the compute-time barrier instead of failing
                    # the flush. _ov_issue only advances the synced index
                    # per state AFTER that state's gather succeeds, so slot
                    # rotation stays intact and _ov_barrier re-gathers
                    # exactly the rows this attempt did not cover.
                    try:
                        if _sp is None:
                            self._ov_issue(backend, pre_counts)
                        else:
                            with _spans.trace_span("buffered.overlap_issue"):
                                self._ov_issue(backend, pre_counts)
                    except TimeoutError:
                        note_overlap_deferred()
        finally:
            self.__dict__["_flushing"] = False
            _FLUSH_LATENCY.observe(time.perf_counter() - _t0, window=str(self._window))
            if _sp is not None:
                _sp.end()

    # -- sync/compute overlap -------------------------------------------
    def _ov_cat_names(self) -> List[str]:
        m = self.__dict__["_metric"]
        return [
            name
            for name in m._list_states
            if m._reductions.get(name) == Reduction.CAT
        ]

    def _ov_issue(self, backend, counts: Dict[str, int]) -> None:
        """Gather each cat state's rows in ``[synced_idx, counts[name])``.

        Called right after the (asynchronous) flush dispatch: the device is
        busy scanning the new window while the host gather moves the
        previous windows' increments over DCN. A gather is issued even for
        an empty range so every rank executes the same collective sequence.
        """
        m = self.__dict__["_metric"]
        idx = self.__dict__["_ov_synced_idx"]
        gathered = self.__dict__["_ov_gathered"]
        addressed = hasattr(backend, "set_current")
        for name in self._ov_cat_names():
            start, stop = idx.get(name, 0), counts.get(name, 0)
            if stop < start:  # state shrank (reset/load) — resync from zero
                start = 0
                gathered.pop(name, None)
            value = m._state_view()[name]
            if isinstance(value, CatBuffer):
                # the padded layout indexes rows, not increments: the buffer
                # slice IS the increment range (counts are row counts there)
                local = value.rows(start, stop)
            else:
                rows = list(value)[start:stop]
                if rows:
                    local = jnp.concatenate([jnp.atleast_1d(jnp.asarray(r)) for r in rows])
                else:
                    probe = m._precat(name)
                    local = probe[:0]
            if addressed:
                backend.set_current((name, start, stop))
            piece = backend.sync_tensor(local, Reduction.CAT)
            if piece.shape[0]:
                gathered.setdefault(name, []).append(piece)
            idx[name] = stop

    def _ov_barrier(self, backend) -> None:
        """Final sync point: gather the tail increments plus every remaining
        state bucket, then install the merged states exactly as
        :meth:`Metric.sync` would (cache local, ``_is_synced=True``).

        The merged cat order interleaves windows (window-major, rank-major
        within a window) rather than the plain rank-major order of
        ``merge_states`` — metric results are order-independent over cat
        states, only the row multiset matters.
        """
        m = self.__dict__["_metric"]
        if m._is_synced:
            raise TorchMetricsUserError("The Metric has already been synced.")
        cat_names = self._ov_cat_names()
        m._cache = m._snapshot_state()
        _sp = (
            _spans.start_span(
                "buffered.overlap_barrier",
                metric=type(m).__name__,
                world=backend.world_size(),
            )
            if _spans.ENABLED
            else None
        )
        try:
            begin_sync()
            # same elastic round lifecycle as Metric.sync: settle membership
            # before the tail gathers, record coverage for the whole window
            elastic = hasattr(backend, "begin_round")
            if elastic:
                backend.begin_round(
                    contrib=int(m._update_count), policy=m._sync_policy
                )
            self._ov_issue(
                backend, {name: len(m._state_view()[name]) for name in cat_names}
            )
            synced = m._gather_synced(backend, skip=frozenset(cat_names))
            for name in cat_names:
                synced[name] = list(self.__dict__["_ov_gathered"].get(name, []))
            if elastic:
                backend.end_round()
        except Exception:
            m._cache = None
            raise
        finally:
            if _sp is not None:
                _sp.end()
        m._state_view().update(synced)
        m._is_synced = True

    # -- observation (flush-first delegation) ---------------------------
    def compute(self) -> Any:
        m = self.__dict__["_metric"]
        if self.__dict__["_overlap"] and not m._is_synced and m.sync_on_compute:
            backend = m.sync_backend
            if backend.is_available():
                self.flush()
                self._ov_barrier(backend)
                try:
                    return m.compute()
                finally:
                    m.unsync()
        self.flush()
        return m.compute()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Per-step batch values defeat buffering; flush and run eagerly."""
        self.flush()
        return self._metric.forward(*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def reset(self) -> None:
        self.flush()
        self.__dict__["_ov_gathered"] = {}
        self.__dict__["_ov_synced_idx"] = {}
        self._metric.reset()

    def sync(self, should_sync: bool = True, sync_backend: Any = None) -> None:
        self.flush()
        m = self.__dict__["_metric"]
        if self.__dict__["_overlap"] and should_sync:
            backend = sync_backend or m.sync_backend
            if backend.is_available():
                self._ov_barrier(backend)
                return
        m.sync(should_sync=should_sync, sync_backend=sync_backend)

    def unsync(self, *args: Any, **kwargs: Any) -> None:
        self._metric.unsync(*args, **kwargs)

    @property
    def metric_state(self) -> StateDict:
        self.flush()
        return self._metric.metric_state

    def state_dict(self) -> Dict[str, Any]:
        self.flush()
        return self._metric.state_dict()

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        self.flush()
        self._metric.load_state_dict(state_dict, strict=strict)

    def __getstate__(self) -> Dict[str, Any]:
        self.flush()
        return {
            "_metric": self.__dict__["_metric"],
            "_window": self._window,
            "_overlap": self.__dict__["_overlap"],
        }

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__init__(state["_metric"], state["_window"], state.get("_overlap", False))

    def __getattr__(self, name: str) -> Any:
        # any other attribute (including registered state leaves) is a state
        # observation: flush, then read through to the wrapped metric
        if name.startswith("__") or "_metric" not in self.__dict__:
            raise AttributeError(name)
        self.flush()
        return getattr(self.__dict__["_metric"], name)

    def __repr__(self) -> str:
        return f"BufferedMetric({self.metric!r}, window={self._window}, pending={self.pending})"


class BufferedMetricCollection:
    """Streaming-update handle over a :class:`MetricCollection`.

    One shared K-step window for the whole collection: a flush runs a single
    scanned executable whose body applies every jit-capable compute-group
    representative's update (the PR-1 fused dispatch, scanned over K steps).
    Host-side (non-jittable) members keep their eager per-step path at stage
    time — member states are independent, so ordering across the two paths
    is unobservable. Created via :meth:`MetricCollection.buffered`.
    """

    def __init__(self, collection, window: int = 32) -> None:
        if not isinstance(window, int) or isinstance(window, bool) or window < 1:
            raise ValueError(f"Expected `window` to be a positive integer, got {window!r}")
        self.__dict__["_collection"] = collection
        self.__dict__["_window"] = window
        self.__dict__["_ring"] = _Ring(window)
        self.__dict__["_flushing"] = False
        for m in collection._metrics.values():
            prior = m.__dict__.get("_stream_buffer")
            if prior is not None and prior is not self:
                prior.flush()
            object.__setattr__(m, "_stream_buffer", self)

    @property
    def window(self) -> int:
        return self._window

    @property
    def pending(self) -> int:
        return self._ring.count

    @property
    def collection(self):
        """The wrapped collection WITHOUT forcing a flush (raw access)."""
        return self.__dict__["_collection"]

    def update(self, *args: Any, **kwargs: Any) -> None:
        coll = self.__dict__["_collection"]
        if coll._state_is_copy:
            coll._create_state_refs()
        if not coll._groups_checked:
            # first update: eager group discovery (collections.py); nothing
            # staged yet, so ordering is trivially preserved
            coll.update(*args, **kwargs)
            return
        fused, eager, _ = coll._fused_update_plan()
        if not fused:
            self.flush()
            coll.update(*args, **kwargs)
            return
        conv = fused[0][1]._to_array
        args = tuple(conv(a) for a in args)
        kwargs = {k: conv(v) for k, v in kwargs.items()}
        if not _jit_safe_inputs(args, kwargs):
            self.flush()
            coll.update(*args, **kwargs)
            return
        for _name, rep in fused:
            if rep._is_synced:
                raise TorchMetricsUserError(
                    "The Metric is currently synced; call `unsync()` before `update`."
                )
            rep._eager_validate(*args, **_filter_kwargs(rep._update_impl, **kwargs))
        ring: _Ring = self._ring
        sig = _input_signature(args, kwargs)
        if ring.count and ring.signature != sig:
            self.flush()
        ring.signature = sig
        ring.stage((args, kwargs))
        for _name, rep in fused:
            rep._computed = None
            rep._update_count += 1
        # host-side members stay on the eager path; their states are
        # independent of the staged fused reps, so updating them now (under
        # the reentrancy guard — their _flush_pending hook points back at
        # this buffer) cannot reorder anything observable
        if eager:
            self.__dict__["_flushing"] = True
            try:
                for _name, rep in eager:
                    rep.update(*args, **_filter_kwargs(rep._update_impl, **kwargs))
            finally:
                self.__dict__["_flushing"] = False
        for members in coll._groups.values():
            rep = coll._metrics[members[0]]
            for name in members[1:]:
                coll._metrics[name]._update_count = rep._update_count
                coll._metrics[name]._computed = None
        if ring.full:
            self.flush()

    def _flush_fn(self, reps: Tuple[Tuple[str, Metric], ...]):
        window = self._window

        def flush(states: Dict[str, StateDict], valid, steps):
            stacked = _stack_steps(steps)

            def body(carry, step):
                idx, (step_args, step_kwargs) = step
                keep = idx < valid
                out: Dict[str, StateDict] = {}
                appends: Dict[str, Any] = {}
                for name, rep in reps:
                    fkw = _filter_kwargs(rep._update_impl, **step_kwargs)
                    tensors, app = rep._pure_update(carry[name], step_args, fkw)
                    out[name] = _masked_merge(keep, tensors, carry[name])
                    appends[name] = app
                return out, appends

            final, appends = lax.scan(body, states, (jnp.arange(window), stacked))
            return final, appends

        key = (
            "stream_flush_mc",
            window,
            tuple((name, rep._executable_cache_key()) for name, rep in reps),
        )
        return _global_jit(key, flush, donate_state=True)

    def flush(self) -> None:
        """One scanned dispatch applying all staged steps to every fused rep."""
        ring: _Ring = self._ring
        if ring.count == 0 or self.__dict__["_flushing"]:
            return
        self.__dict__["_flushing"] = True
        _t0 = time.perf_counter()
        try:
            coll = self.__dict__["_collection"]
            fused, _eager, _ = coll._fused_update_plan()
            reps = tuple(fused)
            steps, valid = ring.take()
            fn = self._flush_fn(reps)
            states = _donation_safe_states(reps, set())
            new_states, appends = fn(states, jnp.asarray(valid, jnp.int32), steps)
            for name, rep in reps:
                st = rep._state_view()  # shared dict: group members see it
                for k, v in new_states[name].items():
                    st[k] = v
                rep._extend_list_states_stacked(appends[name], valid)
        finally:
            self.__dict__["_flushing"] = False
            _FLUSH_LATENCY.observe(time.perf_counter() - _t0, window=str(self._window))

    # -- observation (flush-first delegation) ---------------------------
    def compute(self) -> Dict[str, Any]:
        self.flush()
        return self._collection.compute()

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        self.flush()
        return self._collection.forward(*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def reset(self) -> None:
        self.flush()
        self._collection.reset()

    def state_dict(self) -> Dict[str, Any]:
        self.flush()
        return self._collection.state_dict()

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        self.flush()
        self._collection.load_state_dict(state_dict, strict=strict)

    def __getstate__(self) -> Dict[str, Any]:
        self.flush()
        return {"_collection": self.__dict__["_collection"], "_window": self._window}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__init__(state["_collection"], state["_window"])

    def __getitem__(self, key: str) -> Metric:
        self.flush()
        return self._collection[key]

    def __len__(self) -> int:
        return len(self._collection)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("__") or "_collection" not in self.__dict__:
            raise AttributeError(name)
        self.flush()
        return getattr(self.__dict__["_collection"], name)

    def __repr__(self) -> str:
        return (
            f"BufferedMetricCollection({self.collection!r}, "
            f"window={self._window}, pending={self.pending})"
        )
