"""Jittable fixed-shape exact-mode curve computes (scalar consumers).

Exact mode (``thresholds=None``) concatenates raw preds/target at epoch end,
so the shape is static from there on — but the classic ``_binary_clf_curve``
(reference ``functional/classification/precision_recall_curve.py:28``) keeps
only distinct-threshold positions and is therefore shape-dynamic and eager.

The trick here: return length-N arrays where every position that is NOT the
last element of a tied-prediction block repeats the previous block end (and
the origin before the first block end). Trapezoids, step-sums and
constrained-argmax consumers are invariant to such held duplicates (they
contribute zero-width segments / duplicate candidate triples), so AUROC,
AveragePrecision and the at-fixed scanners computed from these arrays equal
the eager distinct-only results while tracing with fixed shapes — one XLA
compile per epoch length instead of a host round-trip per compute.

Used by the class layer for exact-mode computes; the eager functional path
remains the parity oracle (``tests/classification/test_exact_jit.py``).
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utils.compute import _safe_divide
from .auroc import _reduce_auroc, _trapz
from .average_precision import _ap_from_curve, _reduce_average_precision
from .specificity_sensitivity import _best_subject_to

Array = jax.Array


def _clf_curve_filled(preds: Array, target: Array, weights: Optional[Array] = None) -> Tuple[Array, Array, Array]:
    """Fixed-shape ``_binary_clf_curve``: (fps, tps, thresh, is_real) length N.

    Positions before the first tied-block end hold the origin (0, 0, +inf,
    is_real=False); interior non-block-end positions hold the previous block
    end. ``weights`` (0/1) supports per-sample ignore masks without
    data-dependent filtering.
    """
    n = preds.shape[0]
    desc = jnp.argsort(preds)[::-1]  # same tie/NaN placement as the eager path
    p = preds[desc]
    t = target[desc].astype(jnp.float32)
    if weights is None:
        w = jnp.ones_like(p)
    else:
        w = weights[desc].astype(jnp.float32)
    tps_all = jnp.cumsum(t * w)
    fps_all = jnp.cumsum((1.0 - t) * w)
    idx = jnp.arange(n)
    distinct = jnp.concatenate([p[:-1] != p[1:], jnp.ones((1,), bool)])
    marker = jnp.where(distinct, idx, -1)
    last_end = jax.lax.associative_scan(jnp.maximum, marker)  # cummax
    safe = jnp.clip(last_end, 0, None)
    has = last_end >= 0
    fps = jnp.where(has, fps_all[safe], 0.0)
    tps = jnp.where(has, tps_all[safe], 0.0)
    thresh = jnp.where(has, p[safe], jnp.inf)
    return fps, tps, thresh, has


def _roc_filled(preds: Array, target: Array, weights: Optional[Array] = None) -> Tuple[Array, Array, Array]:
    """(fpr, tpr, thresh) length N+1 with the sklearn inf-threshold origin."""
    fps, tps, thresh, _ = _clf_curve_filled(preds, target, weights)
    tps = jnp.concatenate([jnp.zeros(1, tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, fps.dtype), fps])
    thresh = jnp.concatenate([jnp.asarray([jnp.inf], thresh.dtype), thresh])
    tpr = _safe_divide(tps, tps[-1])
    fpr = _safe_divide(fps, fps[-1])
    return fpr, tpr, thresh


def _prc_filled(preds: Array, target: Array, weights: Optional[Array] = None) -> Tuple[Array, Array, Array]:
    """(precision, recall, thresh) mirroring the eager exact PRC compute
    (reversed block order, appended (1, 0) endpoint, length N+1/N+1/N).

    Unlike ROC (whose eager arrays contain the inf-threshold origin), the
    eager PR curve has no origin point, so pre-first-block-end positions
    must replicate the FIRST block end rather than (0, 0, inf) — otherwise
    an at-fixed argmax can pick a fake point and return threshold=inf.
    """
    fps, tps, thresh, is_real = _clf_curve_filled(preds, target, weights)
    first_end = jnp.argmax(is_real)  # index of the first block end
    fps = jnp.where(is_real, fps, fps[first_end])
    tps = jnp.where(is_real, tps, tps[first_end])
    thresh = jnp.where(is_real, thresh, thresh[first_end])
    precision = _safe_divide(tps, tps + fps)
    # no positives → recall 1 everywhere (modern-sklearn semantics)
    recall = jnp.where(tps[-1] == 0, jnp.ones_like(tps), tps / jnp.where(tps[-1] == 0, 1.0, tps[-1]))
    precision = jnp.concatenate([precision[::-1], jnp.ones(1, dtype=precision.dtype)])
    recall = jnp.concatenate([recall[::-1], jnp.zeros(1, dtype=recall.dtype)])
    return precision, recall, thresh[::-1]


def _ovr_targets(target: Array, num_classes: int) -> Array:
    return (target[:, None] == jnp.arange(num_classes)).astype(jnp.int32)  # (N, C)


def _ml_weights(target: Array, ignore_index: Optional[int]) -> Tuple[Array, Optional[Array]]:
    """Multilabel per-label ignore handling: (clipped target, 0/1 weights)."""
    if ignore_index is None:
        return target, None
    w = (target != ignore_index).astype(jnp.float32)
    return jnp.clip(target, 0, 1), w


# ------------------------------------------------------------------- AUROC

@jax.jit
def binary_auroc_exact(preds: Array, target: Array, weights: Optional[Array] = None) -> Array:
    """``weights`` (0/1) folds an ignore mask in without dynamic filtering
    (multilabel micro path)."""
    fpr, tpr, _ = _roc_filled(preds, target, weights)
    return _trapz(tpr, fpr)


@partial(jax.jit, static_argnames=("average",))
def multiclass_auroc_exact(preds: Array, target: Array, average: Optional[str] = "macro") -> Array:
    tgt = _ovr_targets(target, preds.shape[1])
    fpr, tpr, _ = jax.vmap(_roc_filled, in_axes=(1, 1))(preds, tgt)  # (C, N+1)
    support = jnp.sum(tgt, axis=0).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=support)


@partial(jax.jit, static_argnames=("average", "ignore_index"))
def multilabel_auroc_exact(preds: Array, target: Array, average: Optional[str] = "macro",
                           ignore_index: Optional[int] = None) -> Array:
    tgt, w = _ml_weights(target, ignore_index)
    if w is None:
        fpr, tpr, _ = jax.vmap(_roc_filled, in_axes=(1, 1))(preds, tgt)
    else:
        fpr, tpr, _ = jax.vmap(_roc_filled, in_axes=(1, 1, 1))(preds, tgt, w)
    support = jnp.sum(target == 1, axis=0).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=support)


# ---------------------------------------------------------- AveragePrecision

@jax.jit
def binary_ap_exact(preds: Array, target: Array, weights: Optional[Array] = None) -> Array:
    """``weights`` (0/1) folds an ignore mask in without dynamic filtering
    (multilabel micro path)."""
    precision, recall, _ = _prc_filled(preds, target, weights)
    ap = _ap_from_curve(precision, recall)
    # the reference's recall is 0/0 -> nan with no positive samples
    n_pos = jnp.sum((target == 1) * (1.0 if weights is None else weights))
    return jnp.where(n_pos > 0, ap, jnp.nan)


@partial(jax.jit, static_argnames=("average",))
def multiclass_ap_exact(preds: Array, target: Array, average: Optional[str] = "macro") -> Array:
    tgt = _ovr_targets(target, preds.shape[1])
    precision, recall, _ = jax.vmap(_prc_filled, in_axes=(1, 1))(preds, tgt)  # (C, N+1)
    support = jnp.sum(tgt, axis=0).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=support, exclude_empty=True)


@partial(jax.jit, static_argnames=("average", "ignore_index"))
def multilabel_ap_exact(preds: Array, target: Array, average: Optional[str] = "macro",
                        ignore_index: Optional[int] = None) -> Array:
    tgt, w = _ml_weights(target, ignore_index)
    if w is None:
        precision, recall, _ = jax.vmap(_prc_filled, in_axes=(1, 1))(preds, tgt)
    else:
        precision, recall, _ = jax.vmap(_prc_filled, in_axes=(1, 1, 1))(preds, tgt, w)
    # raw-target support, mirroring MultilabelAveragePrecision's eager path
    support = jnp.sum(target == 1, axis=0).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=support, exclude_empty=True)


# ----------------------------------------------------------- at-fixed scans

@partial(jax.jit, static_argnames=("curve", "objective_first"))
def binary_at_fixed_exact(preds: Array, target: Array, min_value, curve: str = "prc",
                          objective_first: bool = True) -> Tuple[Array, Array]:
    """Constrained scan over the filled exact curve.

    ``curve="prc"``: arrays (precision, recall); ``curve="roc"``: (tpr,
    1-fpr) i.e. (sensitivity, specificity). ``objective_first=True``
    maximizes the first array subject to the second >= min_value; False
    swaps roles.
    """
    if curve == "prc":
        precision, recall, t = _prc_filled(preds, target)
        a, b = (recall, precision) if objective_first else (precision, recall)
    else:
        fpr, tpr, t = _roc_filled(preds, target)
        a, b = (tpr, 1 - fpr) if objective_first else (1 - fpr, tpr)
    return _best_subject_to(a, b, t, min_value)


@partial(jax.jit, static_argnames=("curve", "objective_first"))
def ovr_at_fixed_exact(preds: Array, target: Array, min_value, curve: str = "prc",
                       objective_first: bool = True) -> Tuple[Array, Array]:
    """Per-class constrained scan (multiclass one-vs-rest)."""
    tgt = _ovr_targets(target, preds.shape[1])
    return _batched_at_fixed(preds, tgt, None, min_value, curve, objective_first)


@partial(jax.jit, static_argnames=("curve", "objective_first", "ignore_index"))
def multilabel_at_fixed_exact(preds: Array, target: Array, min_value, curve: str = "prc",
                              objective_first: bool = True,
                              ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    tgt, w = _ml_weights(target, ignore_index)
    return _batched_at_fixed(preds, tgt, w, min_value, curve, objective_first)


def _batched_at_fixed(preds, tgt, w, min_value, curve, objective_first):
    fill = _prc_filled if curve == "prc" else _roc_filled
    if w is None:
        x, y, t = jax.vmap(fill, in_axes=(1, 1))(preds, tgt)
    else:
        x, y, t = jax.vmap(fill, in_axes=(1, 1, 1))(preds, tgt, w)
    if curve == "prc":
        a, b = (y, x) if objective_first else (x, y)  # (recall, precision) / swap
    else:
        fpr, tpr = x, y
        a, b = (tpr, 1 - fpr) if objective_first else (1 - fpr, tpr)
    return _best_subject_to(a, b, t, min_value)
