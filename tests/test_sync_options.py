"""Behavioral tests for every distributed-sync constructor option.

Parity: reference ``tests/unittests/bases/test_ddp.py:101-277`` —
``compute_on_cpu``, ``sync_on_compute`` variants, ``dist_sync_on_step``,
compositional-metric sync, state-dict-while-synced, plus a REAL two-process
``HostSync`` run (``jax.distributed`` over localhost, the DCN path) asserting
the gathered state equals the single-process ground truth.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu import CatMetric, MeanMetric, MeanSquaredError, SumMetric
from torchmetrics_tpu.aggregation import MaxMetric
from torchmetrics_tpu.parallel.sync import FakeSync
from torchmetrics_tpu.utils.data import dim_zero_cat


def _group(metrics):
    """FakeSync world from per-rank metric replicas (cat states pre-concat,
    mirroring the reference's list pre-concat at metric.py:430-433)."""
    states = []
    for m in metrics:
        state = {}
        for k, v in m.metric_state.items():
            state[k] = jnp.concatenate([jnp.atleast_1d(x) for x in v]) if isinstance(v, list) else v
        states.append(state)
    return states


# ------------------------------------------------------------ compute_on_cpu
def test_compute_on_cpu_offloads_cat_states_to_host():
    m = CatMetric(compute_on_cpu=True)
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray([3.0]))
    # list-state increments moved to host memory after each update
    assert all(isinstance(x, np.ndarray) for x in m.metric_state["value"])
    np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_compute_on_cpu_matches_device_result():
    a = CatMetric(compute_on_cpu=True)
    b = CatMetric()
    for batch in ([0.5, 1.5], [2.5], [3.5, 4.5]):
        a.update(jnp.asarray(batch))
        b.update(jnp.asarray(batch))
    np.testing.assert_allclose(np.asarray(a.compute()), np.asarray(b.compute()))


# ---------------------------------------------------------- sync_on_compute
def test_sync_on_compute_false_returns_local_value():
    ranks = [MeanMetric(sync_on_compute=False) for _ in range(2)]
    ranks[0].update(jnp.asarray([1.0, 1.0]))
    ranks[1].update(jnp.asarray([5.0, 5.0]))
    group = _group(ranks)
    for r, m in enumerate(ranks):
        m._sync_backend = FakeSync(group, r)
    # sync_on_compute=False: compute() must NOT consult the backend
    assert float(ranks[0].compute()) == pytest.approx(1.0)
    assert float(ranks[1].compute()) == pytest.approx(5.0)


def test_sync_on_compute_true_reduces_across_ranks():
    ranks = [MeanMetric() for _ in range(2)]
    ranks[0].update(jnp.asarray([1.0, 1.0]))
    ranks[1].update(jnp.asarray([5.0, 5.0]))
    group = _group(ranks)
    for r, m in enumerate(ranks):
        m._sync_backend = FakeSync(group, r)
    for m in ranks:
        assert float(m.compute()) == pytest.approx(3.0)
        # unsync restored local state: a second compute still syncs cleanly
        assert float(m.compute()) == pytest.approx(3.0)


# --------------------------------------------------------- dist_sync_on_step
def test_dist_sync_on_step_forward_sees_peer_batches():
    ranks = [SumMetric(dist_sync_on_step=True) for _ in range(2)]
    # pre-register the PER-BATCH states the sync will see: each rank's
    # forward computes on the batch state, then syncs it with the peers
    batch = {0: jnp.asarray([1.0, 2.0]), 1: jnp.asarray([10.0, 20.0])}
    group = [{"value": jnp.sum(batch[r])} for r in range(2)]
    for r, m in enumerate(ranks):
        m._sync_backend = FakeSync(group, r)
    # forward returns the batch value computed on the SYNCED batch state
    out0 = ranks[0](batch[0])
    out1 = ranks[1](batch[1])
    assert float(out0) == pytest.approx(33.0)
    assert float(out1) == pytest.approx(33.0)
    # the local accumulator holds only the local contribution
    assert float(ranks[0].compute_state(ranks[0].metric_state)) == pytest.approx(3.0)


# ------------------------------------------------------- compositional sync
def test_compositional_metric_children_sync_themselves():
    a_ranks = [SumMetric() for _ in range(2)]
    b_ranks = [SumMetric() for _ in range(2)]
    a_ranks[0].update(jnp.asarray([1.0])); a_ranks[1].update(jnp.asarray([2.0]))
    b_ranks[0].update(jnp.asarray([10.0])); b_ranks[1].update(jnp.asarray([20.0]))
    ga, gb = _group(a_ranks), _group(b_ranks)
    for r in range(2):
        a_ranks[r]._sync_backend = FakeSync(ga, r)
        b_ranks[r]._sync_backend = FakeSync(gb, r)
    comp0 = a_ranks[0] + b_ranks[0]
    comp1 = a_ranks[1] + b_ranks[1]
    # children sync inside their own compute; composition just combines
    assert float(comp0.compute()) == pytest.approx(33.0)
    assert float(comp1.compute()) == pytest.approx(33.0)


# ------------------------------------------------- state dict while synced
def test_state_dict_captures_synced_state():
    """Reference ``test_ddp.py:234`` (test_state_dict_is_synced)."""
    ranks = [SumMetric() for _ in range(2)]
    ranks[0].update(jnp.asarray([1.0]))
    ranks[1].update(jnp.asarray([4.0]))
    group = _group(ranks)
    m = ranks[0]
    m.persistent(True)
    m._sync_backend = FakeSync(group, 0)
    with m.sync_context(should_sync=True):
        sd = {k: np.asarray(v) for k, v in m.state_dict().items()}
    assert float(sd["value"]) == pytest.approx(5.0)
    # after the context, the state dict reverts to the local value
    sd_local = {k: np.asarray(v) for k, v in m.state_dict().items()}
    assert float(sd_local["value"]) == pytest.approx(1.0)


# ------------------------------------------------------ 2-process HostSync
_HOST_SYNC_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    rank = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=rank)
    import jax.numpy as jnp
    import numpy as np
    from torchmetrics_tpu import CatMetric, MeanMetric
    from torchmetrics_tpu.parallel.sync import HostSync

    # sum/mean-style state
    m = MeanMetric(sync_backend=HostSync())
    m.update(jnp.asarray([1.0, 2.0]) if rank == 0 else jnp.asarray([3.0, 6.0]))
    assert float(m.compute()) == 3.0, float(m.compute())

    # cat state (equal per-rank shapes over the DCN gather)
    c = CatMetric(sync_backend=HostSync())
    c.update(jnp.asarray([float(rank), float(rank) + 0.5]))
    vals = np.sort(np.asarray(c.compute()))
    assert np.allclose(vals, [0.0, 0.5, 1.0, 1.5]), vals

    # UNEVEN cat state: rank0 holds 3 samples, rank1 holds 1 (the reference's
    # pad-to-max protocol, utilities/distributed.py:124-147)
    u = CatMetric(sync_backend=HostSync())
    u.update(jnp.asarray([1.0, 2.0, 3.0]) if rank == 0 else jnp.asarray([4.0]))
    vals = np.sort(np.asarray(u.compute()))
    assert np.allclose(vals, [1.0, 2.0, 3.0, 4.0]), vals

    # EMPTY rank: rank0 never updates (its placeholder is (0,) float32)
    e = CatMetric(sync_backend=HostSync())
    if rank == 1:
        e.update(jnp.asarray([7.0, 8.0]))
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # rank0: compute-before-update
        vals = np.sort(np.asarray(e.compute()))
    assert np.allclose(vals, [7.0, 8.0]), vals

    # exact-mode AUROC across uneven shards == single-process ground truth
    from torchmetrics_tpu.classification import BinaryAUROC
    preds = {0: [0.9, 0.4, 0.6], 1: [0.2]}
    tgt = {0: [1, 0, 1], 1: [0]}
    a = BinaryAUROC(thresholds=None, sync_backend=HostSync())
    a.update(jnp.asarray(preds[rank]), jnp.asarray(tgt[rank]))
    ref = BinaryAUROC(thresholds=None)
    ref.update(jnp.asarray(preds[0] + preds[1]), jnp.asarray(tgt[0] + tgt[1]))
    assert abs(float(a.compute()) - float(ref.compute())) < 1e-6, float(a.compute())

    # empty-rank exact AUROC: rank0 holds NO samples; its float32 (0,)
    # placeholders must adopt the group's int target dtype in the gather
    a2 = BinaryAUROC(thresholds=None, sync_backend=HostSync())
    if rank == 1:
        a2.update(jnp.asarray([0.9, 0.4, 0.6, 0.2]), jnp.asarray([1, 0, 1, 0]))
    ref2 = BinaryAUROC(thresholds=None)
    ref2.update(jnp.asarray([0.9, 0.4, 0.6, 0.2]), jnp.asarray([1, 0, 1, 0]))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got2 = float(a2.compute())
    assert abs(got2 - float(ref2.compute())) < 1e-6, got2

    # dtype generality: int8 cat shards, uneven (outside any whitelist)
    from torchmetrics_tpu.parallel.reduction import Reduction
    hs = HostSync()
    shard = jnp.asarray([1, 2, 3] if rank == 0 else [4], dtype=jnp.int8)
    merged = np.asarray(hs.sync_tensor(shard, Reduction.CAT))
    assert merged.dtype == np.int8 and sorted(merged.tolist()) == [1, 2, 3, 4], merged

    # BootStrapper vmap path syncs its stacked state like the replay loop
    from copy import deepcopy
    from torchmetrics_tpu import BootStrapper
    from torchmetrics_tpu.classification import BinaryF1Score

    def shard_batches(r):
        rng2 = np.random.RandomState(100 + r)
        return [(rng2.rand(12).astype(np.float32), rng2.randint(0, 2, 12)) for _ in range(2)]

    fast = BootStrapper(BinaryF1Score(sync_backend=HostSync()), num_bootstraps=4,
                        sampling_strategy="multinomial", seed=5, raw=True)
    slow = BootStrapper(BinaryF1Score(sync_backend=HostSync()), num_bootstraps=4,
                        sampling_strategy="multinomial", seed=5, raw=True)
    assert fast._vmap_path
    slow._vmap_path = False
    slow.metrics = [deepcopy(slow.base_metric) for _ in range(4)]
    for p, t in shard_batches(rank):
        fast.update(jnp.asarray(p), jnp.asarray(t))
        slow.update(jnp.asarray(p), jnp.asarray(t))
    f_raw = np.asarray(fast.compute()["raw"])
    s_raw = np.asarray(slow.compute()["raw"])
    assert np.allclose(f_raw, s_raw, atol=1e-6), (f_raw, s_raw)
    print(f"RANK{rank} OK")
    """
)


def test_hostsync_timeout_raises_instead_of_hanging(monkeypatch):
    """A stalled peer must surface as TimeoutError, not a hang (the reference
    blocks forever at utilities/distributed.py:118)."""
    import time

    from jax.experimental import multihost_utils

    from torchmetrics_tpu.parallel.sync import HostSync

    def stalled_gather(value, *a, **k):
        time.sleep(30)
        return value

    monkeypatch.setattr(multihost_utils, "process_allgather", stalled_gather)
    hs = HostSync(timeout_s=0.5)
    t0 = time.monotonic()
    from torchmetrics_tpu.parallel.reduction import Reduction

    with pytest.raises(TimeoutError, match="stalled or dead"):
        hs.sync_tensor(jnp.asarray([1.0]), Reduction.SUM)
    assert time.monotonic() - t0 < 5.0
    # the timed-out collective may still be in flight: every further gather
    # on THIS instance must refuse to run rather than pair with it and
    # silently desequence (ADVICE r4). Poison is instance-scoped: a fresh
    # HostSync (new watchdog, its own collective sequence) starts unpoisoned
    # and times out afresh against the still-stalled peer.
    assert hs.poisoned
    with pytest.raises(RuntimeError, match="poisoned"):
        hs.all_gather_object({"a": 1})
    fresh = HostSync(timeout_s=0.5)
    assert not fresh.poisoned
    with pytest.raises(TimeoutError, match="stalled or dead"):
        fresh.sync_tensor(jnp.asarray([1.0]), Reduction.SUM)
    # instance clear_poison() re-arms (caller's contract: only after
    # jax.distributed re-init) — the next gather runs and times out afresh
    hs.clear_poison()
    assert not hs.poisoned
    with pytest.raises(TimeoutError, match="stalled or dead"):
        hs.sync_tensor(jnp.asarray([1.0]), Reduction.SUM)


def test_hostsync_recovery_barrier_autoclears_poison(monkeypatch):
    """A successful post-recovery barrier re-arms a poisoned instance without
    any manual clear_poison() call; a failed barrier leaves it poisoned."""
    import time

    from jax.experimental import multihost_utils

    from torchmetrics_tpu.parallel.reduction import Reduction
    from torchmetrics_tpu.parallel.sync import HostSync

    def stalled_gather(value, *a, **k):
        time.sleep(30)
        return value

    monkeypatch.setattr(multihost_utils, "process_allgather", stalled_gather)
    hs = HostSync(timeout_s=0.3)
    with pytest.raises(TimeoutError):
        hs.sync_tensor(jnp.asarray([1.0]), Reduction.SUM)
    assert hs.poisoned
    # peer still stalled: the barrier itself times out, poison survives
    with pytest.raises(TimeoutError):
        hs.recovery_barrier(timeout_s=0.3)
    assert hs.poisoned
    # peer recovers: the barrier succeeds and auto-clears the flag
    monkeypatch.setattr(multihost_utils, "process_allgather", lambda v, *a, **k: v)
    hs.recovery_barrier()
    assert not hs.poisoned
    np.testing.assert_array_equal(
        np.asarray(hs.sync_tensor(jnp.asarray([1.0]), Reduction.SUM)), [1.0]
    )


def test_module_clear_poison_deprecated_alias(monkeypatch):
    """Module-level clear_poison() still works for existing callers but warns
    and clears every live poisoned instance."""
    import time

    from jax.experimental import multihost_utils

    from torchmetrics_tpu.parallel import sync as sync_mod
    from torchmetrics_tpu.parallel.reduction import Reduction
    from torchmetrics_tpu.parallel.sync import HostSync

    def stalled_gather(value, *a, **k):
        time.sleep(30)
        return value

    monkeypatch.setattr(multihost_utils, "process_allgather", stalled_gather)
    hs = HostSync(timeout_s=0.3)
    with pytest.raises(TimeoutError):
        hs.sync_tensor(jnp.asarray([1.0]), Reduction.SUM)
    assert hs.poisoned
    with pytest.warns(DeprecationWarning, match="recovery_barrier"):
        sync_mod.clear_poison()
    assert not hs.poisoned


def test_failed_sync_leaves_local_state_intact(monkeypatch):
    """A gather failure mid-sync must not corrupt the metric: state stays
    local, no half-synced mix is left behind, and the metric keeps working."""
    import time

    from jax.experimental import multihost_utils

    from torchmetrics_tpu.parallel.sync import HostSync

    def stalled_gather(value, *a, **k):
        time.sleep(30)
        return value

    monkeypatch.setattr(multihost_utils, "process_allgather", stalled_gather)
    hs = HostSync(timeout_s=0.3)
    monkeypatch.setattr(hs, "is_available", lambda: True)
    m = CatMetric(sync_backend=hs)
    m.update(jnp.asarray([1.0, 2.0]))
    with pytest.raises(TimeoutError):
        m.sync()
    assert not m._is_synced
    assert m._cache is None
    # local state is untouched and still usable (dim_zero_cat masks the
    # padded buffer to its valid prefix)
    np.testing.assert_array_equal(np.asarray(dim_zero_cat(m.metric_state["value"])), [1.0, 2.0])
    m.update(jnp.asarray([3.0]))
    m._sync_backend = None  # back to NoSync
    np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_hostsync_timeout_validation():
    from torchmetrics_tpu.parallel.sync import HostSync

    with pytest.raises(ValueError, match="timeout_s"):
        HostSync(timeout_s=0.0)


@pytest.mark.slow
def test_hostsync_two_process_localhost(tmp_path):
    """Real multi-process HostSync over jax.distributed (CPU, localhost)."""
    import socket

    worker = tmp_path / "worker.py"
    worker.write_text(_HOST_SYNC_WORKER)
    with socket.socket() as s:  # pick a free port to avoid collisions
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [
        subprocess.Popen([sys.executable, str(worker), str(r), port],
                         env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                         cwd=repo_root)
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("HostSync workers timed out")
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-2000:]}"
        assert f"RANK{r} OK" in out
