"""CalibrationError metric classes.

Parity: reference ``src/torchmetrics/classification/calibration_error.py``.
"""
from typing import Any, Optional

import jax

from ..functional.classification.calibration_error import (
    _binary_calibration_error_update,
    _ce_compute,
    _multiclass_calibration_error_update,
)
from ..metric import Metric
from ..utils.data import dim_zero_cat
from ..utils.enums import ClassificationTaskNoMultilabel
from .base import _ClassificationTaskWrapper

Array = jax.Array


class BinaryCalibrationError(Metric):
    """Parity: reference ``classification/calibration_error.py:40``."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, n_bins: int = 15, norm: str = "l1", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args:
            if not isinstance(n_bins, int) or n_bins < 1:
                raise ValueError(f"Expected argument `n_bins` to be an integer larger than 0, but got {n_bins}")
            if norm not in ("l1", "l2", "max"):
                raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        if ignore_index is not None:
            self._use_jit = False  # eager filtering keeps sklearn-equal semantics
        self.add_state("confidences", [], dist_reduce_fx="cat")
        self.add_state("accuracies", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        confidences, accuracies = _binary_calibration_error_update(preds, target, self.ignore_index)
        self.confidences.append(confidences)
        self.accuracies.append(accuracies)

    def compute(self) -> Array:
        return _ce_compute(dim_zero_cat(self.confidences), dim_zero_cat(self.accuracies), self.n_bins, self.norm)


class MulticlassCalibrationError(Metric):
    """Parity: reference ``classification/calibration_error.py:151``."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, n_bins: int = 15, norm: str = "l1",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        if ignore_index is not None:
            self._use_jit = False
        self.add_state("confidences", [], dist_reduce_fx="cat")
        self.add_state("accuracies", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        confidences, accuracies = _multiclass_calibration_error_update(
            preds, target, self.num_classes, self.ignore_index
        )
        self.confidences.append(confidences)
        self.accuracies.append(accuracies)

    def compute(self) -> Array:
        return _ce_compute(dim_zero_cat(self.confidences), dim_zero_cat(self.accuracies), self.n_bins, self.norm)


class CalibrationError(_ClassificationTaskWrapper):
    """Task facade. Parity: reference ``classification/calibration_error.py:259``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import CalibrationError
        >>> metric = CalibrationError(task="multiclass", num_classes=3)
        >>> preds = jnp.asarray([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.6, 0.1]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.125
    """

    def __new__(cls, task: str, n_bins: int = 15, norm: str = "l1", num_classes: Optional[int] = None,
                ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"n_bins": n_bins, "norm": norm, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCalibrationError(**kwargs)
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
        return MulticlassCalibrationError(num_classes, **kwargs)
