"""Rank-zero-only printing/warning helpers.

Parity: reference ``src/torchmetrics/utilities/prints.py:22-57``. On TPU pods the
process index comes from ``jax.process_index()``.
"""
import logging
import warnings
from functools import partial, wraps

log = logging.getLogger("torchmetrics_tpu")


def _is_rank_zero() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def rank_zero_only(fn):
    @wraps(fn)
    def wrapped(*args, **kwargs):
        if _is_rank_zero():
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def rank_zero_warn(message: str, category=UserWarning, stacklevel: int = 3) -> None:
    warnings.warn(message, category=category, stacklevel=stacklevel)


@rank_zero_only
def rank_zero_info(message: str) -> None:
    log.info(message)


@rank_zero_only
def rank_zero_debug(message: str) -> None:
    log.debug(message)


rank_zero_print = rank_zero_only(partial(print, flush=True))
