"""Distributed compute kernels over sharded cat state.

The replicated exact path gathers every rank's cat rows onto one device and
computes there — O(N) wire and O(N) single-chip HBM at compute time. With
:class:`~torchmetrics_tpu.buffers.ShardedCatBuffer` residency the rows never
leave their owner shard; the kernels here read them in place:

- :func:`cat_compact` — the *sort-based* read path: a jitted stable
  compaction that orders valid rows shard-major (identical to the oracle's
  ``materialize()`` order) while XLA keeps the data movement distributed.
  Exact consumers (PR-curve, AUROC, rank correlations, retrieval grouping)
  are row-order-invariant, so results are BITWISE-identical to the
  gather-then-compute oracle for integer-weighted states.
- :func:`histogram_auroc` / :func:`histogram_pr_curve` — the *bucketed*
  path: each shard histograms its own rows at a fixed bucket count and one
  small cross-shard reduction (O(buckets), not O(N)) produces the curve.
  Accuracy is ε-bounded by the bucket width (scores that differ by less
  than ``(hi - lo) / bins`` may merge into one threshold).
- :func:`sharded_topk` — exact distributed top-k: per-shard ``lax.top_k``
  then a final top-k over the ``n_shards * k`` candidates.
- :func:`sharded_mean` / :func:`sharded_moments` — count-weighted first and
  second moments across uneven shards (spearman/kendall preprocessing).
- :func:`reshard` — the redistribution plan: chunked per-device
  ``device_put`` rebuilds balanced shards on a new mesh (elastic rejoin
  after preemption, mesh grow/shrink) without ever materializing the full
  state on one device.

Every kernel takes the ``(buffer, counts)`` pair directly; garbage rows at
or past each shard's count are masked inside the kernel. Densifying through
``dim_zero_cat``/``padded_cat`` instead raises unless wrapped in
:func:`~torchmetrics_tpu.utils.data.sharded_oracle` (tpulint TPU015 flags
the accidental form statically).
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..buffers import (
    CatBuffer,
    ShardedCatBuffer,
    _capacity_for,
    batch_sharding,
    default_eval_mesh,
)
from .strategies import record_collective

Array = jax.Array

__all__ = [
    "cat_compact",
    "padded_or_sharded_cat",
    "sharded_histogram",
    "histogram_auroc",
    "histogram_pr_curve",
    "sharded_topk",
    "sharded_mean",
    "sharded_moments",
    "reshard",
]


def _jit(key: Any, fn: Any, donate: bool = False) -> Any:
    from ..metric import _global_jit

    return _global_jit(key, fn, donate_state=donate)


def _mesh_key(buf: ShardedCatBuffer) -> tuple:
    return tuple(d.id for d in buf.mesh.devices.flat)


def _shape_key(buf: ShardedCatBuffer) -> tuple:
    return (buf.n_shards, buf.capacity, buf.trailing, str(buf.dtype), _mesh_key(buf))


# ---------------------------------------------------------------------------
# sort-based read path (bitwise vs the oracle)
# ---------------------------------------------------------------------------

def _make_compact(n_shards: int, cap: int, trailing: tuple) -> Any:
    def compact(buf: Array, counts: Array) -> Array:
        # stable argsort on the invalid mask floats valid rows to the front
        # in shard-major order — exactly materialize()'s concatenation order,
        # so downstream sort-based consumers match the oracle bitwise
        invalid = jnp.arange(cap)[None, :] >= counts[:, None]  # (S, cap)
        order = jnp.argsort(invalid.reshape(-1), stable=True)
        flat = buf.reshape((n_shards * cap,) + trailing)
        return jnp.take(flat, order, axis=0)

    return compact


def cat_compact(x: Any) -> Array:
    """Valid rows of a cat state in any layout, as one dense array.

    The sanctioned read path for sharded state: for a
    :class:`ShardedCatBuffer` the compaction runs as a cached jitted kernel
    over the sharded buffer (XLA distributes the reorder); replicated
    buffers, lists, and plain arrays pass through ``dim_zero_cat``
    semantics unchanged. Row order for sharded state is shard-major; states
    appended in lockstep (``preds``/``target``/``valid`` of one metric)
    compact under the SAME permutation, so row alignment across states is
    preserved.
    """
    if isinstance(x, ShardedCatBuffer):
        if x.count == 0:
            return jnp.zeros((0,) + x.trailing, x.dtype)
        fn = _jit(
            ("sharded_cat_compact",) + _shape_key(x),
            _make_compact(x.n_shards, x.capacity, x.trailing),
        )
        counts = x._counts_dev
        if counts is None:
            counts = jnp.asarray(x.counts)
        return fn(x.buffer, counts)[: x.count]
    from ..utils.data import dim_zero_cat

    return dim_zero_cat(x)


def padded_or_sharded_cat(x: Any) -> Tuple[Array, int]:
    """``(values, count)`` of a cat state; the layout-aware ``padded_cat``."""
    values = cat_compact(x)
    return values, values.shape[0]


# ---------------------------------------------------------------------------
# bucketed-histogram path (O(buckets) wire, documented ε)
# ---------------------------------------------------------------------------

def _make_histogram(
    n_shards: int, cap: int, bins: int, lo: float, hi: float, weighted: bool, masked: bool
) -> Any:
    def hist(buf: Array, counts: Array, w: Optional[Array] = None, m: Optional[Array] = None) -> Array:
        valid = (jnp.arange(cap)[None, :] < counts[:, None]).astype(jnp.float32)
        if masked:
            valid = valid * m
        idx = jnp.clip(
            ((buf - lo) * (bins / (hi - lo))).astype(jnp.int32), 0, bins - 1
        )
        weight = valid * w if weighted else valid
        # each shard scatter-adds its own cap rows into a (bins,) partial;
        # the per-shard partials meet in one small cross-shard reduction
        # (GSPMD lowers the segment sum over the sharded axis to a psum of
        # (bins,) — O(buckets) on the wire, never O(N))
        per_shard = jax.vmap(
            lambda i, ww: jnp.zeros(bins, jnp.float32).at[i].add(ww)
        )(idx, weight)
        return jnp.sum(per_shard, axis=0)

    return hist


def sharded_histogram(
    buf: ShardedCatBuffer,
    bins: int = 8192,
    lo: float = 0.0,
    hi: float = 1.0,
    weights: Optional[ShardedCatBuffer] = None,
    mask: Optional[ShardedCatBuffer] = None,
) -> Array:
    """Fixed-bucket histogram of a sharded 1-D cat state.

    ``weights`` (e.g. the target buffer for per-bucket positive counts) and
    ``mask`` (an ``ignore_index`` validity state) must be appended in
    lockstep with ``buf`` so the shard layouts coincide.
    """
    if buf.trailing != ():
        raise ValueError("sharded_histogram expects a 1-D (scalar-row) cat state")
    fn = _jit(
        ("sharded_hist", bins, float(lo), float(hi), weights is not None, mask is not None)
        + _shape_key(buf),
        _make_histogram(
            buf.n_shards, buf.capacity, bins, lo, hi, weights is not None, mask is not None
        ),
    )
    counts = buf._counts_dev if buf._counts_dev is not None else jnp.asarray(buf.counts)
    record_collective("psum", bins * 4, buf.n_shards, dtype=jnp.float32)
    w = weights.buffer.astype(jnp.float32) if weights is not None else None
    m = mask.buffer.astype(jnp.float32) if mask is not None else None
    if w is not None and m is not None:
        return fn(buf.buffer, counts, w, m)
    if w is not None:
        return fn(buf.buffer, counts, w)
    if m is not None:
        return fn(buf.buffer, counts, m=m)
    return fn(buf.buffer, counts)


def _hist_curve_counts(
    preds: ShardedCatBuffer,
    target: ShardedCatBuffer,
    bins: int,
    lo: float,
    hi: float,
    valid: Optional[ShardedCatBuffer] = None,
) -> Tuple[Array, Array]:
    pos = sharded_histogram(preds, bins, lo, hi, weights=target, mask=valid)
    all_ = sharded_histogram(preds, bins, lo, hi, mask=valid)
    # descending-threshold cumulatives: bucket b covers preds >= its lower edge
    tps = jnp.cumsum(pos[::-1])
    fps = jnp.cumsum((all_ - pos)[::-1])
    return tps, fps


def histogram_auroc(
    preds: ShardedCatBuffer,
    target: ShardedCatBuffer,
    bins: int = 8192,
    lo: float = 0.0,
    hi: float = 1.0,
    valid: Optional[ShardedCatBuffer] = None,
) -> Array:
    """Binary AUROC from per-shard bucketed histograms.

    O(bins) cross-shard traffic instead of an O(N) gather. ε contract:
    scores within one bucket (width ``(hi - lo) / bins``) merge into a
    single ROC vertex — for approximately uniform score distributions the
    trapezoidal error is O(1 / bins); callers needing bitwise parity use
    the sort-based :func:`cat_compact` path instead.
    """
    tps, fps = _hist_curve_counts(preds, target, bins, lo, hi, valid)
    p = tps[-1]
    n = fps[-1]
    tpr = jnp.concatenate([jnp.zeros(1), tps / jnp.maximum(p, 1.0)])
    fpr = jnp.concatenate([jnp.zeros(1), fps / jnp.maximum(n, 1.0)])
    return jnp.trapezoid(tpr, fpr)


def histogram_pr_curve(
    preds: ShardedCatBuffer,
    target: ShardedCatBuffer,
    bins: int = 8192,
    lo: float = 0.0,
    hi: float = 1.0,
    valid: Optional[ShardedCatBuffer] = None,
) -> Tuple[Array, Array, Array]:
    """Binned precision-recall curve over sharded state (same ε contract as
    :func:`histogram_auroc`); thresholds are the descending bucket lower
    edges."""
    tps, fps = _hist_curve_counts(preds, target, bins, lo, hi, valid)
    p = tps[-1]
    precision = tps / jnp.maximum(tps + fps, 1.0)
    recall = tps / jnp.maximum(p, 1.0)
    precision = jnp.concatenate([precision, jnp.ones(1)])
    recall = jnp.concatenate([recall, jnp.zeros(1)])
    edges = lo + (hi - lo) * jnp.arange(bins, dtype=jnp.float32) / bins
    return precision, recall, edges[::-1]


# ---------------------------------------------------------------------------
# exact distributed top-k (retrieval base)
# ---------------------------------------------------------------------------

def _make_topk(n_shards: int, cap: int, k: int) -> Any:
    def topk(buf: Array, counts: Array) -> Array:
        valid = jnp.arange(cap)[None, :] < counts[:, None]
        masked = jnp.where(valid, buf, -jnp.inf)
        per_shard, _ = lax.top_k(masked, min(k, cap))  # (S, k') local candidates
        merged, _ = lax.top_k(per_shard.reshape(-1), k)
        return merged

    return topk


def sharded_topk(buf: ShardedCatBuffer, k: int) -> Array:
    """Exact global top-k of a sharded 1-D cat state: each shard surfaces
    its own top-k candidates (local sort, no materialization) and one
    ``n_shards * k`` merge picks the winners — wire cost O(S·k), not O(N)."""
    if buf.trailing != ():
        raise ValueError("sharded_topk expects a 1-D (scalar-row) cat state")
    k = int(min(k, buf.count))
    if k == 0:
        return jnp.zeros((0,), buf.dtype)
    fn = _jit(("sharded_topk", k) + _shape_key(buf), _make_topk(buf.n_shards, buf.capacity, k))
    counts = buf._counts_dev if buf._counts_dev is not None else jnp.asarray(buf.counts)
    record_collective(
        "all_gather", buf.n_shards * k * buf.dtype.itemsize, buf.n_shards, dtype=buf.dtype
    )
    return fn(buf.buffer, counts)


# ---------------------------------------------------------------------------
# count-weighted moments (spearman / kendall preprocessing)
# ---------------------------------------------------------------------------

def _make_moments(n_shards: int, cap: int) -> Any:
    def moments(buf: Array, counts: Array) -> Tuple[Array, Array]:
        valid = (jnp.arange(cap)[None, :] < counts[:, None]).astype(buf.dtype)
        total = jnp.maximum(jnp.sum(counts.astype(buf.dtype)), 1.0)
        # per-shard partial sums weighted by each shard's own valid count
        # reduce in one small cross-shard step (psum of two scalars)
        s1 = jnp.sum(buf * valid)
        s2 = jnp.sum(buf * buf * valid)
        mean = s1 / total
        var = s2 / total - mean * mean
        return mean, var

    return moments


def sharded_mean(buf: ShardedCatBuffer) -> Array:
    """Count-weighted mean across uneven shards (O(1) wire)."""
    return sharded_moments(buf)[0]


def sharded_moments(buf: ShardedCatBuffer) -> Tuple[Array, Array]:
    """Count-weighted ``(mean, variance)`` across uneven shards."""
    fn = _jit(("sharded_moments",) + _shape_key(buf), _make_moments(buf.n_shards, buf.capacity))
    counts = buf._counts_dev if buf._counts_dev is not None else jnp.asarray(buf.counts)
    record_collective("psum", 2 * buf.dtype.itemsize, buf.n_shards, dtype=buf.dtype)
    return fn(buf.buffer, counts)


# ---------------------------------------------------------------------------
# redistribution plan (elastic rejoin / mesh change)
# ---------------------------------------------------------------------------

def reshard(
    buf: ShardedCatBuffer,
    devices: Optional[Any] = None,
    mesh: Optional[Any] = None,
) -> ShardedCatBuffer:
    """Rebuild ``buf`` balanced over a new mesh via chunked ``device_put``.

    The redistribution plan from "Memory-efficient array redistribution
    through portable collective communication": each target shard's rows are
    assembled from the source shards' valid prefixes one slab at a time and
    placed directly on the owning device — peak host/device footprint is one
    ``capacity``-row slab, never the full state. Wired into
    ``ElasticSync.merge_on_rejoin`` and ``rejoin_metric`` so a preempted
    owner's rows re-shard onto the survivors (or onto a larger mesh on
    rejoin) with coverage accounting intact.
    """
    if mesh is None:
        mesh = default_eval_mesh(devices)
    n2 = mesh.devices.size
    total = buf.count
    chunk = -(-max(total, 1) // n2)
    cap2 = _capacity_for(chunk)
    counts2 = np.clip(total - np.arange(n2) * chunk, 0, chunk).astype(np.int32)
    trailing = buf.trailing

    # shard-major source spans: (source shard, local start, local stop)
    spans = []
    for s, c in enumerate(buf.counts):
        if int(c):
            spans.append((s, 0, int(c)))

    def take_rows(lo: int, n_rows: int) -> Array:
        """Rows [lo, lo + n_rows) of the shard-major valid sequence, pulled
        as per-source-shard slices (each a device-local read)."""
        parts = []
        seen = 0
        need_lo, need_hi = lo, lo + n_rows
        for s, a, b in spans:
            span_lo, span_hi = seen, seen + (b - a)
            seen = span_hi
            if span_hi <= need_lo or span_lo >= need_hi:
                continue
            cut_a = a + max(need_lo - span_lo, 0)
            cut_b = a + min(need_hi - span_lo, b - a)
            parts.append(buf.buffer[s, cut_a:cut_b])
        if not parts:
            return jnp.zeros((0,) + trailing, buf.dtype)
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)

    sharding = batch_sharding(mesh)
    devices_flat = list(mesh.devices.flat)
    record_collective(
        "all_gather",
        int(total) * int(np.prod(trailing, dtype=np.int64) or 1) * buf.dtype.itemsize,
        n2,
        dtype=buf.dtype,
    )
    slabs = []
    for t in range(n2):
        rows = take_rows(t * chunk, int(counts2[t]))
        slab = jnp.zeros((1, cap2) + trailing, buf.dtype)
        if rows.shape[0]:
            slab = slab.at[0, : rows.shape[0]].set(rows)
        slabs.append(jax.device_put(slab, devices_flat[t]))
    arr = jax.make_array_from_single_device_arrays(
        (n2, cap2) + trailing, sharding, slabs
    )
    return ShardedCatBuffer(arr, counts2, mesh=mesh, owner=buf.owner)
