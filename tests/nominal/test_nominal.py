"""Nominal association metrics vs hand-numpy/scipy oracles.

Parity model: reference ``tests/unittests/nominal/`` (which compares against
``dython`` / ``pandas`` implementations; here the oracles are direct numpy
transcriptions of the published formulas).
"""
import numpy as np
import pytest
import scipy.stats

import jax.numpy as jnp

from torchmetrics_tpu.functional.nominal import (
    cramers_v,
    cramers_v_matrix,
    fleiss_kappa,
    pearsons_contingency_coefficient,
    theils_u,
    tschuprows_t,
)
from torchmetrics_tpu.nominal import (
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

rng = np.random.RandomState(11)
N = 400
NUM_CLASSES = 4
PREDS = rng.randint(0, NUM_CLASSES, size=N)
TARGET = np.where(rng.rand(N) < 0.6, PREDS, rng.randint(0, NUM_CLASSES, size=N))


def np_confmat(p, t, c):
    m = np.zeros((c, c))
    for a, b in zip(p, t):
        m[a, b] += 1
    return m


def np_chi2(confmat, bias_correction):
    rows, cols = confmat.sum(1), confmat.sum(0)
    n = confmat.sum()
    expected = np.outer(rows, cols) / n
    r, c = confmat.shape
    df = r * c - r - c + 1
    if df == 0:
        return 0.0
    if df == 1 and bias_correction:
        diff = expected - confmat
        confmat = confmat + np.sign(diff) * np.minimum(0.5, np.abs(diff))
    return float(((confmat - expected) ** 2 / expected).sum())


def np_cramers_v(p, t, bias_correction=True):
    m = np_confmat(p, t, NUM_CLASSES)
    m = m[m.sum(1) != 0][:, m.sum(0) != 0]
    n = m.sum()
    phi2 = np_chi2(m, bias_correction) / n
    r, c = m.shape
    if bias_correction:
        phi2c = max(0.0, phi2 - (r - 1) * (c - 1) / (n - 1))
        rc = r - (r - 1) ** 2 / (n - 1)
        cc = c - (c - 1) ** 2 / (n - 1)
        return np.clip(np.sqrt(phi2c / min(rc - 1, cc - 1)), 0, 1)
    return np.clip(np.sqrt(phi2 / min(r - 1, c - 1)), 0, 1)


def np_tschuprows_t(p, t, bias_correction=True):
    m = np_confmat(p, t, NUM_CLASSES)
    m = m[m.sum(1) != 0][:, m.sum(0) != 0]
    n = m.sum()
    phi2 = np_chi2(m, bias_correction) / n
    r, c = m.shape
    if bias_correction:
        phi2c = max(0.0, phi2 - (r - 1) * (c - 1) / (n - 1))
        rc = r - (r - 1) ** 2 / (n - 1)
        cc = c - (c - 1) ** 2 / (n - 1)
        return np.clip(np.sqrt(phi2c / np.sqrt((rc - 1) * (cc - 1))), 0, 1)
    return np.clip(np.sqrt(phi2 / np.sqrt((r - 1) * (c - 1))), 0, 1)


def np_pearson_cc(p, t):
    m = np_confmat(p, t, NUM_CLASSES)
    m = m[m.sum(1) != 0][:, m.sum(0) != 0]
    phi2 = np_chi2(m, False) / m.sum()
    return np.clip(np.sqrt(phi2 / (1 + phi2)), 0, 1)


def np_theils_u(p, t):
    # reference convention (theils_u.py): the confusion table has target as
    # rows, so U = (H(preds) - H(preds|target)) / H(preds)
    def entropy(labels):
        _, counts = np.unique(labels, return_counts=True)
        pr = counts / counts.sum()
        return -np.sum(pr * np.log(pr))

    s_x = entropy(p)
    if s_x == 0:
        return 0.0
    s_xy = 0.0  # conditional entropy H(preds|target)
    for y in np.unique(t):
        sel = t == y
        w = sel.mean()
        s_xy += w * entropy(p[sel])
    return (s_x - s_xy) / s_x


def np_fleiss(counts):
    total = counts.shape[0]
    num_raters = counts.sum(1).max()
    p_i = counts.sum(0) / (total * num_raters)
    p_j = ((counts**2).sum(1) - num_raters) / (num_raters * (num_raters - 1))
    return (p_j.mean() - (p_i**2).sum()) / (1 - (p_i**2).sum() + 1e-5)


@pytest.mark.parametrize("bias_correction", [True, False])
def test_cramers_v(bias_correction):
    res = float(cramers_v(jnp.asarray(PREDS), jnp.asarray(TARGET), bias_correction))
    np.testing.assert_allclose(res, np_cramers_v(PREDS, TARGET, bias_correction), atol=1e-4)


@pytest.mark.parametrize("bias_correction", [True, False])
def test_tschuprows_t(bias_correction):
    res = float(tschuprows_t(jnp.asarray(PREDS), jnp.asarray(TARGET), bias_correction))
    np.testing.assert_allclose(res, np_tschuprows_t(PREDS, TARGET, bias_correction), atol=1e-4)


def test_pearsons_contingency_coefficient():
    res = float(pearsons_contingency_coefficient(jnp.asarray(PREDS), jnp.asarray(TARGET)))
    np.testing.assert_allclose(res, np_pearson_cc(PREDS, TARGET), atol=1e-4)
    # cross-check chi2 against scipy on the same table
    m = np_confmat(PREDS, TARGET, NUM_CLASSES)
    chi2 = scipy.stats.chi2_contingency(m, correction=False)[0]
    np.testing.assert_allclose(np_chi2(m, False), chi2, rtol=1e-6)


def test_theils_u():
    res = float(theils_u(jnp.asarray(PREDS), jnp.asarray(TARGET)))
    np.testing.assert_allclose(res, np_theils_u(PREDS, TARGET), atol=1e-4)


def test_fleiss_kappa_counts_and_probs():
    counts = rng.multinomial(10, [0.3, 0.4, 0.3], size=50)
    res = float(fleiss_kappa(jnp.asarray(counts)))
    np.testing.assert_allclose(res, np_fleiss(counts.astype(float)), atol=1e-4)

    probs = rng.rand(20, 4, 6).astype(np.float32)
    res_p = float(fleiss_kappa(jnp.asarray(probs), mode="probs"))
    chosen = probs.argmax(1)
    counts_p = np.stack([(chosen == c).sum(1) for c in range(4)], axis=1)
    np.testing.assert_allclose(res_p, np_fleiss(counts_p.astype(float)), atol=1e-4)


def test_nan_handling():
    p = PREDS.astype(np.float32).copy()
    p[::17] = np.nan
    res_rep = float(cramers_v(jnp.asarray(p), jnp.asarray(TARGET.astype(np.float32)), True, "replace", 0.0))
    p_rep = np.nan_to_num(p, nan=0.0).astype(int)
    np.testing.assert_allclose(res_rep, np_cramers_v(p_rep, TARGET), atol=1e-4)

    res_drop = float(cramers_v(jnp.asarray(p), jnp.asarray(TARGET.astype(np.float32)), True, "drop"))
    keep = ~np.isnan(p)
    np.testing.assert_allclose(res_drop, np_cramers_v(p[keep].astype(int), TARGET[keep]), atol=1e-4)


def test_matrix_form():
    mat = np.stack([PREDS, TARGET, rng.randint(0, 3, N)], axis=1)
    out = np.asarray(cramers_v_matrix(jnp.asarray(mat)))
    assert out.shape == (3, 3)
    np.testing.assert_allclose(np.diag(out), 1.0)
    np.testing.assert_allclose(out[0, 1], np_cramers_v(PREDS, TARGET), atol=1e-4)


CLASS_CASES = [
    (CramersV, np_cramers_v, {"num_classes": NUM_CLASSES}),
    (TschuprowsT, np_tschuprows_t, {"num_classes": NUM_CLASSES}),
    (PearsonsContingencyCoefficient, np_pearson_cc, {"num_classes": NUM_CLASSES}),
    (TheilsU, np_theils_u, {"num_classes": NUM_CLASSES}),
]


@pytest.mark.parametrize(("cls", "oracle", "kwargs"), CLASS_CASES)
def test_class_accumulate(cls, oracle, kwargs):
    metric = cls(**kwargs)
    for i in range(4):
        sl = slice(i * (N // 4), (i + 1) * (N // 4))
        metric.update(jnp.asarray(PREDS[sl]), jnp.asarray(TARGET[sl]))
    np.testing.assert_allclose(float(metric.compute()), oracle(PREDS, TARGET),
                               atol=1e-4, err_msg=cls.__name__)


def test_fleiss_class():
    counts = rng.multinomial(10, [0.25, 0.25, 0.5], size=60)
    metric = FleissKappa()
    metric.update(jnp.asarray(counts[:30]))
    metric.update(jnp.asarray(counts[30:]))
    np.testing.assert_allclose(float(metric.compute()), np_fleiss(counts.astype(float)), atol=1e-4)


def test_ddp_merge_states():
    full = CramersV(num_classes=NUM_CLASSES)
    full.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ref = float(full.compute())
    r0, r1 = CramersV(num_classes=NUM_CLASSES), CramersV(num_classes=NUM_CLASSES)
    r0.update(jnp.asarray(PREDS[: N // 2]), jnp.asarray(TARGET[: N // 2]))
    r1.update(jnp.asarray(PREDS[N // 2 :]), jnp.asarray(TARGET[N // 2 :]))
    merged = r0.merge_states([r0.metric_state, r1.metric_state])
    np.testing.assert_allclose(float(r0.compute_state(merged)), ref, atol=1e-6)
