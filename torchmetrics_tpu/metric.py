"""Core metric runtime (L2).

Parity target: reference ``src/torchmetrics/metric.py`` (1211 LoC) — state
registry (``add_state`` :195-272), dual-path ``forward`` (:275-391), wrapped
``update``/``compute`` (:459-481, :593-623), sync protocol (:427-591),
persistence (:834-890), operator overloading (:938-1073),
``CompositionalMetric`` (:1088-1211).

TPU-first architecture (NOT a port — see SURVEY.md §7):

- A metric is ``(init() -> State, update(State, batch) -> State,
  compute(State) -> Result)`` over a dict-of-arrays state where each leaf
  carries a :class:`~torchmetrics_tpu.parallel.Reduction` tag. The class below
  is a thin ergonomic shell storing that pytree; subclasses write the familiar
  mutate-``self`` update bodies, which are *pure by construction* w.r.t.
  (state, inputs) because JAX arrays are immutable — attribute writes are just
  rebinding. The shell exploits this to trace the whole update (and the whole
  ``forward`` fast path: batch-update + batch-compute + merge) into ONE jitted
  XLA call per step, amortizing what the reference pays in per-metric Python
  bookkeeping every step.
- ``cat`` (list) states: the traced update returns the *appended increments*
  as outputs; the shell extends a host-side list. Shapes stay static per batch
  signature, so XLA caches one executable per input shape.
- Distributed sync: eager class API uses an injectable
  :class:`~torchmetrics_tpu.parallel.SyncBackend` (parity with
  ``dist_sync_fn`` injection, ``metric.py:127``); the SPMD path is the pure
  functional API (:meth:`Metric.init_state` / :meth:`update_state` /
  :meth:`reduce_state` / :meth:`compute_state`) used inside
  ``shard_map``/``pjit``, where sum/mean/max/min states lower to
  ``lax.psum/pmean/pmax/pmin`` (O(state) on ICI).
"""
from __future__ import annotations

import copy
import enum
import functools
import hashlib
import inspect
import itertools
import sys
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .buffers import CatBuffer, CatLayoutError, ShardedCatBuffer
from .observability import ledger as _ledger
from .observability import spans as _spans
from .observability.registry import REGISTRY as _REGISTRY
from .parallel.reduction import ELEMENTWISE_REDUCTIONS, Reduction, resolve_reduction
from .parallel.strategies import (
    SyncPolicy,
    begin_sync,
    default_policy,
    dequantize_chunks,
    quantize_chunks,
    record_collective,
    reset_wire_stats,
    wire_stats,
)
from .parallel.elastic import elastic_stats, reset_elastic_stats
from .parallel.sync import NoSync, SyncBackend, default_sync_backend, reduce_state_in_graph
from .state import MetricState
from .utils.data import dim_zero_cat
from .utils.exceptions import TorchMetricsUserError
from .utils.prints import rank_zero_warn

Array = jax.Array
StateDict = Dict[str, Any]

_CONST_ATTRS = ("is_differentiable", "higher_is_better", "full_state_update")


def _squeeze_if_scalar(data: Any) -> Any:
    """Shape-(1,) arrays become scalars; parity with reference output squeeze."""
    if isinstance(data, (jax.Array, jnp.ndarray)) and data.ndim == 1 and data.shape[0] == 1:
        return data.reshape(())
    if isinstance(data, dict):
        return {k: _squeeze_if_scalar(v) for k, v in data.items()}
    if isinstance(data, tuple):
        return tuple(_squeeze_if_scalar(v) for v in data)
    return data


def _filter_kwargs(fn: Callable, **kwargs: Any) -> Dict[str, Any]:
    """Keep only kwargs accepted by ``fn``'s signature.

    Parity: reference ``Metric._filter_kwargs`` (``metric.py:892-911``) — used
    by MetricCollection/CompositionalMetric to route a shared kwarg dict to
    members with different update signatures.
    """
    sig = inspect.signature(fn)
    params = sig.parameters
    if any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values()):
        return kwargs
    names = {
        n
        for n, p in params.items()
        if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD, inspect.Parameter.KEYWORD_ONLY)
        and n != "self"
    }
    return {k: v for k, v in kwargs.items() if k in names}


def jit_update_disabled():
    """Context manager disabling jitted update paths globally (debugging aid)."""
    return jax.disable_jit()


def _jit_safe_inputs(*trees: Any) -> bool:
    """True iff every pytree leaf can be passed as a jit argument."""
    for leaf in jax.tree_util.tree_leaves(trees):
        if not isinstance(leaf, (jax.Array, np.ndarray, np.generic, int, float, bool, complex)):
            return False
    return True


# ---------------------------------------------------------------------------
# process-global executable cache
#
# Equal-config metric instances (clone(), BootStrapper's B replay copies,
# MetricTracker epochs, MetricCollection.clone()) share one compiled program
# instead of retracing per instance. Keys are derived from
# (class, frozen config attributes, frozen state defaults); jit's own aval
# cache layered underneath handles per-input-shape specialization.
# ---------------------------------------------------------------------------

_EXECUTABLE_CACHE: Dict[Any, Callable] = {}
# registry-backed (see observability/registry.py): same mutation idiom as the
# historical plain dicts, but scrapeable via to_prometheus()
_CACHE_STATS = _REGISTRY.group(
    "cache",
    {"hits": 0, "misses": 0, "compiles": 0, "retraces": 0},
    help="process-global executable cache",
)
_DISPATCH_COUNT = _REGISTRY.counter("cache.dispatches", "jitted dispatches")
# observers called as cb(key, new_compiles, retraces) whenever a dispatch
# triggers XLA compilation; used by debug.strict_mode() to fail fast
_COMPILE_OBSERVERS: List[Callable[[Any, int, int], None]] = []
_INSTANCE_KEY_COUNTER = itertools.count()

_MAX_KEY_ARRAY_BYTES = 4096

# bytes fed through hashing in Metric.__hash__ — the incremental-digest
# regression test asserts re-hashing an unchanged metric feeds zero bytes
_HASH_STATS = _REGISTRY.group("hash", {"bytes_hashed": 0}, help="Metric.__hash__ traffic")

# attributes that never change the traced program (pure host-side bookkeeping)
_RUNTIME_ATTRS = frozenset(
    {
        "_state",
        "_defaults",
        "_reductions",
        "_persistent",
        "_list_states",
        "_cache",
        "_computed",
        "_update_count",
        "_is_synced",
        "_in_pure_update",
        "_sync_backend",
        "_sync_policy",
        "_sync_residuals",
        "_list_layout",
        "_cat_layout",
        "_cat_meta",
        "_layout_fallback",
        "_hash_digests",
        "_jit_bound",
        "_exec_key_cache",
        "_exec_nonce",
        "_use_jit",
        "_compute_jittable",
        "_stream_buffer",
        "compute_on_cpu",
        "dist_sync_on_step",
        "sync_on_compute",
        "compute_with_cache",
    }
)


def _runtime_attrs_for(cls: type) -> frozenset:
    """Attributes excluded from executable-key scanning for ``cls``.

    Subclasses with their own host-side bookkeeping (e.g. ``TenantStack``'s
    tenant-id table) extend the base set via ``_extra_runtime_attrs``."""
    extra = getattr(cls, "_extra_runtime_attrs", None)
    return _RUNTIME_ATTRS | extra if extra else _RUNTIME_ATTRS


class _Unkeyable(Exception):
    """Config value cannot be part of a process-shared cache key."""


def _freeze_config_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return v
    if isinstance(v, enum.Enum):
        return v
    if isinstance(v, type):
        return v
    if isinstance(v, np.dtype):
        return ("dtype", str(v))
    if isinstance(v, np.generic):
        # tobytes() keys the exact bit pattern without a host scalar
        # materialization (and distinguishes NaN payloads, unlike .item())
        return ("npscalar", str(v.dtype), v.tobytes())
    if isinstance(v, (jax.Array, np.ndarray)):
        arr = np.asarray(v)
        if arr.nbytes > _MAX_KEY_ARRAY_BYTES:
            raise _Unkeyable("array attribute too large for a shared cache key")
        return ("arr", arr.shape, str(arr.dtype), arr.tobytes())
    if isinstance(v, (tuple, list)):
        return ("seq", type(v).__name__, tuple(_freeze_config_value(x) for x in v))
    if isinstance(v, (set, frozenset)):
        return ("set", frozenset(_freeze_config_value(x) for x in v))
    if isinstance(v, dict):
        return ("map", tuple(sorted((k, _freeze_config_value(x)) for k, x in v.items())))
    if isinstance(v, Metric):
        # Metric.__eq__ builds a CompositionalMetric, so instances must never
        # participate in key equality — fall back to a per-instance key
        raise _Unkeyable("Metric-valued attribute")
    if callable(v):
        # identity-keyed: deepcopy keeps function objects, so clones share
        return ("fn", id(v))
    raise _Unkeyable(f"unkeyable config attribute of type {type(v).__name__}")


def _jit_compile_count(jitted: Callable) -> int:
    """Number of compiled specializations held by a ``jax.jit`` wrapper."""
    try:
        return jitted._cache_size()
    except Exception:  # pragma: no cover - jax without the private API
        return 0


def _global_jit(key: Any, fn: Callable, donate_state: bool = False) -> Callable:
    """jit ``fn`` under a process-global key; count dispatches per call."""
    key = (key, donate_state)
    entry = _EXECUTABLE_CACHE.get(key)
    if entry is None:
        _CACHE_STATS["misses"] += 1
        jitted = jax.jit(fn, donate_argnums=(0,) if donate_state else ())
        seen_compiles = [0]

        def entry(*args: Any, **kwargs: Any) -> Any:
            _DISPATCH_COUNT.inc()
            # abstract shapes are snapshotted BEFORE dispatch: donation may
            # consume argument buffers, and the ledger must never touch them
            spec = _ledger.arg_specs(args, kwargs) if _ledger.ENABLED else None
            before = _jit_compile_count(jitted)
            out = jitted(*args, **kwargs)
            new = _jit_compile_count(jitted) - before
            if new > 0:
                # the first compile of an entry is the expected cost of a
                # cache miss; every further compile is a retrace (new input
                # shape/dtype against an already-warm executable)
                retraces = new if seen_compiles[0] else new - 1
                seen_compiles[0] += new
                _CACHE_STATS["compiles"] += new
                _CACHE_STATS["retraces"] += retraces
                if _ledger.ENABLED:
                    _ledger.record_compile(key, jitted, spec, donate_state, new, retraces)
                for cb in list(_COMPILE_OBSERVERS):
                    cb(key, new, retraces)
            return out

        entry._jitted = jitted  # type: ignore[attr-defined]
        _EXECUTABLE_CACHE[key] = entry
    else:
        _CACHE_STATS["hits"] += 1
    return entry


def reset_cache_stats() -> None:
    """Zero EVERY telemetry island: cache, wire, elastic, ledger, and online.

    The historical reset skipped the online counters (they live in a
    lazily-imported module), silently skewing any before/after
    measurement that mixed streaming and batch metrics; resetting here
    goes through all four islands so deltas line up.
    """
    _CACHE_STATS.reset()
    _DISPATCH_COUNT.reset()
    _HASH_STATS.reset()
    reset_wire_stats()
    reset_elastic_stats()
    _ledger.reset_ledger()
    mod = sys.modules.get("torchmetrics_tpu.online")
    if mod is not None:
        mod.reset_online_stats()


def clear_executable_cache() -> None:
    """Drop all cached executables and reset counters (tests/benchmarks)."""
    _EXECUTABLE_CACHE.clear()
    reset_cache_stats()


def executable_cache_stats() -> Dict[str, int]:
    """Cache size, hit/miss counts, compile/retrace counts, dispatches, and
    wire-level sync counters (modelled bytes reduced/gathered + collectives
    issued; in-graph collectives count once per trace, eager once per call —
    see ``parallel.strategies.record_collective``), and elastic-sync health
    (retry/timeout/degraded counts plus the last round's coverage record —
    see ``parallel.elastic``; the per-metric view of the same record is the
    :attr:`Metric.coverage` property). The ``online`` entry carries the
    online-evaluation dispatch counters (windowed/decayed metrics created,
    eager update dispatches, estimated window rotations — see
    ``online.online_stats``); it is ``{}`` until ``torchmetrics_tpu.online``
    is first used. The ``ledger`` entry summarizes the device-truth
    executable ledger (XLA cost/memory analysis per executable — see
    ``observability.ledger``); it reports zero entries unless the ledger
    was armed via ``observability.enable_ledger()``.

    This is a backward-compatibility view: the counters themselves live in
    the :mod:`~torchmetrics_tpu.observability.registry` and can be scraped
    directly via :func:`~torchmetrics_tpu.observability.to_prometheus`."""
    wire = wire_stats()
    es = elastic_stats()
    online: Dict[str, int] = {}
    mod = sys.modules.get("torchmetrics_tpu.online")
    if mod is not None:
        online = mod.online_stats()
    return {
        "size": len(_EXECUTABLE_CACHE),
        "hits": _CACHE_STATS["hits"],
        "misses": _CACHE_STATS["misses"],
        "compiles": _CACHE_STATS["compiles"],
        "retraces": _CACHE_STATS["retraces"],
        "dispatches": int(_DISPATCH_COUNT.value),
        "bytes_reduced": wire["bytes_reduced"],
        "bytes_gathered": wire["bytes_gathered"],
        "collectives_issued": wire["collectives_issued"],
        "syncs": wire["syncs"],
        "sync_retries": es["retries"],
        "sync_timeouts": es["timeouts"],
        "degraded_syncs": es["degraded_syncs"],
        "coverage": es["last_coverage"],
        "online": online,
        "ledger": _ledger.ledger_summary(),
    }


class Metric:
    """Base class for all metrics.

    Ergonomics mirror the reference (``add_state`` in ``__init__``; ``update``
    mutates state attributes; ``compute`` reads them), but the runtime is
    JAX-native: states are immutable arrays in a tagged pytree and every
    update/forward runs as a single jitted XLA program when ``jit=True``
    (default; set class attr ``jittable = False`` for host-side metrics like
    text edit distances).

    Constructor kwargs (parity with reference ``metric.py:100-148``):
        compute_on_cpu: move ``cat`` list-state increments to host memory after
            each update (parity ``metric.py:113``; on TPU this offloads HBM).
        dist_sync_on_step: sync state every ``forward`` (expensive eagerly; in
            the SPMD functional path a psum-per-step is nearly free).
        sync_on_compute: sync before ``compute`` (default True).
        compute_with_cache: cache ``compute`` result until next update.
        sync_backend: a :class:`SyncBackend`; default picks HostSync when
            multi-process else NoSync. Replaces ``dist_sync_fn`` /
            ``process_group`` / ``distributed_available_fn``.
        sync_policy: a :class:`~torchmetrics_tpu.parallel.SyncPolicy`
            selecting the wire strategy for state sync (gather mode,
            reduce-scatter decomposition, opt-in quantized collectives);
            ``None`` uses the process default — exact, full precision.
        jit: trace update/forward with ``jax.jit`` (per input-shape cache).
        list_layout: storage for ``cat`` list states — ``"padded"`` (default)
            accumulates increments in a power-of-two :class:`CatBuffer` via
            in-place donated ``dynamic_update_slice`` writes (O(1) amortized,
            O(log n) executables); ``"list"`` keeps the legacy
            one-array-per-update Python list (the equivalence oracle,
            bitwise-identical results).
        cat_layout: residency for padded ``cat`` states — ``"replicated"``
            (default) keeps each :class:`CatBuffer` whole on one device;
            ``"sharded"`` partitions the ``(buffer, count)`` pair across the
            eval mesh under ``NamedSharding(P('batch'))``
            (:class:`~torchmetrics_tpu.buffers.ShardedCatBuffer`), so
            resident cat-state bytes per device scale with the pod.
            Compute reads then go through the distributed kernels in
            :mod:`~torchmetrics_tpu.parallel.sharded_compute`; densifying
            via ``dim_zero_cat``/``padded_cat`` raises unless wrapped in
            ``sharded_oracle()``.

    Example (defining a custom metric):
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import Metric
        >>> class RunningTotal(Metric):
        ...     def __init__(self):
        ...         super().__init__()
        ...         self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
        ...     def update(self, x):
        ...         self.total = self.total + jnp.sum(x)
        ...     def compute(self):
        ...         return self.total
        >>> metric = RunningTotal()
        >>> metric.update(jnp.asarray([1.0, 2.0]))
        >>> metric.update(jnp.asarray([3.0]))
        >>> float(metric.compute())
        6.0
    """

    __jit_state_names__: Tuple[str, ...] = ()

    # subclass hook: extra attribute names excluded from executable-key
    # scanning (host-side bookkeeping that never changes the traced program)
    _extra_runtime_attrs: frozenset = frozenset()

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False
    jittable: bool = True
    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None

    _signature_base: Optional[type] = None  # engine base whose update must be unoverridden

    @property
    def update_signature(self):
        """Hashable key identifying this metric's update semantics, or None.

        Two metrics with equal signatures produce identical states from
        identical inputs (same engine, same parameters) — the trace-safe
        analogue of the reference's post-update state comparison for
        compute groups (``collections.py:264``). ``MetricCollection``'s pure
        ``update_state``/``reduce_state`` run one update per distinct
        signature and share the resulting state subtree across members whose
        input states are identical.

        Engine base classes set ``_signature_base`` to themselves and
        implement ``_engine_signature()`` returning the key; the guard here
        disables sharing for any subclass that overrides ``update``.
        """
        base = self._signature_base
        if base is None or type(self).update is not base.update:
            return None
        return self._engine_signature()

    def _engine_signature(self):
        raise NotImplementedError  # pragma: no cover - only reached via _signature_base

    def __init__(
        self,
        *,
        compute_on_cpu: bool = False,
        dist_sync_on_step: bool = False,
        sync_on_compute: bool = True,
        compute_with_cache: bool = True,
        sync_backend: Optional[SyncBackend] = None,
        sync_policy: Optional[SyncPolicy] = None,
        jit: bool = True,
        list_layout: str = "padded",
        cat_layout: str = "replicated",
        **kwargs: Any,
    ) -> None:
        if kwargs:
            raise ValueError(f"Unexpected keyword arguments: {sorted(kwargs)}")
        if list_layout not in ("padded", "list"):
            raise ValueError(f"list_layout must be 'padded' or 'list', got {list_layout!r}")
        if cat_layout not in ("replicated", "sharded"):
            raise ValueError(
                f"cat_layout must be 'replicated' or 'sharded', got {cat_layout!r}"
            )
        if cat_layout == "sharded" and list_layout != "padded":
            raise ValueError("cat_layout='sharded' requires list_layout='padded'")
        # bypass __setattr__ guards during bootstrap; state lives in ONE
        # explicit MetricState pytree — the class below is a thin view on it
        object.__setattr__(self, "_defaults", {})
        object.__setattr__(self, "_state", MetricState())
        self._reductions: Dict[str, Union[Reduction, Callable]] = {}
        self._persistent: Dict[str, bool] = {}
        self._list_states: set = set()
        self._list_layout = list_layout
        self._cat_layout = cat_layout
        self._cat_meta: Dict[str, tuple] = {}  # name -> (np.dtype | None, trailing | None)
        self._layout_fallback: set = set()  # cat states degraded to the list layout
        self._hash_digests: Dict[str, list] = {}  # name -> [state obj, covered, hasher]

        self.compute_on_cpu = compute_on_cpu
        self.dist_sync_on_step = dist_sync_on_step
        self.sync_on_compute = sync_on_compute
        self.compute_with_cache = compute_with_cache
        self._sync_backend = sync_backend
        self._sync_policy = sync_policy
        self._sync_residuals: Dict[Any, Array] = {}  # quantized-sync error feedback
        self._use_jit = bool(jit) and type(self).jittable

        self._update_count = 0
        self._compute_jittable = True  # False for data-dependent-shape computes (exact curves)
        self._computed: Any = None
        self._is_synced = False
        self._cache: Optional[StateDict] = None
        self._dtype = jnp.float32

    # ------------------------------------------------------------------
    # subclass machinery: wrap update/compute once per class definition
    # ------------------------------------------------------------------
    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        if "update" in cls.__dict__ and not getattr(cls.__dict__["update"], "_tm_wrapped", False):
            cls._update_impl = cls.__dict__["update"]
            cls.update = _wrap_update(cls.__dict__["update"])
        if "compute" in cls.__dict__ and not getattr(cls.__dict__["compute"], "_tm_wrapped", False):
            cls._compute_impl = cls.__dict__["compute"]
            cls.compute = _wrap_compute(cls.__dict__["compute"])

    # ------------------------------------------------------------------
    # state registry
    # ------------------------------------------------------------------
    def add_state(
        self,
        name: str,
        default: Union[Array, list, float, int],
        dist_reduce_fx: Union[str, Callable, None] = None,
        persistent: bool = False,
        dtype: Any = None,
    ) -> None:
        """Register a state leaf. Parity: reference ``metric.py:195-272``.

        ``default`` must be an array (fixed-shape state) or an empty list
        (``cat`` list state whose increments concatenate along dim 0).
        ``dtype`` declares a list state's element dtype up front, so an
        empty state concatenates to a 0-length array of that dtype (e.g.
        integer retrieval indexes) instead of the metric-wide float default;
        it is also learned automatically from the first appended increment.
        """
        if not name.isidentifier():
            raise ValueError(f"state name must be a valid identifier, got {name!r}")
        if isinstance(default, list):
            if default:
                raise ValueError("list state default must be an empty list")
            self._list_states.add(name)
            if dtype is not None:
                self._cat_meta[name] = (np.dtype(dtype), None)
            value: Any = []
        else:
            if dtype is not None:
                raise ValueError("dtype declaration is only supported for list states")
            value = jnp.asarray(default)
        red = resolve_reduction(dist_reduce_fx)
        self._defaults[name] = [] if name in self._list_states else value
        self._reductions[name] = red
        self._persistent[name] = persistent
        st = self.__dict__["_state"]
        if isinstance(st, MetricState):
            st.register(
                name,
                red,
                list_state=name in self._list_states,
                sharded=self._uses_sharded(name),
            )
        st[name] = [] if name in self._list_states else value
        self._invalidate_executable_key()

    # attribute routing: registered states live in self._state
    def __getattr__(self, name: str) -> Any:
        state = self.__dict__.get("_state")
        if state is not None and name in state:
            return state[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        if name in _CONST_ATTRS and getattr(type(self), "_allow_const_set", False) is False and "_state" in self.__dict__:
            raise RuntimeError(f"Can't change const `{name}`.")
        state = self.__dict__.get("_state")
        if state is not None and name in state:
            state[name] = value
            return
        object.__setattr__(self, name, value)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def update(self, *args: Any, **kwargs: Any) -> None:  # overridden by subclasses
        raise NotImplementedError(f"{type(self).__name__} must implement update()")

    def compute(self) -> Any:  # overridden by subclasses
        raise NotImplementedError(f"{type(self).__name__} must implement compute()")

    # ------------------------------------------------------------------
    # streaming buffer protocol (streaming.py)
    # ------------------------------------------------------------------
    def _flush_pending(self) -> None:
        """Drain any staged-but-unflushed streaming updates before a state
        observation, so buffered semantics stay identical to eager updates
        (see ``streaming.py``; the buffer installs itself as
        ``_stream_buffer`` on the metric it wraps)."""
        buf = self.__dict__.get("_stream_buffer")
        if buf is not None and buf.pending:
            buf.flush()

    def buffered(self, window: int = 32, overlap_sync: bool = False) -> "Any":
        """Return a :class:`~torchmetrics_tpu.streaming.BufferedMetric` that
        stages ``window`` updates on device and flushes them in ONE scanned
        XLA dispatch — K steps of metric work per dispatch instead of K
        dispatches. Results are bitwise-identical to eager updates; any
        state observation (``compute``/``sync``/``reset``/state access/
        pickling) forces a flush first.

        ``overlap_sync=True`` additionally gathers each previous window's
        cat-state increments right after the asynchronous flush dispatch, so
        sync communication hides under the next window's scan; the remaining
        states sync at the :meth:`compute` barrier (see
        ``docs/streaming_pipeline.md``)."""
        from .streaming import BufferedMetric

        return BufferedMetric(self, window, overlap_sync=overlap_sync)

    def windowed(self, horizon: int, slots: int = 8) -> "Any":
        """Return a :class:`~torchmetrics_tpu.online.WindowedMetric` tracking
        this metric over a sliding window of (approximately) the last
        ``horizon`` updates, as a ring of ``slots`` sub-epoch state slots
        rotated entirely in-graph (see ``docs/online_evaluation.md``)."""
        from .online import WindowedMetric

        return WindowedMetric(self, horizon=horizon, slots=slots)

    def decayed(self, halflife: float) -> "Any":
        """Return a :class:`~torchmetrics_tpu.online.DecayedMetric` tracking
        this metric with per-update exponential decay: an observation made
        ``halflife`` updates ago contributes half its original weight (see
        ``docs/online_evaluation.md``)."""
        from .online import DecayedMetric

        return DecayedMetric(self, halflife=halflife)

    def reset(self) -> None:
        """Restore default states. Parity: reference ``metric.py:673-688``."""
        self._flush_pending()
        self._update_count = 0
        self._computed = None
        self._cache = None
        self._is_synced = False
        self._hash_digests.clear()
        for name, default in self._defaults.items():
            if name in self._list_states:
                self._state[name] = []
            elif isinstance(default, jax.Array):
                # fresh buffer, never an alias: grouped members share one
                # state dict, so aliasing defaults here would let a later
                # donated update delete ANOTHER member's default buffers
                # (the donation guard can only recognise its own defaults)
                self._state[name] = jnp.array(default, copy=True)
            else:
                self._state[name] = default

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate global state AND return the batch-local value.

        Dual-path semantics, parity: reference ``metric.py:275-391``. The fast
        path (``full_state_update=False``) traces batch-update, batch-compute
        and global-merge into one XLA call.
        """
        self._flush_pending()
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric has been synced and `forward` assumes local state; call `unsync()` first."
            )
        _sp = (
            _spans.start_span("metric.forward", metric=type(self).__name__)
            if _spans.ENABLED
            else None
        )
        try:
            if self.full_state_update or self.full_state_update is None or self.dist_sync_on_step:
                return self._forward_full_state_update(*args, **kwargs)
            return self._forward_reduce_state_update(*args, **kwargs)
        finally:
            if _sp is not None:
                _sp.end()

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    # -- forward: slow path (update reads global state) ------------------
    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        self.update(*args, **kwargs)  # accumulate into global
        cache = self._snapshot_state()
        count = self._update_count
        self._restore_defaults()
        self.update(*args, **kwargs)  # batch-only state
        with self.sync_context(should_sync=self.dist_sync_on_step):
            batch_val = _squeeze_if_scalar(self._compute_impl())
        self._install_state(cache)
        self._update_count = count
        self._computed = None
        return batch_val

    # -- forward: fast path (batch update + merge), single jitted call ---
    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        n_prev = self._update_count
        self._update_count += 1
        self._computed = None
        args = tuple(self._to_array(a) for a in args)
        kwargs = {k: self._to_array(v) for k, v in kwargs.items()}
        self._eager_validate(*args, **kwargs)

        if self._use_jit and self._compute_jittable:
            fwd = self._get_jitted("forward", self._pure_forward, donate_state=True)
            value, merged, appends = fwd(
                self._donation_safe_tensor_state(), jnp.asarray(n_prev), args, kwargs
            )
        else:
            value, merged, appends = self._pure_forward(self._tensor_state(), n_prev, args, kwargs)
        for k, v in merged.items():
            self._state[k] = v
        self._extend_list_states(appends)
        if self.dist_sync_on_step:
            # eager multi-process per-step sync of the batch value's state is
            # handled by full-state path; here we only warn once
            pass
        return _squeeze_if_scalar(value)

    def _pure_forward(self, gstate: StateDict, n_prev: Any, args: tuple, kwargs: dict):
        defaults = {k: v for k, v in self._defaults.items() if k not in self._list_states}
        batch_tensors, appends = self._pure_update(defaults, args, kwargs)
        value = self._pure_compute(batch_tensors, appends)
        merged = self._merge_tensor_states(gstate, batch_tensors, n_prev)
        return value, merged, appends

    # ------------------------------------------------------------------
    # pure kernels over the state pytree (the functional core)
    # ------------------------------------------------------------------
    def _pure_update(self, tensor_state: StateDict, args: tuple, kwargs: dict):
        """Run the subclass update body against a shadow state; pure."""
        shadow: StateDict = dict(tensor_state)
        for k in self._list_states:
            shadow[k] = []
        old = self.__dict__["_state"]
        object.__setattr__(self, "_state", shadow)
        object.__setattr__(self, "_in_pure_update", True)
        try:
            self._update_impl(*args, **kwargs)
            captured = self.__dict__["_state"]
        finally:
            object.__setattr__(self, "_state", old)
            object.__setattr__(self, "_in_pure_update", False)
        new_tensors = {k: captured[k] for k in tensor_state}
        appends = {k: tuple(captured[k]) for k in self._list_states}
        return new_tensors, appends

    def _pure_compute(self, tensor_state: StateDict, list_state: Dict[str, tuple]) -> Any:
        shadow: StateDict = dict(tensor_state)
        for k, v in list_state.items():
            shadow[k] = list(v)
        old = self.__dict__["_state"]
        object.__setattr__(self, "_state", shadow)
        try:
            return self._compute_impl()
        finally:
            object.__setattr__(self, "_state", old)

    def _merge_tensor_states(self, global_state: StateDict, batch_state: StateDict, n_prev: Any) -> StateDict:
        """Merge a batch-local state into the running global state.

        Parity: reference ``Metric._reduce_states`` (``metric.py:393-425``).
        """
        merged = {}
        for name, batch in batch_state.items():
            red = self._reductions[name]
            glob = global_state[name]
            if red == Reduction.SUM:
                merged[name] = glob + batch
            elif red == Reduction.MEAN:
                n = jnp.asarray(n_prev, dtype=jnp.float32)
                merged[name] = jnp.where(n == 0, batch, (glob * n + batch) / (n + 1.0))
            elif red == Reduction.MAX:
                merged[name] = jnp.maximum(glob, batch)
            elif red == Reduction.MIN:
                merged[name] = jnp.minimum(glob, batch)
            elif callable(red) and getattr(red, "mergeable", False):
                # sketch reductions (reservoir/t-digest): the tag IS the
                # n-way merge over a leading stack axis
                merged[name] = red(jnp.stack([glob, batch]))
            else:  # NONE / custom: forward fast path keeps the batch value;
                # metrics whose update reads global state set full_state_update=True
                merged[name] = batch
        return merged

    # -- public pure-functional API (for shard_map / pjit users) ---------
    def init_state(self) -> StateDict:
        """Default state pytree (list states as empty tuples). Pure."""
        out: StateDict = {}
        for k, v in self._defaults.items():
            out[k] = () if k in self._list_states else v
        return out

    def update_state(self, state: StateDict, *args: Any, **kwargs: Any) -> StateDict:
        """Pure update: returns the new state pytree; jit/shard_map-safe."""
        tensors = {k: v for k, v in state.items() if k not in self._list_states}
        new_tensors, appends = self._pure_update(tensors, args, kwargs)
        out = dict(new_tensors)
        for k in self._list_states:
            prev = state.get(k, ())
            if isinstance(prev, CatBuffer):
                prev = (prev.materialize(),) if len(prev) else ()
            out[k] = tuple(prev) + appends[k]
        return out

    def update_state_batched(
        self, state: StateDict, *args: Any, update_count: Any = 0, **kwargs: Any
    ) -> StateDict:
        """Bulk update over a leading steps axis: ``args`` are (S, ...) stacks.

        TPU-native alternative to a sequential ``lax.scan`` over updates:
        per-step batch states are computed in parallel with ``vmap`` and
        merged by reduction tag (updates are independent; merging is
        associative). Not available for metrics with ``None``/custom
        reductions whose update reads prior state (e.g. Pearson) — use
        ``update_state`` in a scan for those.

        ``update_count`` is the number of updates already folded into
        ``state``; MEAN states merge the new steps with the prior value
        weighted by it (the closed form of S sequential
        ``_merge_tensor_states`` applications). With the default of 0 the
        prior MEAN value is ignored, matching a fresh state.
        """
        for red in self._reductions.values():
            if red == Reduction.NONE or (
                callable(red) and not isinstance(red, Reduction) and not getattr(red, "mergeable", False)
            ):
                raise TorchMetricsUserError(
                    f"{type(self).__name__} has a custom/None reduction state; "
                    "update_state_batched requires associative (sum/mean/max/min/cat/sketch) reductions."
                )

        def one_step(step_args, step_kwargs):
            return self._pure_update(
                {k: v for k, v in self._defaults.items() if k not in self._list_states},
                step_args,
                step_kwargs,
            )

        new_tensors, appends = jax.vmap(one_step)(args, kwargs)
        out: StateDict = {}
        for name in self._defaults:
            red = self._reductions[name]
            if name in self._list_states:
                stacked = appends[name]  # tuple of (S, B, ...) arrays
                flat = [v.reshape((-1,) + v.shape[2:]) for v in stacked]
                out[name] = tuple(state.get(name, ())) + tuple(flat)
                continue
            v = new_tensors[name]  # (S, ...)
            if red == Reduction.SUM:
                out[name] = state[name] + jnp.sum(v, axis=0)
            elif red == Reduction.MEAN:
                # weighted merge with the prior state: with n prior updates
                # the running mean becomes (prior * n + sum(steps)) / (n + S)
                n = jnp.asarray(update_count, dtype=jnp.float32)
                steps = jnp.asarray(v.shape[0], dtype=jnp.float32)
                total = jnp.sum(v, axis=0)
                out[name] = jnp.where(
                    n == 0, total / steps, (state[name] * n + total) / (n + steps)
                )
            elif red == Reduction.MAX:
                out[name] = jnp.maximum(state[name], jnp.max(v, axis=0))
            elif red == Reduction.MIN:
                out[name] = jnp.minimum(state[name], jnp.min(v, axis=0))
            elif callable(red):  # mergeable sketch: n-way merge with prior state
                out[name] = red(jnp.concatenate([state[name][None], v], axis=0))
        return out

    def compute_state(self, state: StateDict) -> Any:
        """Pure compute over an explicit state pytree."""
        tensors = {k: v for k, v in state.items() if k not in self._list_states}
        lists = {}
        for k in self._list_states:
            v = state.get(k, ())
            if isinstance(v, CatBuffer):
                lists[k] = (v.materialize(),) if len(v) else ()
            else:
                lists[k] = tuple(v)
        return _squeeze_if_scalar(self._pure_compute(tensors, lists))

    def reduce_state(
        self, state: StateDict, axis_name: str, policy: Optional[SyncPolicy] = None
    ) -> StateDict:
        """In-graph cross-device sync over a mesh axis (psum/pmax/.../gather).

        ``policy`` (or the metric's ``sync_policy`` ctor kwarg) selects the
        wire strategy; ``None`` falls back to the exact process default.
        """
        return reduce_state_in_graph(
            state, self._reductions, axis_name, policy or self._sync_policy
        )

    def merge_states(self, states: Sequence[StateDict]) -> StateDict:
        """Eagerly merge per-rank state pytrees (host-side DDP emulation)."""
        out: StateDict = {}
        for name in self._defaults:
            red = self._reductions[name]
            vals = [s[name] for s in states]
            if name in self._list_states:
                merged_list: list = []
                for v in vals:
                    if isinstance(v, CatBuffer):
                        if len(v):
                            merged_list.append(v.materialize())
                    else:
                        merged_list.extend(list(v))
                out[name] = tuple(merged_list)
                continue
            if red == Reduction.CAT:
                # per-rank sample counts may differ (reference pad-to-max
                # gather protocol) — concatenate without equal-shape stacking
                out[name] = jnp.concatenate([jnp.asarray(v) for v in vals], axis=0)
                continue
            stack = jnp.stack([jnp.asarray(v) for v in vals])
            if red == Reduction.SUM:
                out[name] = jnp.sum(stack, axis=0)
            elif red == Reduction.MEAN:
                out[name] = jnp.mean(stack, axis=0)
            elif red == Reduction.MAX:
                out[name] = jnp.max(stack, axis=0)
            elif red == Reduction.MIN:
                out[name] = jnp.min(stack, axis=0)
            elif callable(red):
                out[name] = red(stack)
            else:
                out[name] = stack
        return out

    # ------------------------------------------------------------------
    # eager state plumbing — every read/write goes through ONE MetricState
    # ------------------------------------------------------------------
    def _state_view(self) -> MetricState:
        """The live :class:`MetricState`, without flushing staged updates.

        Grouped collections and legacy pickles occasionally install a plain
        dict as ``_state``; the view re-wraps it in place with this metric's
        reduction/layout metadata so downstream layers (streaming, sync,
        multitenant) always observe an explicit MetricState."""
        st = self.__dict__["_state"]
        if not isinstance(st, MetricState):
            st = MetricState(
                st,
                reductions=self._reductions,
                list_states=self._list_states,
                sharded_states=self._sharded_state_names(),
            )
            object.__setattr__(self, "_state", st)
        return st

    def _sharded_state_names(self) -> frozenset:
        return frozenset(n for n in self._list_states if self._uses_sharded(n))

    def _install_state(self, mapping: Mapping) -> None:
        """Replace ``_state`` with a fresh MetricState over ``mapping``."""
        object.__setattr__(
            self,
            "_state",
            MetricState(
                mapping,
                reductions=self._reductions,
                list_states=self._list_states,
                sharded_states=self._sharded_state_names(),
            ),
        )

    def as_state(self) -> MetricState:
        """Current state as an explicit :class:`MetricState` pytree.

        Flushes staged streaming updates first, then returns the live state
        (leaves are shared, not copied). The returned object is a registered
        pytree: it can be passed to ``jit``/``vmap``/``shard_map`` directly
        and to :func:`~torchmetrics_tpu.parallel.sync.reduce_state_in_graph`
        without a separate reductions mapping."""
        self._flush_pending()
        return self._state_view()

    def load_state(self, state: Mapping) -> None:
        """Install leaf values from a mapping / MetricState (shared leaves)."""
        self._flush_pending()
        view = self._state_view()
        for name, v in state.items():
            if name not in self._defaults:
                raise KeyError(f"Unexpected state {name!r} for {type(self).__name__}")
            view[name] = v
        self._computed = None

    def _tensor_state(self) -> StateDict:
        return {k: v for k, v in self._state.items() if k not in self._list_states}

    def _snapshot_state(self) -> StateDict:
        out: StateDict = {}
        for k, v in self._state.items():
            if isinstance(v, CatBuffer):
                out[k] = v.snapshot()  # O(1) copy-on-write alias
            else:
                out[k] = list(v) if k in self._list_states else v
        return out

    def _restore_defaults(self) -> None:
        for name, default in self._defaults.items():
            self._state[name] = [] if name in self._list_states else default

    # -- cat-state layout (padded CatBuffer vs legacy list) --------------
    def _uses_padded(self, name: str) -> bool:
        return (
            self._list_layout == "padded"
            and not self.compute_on_cpu
            and name not in self._layout_fallback
            and self._reductions.get(name) == Reduction.CAT
        )

    def _uses_sharded(self, name: str) -> bool:
        return self._cat_layout == "sharded" and self._uses_padded(name)

    def _new_cat_buffer(self, name: str, increments: Any, single: bool) -> CatBuffer:
        """Allocate the layout-appropriate buffer for one cat state; sharded
        buffers carry the owning ``Metric.state`` name so a refused densify
        can say which metric to re-wire (utils/data.py)."""
        if self._uses_sharded(name):
            owner = f"{type(self).__name__}.{name}"
            if single:
                return ShardedCatBuffer.allocate(increments, owner=owner)
            return ShardedCatBuffer.from_increments(increments, owner=owner)
        return CatBuffer.allocate(increments) if single else CatBuffer.from_increments(increments)

    def _record_cat_meta(self, name: str, inc: Any) -> None:
        arr = inc if isinstance(inc, (jax.Array, np.ndarray)) else jnp.asarray(inc)
        self._cat_meta[name] = (np.dtype(arr.dtype), arr.shape[1:] if arr.ndim else ())

    def _degrade_cat_state(self, name: str) -> list:
        """Fall back to the list layout for one state (ragged increments)."""
        self._layout_fallback.add(name)
        value = self._state[name]
        if isinstance(value, CatBuffer):
            self._state[name] = [value.materialize()] if len(value) else []
        return self._state[name]

    def _append_cat_increment(self, name: str, inc: Any) -> None:
        self._record_cat_meta(name, inc)
        target = self._state[name]
        if self._uses_padded(name):
            try:
                if isinstance(target, CatBuffer):
                    target.append(inc)
                    return
                if isinstance(target, list):
                    # lazy: the empty state stays a plain [] until the first
                    # append; loaded legacy increments fold in on the fly
                    if target:
                        buf = self._new_cat_buffer(name, target, single=False)
                        buf.append(inc)
                    else:
                        buf = self._new_cat_buffer(name, inc, single=True)
                    self._state[name] = buf
                    return
            except CatLayoutError:
                target = self._degrade_cat_state(name)
        target.append(np.asarray(inc) if self.compute_on_cpu else inc)

    def _extend_list_states(self, appends: Dict[str, tuple]) -> None:
        for k, vs in appends.items():
            for v in vs:
                self._append_cat_increment(k, v)

    def _adopt_padded_lists(self) -> None:
        """Fold increments an eager (non-jit) update body appended onto a
        plain list into the padded buffer. Under the padded layout any
        non-empty plain list consists entirely of raw increments (earlier
        appends already live in a CatBuffer), so whole-list conversion is
        exact; ragged increments degrade the state to the list layout."""
        for k in self._list_states:
            v = self._state[k]
            if isinstance(v, list) and v and self._uses_padded(k):
                self._record_cat_meta(k, v[-1])
                try:
                    self._state[k] = self._new_cat_buffer(k, v, single=False)
                except CatLayoutError:
                    self._layout_fallback.add(k)

    def _extend_list_states_stacked(self, appends: Dict[str, tuple], valid: int) -> None:
        """Extend list states from scanned ``(K, ...)`` append stacks.

        The streaming flush scan stacks each per-step increment along a
        leading steps axis; rows at or past ``valid`` are padding garbage.
        Under the padded layout the whole window lands in the CatBuffer as
        ONE fused device write (step-major row order, bitwise-identical to
        per-step appends); the list layout keeps per-step increments.
        """
        for k, arrs in appends.items():
            if not arrs or valid == 0:
                continue
            if self._uses_padded(k):
                trailings = {a.shape[2:] if a.ndim >= 2 else () for a in arrs}
                if len(trailings) == 1:
                    trailing = next(iter(trailings))
                    cols = [a[:valid, None] if a.ndim == 1 else a[:valid] for a in arrs]
                    flat = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=1)
                    self._append_cat_increment(k, flat.reshape((-1,) + trailing))
                    continue
            for i in range(valid):
                for a in arrs:
                    self._append_cat_increment(k, a[i])

    def _to_array(self, value: Any) -> Any:
        if isinstance(value, (np.ndarray, list, float, int, bool)) and not isinstance(value, (str,)):
            try:
                return jnp.asarray(value)
            except (TypeError, ValueError):
                return value
        try:  # torch tensors (CPU) — accept transparently for drop-in parity
            import torch

            if isinstance(value, torch.Tensor):
                return jnp.asarray(value.detach().cpu().numpy())
        except ImportError:
            pass
        return value

    def _eager_validate(self, *args: Any, **kwargs: Any) -> None:
        """Hook: subclasses may override for host-side value validation."""

    # ------------------------------------------------------------------
    # executable cache plumbing
    # ------------------------------------------------------------------
    def _invalidate_executable_key(self) -> None:
        self.__dict__.pop("_exec_key_cache", None)
        self.__dict__.pop("_jit_bound", None)

    def _executable_cache_key(self) -> tuple:
        """Process-global cache key: equal keys guarantee equal traced programs.

        Built from (class, frozen non-runtime config attributes, frozen state
        defaults + reduction tags). Instances whose config cannot be frozen
        (huge array attrs, Metric-valued attrs, exotic objects) fall back to a
        private per-instance key from a monotonic counter — never ``id(self)``
        (ids are reused after gc) and never the instance itself
        (``Metric.__eq__`` is overloaded to build compositions).
        """
        cached = self.__dict__.get("_exec_key_cache")
        if cached is not None:
            return cached
        runtime = _runtime_attrs_for(type(self))
        try:
            cfg = tuple(
                (k, _freeze_config_value(v))
                for k, v in sorted(self.__dict__.items())
                if k not in runtime
            )
            defaults = []
            for k in sorted(self._defaults):
                v = self._defaults[k]
                frozen = "list" if isinstance(v, list) else _freeze_config_value(v)
                defaults.append((k, frozen, str(self._reductions[k])))
            key: tuple = ("cfg", type(self), cfg, tuple(defaults))
        except (_Unkeyable, TypeError, ValueError):
            nonce = self.__dict__.get("_exec_nonce")
            if nonce is None:
                nonce = next(_INSTANCE_KEY_COUNTER)
                object.__setattr__(self, "_exec_nonce", nonce)
            key = ("instance", type(self), nonce)
        object.__setattr__(self, "_exec_key_cache", key)
        return key

    def _get_jitted(self, key: str, fn: Callable, donate_state: bool = False) -> Callable:
        bound = self.__dict__.get("_jit_bound")
        if bound is None:
            bound = {}
            object.__setattr__(self, "_jit_bound", bound)
        entry = bound.get(key)
        if entry is None:
            entry = _global_jit((key, self._executable_cache_key()), fn, donate_state)
            bound[key] = entry
        return entry

    def _donation_safe_tensor_state(self) -> StateDict:
        """Tensor states safe to pass to a ``donate_argnums`` jit call.

        Leaves that alias ``_defaults`` (first update after reset) or repeat
        within the dict are copied first: donating them would delete the
        buffer ``reset()`` re-installs, or double-donate one buffer.
        """
        out: StateDict = {}
        seen: set = set()
        for k, v in self._state.items():
            if k in self._list_states:
                continue
            if isinstance(v, jax.Array):
                if v is self._defaults.get(k) or id(v) in seen:
                    v = jnp.array(v, copy=True)
                seen.add(id(v))
            out[k] = v
        return out

    # ------------------------------------------------------------------
    # sync protocol (eager, class API)
    # ------------------------------------------------------------------
    @property
    def sync_backend(self) -> SyncBackend:
        if self._sync_backend is None:
            self._sync_backend = default_sync_backend()
        return self._sync_backend

    def sync(
        self,
        should_sync: bool = True,
        sync_backend: Optional[SyncBackend] = None,
    ) -> None:
        """Replace local states with group-reduced states (cache local).

        Parity: reference ``metric.py:490-532``. List states are
        pre-concatenated to one tensor so one gather happens per state
        (reference ``metric.py:430-433``). Fixed-shape states with an
        elementwise reduction (sum/mean/max/min) are additionally *bucketed*:
        all leaves sharing a ``(Reduction, dtype)`` pair are flattened into
        one buffer and synced with a single ``sync_tensor`` call — one
        latency-bound small-message collective per bucket instead of one per
        state name. ``cat``/``NONE``/custom-reduction states stay per-leaf.
        """
        self._flush_pending()
        if self._is_synced:
            raise TorchMetricsUserError("The Metric has already been synced.")
        backend = sync_backend or self.sync_backend
        if not should_sync or not backend.is_available():
            return
        self._cache = self._snapshot_state()
        # gather into a scratch dict and swap atomically: a failed gather
        # (e.g. HostSync TimeoutError on a stalled peer) must leave local
        # state intact — a half-synced state dict would be checkpointed or
        # double-counted by the recovery path
        _sp = (
            _spans.start_span(
                "metric.sync", metric=type(self).__name__, world=backend.world_size()
            )
            if _spans.ENABLED
            else None
        )
        try:
            begin_sync()
            # elastic membership round: the contribution probe settles who is
            # present BEFORE any state bytes move, every gather below is
            # retried/degraded per SyncPolicy, and end_round() records the
            # coverage fraction (raising CoverageError below min_coverage)
            elastic = hasattr(backend, "begin_round")
            if elastic:
                backend.begin_round(
                    contrib=int(self._update_count), policy=self._sync_policy
                )
            synced = self._gather_synced(backend)
            if elastic:
                backend.end_round()
        except Exception:
            self._cache = None
            raise
        finally:
            if _sp is not None:
                _sp.end()
        self._state.update(synced)
        self._is_synced = True

    def _quantized_bucket_sync(
        self, backend: SyncBackend, names: List[str], flat: Array, red, policy: SyncPolicy
    ) -> Array:
        """Eager quantized all-reduce of one float SUM/MEAN bucket.

        int8/int16 payload + per-chunk scales travel instead of the
        full-precision buffer; each rank's shard is dequantized and summed
        host-side. Error feedback: the local quantization residual is keyed
        by the bucket's name tuple in ``_sync_residuals`` and folded into the
        next sync of the same bucket.
        """
        bits = policy.quantize_bits or 8
        key = tuple(names)
        residual = self._sync_residuals.get(key)
        x = flat if residual is None or residual.size != flat.size else flat + residual
        q, scales, pad = quantize_chunks(x, bits, policy.quantize_chunk)
        dq = dequantize_chunks(q, scales, flat.dtype)
        self._sync_residuals[key] = (jnp.pad(x, (0, pad)) - dq)[: flat.size]
        record_collective(
            "eager_gather",
            q.size * q.dtype.itemsize + scales.size * scales.dtype.itemsize,
            backend.world_size(),
            dtype=q.dtype,
        )
        gq = backend.sync_tensor(q, Reduction.NONE)  # (world, Q)
        gs = backend.sync_tensor(scales, Reduction.NONE)  # (world, C)
        total = sum(
            dequantize_chunks(gq[r], gs[r], flat.dtype) for r in range(gq.shape[0])
        )[: flat.size]
        if red == Reduction.MEAN:
            total = total / gq.shape[0]
        return total

    def _gather_synced(self, backend: SyncBackend, skip: frozenset = frozenset()) -> Dict[str, Any]:
        """Gather every state (except ``skip``) into a scratch dict.

        List states are pre-concatenated to one tensor so one gather happens
        per state (reference ``metric.py:430-433``); fixed-shape elementwise
        states are bucketed by ``(Reduction, dtype)``. Used by :meth:`sync`
        and by the overlapped-flush barrier (``streaming.py``), which passes
        the cat states it already gathered incrementally as ``skip``.
        """
        policy = self._sync_policy or default_policy()
        synced: Dict[str, Any] = {}
        addressed = hasattr(backend, "set_current")  # FakeSync group addressing
        buckets: Dict[Tuple[Any, str], List[str]] = {}
        for name in self._state:
            if name in skip:
                continue
            red = self._reductions[name]
            if name in self._list_states and red == Reduction.NONE:
                # ragged object list states (dist_reduce_fx=None: per-image
                # arrays, COCO RLE dicts) — gather whole per-rank lists and
                # extend in rank order, preserving element boundaries
                # (reference detection/mean_ap.py:1007-1032 all_gather_object)
                if addressed:
                    backend.set_current(name)
                gathered = backend.all_gather_object(list(self._state[name]))
                merged: list = []
                for rank_list in gathered:
                    merged.extend(rank_list)
                synced[name] = merged
            elif name not in self._list_states and isinstance(red, Reduction) and red in ELEMENTWISE_REDUCTIONS:
                arr = jnp.asarray(self._state[name])
                buckets.setdefault((red, str(arr.dtype)), []).append(name)
            elif (
                red == Reduction.CAT
                and name in self._list_states
                and self._uses_padded(name)
                and hasattr(backend, "sync_cat_padded")
            ):
                # padded gather contract: ship the power-of-two buffer plus
                # the valid count; the backend masks each shard's invalid
                # tail. The branch is layout-config-driven (not value-driven)
                # so every rank issues the same collective sequence even when
                # some ranks saw no updates.
                if addressed:
                    backend.set_current(name)
                value = self._state[name]
                if isinstance(value, ShardedCatBuffer):
                    # the DCN wire is layout-independent (a host gather
                    # materializes bytes either way); the gathered rows are
                    # immediately re-sharded so residency stays distributed
                    # through compute and the next round's appends
                    wire, cnt = value.padded_wire()
                    gathered = backend.sync_cat_padded(wire, cnt)
                    synced[name] = ShardedCatBuffer.from_rows(
                        gathered, mesh=value.mesh, owner=value.owner
                    )
                elif isinstance(value, CatBuffer):
                    synced[name] = backend.sync_cat_padded(value.buffer, value.count)
                else:
                    probe = self._precat(name)
                    synced[name] = backend.sync_cat_padded(probe, probe.shape[0])
            else:
                if addressed:
                    backend.set_current(name)
                synced[name] = backend.sync_tensor(self._precat(name), red)
        for (red, _dtype), names in buckets.items():
            arrs = [jnp.asarray(self._state[n]) for n in names]
            flat = arrs[0] if len(arrs) == 1 else jnp.concatenate([a.reshape(-1) for a in arrs])
            # opt-in quantized wire format for float SUM/MEAN buckets above
            # the size threshold; addressed (state-reading) test backends
            # can't transport an ad-hoc payload, so they stay full-precision.
            # (unlike the in-graph path this needs no all_gather version gate
            # — the payload travels as a plain NONE-gather of int8/int16)
            if (
                not addressed
                and not policy.exact
                and policy.quantize_bits is not None
                and red in (Reduction.SUM, Reduction.MEAN)
                and flat.size >= policy.quantize_threshold
                and jnp.issubdtype(jnp.asarray(flat).dtype, jnp.floating)
            ):
                reduced = self._quantized_bucket_sync(
                    backend, names, flat.reshape(-1), red, policy
                )
                offset = 0
                for n, a in zip(names, arrs):
                    synced[n] = reduced[offset : offset + a.size].reshape(a.shape)
                    offset += a.size
                continue
            if len(arrs) == 1:
                if addressed:
                    backend.set_current(names[0])
                synced[names[0]] = backend.sync_tensor(arrs[0], red)
                continue
            if addressed:
                backend.set_current(tuple(names))
            reduced = backend.sync_tensor(flat, red)
            offset = 0
            for n, a in zip(names, arrs):
                synced[n] = reduced[offset : offset + a.size].reshape(a.shape)
                offset += a.size
        return synced

    def _precat(self, name: str) -> Array:
        value = self._state[name]
        if name in self._list_states:
            if isinstance(value, CatBuffer):
                return value.materialize()
            return dim_zero_cat(value) if value else self._empty_cat(name)
        return jnp.asarray(value)

    def _empty_cat(self, name: str) -> Array:
        """0-length concat of an empty cat state in its declared/learned
        element dtype — NOT the metric-wide float ``_dtype`` (which silently
        floated integer states like retrieval indexes after reset+compute)."""
        meta = self._cat_meta.get(name)
        dtype = meta[0] if meta is not None and meta[0] is not None else self._dtype
        trailing = meta[1] if meta is not None and meta[1] is not None else ()
        return jnp.zeros((0,) + tuple(trailing), dtype=dtype)

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore cached local states. Parity: reference ``metric.py:534-553``."""
        if not should_unsync or not self._is_synced:
            return
        if self._cache is None:
            raise TorchMetricsUserError("The Metric has no cache to restore from.")
        self._install_state(self._cache)
        self._cache = None
        self._is_synced = False

    @contextmanager
    def sync_context(self, should_sync: bool = True, should_unsync: bool = True):
        """Parity: reference ``metric.py:556-591``."""
        was_synced = self._is_synced
        if not was_synced:
            self.sync(should_sync=should_sync)
        try:
            yield
        finally:
            if not was_synced:
                self.unsync(should_unsync=should_unsync)

    @property
    def _to_sync(self) -> bool:
        return self.sync_on_compute

    # ------------------------------------------------------------------
    # introspection / serialization
    # ------------------------------------------------------------------
    @property
    def metric_state(self) -> StateDict:
        """Current state values. Parity: reference ``metric.py`` property."""
        self._flush_pending()
        return {k: self._state[k] for k in self._defaults}

    @property
    def update_count(self) -> int:
        return self._update_count

    @property
    def coverage(self):
        """Coverage record of this metric's last elastic sync round
        (``parallel.elastic.Coverage``), or ``None`` when the backend is not
        elastic or no round has settled. A fraction below 1.0 marks the
        current computed value as a partial result over the surviving
        membership."""
        return getattr(self._sync_backend, "last_coverage", None)

    @property
    def device(self):
        for v in self._state.values():
            if isinstance(v, jax.Array):
                return list(v.devices())[0]
        return jax.devices()[0]

    def to_device(self, device) -> "Metric":
        self._flush_pending()
        for k, v in self._state.items():
            if isinstance(v, CatBuffer):
                self._state[k] = v.to_device(device)
            elif k in self._list_states:
                self._state[k] = [jax.device_put(e, device) for e in v]
            else:
                self._state[k] = jax.device_put(v, device)
        self._defaults = {
            k: (v if isinstance(v, list) else jax.device_put(v, device)) for k, v in self._defaults.items()
        }
        return self

    def set_dtype(self, dtype) -> "Metric":
        """Cast float states. Parity: reference ``set_dtype`` ``metric.py:770``."""
        self._flush_pending()
        self._dtype = dtype
        for k, v in self._state.items():
            if isinstance(v, CatBuffer):
                if jnp.issubdtype(v.dtype, jnp.floating):
                    self._state[k] = v.astype(dtype)
            elif k in self._list_states:
                self._state[k] = [
                    e.astype(dtype) if jnp.issubdtype(e.dtype, jnp.floating) else e for e in v
                ]
            elif isinstance(v, jax.Array) and jnp.issubdtype(v.dtype, jnp.floating):
                self._state[k] = v.astype(dtype)
        for k, meta in list(self._cat_meta.items()):
            if meta[0] is not None and np.issubdtype(meta[0], np.floating):
                self._cat_meta[k] = (np.dtype(dtype), meta[1])
        self._invalidate_executable_key()
        return self

    def persistent(self, mode: bool = False) -> None:
        for name in self._persistent:
            self._persistent[name] = mode

    def state_dict(self) -> Dict[str, Any]:
        """Persistent states as numpy arrays. Parity: ``metric.py:834-871``."""
        self._flush_pending()
        out: Dict[str, Any] = {}
        for name, keep in self._persistent.items():
            if not keep:
                continue
            v = self._state[name]
            if isinstance(v, CatBuffer):
                # increment boundaries are already gone in the buffer; one
                # concat-equal entry round-trips through load_state_dict
                out[name] = [np.asarray(v.materialize())] if len(v) else []
            elif name in self._list_states:
                out[name] = [np.asarray(e) for e in v]
            else:
                out[name] = np.asarray(v)
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        for name, v in state_dict.items():
            if name not in self._defaults:
                if strict:
                    raise KeyError(f"Unexpected state {name!r} for {type(self).__name__}")
                continue
            if name in self._list_states:
                self._state[name] = [jnp.asarray(e) for e in v]
            else:
                self._state[name] = jnp.asarray(v)
        # restored increments fold back into the padded layout
        self._adopt_padded_lists()

    def clone(self) -> "Metric":
        return copy.deepcopy(self)

    def __getstate__(self) -> Dict[str, Any]:
        self._flush_pending()
        state = self.__dict__.copy()
        # staged streaming buffers hold jitted closures and a back-reference
        # to this metric; they are flushed above and never travel
        state.pop("_stream_buffer", None)
        # bound jitted entries hold unpicklable closures; the per-instance
        # nonce must not leak across processes (a fresh process hands the
        # same counter values to different configs). Clones/unpickles with a
        # keyable config recompute the same key and still share executables.
        state.pop("_jit_bound", None)
        state.pop("_exec_key_cache", None)
        state.pop("_exec_nonce", None)
        # hashlib digests are unpicklable; the cache rebuilds on demand
        state.pop("_hash_digests", None)
        state["_sync_backend"] = None if not isinstance(state.get("_sync_backend"), NoSync) else state["_sync_backend"]
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        object.__setattr__(self, "_state", state.pop("_state"))
        object.__setattr__(self, "_defaults", state.pop("_defaults"))
        for k, v in state.items():
            object.__setattr__(self, k, v)
        # attrs absent from pre-padded-layout pickles (and the popped digests)
        for attr, factory in (
            ("_hash_digests", dict),
            ("_cat_meta", dict),
            ("_layout_fallback", set),
            ("_list_layout", lambda: "padded"),
            ("_cat_layout", lambda: "replicated"),
        ):
            if attr not in self.__dict__:
                object.__setattr__(self, attr, factory())
        # legacy pickles carry a plain state dict — normalize to MetricState
        self._state_view()

    def _cat_state_digest(self, name: str, value: Any) -> bytes:
        """Incremental digest of a cat state's content.

        The hasher is keyed by state-object identity and the covered element
        count: appends only ever extend a list/CatBuffer in place, so
        re-hashing feeds just the new suffix; reset/sync/unsync replace the
        state object, which invalidates the cache automatically.
        """
        rec = self._hash_digests.get(name)
        n = len(value)
        if (
            rec is None
            or rec[0] is not value
            or rec[1] > n
            # sharded buffers append per shard: the global shard-major prefix
            # is NOT append-stable, so growth rehashes from row 0
            or (rec[1] < n and isinstance(value, ShardedCatBuffer))
        ):
            rec = [value, 0, hashlib.blake2b(digest_size=16)]
            self._hash_digests[name] = rec
        if rec[1] < n:
            if isinstance(value, CatBuffer):
                chunk = np.asarray(value.rows(rec[1], n)).tobytes()
                rec[2].update(chunk)
                _HASH_STATS["bytes_hashed"] += len(chunk)
            else:
                for e in list(value)[rec[1] : n]:
                    b = np.asarray(e).tobytes()
                    rec[2].update(b)
                    _HASH_STATS["bytes_hashed"] += len(b)
            rec[1] = n
        return rec[2].digest()

    def __hash__(self) -> int:
        self._flush_pending()
        vals = []
        for k in sorted(self._defaults):
            v = self._state[k]
            if k in self._list_states and isinstance(v, (list, tuple, CatBuffer)):
                vals.append(self._cat_state_digest(k, v))
            else:
                b = np.asarray(v).tobytes()
                _HASH_STATS["bytes_hashed"] += len(b)
                vals.append(b)
        return hash((type(self).__name__, tuple(vals)))

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def _defaults_signature(self) -> tuple:
        """Structural signature used by compute-group discovery."""
        items = []
        for k in sorted(self._defaults):
            v = self._defaults[k]
            if isinstance(v, list):
                items.append((k, "list", str(self._reductions[k])))
            else:
                items.append((k, v.shape, str(v.dtype), str(self._reductions[k])))
        return tuple(items)

    # ------------------------------------------------------------------
    # plotting (single/multi value), parity: reference metric.py:641-671
    # ------------------------------------------------------------------
    def plot(self, val: Any = None, ax: Any = None):
        from .utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name or type(self).__name__,
        )

    # ------------------------------------------------------------------
    # operator overloading → CompositionalMetric (reference metric.py:938-1073)
    # ------------------------------------------------------------------
    def __add__(self, other):  # noqa: D105
        return CompositionalMetric(jnp.add, self, other)

    def __radd__(self, other):
        return CompositionalMetric(jnp.add, other, self)

    def __sub__(self, other):
        return CompositionalMetric(jnp.subtract, self, other)

    def __rsub__(self, other):
        return CompositionalMetric(jnp.subtract, other, self)

    def __mul__(self, other):
        return CompositionalMetric(jnp.multiply, self, other)

    def __rmul__(self, other):
        return CompositionalMetric(jnp.multiply, other, self)

    def __truediv__(self, other):
        return CompositionalMetric(jnp.divide, self, other)

    def __rtruediv__(self, other):
        return CompositionalMetric(jnp.divide, other, self)

    def __floordiv__(self, other):
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __rfloordiv__(self, other):
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __mod__(self, other):
        return CompositionalMetric(jnp.mod, self, other)

    def __rmod__(self, other):
        return CompositionalMetric(jnp.mod, other, self)

    def __pow__(self, other):
        return CompositionalMetric(jnp.power, self, other)

    def __rpow__(self, other):
        return CompositionalMetric(jnp.power, other, self)

    def __matmul__(self, other):
        return CompositionalMetric(jnp.matmul, self, other)

    def __rmatmul__(self, other):
        return CompositionalMetric(jnp.matmul, other, self)

    def __and__(self, other):
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __rand__(self, other):
        return CompositionalMetric(jnp.bitwise_and, other, self)

    def __or__(self, other):
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __ror__(self, other):
        return CompositionalMetric(jnp.bitwise_or, other, self)

    def __xor__(self, other):
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __rxor__(self, other):
        return CompositionalMetric(jnp.bitwise_xor, other, self)

    def __eq__(self, other):
        return CompositionalMetric(jnp.equal, self, other)

    def __ne__(self, other):
        return CompositionalMetric(jnp.not_equal, self, other)

    def __lt__(self, other):
        return CompositionalMetric(jnp.less, self, other)

    def __le__(self, other):
        return CompositionalMetric(jnp.less_equal, self, other)

    def __gt__(self, other):
        return CompositionalMetric(jnp.greater, self, other)

    def __ge__(self, other):
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __neg__(self):
        return CompositionalMetric(jnp.negative, self, None)

    def __pos__(self):
        return CompositionalMetric(jnp.abs, self, None)

    def __abs__(self):
        return CompositionalMetric(jnp.abs, self, None)

    def __invert__(self):
        return CompositionalMetric(jnp.logical_not, self, None)

    def __getitem__(self, idx):
        return CompositionalMetric(lambda x: x[idx], self, None)


def _wrap_update(update_fn: Callable) -> Callable:
    @functools.wraps(update_fn)
    def wrapped(self: Metric, *args: Any, **kwargs: Any) -> None:
        if getattr(self, "_in_pure_update", False):
            # super().update() from inside a traced _pure_update: run the
            # raw body against the shadow state (re-entering jit would leak
            # tracers / recurse; bookkeeping already done by the outer call)
            update_fn(self, *args, **kwargs)
            return
        # an eager update interleaved with staged streaming updates must see
        # (and extend) the post-flush state, or step order would be lost
        self._flush_pending()
        self._computed = None
        self._update_count += 1
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric is currently synced; call `unsync()` before `update`."
            )
        _sp = (
            _spans.start_span("metric.update", metric=type(self).__name__)
            if _spans.ENABLED
            else None
        )
        try:
            args = tuple(self._to_array(a) for a in args)
            kwargs = {k: self._to_array(v) for k, v in kwargs.items()}
            self._eager_validate(*args, **kwargs)
            if self._use_jit and _jit_safe_inputs(args, kwargs):
                upd = self._get_jitted("update", self._pure_update, donate_state=True)
                new_tensors, appends = upd(self._donation_safe_tensor_state(), args, kwargs)
                for k, v in new_tensors.items():
                    self._state[k] = v
                self._extend_list_states(appends)
                if _sp is not None:
                    _sp.set_attr(jit=True).fence(new_tensors)
            else:
                update_fn(self, *args, **kwargs)
                if self.compute_on_cpu:
                    for k in self._list_states:
                        self._state[k] = [np.asarray(e) for e in self._state[k]]
                else:
                    self._adopt_padded_lists()
        finally:
            if _sp is not None:
                _sp.end()

    wrapped._tm_wrapped = True
    return wrapped


def _wrap_compute(compute_fn: Callable) -> Callable:
    @functools.wraps(compute_fn)
    def wrapped(self: Metric, *args: Any, **kwargs: Any) -> Any:
        self._flush_pending()
        if self._update_count == 0:
            rank_zero_warn(
                f"The ``compute`` method of metric {type(self).__name__} was called before the "
                "``update`` method; returned values may not reflect any data.",
                UserWarning,
            )
        if self.compute_with_cache and self._computed is not None:
            return self._computed
        _sp = (
            _spans.start_span("metric.compute", metric=type(self).__name__)
            if _spans.ENABLED
            else None
        )
        try:
            with self.sync_context(should_sync=self._to_sync):
                value = _squeeze_if_scalar(compute_fn(self, *args, **kwargs))
        finally:
            if _sp is not None:
                _sp.end()
        if self.compute_with_cache:
            self._computed = value
        return value

    wrapped._tm_wrapped = True
    return wrapped


class CompositionalMetric(Metric):
    """Lazy arithmetic composition of two metrics (or metric & scalar).

    Parity: reference ``metric.py:1088-1211`` — update/reset/persistent fan
    out to child metrics; sync is a no-op (children sync themselves inside
    their own compute).

    Example (built via operator overloading, not directly):
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanMetric, SumMetric
        >>> combined = SumMetric() + MeanMetric()
        >>> type(combined).__name__
        'CompositionalMetric'
        >>> combined.update(jnp.asarray([1.0, 2.0, 3.0]))
        >>> float(combined.compute())  # sum (6.0) + mean (2.0)
        8.0
    """

    jittable = False
    full_state_update = True

    def __init__(self, operator: Callable, metric_a: Any, metric_b: Any) -> None:
        super().__init__(jit=False)
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else self._to_array(metric_a)
        self.metric_b = metric_b if isinstance(metric_b, Metric) else (
            self._to_array(metric_b) if metric_b is not None else None
        )

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **_filter_kwargs(self.metric_a._update_impl, **kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **_filter_kwargs(self.metric_b._update_impl, **kwargs))

    def compute(self) -> Any:
        a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if b is None:
            return _squeeze_if_scalar(self.op(a))
        return _squeeze_if_scalar(self.op(a, b))

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        a = (
            self.metric_a.forward(*args, **_filter_kwargs(self.metric_a._update_impl, **kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        b = (
            self.metric_b.forward(*args, **_filter_kwargs(self.metric_b._update_impl, **kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        self._update_count += 1
        if a is None or (b is None and self.metric_b is not None):
            return None
        if b is None:
            return _squeeze_if_scalar(self.op(a))
        return _squeeze_if_scalar(self.op(a, b))

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()
        self._update_count = 0
        self._computed = None

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode)

    def sync(self, *args: Any, **kwargs: Any) -> None:  # children sync themselves
        self._is_synced = True

    def unsync(self, *args: Any, **kwargs: Any) -> None:
        self._is_synced = False

    def __repr__(self) -> str:
        _op = getattr(self.op, "__name__", str(self.op))
        return f"CompositionalMetric({_op}, {self.metric_a!r}, {self.metric_b!r})"
