"""Inline waiver parsing: ``# tpulint: disable=TPU002(reason text)``.

A waiver suppresses matching violations on its own line; placed on a ``def``
line (or the line directly above it) it covers the whole function. Reasons
are mandatory — a bare ``disable=TPU002`` is itself reported as TPU000 so
waivers stay auditable.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

from .corpus import ModuleInfo
from .rules import Violation

_WAIVER_LINE_RE = re.compile(r"#\s*tpulint:\s*disable=(.*)$")
_WAIVER_ITEM_RE = re.compile(r"(TPU\d{3})\s*(?:\(([^)]*)\))?")


@dataclass
class Waivers:
    # line -> {rule -> reason}
    by_line: Dict[int, Dict[str, str]] = field(default_factory=dict)
    # (start_line, end_line) function spans carrying waivers
    by_span: List[Tuple[int, int, Dict[str, str]]] = field(default_factory=list)
    malformed: List[Violation] = field(default_factory=list)
    used: Set[Tuple[int, str]] = field(default_factory=set)

    def lookup(self, line: int, rule: str) -> Tuple[bool, str]:
        rules = self.by_line.get(line)
        if rules and rule in rules:
            self.used.add((line, rule))
            return True, rules[rule]
        for start, end, span_rules in self.by_span:
            if start <= line <= end and rule in span_rules:
                self.used.add((start, rule))
                return True, span_rules[rule]
        return False, ""


def collect_waivers(mod: ModuleInfo) -> Waivers:
    w = Waivers()
    for idx, text in enumerate(mod.source_lines, start=1):
        m = _WAIVER_LINE_RE.search(text)
        if not m:
            continue
        rules: Dict[str, str] = {}
        for rule, reason in _WAIVER_ITEM_RE.findall(m.group(1)):
            reason = (reason or "").strip()
            if not reason:
                w.malformed.append(Violation(
                    "TPU000", mod.path, idx, text.index("#"),
                    f"waiver for {rule} is missing a reason: use `# tpulint: disable={rule}(why)`",
                    mod.name,
                ))
                continue
            rules[rule] = reason
        if rules:
            w.by_line[idx] = rules

    # promote def-line (or line-above-def) waivers to whole-function spans
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            for line in (node.lineno, node.lineno - 1):
                rules = w.by_line.get(line)
                if rules:
                    w.by_span.append((node.lineno, end, rules))
    return w


def apply_waivers(violations: List[Violation], waivers_by_path: Dict[str, Waivers]) -> None:
    for v in violations:
        w = waivers_by_path.get(v.path)
        if w is None:
            continue
        waived, reason = w.lookup(v.line, v.rule)
        if waived:
            v.waived = True
            v.waive_reason = reason
