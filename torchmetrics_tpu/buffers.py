"""Padded geometric cat-state buffers.

List/``cat`` states historically stored one device array per ``update`` and
re-concatenated the whole list at compute/sync time — every jitted consumer
specialized on the running total length (O(n) retraces across an n-step run)
and every observation copied O(n) elements. ``CatBuffer`` replaces the list
with a ``(buffer, count)`` pair: ``buffer`` has power-of-two row capacity
(doubling on overflow, so only O(log n) distinct shapes ever exist) and
appends are in-place ``lax.dynamic_update_slice`` writes into a donated
buffer — O(1) amortized. The valid prefix is ``buffer[:count]``; rows at or
past ``count`` are garbage and must be masked by every reader.

Append/grow kernels go through the process-global executable cache
(``metric._global_jit``), so the number of cat-path executables for an
n-append run is O(log n) (one per capacity) and steady-state appends are
pure cache hits. ``count`` rides into the kernels as a weak-typed ``int32``
scalar, so it never causes a retrace.

Snapshots are copy-on-write: ``snapshot()`` aliases the device buffer and
marks both sides unowned; the next append first copies, so a cached snapshot
(``Metric._cache``, forward full-state restore) is never clobbered by buffer
donation.
"""
from typing import Any, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

MIN_CAPACITY = 8


class CatLayoutError(TypeError):
    """An increment is incompatible with the padded buffer's row layout.

    Raised when the trailing (non-concatenated) dimensions of an increment
    differ from the buffer's; the owning metric degrades that state to the
    list layout, which tolerates ragged increments until concat time.
    """


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length() if n > 1 else 1


def _capacity_for(rows: int) -> int:
    return max(_next_pow2(rows), MIN_CAPACITY)


# public aliases: the pow2 shape-stability trick is shared infrastructure —
# tenant slots (multitenant.py) pad to the same geometric capacities as cat
# rows, so churn within capacity never changes a traced shape
next_pow2 = _next_pow2
capacity_for = _capacity_for


def _row_form(inc: Any) -> Array:
    """Increment as (rows,) + trailing — scalars become a single row,
    matching ``dim_zero_cat``'s ``atleast_1d`` semantics."""
    arr = inc if isinstance(inc, jax.Array) else jnp.asarray(inc)
    return arr[None] if arr.ndim == 0 else arr


def _jit(key: Any, fn: Any, donate: bool = False) -> Any:
    from .metric import _global_jit  # deferred: metric.py imports this module

    return _global_jit(key, fn, donate_state=donate)


def _append_kernel(buf: Array, inc: Array, count: Array) -> Tuple[Array, Array]:
    """(new_buf, new_count). ``count`` rides as a DEVICE scalar and the
    increment is folded in on-device, so a steady-state append issues zero
    host→device transfers (strict_mode transfer_guard clean)."""
    start = (count,) + (0,) * (buf.ndim - 1)
    return lax.dynamic_update_slice(buf, inc, start), count + inc.shape[0]


def _make_grow_append(new_capacity: int) -> Any:
    def grow_append(buf: Array, inc: Array, count: Array) -> Tuple[Array, Array]:
        pad = jnp.zeros((new_capacity - buf.shape[0],) + buf.shape[1:], buf.dtype)
        grown = jnp.concatenate([buf, pad], axis=0)
        return _append_kernel(grown, inc, count)

    return grow_append


class CatBuffer:
    """Growable padded cat state: ``(buffer, count)`` with pow2 capacity.

    Mutation rebinds ``buffer``/``count`` on the *same* object, so aliases
    held by compute groups and the incremental hash cache stay current.
    Equality compares the valid prefix (a list/tuple compares as its
    concatenation); hashing is by identity, as for lists.
    """

    __slots__ = ("buffer", "count", "_count_dev", "_owns")

    def __init__(self, buffer: Array, count: int, owns: bool = True) -> None:
        self.buffer = buffer
        self.count = int(count)
        # device mirror of `count`, fed to the append kernels so steady-state
        # appends never transfer a host scalar; created lazily on first append
        self._count_dev: Optional[Array] = None
        self._owns = owns

    # ------------------------------------------------------------- creation

    @classmethod
    def allocate(cls, first_inc: Any) -> "CatBuffer":
        inc = _row_form(first_inc)
        cap = _capacity_for(inc.shape[0])
        buf = cls(jnp.zeros((cap,) + inc.shape[1:], inc.dtype), 0)
        buf.append(inc)
        return buf

    @classmethod
    def from_increments(cls, increments: Sequence[Any]) -> "CatBuffer":
        rows = [_row_form(e) for e in increments]
        trailings = {r.shape[1:] for r in rows}
        if len(trailings) > 1:
            raise CatLayoutError(f"ragged increment trailing shapes {sorted(trailings)}")
        return cls.allocate(rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0))

    # ------------------------------------------------------------ properties

    @property
    def capacity(self) -> int:
        return self.buffer.shape[0]

    @property
    def dtype(self) -> Any:
        return self.buffer.dtype

    @property
    def trailing(self) -> Tuple[int, ...]:
        return self.buffer.shape[1:]

    # -------------------------------------------------------------- mutation

    def append(self, inc: Any) -> None:
        """In-place append of one increment (O(1) amortized device writes)."""
        inc = _row_form(inc)
        if inc.shape[1:] != self.trailing:
            raise CatLayoutError(
                f"increment trailing shape {inc.shape[1:]} != buffer trailing {self.trailing}"
            )
        if inc.dtype != self.dtype:
            promoted = jnp.promote_types(self.dtype, inc.dtype)
            if promoted != self.dtype:
                # rare dtype widening: eager cast of the whole buffer
                self.buffer = self.buffer.astype(promoted)
                self._owns = True
            if promoted != inc.dtype:
                inc = inc.astype(promoted)
        rows = inc.shape[0]
        if rows == 0:
            return
        needed = self.count + rows
        count = self._count_dev
        if count is None:
            count = jnp.asarray(self.count, jnp.int32)
        if needed > self.capacity:
            new_cap = _capacity_for(needed)
            # no donation: the old capacity can't back the larger output
            # buffer anyway, and XLA warns on unusable donations
            fn = _jit(
                ("catbuf_grow_append", self.capacity, new_cap, inc.shape, str(inc.dtype)),
                _make_grow_append(new_cap),
            )
            self.buffer, self._count_dev = fn(self.buffer, inc, count)
        else:
            if not self._owns:
                # copy-on-write: a snapshot aliases this buffer, so the
                # donating append must not clobber it
                self.buffer = jnp.array(self.buffer, copy=True)
            fn = _jit(
                ("catbuf_append", self.capacity, inc.shape, str(inc.dtype)),
                _append_kernel,
                donate=True,
            )
            self.buffer, self._count_dev = fn(self.buffer, inc, count)
        self._owns = True
        self.count = needed

    def extend(self, increments: Iterable[Any]) -> None:
        for inc in increments:
            self.append(inc)

    # --------------------------------------------------------------- reading

    def materialize(self) -> Array:
        """Masked valid slice ``buffer[:count]`` (never the raw buffer)."""
        return self.buffer[: self.count]

    def rows(self, start: int, stop: int) -> Array:
        """Rows ``[start, stop)`` of the valid region; ``stop`` is clamped to
        ``count`` so capacity padding never leaks into a sync payload."""
        return self.buffer[start : min(stop, self.count)]

    def snapshot(self) -> "CatBuffer":
        """Cheap O(1) copy sharing the device buffer; the next append on
        either side copies first (copy-on-write)."""
        self._owns = False
        out = CatBuffer(self.buffer, self.count, owns=False)
        out._count_dev = self._count_dev  # device scalars are immutable
        return out

    def astype(self, dtype: Any) -> "CatBuffer":
        return CatBuffer(self.buffer.astype(dtype), self.count)

    def to_device(self, device: Any) -> "CatBuffer":
        return CatBuffer(jax.device_put(self.buffer, device), self.count)

    # ------------------------------------------------------------- protocols

    def __len__(self) -> int:
        return self.count

    def __iter__(self) -> Iterator[Array]:
        for i in range(self.count):
            yield self.buffer[i]

    def __eq__(self, other: Any) -> Any:
        if other is self:
            return True
        if isinstance(other, CatBuffer):
            if self.count != other.count or self.trailing != other.trailing:
                return False
            if self.count == 0:
                return True
            return bool(jnp.all(self.materialize() == other.materialize()))
        if isinstance(other, (list, tuple)):
            if len(other) == 0:
                return self.count == 0
            try:
                cat = jnp.concatenate([_row_form(e) for e in other], axis=0)
            except Exception:
                return NotImplemented
            if cat.shape != (self.count,) + self.trailing:
                return False
            return bool(jnp.all(self.materialize() == cat))
        return NotImplemented

    __hash__ = object.__hash__

    def __repr__(self) -> str:
        return (
            f"CatBuffer(count={self.count}, capacity={self.capacity}, "
            f"trailing={self.trailing}, dtype={self.dtype})"
        )

    # ------------------------------------------------- pickle / deepcopy

    def __getstate__(self) -> Tuple[Any, int]:
        return np.asarray(self.materialize()), self.count

    def __setstate__(self, state: Tuple[Any, int]) -> None:
        valid, count = state
        cap = _capacity_for(max(count, 1))
        arr = np.zeros((cap,) + valid.shape[1:], valid.dtype)
        arr[:count] = valid
        self.buffer = jnp.asarray(arr)
        self.count = int(count)
        self._count_dev = None
        self._owns = True

    def __deepcopy__(self, memo: dict) -> "CatBuffer":
        # device arrays are immutable; an owned alias is a faithful deep copy
        new = CatBuffer(self.buffer, self.count, owns=True)
        new._count_dev = self._count_dev
        self._owns = False
        new._owns = False
        memo[id(self)] = new
        return new


def cat_rows(value: Any, template: Optional[Array] = None) -> Array:
    """Concatenated valid rows of a cat state in any layout.

    Accepts a ``CatBuffer`` (masked slice), a list/tuple of increments, or an
    already-concatenated array. An empty list yields a 0-row array shaped
    like ``template`` (or ``(0,)`` float32 without one).
    """
    if isinstance(value, CatBuffer):
        return value.materialize()
    if isinstance(value, (list, tuple)):
        if not value:
            if template is not None:
                return jnp.zeros((0,) + template.shape[1:], template.dtype)
            return jnp.zeros((0,), jnp.float32)
        return jnp.concatenate([_row_form(e) for e in value], axis=0)
    arr = jnp.asarray(value)
    return arr[None] if arr.ndim == 0 else arr
