"""Plotting examples: single values, value histories, confusion matrices,
and ROC / PR curves (parity: reference ``examples/plotting.py``).

Run:  python examples/plotting.py [out_dir]
Writes PNGs instead of showing windows, so it works headless.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # in-repo run

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from torchmetrics_tpu import Accuracy, MeanSquaredError, MetricTracker  # noqa: E402
from torchmetrics_tpu.classification import (  # noqa: E402
    BinaryROC,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassPrecisionRecallCurve,
)
from torchmetrics_tpu.wrappers import ClasswiseWrapper  # noqa: E402

OUT = sys.argv[1] if len(sys.argv) > 1 else "plots"
os.makedirs(OUT, exist_ok=True)
rng = np.random.RandomState(42)


def save(fig, name):
    path = os.path.join(OUT, name)
    fig.savefig(path, dpi=100, bbox_inches="tight")
    plt.close(fig)
    print("wrote", path)


# 1. single scalar value
acc = Accuracy(task="multiclass", num_classes=5)
acc.update(jnp.asarray(rng.rand(64, 5).astype(np.float32)), jnp.asarray(rng.randint(0, 5, 64)))
fig, _ = acc.plot()
save(fig, "accuracy_single.png")

# 2. value history across epochs via MetricTracker
tracker = MetricTracker(MeanSquaredError())
for epoch in range(5):
    tracker.increment()
    noise = 1.0 / (epoch + 1)
    preds = jnp.asarray(rng.randn(32).astype(np.float32)) * noise
    tracker.update(preds, jnp.zeros(32))
fig, _ = tracker._base_metric.plot(tracker.compute_all())
save(fig, "mse_history.png")

# 3. per-class values through ClasswiseWrapper
cw = ClasswiseWrapper(MulticlassAccuracy(num_classes=5, average="none"))
cw.update(jnp.asarray(rng.rand(128, 5).astype(np.float32)), jnp.asarray(rng.randint(0, 5, 128)))
fig, _ = cw.plot()
save(fig, "classwise_accuracy.png")

# 4. confusion matrix heatmap
cm = MulticlassConfusionMatrix(num_classes=4)
cm.update(jnp.asarray(rng.rand(256, 4).astype(np.float32)), jnp.asarray(rng.randint(0, 4, 256)))
fig, _ = cm.plot(add_text=True)
save(fig, "confusion_matrix.png")

# 5. ROC + PR curves
scores = jnp.asarray(rng.rand(256).astype(np.float32))
labels = jnp.asarray((np.asarray(scores) + rng.randn(256) * 0.3 > 0.5).astype(np.int32))
roc = BinaryROC()
roc.update(scores, labels)
fig, _ = roc.plot()
save(fig, "binary_roc.png")

prc = MulticlassPrecisionRecallCurve(num_classes=4, thresholds=32)
prc.update(jnp.asarray(rng.rand(256, 4).astype(np.float32)), jnp.asarray(rng.randint(0, 4, 256)))
fig, _ = prc.plot()
save(fig, "multiclass_pr_curve.png")

print("done")
