"""Reference distributed train step: dp x pp x tp (+ep on tp) + metrics.

This module exists to prove — and to give users a template for — metrics
composing with a *fully sharded* training step (SURVEY.md §2.10: the
reference's only parallelism is DP state replication; TP/PP/EP are new
TPU-first design). The model is deliberately tiny; the sharding patterns are
real:

- **top level**: ``jit`` + GSPMD — params placed with ``NamedSharding``
  (the scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
  collectives). Autodiff through the inner ``shard_map`` inserts the correct
  psums for replicated operands via its transpose rule.
- **pp**: GPipe schedule inside ``shard_map`` — each rank owns one stage's
  params (leading stage axis sharded over pp); microbatch activations hop
  rank-to-rank via ``lax.ppermute``; the static tick loop is a ``lax.scan``.
- **tp**: MLP hidden dim sharded; partial matmul outputs ``psum`` over tp.
- **ep**: one expert per tp shard; tokens routed by static round-robin via
  ``lax.all_to_all`` (``parallel/ring.py``) — real dispatch/combine traffic
  with fixed shapes (a learned router adds gating on top, same comms).
- **dp**: batch sharded over dp inside the same shard_map; the loss mean
  outside is global (GSPMD), so grads aggregate over dp automatically.
"""
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .sync import axis_size
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # older jax: experimental API with the check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}

from .ring import expert_all_to_all

Array = jax.Array

__all__ = ["init_demo_params", "demo_param_shardings", "make_demo_train_step"]

_STAGE_KEYS = ("w1", "w2", "we1", "we2")


def init_demo_params(key: Array, vocab: int, d_model: int, d_hidden: int,
                     pp: int, tp: int) -> Dict[str, Array]:
    """Param pytree: stage params carry a leading pp axis and a tp-sharded hidden dim."""
    ks = jax.random.split(key, 6)
    se = d_model ** -0.5
    s = 0.5 * d_hidden ** -0.5
    return {
        "embed": jax.random.normal(ks[0], (vocab, d_model)) * se,       # replicated
        "w1": jax.random.normal(ks[1], (pp, d_model, d_hidden)) * s,    # pp x tp sharded
        "w2": jax.random.normal(ks[2], (pp, d_hidden, d_model)) * s,
        "we1": jax.random.normal(ks[3], (pp, d_model, d_hidden)) * s,   # experts: one per tp shard
        "we2": jax.random.normal(ks[4], (pp, d_hidden, d_model)) * s,
        "out": jax.random.normal(ks[5], (d_model, vocab)) * se,         # replicated
    }


def demo_param_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    """NamedShardings to ``device_put`` the params with before training."""
    return {
        "embed": NamedSharding(mesh, P()),
        "w1": NamedSharding(mesh, P("pp", None, "tp")),
        "w2": NamedSharding(mesh, P("pp", "tp", None)),
        "we1": NamedSharding(mesh, P("pp", None, "tp")),
        "we2": NamedSharding(mesh, P("pp", "tp", None)),
        "out": NamedSharding(mesh, P()),
    }


def _stage(stage_params: Dict[str, Array], x: Array, tp_axis: str) -> Array:
    """One pipeline stage: tensor-parallel MLP + expert-parallel MoE block.

    x: (mb, t, d_model) microbatch activations; stage_params hold the local
    tp slice (hidden dim already divided by tp under shard_map).
    """
    # tensor-parallel MLP: hidden sharded over tp, psum the partial output
    h = jax.nn.gelu(x @ stage_params["w1"])
    x = x + lax.psum(h @ stage_params["w2"], tp_axis)

    # expert-parallel MoE: each tp shard hosts ONE expert (its local we1/we2
    # slice); static round-robin routing by token position keeps shapes fixed
    ep = axis_size(tp_axis)
    mb, t, d = x.shape
    groups = x.reshape(mb, ep, t // ep, d).transpose(1, 0, 2, 3)  # (ep, mb, t/ep, d)
    dispatched = expert_all_to_all(groups, tp_axis)               # tokens for MY expert
    eh = jax.nn.gelu(dispatched @ stage_params["we1"])
    eo = eh @ stage_params["we2"]                                 # local expert output
    combined = expert_all_to_all(eo, tp_axis)                     # route back
    moe = combined.transpose(1, 0, 2, 3).reshape(mb, t, d)
    return x + moe


def _pipeline(stage_params: Dict[str, Array], inputs: Array, pp_axis: str, tp_axis: str) -> Array:
    """GPipe over microbatches: inputs (M, mb, t, d) -> outputs (M, mb, t, d).

    Rank 0 injects microbatch ``m`` at tick ``m``; rank ``p`` processes
    microbatch ``m`` at tick ``m + p``; the last rank collects finished
    microbatches. ``M + pp - 1`` ticks total (the pipeline bubble).
    """
    pp = axis_size(pp_axis)
    idx = lax.axis_index(pp_axis)
    m_count = inputs.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    # local stage axis has size 1 under shard_map; select my stage
    my_stage = {k: v[0] for k, v in stage_params.items()}

    def tick(carry, t):
        act, outbuf = carry
        recv = lax.ppermute(act, pp_axis, perm)
        inj = lax.dynamic_index_in_dim(inputs, jnp.clip(t, 0, m_count - 1), 0, keepdims=False)
        x = jnp.where(idx == 0, jnp.where(t < m_count, inj, jnp.zeros_like(inj)), recv)
        y = _stage(my_stage, x, tp_axis)
        m = t - (pp - 1)
        write = (idx == pp - 1) & (m >= 0)
        outbuf = jnp.where(
            write,
            lax.dynamic_update_index_in_dim(outbuf, y, jnp.clip(m, 0, m_count - 1), 0),
            outbuf,
        )
        return (y, outbuf), None

    act0 = jnp.zeros_like(inputs[0])
    (_, outbuf), _ = lax.scan(tick, (act0, jnp.zeros_like(inputs)), jnp.arange(m_count + pp - 1))
    # finished activations live on the last pp rank; replicate over the axis
    return lax.psum(jnp.where(idx == pp - 1, outbuf, jnp.zeros_like(outbuf)), pp_axis)


def make_demo_train_step(mesh: Mesh, *, microbatches: int = 2, lr: float = 0.1):
    """Build the jitted train step ``(params, tokens, targets) -> (params, loss, logits)``.

    tokens/targets: (B, T) int ids, globally shaped (GSPMD shards them over dp).
    """

    pipeline = _shard_map(
        partial(_pipeline, pp_axis="pp", tp_axis="tp"),
        mesh=mesh,
        in_specs=(
            {k: P("pp", None, "tp") if k in ("w1", "we1") else P("pp", "tp", None) for k in _STAGE_KEYS},
            P(None, "dp", None, None),  # (M, mb, t, d): microbatches over dp
        ),
        out_specs=P(None, "dp", None, None),
        # psum/where mix replicated + device-varying operands
        **_SHARD_MAP_KW,
    )

    def loss_fn(params, tokens, targets):
        x = params["embed"][tokens]  # (B, T, d) under GSPMD
        b, t, d = x.shape
        mb = b // microbatches
        stages_in = x.reshape(microbatches, mb, t, d)
        y = pipeline({k: params[k] for k in _STAGE_KEYS}, stages_in).reshape(b, t, d)
        logits = y @ params["out"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
        return jnp.mean(nll), logits

    @partial(jax.jit, donate_argnums=0)
    def train_step(params, tokens, targets):
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, tokens, targets)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss, logits

    return train_step
