"""Segmentation morphology toolbox (JAX).

Parity targets: reference ``functional/segmentation/utils.py`` (781 LoC,
fns at :27 check_if_binarized, :64 generate_binary_structure, :107
binary_erosion, :177 distance_transform, :278 mask_edges, :336
surface_distance, :387-505 neighbour-code tables).

TPU-first design notes:
- erosion/dilation are windowed reductions (``lax.reduce_window``) — one
  fused XLA op, no im2col unfold like the reference's ``_unfold``.
- distance transforms use Meijster's two-phase separable decomposition,
  with each 1D phase expressed as a dense min-plus broadcast reduce
  (O(n^2) per line but fully vectorized — XLA tiles it; no sequential
  envelope scan, which would serialize on TPU).
"""
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array


def check_if_binarized(x: Array) -> None:
    """Raise if the tensor is not binary (only 0s and 1s).

    Parity: reference ``functional/segmentation/utils.py:27``.
    """
    xv = np.asarray(x)
    if not np.all((xv == 0) | (xv == 1)):
        raise ValueError("Input x should be binarized")


def generate_binary_structure(rank: int, connectivity: int) -> Array:
    """Binary structuring element a la ``scipy.ndimage.generate_binary_structure``.

    Parity: reference ``functional/segmentation/utils.py:64``.
    """
    if connectivity < 1:
        out = np.zeros((3,) * rank, dtype=bool)
        out[(1,) * rank] = True
        return jnp.asarray(out)
    grids = np.meshgrid(*([np.arange(3)] * rank), indexing="ij")
    dist = sum(np.abs(g - 1) for g in grids)
    return jnp.asarray(dist <= connectivity)


def _reduce_window_bool(x: Array, structure: Array, init: float, op) -> Array:
    """Windowed reduce over the trailing spatial dims with a mask-shaped window."""
    # implement "min over structure's True offsets" by shifting: for small 3^r
    # structures a shift-and-combine is cheaper than a dense reduce_window
    rank = structure.ndim
    offs = np.argwhere(np.asarray(structure)) - 1  # offsets in [-1, 0, 1]^rank
    out = None
    for off in offs:
        shifted = x
        for ax, o in enumerate(off):
            shifted = jnp.roll(shifted, -int(o), axis=-(rank - ax))
            # zero-pad semantics at the border (border_value=0)
            idx = [slice(None)] * shifted.ndim
            axis = shifted.ndim - rank + ax
            if o == 1:
                idx[axis] = slice(-1, None)
            elif o == -1:
                idx[axis] = slice(0, 1)
            if o != 0:
                pad = jnp.zeros_like(shifted[tuple(idx)])
                keep = [slice(None)] * shifted.ndim
                keep[axis] = slice(0, -1) if o == 1 else slice(1, None)
                body = shifted[tuple(keep)]
                shifted = jnp.concatenate(
                    (body, pad) if o == 1 else (pad, body), axis=axis
                )
        out = shifted if out is None else op(out, shifted)
    return out


def binary_erosion(image: Array, structure: Optional[Array] = None, border_value: int = 0) -> Array:
    """Binary erosion over the trailing spatial dims of a (B, C, *spatial) image.

    Parity: reference ``functional/segmentation/utils.py:107`` (which unfolds;
    here: shift-and-AND over the structuring element's offsets — fuses in XLA).
    """
    if image.ndim not in (4, 5):
        raise ValueError(f"Expected argument `image` to be of rank 4 or 5 but got rank {image.ndim}")
    check_if_binarized(image)
    rank = image.ndim - 2
    if structure is None:
        structure = generate_binary_structure(rank, 1)
    x = image.astype(jnp.float32)
    if border_value == 0:
        eroded = _reduce_window_bool(x, structure, 1.0, jnp.minimum)
    else:
        # border treated as foreground: pad with 1s via inverted dilation
        eroded = 1.0 - _reduce_window_bool(1.0 - x, structure, 0.0, jnp.maximum)
        # interior handling identical; only borders differ
    return eroded.astype(image.dtype)


def binary_dilation(image: Array, structure: Optional[Array] = None) -> Array:
    """Binary dilation — companion of :func:`binary_erosion`.

    The structuring element is mirrored (scipy semantics: dilation reflects
    the structure about its center before sweeping).
    """
    if image.ndim not in (4, 5):
        raise ValueError(f"Expected argument `image` to be of rank 4 or 5 but got rank {image.ndim}")
    check_if_binarized(image)
    rank = image.ndim - 2
    if structure is None:
        structure = generate_binary_structure(rank, 1)
    mirrored = jnp.asarray(np.flip(np.asarray(structure)).copy())
    x = image.astype(jnp.float32)
    return _reduce_window_bool(x, mirrored, 0.0, jnp.maximum).astype(image.dtype)


def _dt_1d_l1(bg: Array, axis: int, spacing: float) -> Array:
    """Per-line L1 distance to the nearest background element along ``axis``.

    Vectorized min-plus: d[i] = min_j (|i-j| : bg[j]); inf when no bg.
    """
    n = bg.shape[axis]
    idx = jnp.arange(n, dtype=jnp.float32)
    # move axis last
    bgm = jnp.moveaxis(bg, axis, -1)
    dist_pairs = jnp.abs(idx[:, None] - idx[None, :]) * spacing  # (n, n)
    masked = jnp.where(bgm[..., None, :], dist_pairs, jnp.inf)  # (..., n, n)
    out = jnp.min(masked, axis=-1)
    return jnp.moveaxis(out, -1, axis)


def _phase2(g: Array, axis: int, spacing: float, metric: str) -> Array:
    """Meijster phase 2: combine per-column distances g along ``axis``."""
    n = g.shape[axis]
    idx = jnp.arange(n, dtype=jnp.float32)
    gm = jnp.moveaxis(g, axis, -1)  # (..., n)
    dx = jnp.abs(idx[:, None] - idx[None, :]) * spacing  # (n, n) |x - x'|
    if metric == "euclidean":
        cand = jnp.sqrt(dx**2 + jnp.where(jnp.isinf(gm), jnp.inf, gm) [..., None, :] ** 2)
        cand = jnp.where(jnp.isinf(gm)[..., None, :], jnp.inf, cand)
    elif metric == "taxicab":
        cand = dx + gm[..., None, :]
    else:  # chessboard
        cand = jnp.maximum(dx, gm[..., None, :])
    out = jnp.min(cand, axis=-1)
    return jnp.moveaxis(out, -1, axis)


def distance_transform(
    x: Array,
    sampling: Optional[Sequence[float]] = None,
    metric: str = "euclidean",
    engine: str = "xla",
) -> Array:
    """Distance from each foreground element to the nearest background element.

    Parity: reference ``functional/segmentation/utils.py:177`` (metrics
    euclidean / chessboard / taxicab; ``sampling`` = per-axis spacing).
    Supports 2D ``(H, W)`` or batched ``(..., H, W)`` input. Elements with no
    background anywhere get ``inf``.

    TPU-first: Meijster's separable two-phase algorithm with each 1D phase a
    dense min-plus reduce — O(H*W*(H+W)) vectorized work, no sequential scans.
    """
    if metric not in ("euclidean", "chessboard", "taxicab"):
        raise ValueError(
            f"Expected argument `metric` to be one of 'euclidean', 'chessboard', 'taxicab' but got {metric}"
        )
    if engine not in ("xla", "scipy"):
        raise ValueError(f"Expected argument `engine` to be one of 'xla', 'scipy' but got {engine}")
    if engine == "scipy":
        # memory-lean host path (the reference's alternative engine)
        from scipy import ndimage

        xs = np.asarray(x)
        if metric == "euclidean":
            return jnp.asarray(ndimage.distance_transform_edt(xs, sampling=sampling))
        return jnp.asarray(
            ndimage.distance_transform_cdt(xs, metric="chessboard" if metric == "chessboard" else "taxicab").astype(
                np.float32
            )
        )
    if sampling is None:
        sampling = (1.0, 1.0)
    if len(sampling) != 2:
        raise ValueError(f"Expected argument `sampling` to have length 2 but got length {len(sampling)}")
    x = jnp.asarray(x)
    bg = x == 0
    # phase 1: vertical (axis -2) L1 distances to background
    g = _dt_1d_l1(bg, -2, float(sampling[0]))
    # phase 2: combine along horizontal axis
    out = _phase2(g, -1, float(sampling[1]), metric)
    return jnp.where(bg, 0.0, out)


def mask_edges(
    preds: Array,
    target: Array,
    crop: bool = True,
    spacing: Optional[Sequence[float]] = None,
) -> Tuple[Array, ...]:
    """Edges of binary segmentation masks.

    Parity: reference ``functional/segmentation/utils.py:278``. Without
    ``spacing``: edge = mask XOR eroded mask, returns ``(edges_preds,
    edges_target)``. With ``spacing``: neighbour-code convolution against the
    contour-length (2D) / surface-area (3D) table, returns the 4-tuple
    ``(edges_preds, edges_target, areas_preds, areas_target)``. ``crop`` pads
    each spatial dim by 1 (reference keeps the padded frame).
    """
    if preds.shape != target.shape:
        raise ValueError(f"Expected `preds` and `target` to have the same shape, got {preds.shape} and {target.shape}")
    if preds.ndim not in (2, 3):
        raise ValueError(f"Expected argument `preds` to be of rank 2 or 3 but got rank `{preds.ndim}`.")
    check_if_binarized(preds)
    check_if_binarized(target)
    preds = preds.astype(jnp.int32)
    target = target.astype(jnp.int32)

    if crop:
        if not bool(np.asarray(preds | target).any()):
            zp = jnp.zeros_like(preds, dtype=bool)
            zt = jnp.zeros_like(target, dtype=bool)
            if spacing is None:
                return zp, zt
            zf = jnp.zeros(preds.shape, jnp.float32)
            return zp, zt, zf, jnp.zeros(target.shape, jnp.float32)
        pad_width = [(1, 1)] * preds.ndim
        preds = jnp.pad(preds, pad_width)
        target = jnp.pad(target, pad_width)

    if spacing is None:
        structure = generate_binary_structure(preds.ndim, 1)
        p = preds.astype(jnp.float32)[None, None]
        t = target.astype(jnp.float32)[None, None]
        ep = jnp.logical_xor(binary_erosion(p, structure)[0, 0].astype(bool), preds.astype(bool))
        et = jnp.logical_xor(binary_erosion(t, structure)[0, 0].astype(bool), target.astype(bool))
        return ep, et

    if len(spacing) != preds.ndim:
        raise ValueError(f"Expected `spacing` of length {preds.ndim} to match the mask rank, got {len(spacing)}")
    table, kernel = get_neighbour_tables(tuple(spacing))
    ndim = preds.ndim
    vol = jnp.stack([preds, target]).astype(jnp.float32)[:, None]  # (2, 1, *spatial)
    dn = lax.conv_dimension_numbers(vol.shape, (1, 1) + kernel.shape,
                                    ("NCHW", "OIHW", "NCHW") if ndim == 2 else ("NCDHW", "OIDHW", "NCDHW"))
    codes = lax.conv_general_dilated(vol, kernel[None, None], (1,) * ndim, "VALID",
                                     dimension_numbers=dn,
                                     precision=lax.Precision.HIGHEST)[:, 0]
    codes_i = codes.astype(jnp.int32)
    all_ones = len(np.asarray(table)) - 1
    edges = (codes_i != 0) & (codes_i != all_ones)
    areas = jnp.take(table, codes_i)
    return edges[0], edges[1], areas[0], areas[1]


def surface_distance(
    preds: Array,
    target: Array,
    distance_metric: str = "euclidean",
    spacing: Optional[Sequence[float]] = None,
) -> Array:
    """Distances from each foreground element of ``preds`` to the nearest
    foreground element of ``target``.

    Parity: reference ``functional/segmentation/utils.py:336``. Returns a 1D
    array (one distance per foreground element of ``preds``) — host-side
    boolean gather, so call outside jit; the distance field itself is
    device-computed.
    """
    if spacing is None:
        spacing = (1.0, 1.0)
    # distance to target's foreground == distance transform of (1 - target)
    dt = distance_transform(1 - target.astype(jnp.int32), sampling=spacing, metric=distance_metric)
    return jnp.asarray(np.asarray(dt)[np.asarray(preds).astype(bool)])


# ---------------------------------------------------------------------------
# Neighbour-code tables (normalized surface dice support)
# ---------------------------------------------------------------------------

# marching-squares segments per 2x2 neighbour code: each entry is a list of
# (edge_a, edge_b) segments with edges indexed 0=top, 1=right, 2=bottom,
# 3=left; endpoints at edge midpoints. Code bit order: (0,0)=8, (0,1)=4,
# (1,0)=2, (1,1)=1 (matches the reference's neighbour-code convention).
_MS_SEGMENTS = {
    0: [], 15: [],
    1: [(1, 2)], 14: [(1, 2)],
    2: [(2, 3)], 13: [(2, 3)],
    4: [(0, 1)], 11: [(0, 1)],
    8: [(0, 3)], 7: [(0, 3)],
    3: [(1, 3)], 12: [(1, 3)],
    5: [(0, 2)], 10: [(0, 2)],
    6: [(0, 1), (2, 3)],
    9: [(0, 3), (1, 2)],
}


def table_contour_length(spacing: Tuple[float, float], device=None) -> Tuple[Array, Array]:
    """(16,) table mapping 2x2 neighbour codes to contour length, plus the
    2x2 convolution kernel that produces the codes.

    Parity: reference ``functional/segmentation/utils.py:408``.
    """
    dy, dx = float(spacing[0]), float(spacing[1])
    # edge-midpoint coordinates in physical units (y, x)
    mid = {0: (0.0, dx / 2), 1: (dy / 2, dx), 2: (dy, dx / 2), 3: (dy / 2, 0.0)}
    table = np.zeros(16, dtype=np.float32)
    for code, segs in _MS_SEGMENTS.items():
        total = 0.0
        for a, b in segs:
            ya, xa = mid[a]
            yb, xb = mid[b]
            total += float(np.hypot(ya - yb, xa - xb))
        table[code] = total
    kernel = jnp.asarray([[8, 4], [2, 1]], dtype=jnp.float32)
    return jnp.asarray(table), kernel


# standard 6-tetrahedra decomposition of the unit cube; cube corners are
# indexed by (z, y, x) bits, corner k = (k>>2 & 1, k>>1 & 1, k & 1)
_CUBE_TETS = (
    (0, 5, 1, 3), (0, 5, 3, 7), (0, 5, 7, 4),
    (0, 7, 3, 2), (0, 7, 2, 6), (0, 7, 6, 4),
)


def _tet_isosurface_area(vals, pts) -> float:
    """Exact 0.5-isosurface area of the linear interpolant on one tetrahedron
    with binary vertex values (crossings are edge midpoints)."""
    inside = [i for i in range(4) if vals[i] > 0.5]
    k = len(inside)
    if k in (0, 4):
        return 0.0
    outside = [i for i in range(4) if i not in inside]
    if k in (1, 3):
        apex = inside[0] if k == 1 else outside[0]
        others = outside if k == 1 else inside
        p = [(pts[apex] + pts[o]) / 2.0 for o in others]
        return float(np.linalg.norm(np.cross(p[1] - p[0], p[2] - p[0])) / 2.0)
    a, b = inside
    c, d = outside
    q = [(pts[a] + pts[c]) / 2.0, (pts[a] + pts[d]) / 2.0,
         (pts[b] + pts[d]) / 2.0, (pts[b] + pts[c]) / 2.0]
    t1 = np.linalg.norm(np.cross(q[1] - q[0], q[2] - q[0])) / 2.0
    t2 = np.linalg.norm(np.cross(q[2] - q[0], q[3] - q[0])) / 2.0
    return float(t1 + t2)


def table_surface_area(spacing: Tuple[float, float, float], device=None) -> Tuple[Array, Array]:
    """(256,) table mapping 2x2x2 neighbour codes to isosurface area, plus the
    2x2x2 code kernel.

    Parity: reference ``functional/segmentation/utils.py:452``. Areas are
    computed from scratch by marching tetrahedra on the unit cell (6-tet
    decomposition, exact piecewise-linear areas) scaled by ``spacing`` — no
    hard-coded 256-case triangle table.
    """
    dz, dy, dx = (float(s) for s in spacing)
    corner_pts = [np.array([(k >> 2) & 1, (k >> 1) & 1, k & 1], dtype=np.float64) * [dz, dy, dx]
                  for k in range(8)]
    table = np.zeros(256, dtype=np.float32)
    for code in range(256):
        # bit 7-i of the code corresponds to corner i (kernel weights below)
        vals = [(code >> (7 - k)) & 1 for k in range(8)]
        area = 0.0
        for tet in _CUBE_TETS:
            area += _tet_isosurface_area([vals[i] for i in tet], [corner_pts[i] for i in tet])
        table[code] = area
    kernel = jnp.asarray(np.array([[[128, 64], [32, 16]], [[8, 4], [2, 1]]]), dtype=jnp.float32)
    return jnp.asarray(table), kernel


def get_neighbour_tables(
    spacing: Union[Tuple[float, float], Tuple[float, float, float]], device=None
) -> Tuple[Array, Array]:
    """Dispatch to the 2D contour-length or 3D surface-area table.

    Parity: reference ``functional/segmentation/utils.py:387``.
    """
    if len(spacing) == 2:
        return table_contour_length(spacing, device)
    if len(spacing) == 3:
        return table_surface_area(spacing, device)
    raise ValueError(f"Expected argument `spacing` to have length 2 or 3 but got length {len(spacing)}")
