"""HingeLoss metric classes.

Parity: reference ``src/torchmetrics/classification/hinge.py``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..functional.classification.hinge import (
    _binary_hinge_loss_update,
    _multiclass_hinge_loss_update,
)
from ..metric import Metric
from ..utils.enums import ClassificationTaskNoMultilabel
from .base import _ClassificationTaskWrapper

Array = jax.Array


class BinaryHingeLoss(Metric):
    """Parity: reference ``classification/hinge.py:38``."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, squared: bool = False, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.squared = squared
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measures", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        # ignore mask folds in as 0-weights: no dynamic filter, stays traceable
        w = None if self.ignore_index is None else (target.reshape(-1) != self.ignore_index)
        measures, total = _binary_hinge_loss_update(preds, target, self.squared, w)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        return self.measures / self.total


class MulticlassHingeLoss(Metric):
    """Parity: reference ``classification/hinge.py:120``."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, num_classes: int, squared: bool = False, multiclass_mode: str = "crammer-singer",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args and multiclass_mode not in ("crammer-singer", "one-vs-all"):
            raise ValueError(
                "Argument `multiclass_mode` is expected to be 'crammer-singer' or 'one-vs-all' "
                f"but got {multiclass_mode}"
            )
        self.num_classes = num_classes
        self.squared = squared
        self.multiclass_mode = multiclass_mode
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        default = jnp.asarray(0.0) if multiclass_mode == "crammer-singer" else jnp.zeros((num_classes,))
        self.add_state("measures", default, dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        # ignore mask folds in as 0-weights: no dynamic filter, stays traceable
        w = None if self.ignore_index is None else (target.reshape(-1) != self.ignore_index)
        measures, total = _multiclass_hinge_loss_update(
            preds, target, self.num_classes, self.squared, self.multiclass_mode, w
        )
        if self.multiclass_mode == "crammer-singer":
            measures = jnp.sum(measures)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        return self.measures / self.total


class HingeLoss(_ClassificationTaskWrapper):
    """Task facade. Parity: reference ``classification/hinge.py:222``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import HingeLoss
        >>> metric = HingeLoss(task="multiclass", num_classes=3)
        >>> preds = jnp.asarray([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.6, 0.1]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.5875
    """

    def __new__(cls, task: str, num_classes: Optional[int] = None, squared: bool = False,
                multiclass_mode: str = "crammer-singer", ignore_index: Optional[int] = None,
                validate_args: bool = True, **kwargs: Any) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryHingeLoss(squared, **kwargs)
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)}` was passed.")
        return MulticlassHingeLoss(num_classes, squared, multiclass_mode, **kwargs)
