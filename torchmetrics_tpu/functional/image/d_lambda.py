"""Pan-sharpening quality metrics: D_lambda, D_s, QNR.

Parity: reference ``src/torchmetrics/functional/image/{d_lambda,d_s,qnr}.py``:

- **D_lambda** (spectral distortion): per band-pair, the |batch-mean UQI of
  the fused bands minus batch-mean UQI of the low-res ms bands|^p, averaged
  over ordered pairs, ^(1/p). ``target`` is the LOW-RES ms — only batch and
  channel counts must match ``preds`` (``d_lambda.py:41``).
- **D_s** (spatial distortion): per band, |batch-mean UQI(ms, pan_degraded)
  − batch-mean UQI(preds, pan)|^norm_order, reduced over the BAND axis then
  ^(1/norm_order). ``pan_degraded`` is the pan image through a
  ``window_size`` uniform filter (scipy-style symmetric padding) and a
  bilinear antialias-free resize to the ms grid (``d_s.py:175-201``).
- **QNR** = (1 − D_lambda)^alpha · (1 − D_s)^beta on the low-res ms
  directly (``qnr.py:82``).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from .helper import depthwise_conv2d
from .uqi import _uqi_update

Array = jax.Array


def _band_uqi_mean(a: Array, b: Array) -> Array:
    """Scalar batch-mean UQI between two single-band (N, H, W) images."""
    return jnp.mean(_uqi_update(a[:, None], b[:, None]))


def _uniform_filter_2d(x: Array, window_size: int) -> Array:
    """Uniform filter with the reference's scipy-style symmetric padding
    (``utils.py:112-132``): edge-inclusive reflection, asymmetric for even
    windows, 'valid' conv back to the input size."""
    pad_l = window_size // 2
    pad_r = (window_size - 1) // 2
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad_l, pad_r), (pad_l, pad_r)), mode="symmetric")
    kernel = jnp.full((x.shape[1], 1, window_size, window_size), 1.0 / window_size**2, jnp.float32)
    return depthwise_conv2d(xp, kernel)


def _validate_4d(name: str, x: Array) -> None:
    if x.ndim != 4:
        raise ValueError(f"Expected `{name}` to have BxCxHxW shape. Got {name}: {x.shape}.")


def spectral_distortion_index(
    preds: Array, target: Array, p: int = 1, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """D_lambda. Parity: reference ``d_lambda.py:108``."""
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    _validate_4d("preds", jnp.asarray(preds))
    _validate_4d("target", jnp.asarray(target))
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.shape[:2] != target.shape[:2]:
        raise ValueError(
            "Expected `preds` and `target` to have same batch and channel sizes."
            f"Got preds: {preds.shape} and target: {target.shape}."
        )
    length = preds.shape[1]
    total = jnp.asarray(0.0)
    for k in range(length):
        for r in range(k + 1, length):
            q_lr = _band_uqi_mean(target[:, k], target[:, r])
            q_fused = _band_uqi_mean(preds[:, k], preds[:, r])
            total = total + 2.0 * jnp.abs(q_lr - q_fused) ** p  # symmetric pair counted twice
    if length == 1:
        output = jnp.asarray(0.0) ** (1.0 / p)
    else:
        output = (total / (length * (length - 1))) ** (1.0 / p)
    # output is a scalar; the reference's `reduce` over it is the identity
    # for elementwise_mean/sum distinction only on non-scalars
    return output


def spatial_distortion_index(
    preds: Array, ms: Array, pan: Array, pan_lr: Optional[Array] = None,
    norm_order: int = 1, window_size: int = 7, reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """D_s. Parity: reference ``d_s.py:205``.

    preds: fused high-res multispectral (N, C, H, W); ms: low-res
    multispectral (N, C, h, w); pan: panchromatic (N, C, H, W).
    """
    if not isinstance(norm_order, int) or norm_order <= 0:
        raise ValueError(f"Expected `norm_order` to be a positive integer. Got norm_order: {norm_order}.")
    if not isinstance(window_size, int) or window_size <= 0:
        raise ValueError(f"Expected `window_size` to be a positive integer. Got window_size: {window_size}.")
    for name, x in (("preds", preds), ("ms", ms), ("pan", pan)):
        _validate_4d(name, jnp.asarray(x))
    preds = jnp.asarray(preds, jnp.float32)
    ms = jnp.asarray(ms, jnp.float32)
    pan = jnp.asarray(pan, jnp.float32)
    if preds.shape[:2] != ms.shape[:2] or preds.shape[:2] != pan.shape[:2]:
        raise ValueError(
            "Expected `preds`, `ms` and `pan` to have the same batch and channel sizes."
            f" Got preds: {preds.shape}, ms: {ms.shape}, pan: {pan.shape}."
        )
    if preds.shape[-2:] != pan.shape[-2:]:
        raise ValueError(
            f"Expected `preds` and `pan` to have the same spatial size. Got {preds.shape} and {pan.shape}."
        )
    if preds.shape[-2] % ms.shape[-2] or preds.shape[-1] % ms.shape[-1]:
        raise ValueError(
            f"Expected dimensions of `preds` to be multiples of `ms`. Got preds: {preds.shape}, ms: {ms.shape}."
        )
    ms_h, ms_w = ms.shape[-2:]
    if window_size >= ms_h or window_size >= ms_w:
        raise ValueError(
            f"Expected `window_size` to be smaller than dimension of `ms`. Got window_size: {window_size}."
        )
    if pan_lr is None:
        degraded = _uniform_filter_2d(pan, window_size)
        # ambient pin: resize lowers to dot_generals (bf16 on TPU otherwise)
        with jax.default_matmul_precision("highest"):
            degraded = jax.image.resize(
                degraded, degraded.shape[:2] + (ms_h, ms_w), jax.image.ResizeMethod.LINEAR, antialias=False
            )
    else:
        pan_lr = jnp.asarray(pan_lr, jnp.float32)
        if pan_lr.shape[-2:] != (ms_h, ms_w):
            raise ValueError(
                f"Expected `ms` and `pan_lr` to have the same spatial size. Got {ms.shape} and {pan_lr.shape}."
            )
        degraded = pan_lr
    length = preds.shape[1]
    m1 = jnp.stack([_band_uqi_mean(ms[:, i], degraded[:, i]) for i in range(length)])
    m2 = jnp.stack([_band_uqi_mean(preds[:, i], pan[:, i]) for i in range(length)])
    diff = jnp.abs(m1 - m2) ** norm_order  # (C,) — reduced over the band axis
    if reduction == "elementwise_mean":
        return jnp.mean(diff) ** (1.0 / norm_order)
    if reduction == "sum":
        return jnp.sum(diff) ** (1.0 / norm_order)
    return diff ** (1.0 / norm_order)


def quality_with_no_reference(
    preds: Array, ms: Array, pan: Array, pan_lr: Optional[Array] = None,
    alpha: float = 1.0, beta: float = 1.0, norm_order: int = 1, window_size: int = 7,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """QNR = (1 - D_lambda)^alpha * (1 - D_s)^beta. Parity: reference ``qnr.py:28``."""
    if not isinstance(alpha, (int, float)) or alpha < 0:
        raise ValueError(f"Expected `alpha` to be a non-negative real number. Got alpha: {alpha}.")
    if not isinstance(beta, (int, float)) or beta < 0:
        raise ValueError(f"Expected `beta` to be a non-negative real number. Got beta: {beta}.")
    d_l = spectral_distortion_index(preds, ms, norm_order, reduction)
    d_s_val = spatial_distortion_index(preds, ms, pan, pan_lr, norm_order, window_size, reduction)
    return (1 - d_l) ** alpha * (1 - d_s_val) ** beta
