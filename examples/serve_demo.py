"""Multi-tenant online evaluation service: one vmapped stack per fleet.

Simulates a model server handling N tenant cohorts (regions / surfaces) at
once. An ingest thread synthesizes per-tenant (label, latency) traffic into
a bounded queue; the consumer loop drains it into THREE TenantStacks —

- ``TenantStack(WindowedMean)``  — click-through rate over the last window,
- ``TenantStack(DecayedMean)``   — exponentially-weighted latency (EMA),
- ``TenantStack(ApproxQuantile)``— p50 latency via a t-digest sketch,

so every step costs ONE dispatch per stack regardless of tenant count
(the per-tenant Python loop this replaces is exactly what tpulint's TPU011
flags). After warm-up the stream runs inside ``strict_mode()``: a million+
events, ZERO retraces and ZERO implicit host transfers, staged through
``buffered()``'s scanned flush. Mid-service tenant churn (add/remove) flips
slots in the padded pow2 mask through one pre-compiled kernel — no retrace.

A 2-rank sync of the sketch stack then runs under an injected ChaosSync
timeout: ElasticSync retries and recovers the full-coverage merged result —
ONE collective per (Reduction, dtype) bucket, not per tenant.

Ships the two artifacts an operator would scrape: a Perfetto-loadable trace
(``serve_trace.perfetto.json``) and a Prometheus text exposition
(``serve_metrics.prom``) whose ``tmtpu_serve_*`` gauges carry a
``tenant="..."`` label per cohort.

    JAX_PLATFORMS=cpu python examples/serve_demo.py [out_dir]
"""
import os as _os
import queue
import sys as _sys
import tempfile
import threading

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # in-repo run

import numpy as np

import jax.numpy as jnp

from torchmetrics_tpu import (
    ApproxQuantile,
    DecayedMean,
    TenantStack,
    WindowedMean,
)
from torchmetrics_tpu import observability as obs
from torchmetrics_tpu.debug import strict_mode
from torchmetrics_tpu.metric import executable_cache_stats
from torchmetrics_tpu.parallel import ChaosSchedule, ElasticSync, SyncPolicy, chaos_group

TENANTS = ["us", "eu", "apac", "play", "web", "ios"]
# per-cohort traffic character: base CTR and log-latency location
BASE_CTR = np.asarray([0.30, 0.24, 0.36, 0.18, 0.27, 0.33], np.float32)
LAT_MU = np.asarray([3.0, 3.2, 3.4, 2.9, 3.1, 3.0], np.float32)


def _pad(per_tenant: np.ndarray, slots: int) -> np.ndarray:
    """Pad the tenant axis to the pow2 slot count (spare rows are ignored)."""
    out = np.zeros((slots,) + per_tenant.shape[1:], per_tenant.dtype)
    out[: per_tenant.shape[0]] = per_tenant
    return out


def synth_events(rng, slots: int, batch: int):
    """One (slots, batch) step of synthetic per-tenant serving traffic."""
    n = len(TENANTS)
    label = (rng.rand(n, batch) < BASE_CTR[:, None]).astype(np.float32)
    latency = rng.lognormal(mean=LAT_MU[:, None], sigma=0.5, size=(n, batch)).astype(np.float32)
    return _pad(label, slots), _pad(latency, slots)


def ingest(q: "queue.Queue", seed: int, slots: int, batch: int, steps: int) -> None:
    """Producer thread: host-side synthesis feeding the bounded queue."""
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        q.put(synth_events(rng, slots, batch))
    q.put(None)  # end-of-stream


def main() -> None:
    batch = 512
    steps = 260  # x 8 slots x 512 events/slot > 1e6 events total
    warm = 17

    ctr = TenantStack(WindowedMean(horizon=64, slots=8), tenants=TENANTS).buffered(window=16)
    ema = TenantStack(DecayedMean(halflife=32.0), tenants=TENANTS).buffered(window=16)
    p50 = TenantStack(ApproxQuantile(q=0.5, compression=64), tenants=TENANTS).buffered(window=16)
    slots = ctr.metric.slots

    q: "queue.Queue" = queue.Queue(maxsize=8)
    producer = threading.Thread(
        target=ingest, args=(q, 0, slots, batch, steps), daemon=True
    )
    producer.start()

    def step(label: np.ndarray, latency: np.ndarray) -> None:
        lat = jnp.asarray(latency)
        ctr.update(jnp.asarray(label))
        ema.update(lat)
        p50.update(lat)

    # warm-up: first flush traces+compiles each stack's scanned update once
    for _ in range(warm):
        step(*q.get())

    events = warm * slots * batch
    with strict_mode(max_new_executables=0) as stats:
        while (ev := q.get()) is not None:
            step(*ev)  # one dispatch per stack for ALL tenants
            events += slots * batch
    producer.join()
    print(f"streamed {events:,} events across {len(TENANTS)} tenants: "
          f"retraces={stats.retraces} new_executables={stats.new_executables}")

    # mid-service churn: flush staged work, then flip slots through the
    # pre-compiled kernel — roster changes within a capacity never retrace
    for w in (ctr, ema, p50):
        w.compute()
    churn_before = executable_cache_stats()["retraces"]
    for w in (ctr, ema, p50):
        w.metric.remove_tenant("web")  # surface decommissioned...
        w.metric.add_tenant("br")  # ...new region onboarded, same slot
    rng2 = np.random.RandomState(1)
    roster = list(ctr.metric.tenant_ids)
    for _ in range(16):  # traffic continues; 'br' starts accumulating
        step(*synth_events(rng2, slots, batch))
    for w in (ctr, ema, p50):
        w.compute()
    print(f"tenant churn (-web +br): roster={roster} "
          f"retraces={executable_cache_stats()['retraces'] - churn_before}")

    ctr_res = ctr.metric.results()
    ema_res = ema.metric.results()
    p50_res = p50.metric.results()
    for t in roster:
        print(f"  {t:>5}: ctr={float(ctr_res[t]):.3f} "
              f"ema_latency={float(ema_res[t]):6.1f}ms "
              f"p50={float(p50_res[t]):6.1f}ms")
    err = p50.metric._view.members[0][2].error_bound()
    print(f"p50 via stacked t-digest (rank error <= {err:.3f}); "
          f"state bytes independent of stream length")

    # elastic 2-rank sync of the sketch stack under an injected timeout:
    # ONE collective per (Reduction, dtype) bucket — never per tenant
    ranks = [TenantStack(ApproxQuantile(q=0.5, compression=64), tenants=TENANTS) for _ in range(2)]
    rng3 = np.random.RandomState(2)
    for r in range(2):
        _, latency = synth_events(rng3, slots, batch)
        ranks[r].update(jnp.asarray(latency))
    backs = chaos_group(
        [m.metric_state for m in ranks], ChaosSchedule({0: [("timeout", 1)]})
    )
    for r, m in enumerate(ranks):
        m._sync_backend = ElasticSync(backs[r], policy=SyncPolicy(retry_attempts=1))
    backs[0].controller.advance()
    wire_before = executable_cache_stats()["collectives_issued"]
    merged = ranks[0].results()  # sync happens here: timeout -> retry -> ok
    cov = ranks[0].coverage
    print(f"chaos sync: coverage={cov.fraction if cov else 1.0:.1f} "
          f"collectives={executable_cache_stats()['collectives_issued'] - wire_before} "
          f"merged p50[us]={float(merged['us']):.1f}ms")

    # per-tenant-labelled gauges on the shared registry -> Prometheus scrape
    reg = obs.get_registry()
    g_ctr = reg.gauge("serve_ctr", "windowed click-through rate per tenant")
    g_ema = reg.gauge("serve_latency_ema_ms", "EMA latency per tenant (ms)")
    g_p50 = reg.gauge("serve_latency_p50_ms", "p50 latency per tenant (ms)")
    g_slots = reg.gauge("serve_tenant_slots", "padded tenant slot capacity")
    for t in roster:
        g_ctr.set(float(ctr_res[t]), tenant=str(t))
        g_ema.set(float(ema_res[t]), tenant=str(t))
        g_p50.set(float(p50_res[t]), tenant=str(t))
    g_slots.set(float(slots))
    print(f"online dispatch counters: {executable_cache_stats()['online']}")

    # telemetry demo: arm tracing for a short slice (outside the strict
    # measurement above — tracing costs time) and export what an operator
    # would scrape
    out_dir = _sys.argv[1] if len(_sys.argv) > 1 else tempfile.mkdtemp(prefix="serve_demo_")
    with obs.tracing():
        for _ in range(4):
            step(*synth_events(rng2, slots, batch))
        float(jnp.sum(ema.compute()))  # forces a traced flush + compute span
        spans = list(obs.collected_spans())
    trace_path = _os.path.join(out_dir, "serve_trace.perfetto.json")
    obs.write_perfetto(trace_path, spans)
    prom_path = _os.path.join(out_dir, "serve_metrics.prom")
    with open(prom_path, "w") as fh:
        fh.write(obs.to_prometheus())
    phases = sorted({s.name for s in spans})
    print(f"telemetry: {len(spans)} spans over phases {phases} -> {trace_path}")
    print(f"telemetry: prometheus scrape (per-tenant tmtpu_serve_* gauges) -> {prom_path}")


if __name__ == "__main__":
    main()
