"""Retrieval metrics vs per-query numpy/sklearn oracles.

Parity model: reference ``tests/unittests/retrieval/`` — every metric is the
aggregation over query groups of a single-query reference function.
"""
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, ndcg_score, roc_auc_score

import jax.numpy as jnp

from torchmetrics_tpu.functional.retrieval import (
    retrieval_auroc,
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_precision_recall_curve,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)
from torchmetrics_tpu.retrieval import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)

rng = np.random.RandomState(7)
N = 256
INDEXES = rng.randint(0, 20, size=N)
PREDS = rng.rand(N).astype(np.float32)
TARGET = (rng.rand(N) > 0.6).astype(np.int64)
GRADED = rng.randint(0, 4, size=N)


# ---------------- single-query numpy oracles ----------------
def np_ap(preds, target, top_k=None):
    k = top_k or len(preds)
    order = np.argsort(-preds, kind="stable")[:k]
    t = target[order]
    if t.sum() == 0:
        return 0.0
    prec = np.cumsum(t) / np.arange(1, len(t) + 1)
    return float((prec * t).sum() / t.sum())


def np_mrr(preds, target, top_k=None):
    k = top_k or len(preds)
    t = target[np.argsort(-preds, kind="stable")[:k]]
    pos = np.nonzero(t)[0]
    return float(1.0 / (pos[0] + 1)) if len(pos) else 0.0


def np_precision(preds, target, top_k=None, adaptive_k=False):
    n = len(preds)
    k = top_k or n
    if adaptive_k or top_k is None:
        k_eff = min(k, n)
    else:
        k_eff = k
    t = target[np.argsort(-preds, kind="stable")[: min(k, n)]]
    return float(t.sum() / k_eff)


def np_recall(preds, target, top_k=None):
    k = top_k or len(preds)
    if target.sum() == 0:
        return 0.0
    t = target[np.argsort(-preds, kind="stable")[:k]]
    return float(t.sum() / target.sum())


def np_fall_out(preds, target, top_k=None):
    k = top_k or len(preds)
    neg = 1 - target
    if neg.sum() == 0:
        return 0.0
    t = neg[np.argsort(-preds, kind="stable")[:k]]
    return float(t.sum() / neg.sum())


def np_hit_rate(preds, target, top_k=None):
    k = top_k or len(preds)
    t = target[np.argsort(-preds, kind="stable")[:k]]
    return float(t.sum() > 0)


def np_r_precision(preds, target):
    r = int(target.sum())
    if r == 0:
        return 0.0
    t = target[np.argsort(-preds, kind="stable")[:r]]
    return float(t.sum() / r)


def np_ndcg(preds, target, top_k=None):
    k = top_k or len(preds)
    if target.sum() == 0:
        return 0.0
    return float(ndcg_score(target[None].astype(float), preds[None].astype(float), k=k))


def np_auroc(preds, target, top_k=None, max_fpr=None):
    k = top_k or len(preds)
    order = np.argsort(-preds, kind="stable")[:k]
    t, p = target[order], preds[order]
    if len(np.unique(t)) < 2:
        return 0.0
    return float(roc_auc_score(t, p, max_fpr=max_fpr))


FUNCTIONAL_CASES = [
    (retrieval_average_precision, np_ap, {}),
    (retrieval_average_precision, np_ap, {"top_k": 3}),
    (retrieval_reciprocal_rank, np_mrr, {}),
    (retrieval_reciprocal_rank, np_mrr, {"top_k": 2}),
    (retrieval_precision, np_precision, {}),
    (retrieval_precision, np_precision, {"top_k": 4}),
    (retrieval_precision, np_precision, {"top_k": 100, "adaptive_k": True}),
    (retrieval_recall, np_recall, {}),
    (retrieval_recall, np_recall, {"top_k": 3}),
    (retrieval_fall_out, np_fall_out, {"top_k": 3}),
    (retrieval_hit_rate, np_hit_rate, {"top_k": 2}),
    (retrieval_r_precision, np_r_precision, {}),
    (retrieval_auroc, np_auroc, {}),
    (retrieval_auroc, np_auroc, {"top_k": 8}),
    (retrieval_auroc, np_auroc, {"max_fpr": 0.5}),
]


@pytest.mark.parametrize(("fn", "oracle", "kwargs"), FUNCTIONAL_CASES)
def test_functional_single_query(fn, oracle, kwargs):
    for q in range(12):
        sl = INDEXES == q
        p, t = PREDS[sl], TARGET[sl]
        if len(p) == 0:
            continue
        res = float(fn(jnp.asarray(p), jnp.asarray(t), **kwargs))
        ref = oracle(p, t, **kwargs)
        np.testing.assert_allclose(res, ref, atol=1e-5, err_msg=f"{fn.__name__} {kwargs}")


def test_functional_ndcg_binary_and_graded():
    for tgt in (TARGET, GRADED):
        for q in range(10):
            sl = INDEXES == q
            p, t = PREDS[sl], tgt[sl]
            if len(p) < 2 or t.sum() == 0:
                continue
            res = float(retrieval_normalized_dcg(jnp.asarray(p), jnp.asarray(t)))
            np.testing.assert_allclose(res, np_ndcg(p, t), atol=1e-4)
            res_k = float(retrieval_normalized_dcg(jnp.asarray(p), jnp.asarray(t), top_k=3))
            np.testing.assert_allclose(res_k, np_ndcg(p, t, top_k=3), atol=1e-4)


def test_functional_precision_recall_curve():
    p, t = PREDS[:16], TARGET[:16]
    prec, rec, ks = retrieval_precision_recall_curve(jnp.asarray(p), jnp.asarray(t), max_k=5)
    assert prec.shape == (5,) and rec.shape == (5,) and list(np.asarray(ks)) == [1, 2, 3, 4, 5]
    for k in range(1, 6):
        np.testing.assert_allclose(float(prec[k - 1]), np_precision(p, t, top_k=k), atol=1e-5)
        np.testing.assert_allclose(float(rec[k - 1]), np_recall(p, t, top_k=k), atol=1e-5)


CLASS_CASES = [
    (RetrievalMAP, np_ap, {}),
    (RetrievalMRR, np_mrr, {}),
    (RetrievalPrecision, np_precision, {"top_k": 3}),
    (RetrievalRecall, np_recall, {"top_k": 3}),
    (RetrievalHitRate, np_hit_rate, {"top_k": 2}),
    (RetrievalRPrecision, np_r_precision, {}),
    (RetrievalNormalizedDCG, np_ndcg, {}),
    (RetrievalAUROC, np_auroc, {}),
]


def _class_oracle(oracle, empty_action="neg", agg="mean", inverted_empty=False, **kwargs):
    scores = []
    for q in np.unique(INDEXES):
        sl = INDEXES == q
        p, t = PREDS[sl], TARGET[sl]
        empty = (1 - t).sum() == 0 if inverted_empty else t.sum() == 0
        if empty:
            if empty_action == "neg":
                scores.append(0.0)
            elif empty_action == "pos":
                scores.append(1.0)
            continue
        scores.append(oracle(p, t, **kwargs))
    if not scores:
        return 0.0
    if agg == "mean":
        return float(np.mean(scores))
    if agg == "median":
        return float(np.median(scores))
    if agg == "max":
        return float(np.max(scores))
    return float(np.min(scores))


@pytest.mark.parametrize(("cls", "oracle", "kwargs"), CLASS_CASES)
def test_class_accumulate(cls, oracle, kwargs):
    metric = cls(**kwargs)
    for i in range(4):
        sl = slice(i * (N // 4), (i + 1) * (N // 4))
        metric.update(jnp.asarray(PREDS[sl]), jnp.asarray(TARGET[sl]), jnp.asarray(INDEXES[sl]))
    res = float(metric.compute())
    ref = _class_oracle(oracle, **kwargs)
    np.testing.assert_allclose(res, ref, atol=1e-5, err_msg=cls.__name__)


def test_class_fall_out():
    metric = RetrievalFallOut(top_k=3)
    metric.update(jnp.asarray(PREDS), jnp.asarray(TARGET), jnp.asarray(INDEXES))
    res = float(metric.compute())
    ref = _class_oracle(np_fall_out, empty_action="pos", inverted_empty=True, top_k=3)
    np.testing.assert_allclose(res, ref, atol=1e-5)


@pytest.mark.parametrize("agg", ["mean", "median", "min", "max"])
def test_aggregation_modes(agg):
    metric = RetrievalMAP(aggregation=agg)
    metric.update(jnp.asarray(PREDS), jnp.asarray(TARGET), jnp.asarray(INDEXES))
    res = float(metric.compute())
    ref = _class_oracle(np_ap, agg=agg)
    np.testing.assert_allclose(res, ref, atol=1e-5)


@pytest.mark.parametrize("action", ["neg", "pos", "skip"])
def test_empty_target_actions(action):
    idx = np.array([0, 0, 1, 1])
    preds = np.array([0.3, 0.6, 0.2, 0.1], dtype=np.float32)
    tgt = np.array([1, 0, 0, 0])  # query 1 has no positives
    metric = RetrievalMAP(empty_target_action=action)
    metric.update(jnp.asarray(preds), jnp.asarray(tgt), jnp.asarray(idx))
    res = float(metric.compute())
    q0 = np_ap(preds[:2], tgt[:2])
    expected = {"neg": (q0 + 0.0) / 2, "pos": (q0 + 1.0) / 2, "skip": q0}[action]
    np.testing.assert_allclose(res, expected, atol=1e-5)


def test_empty_target_error():
    metric = RetrievalMAP(empty_target_action="error")
    metric.update(jnp.asarray([0.3, 0.6]), jnp.asarray([0, 0]), jnp.asarray([0, 0]))
    with pytest.raises(ValueError, match="no positive target"):
        metric.compute()


def test_ignore_index():
    idx = np.array([0, 0, 0, 0])
    preds = np.array([0.9, 0.6, 0.3, 0.1], dtype=np.float32)
    tgt = np.array([1, -1, 0, 1])
    metric = RetrievalMAP(ignore_index=-1)
    metric.update(jnp.asarray(preds), jnp.asarray(tgt), jnp.asarray(idx))
    keep = tgt != -1
    ref = np_ap(preds[keep], tgt[keep])
    np.testing.assert_allclose(float(metric.compute()), ref, atol=1e-5)


@pytest.mark.parametrize("dtype", [np.int16, np.int32, np.uint32, np.int64])
def test_ignore_index_any_index_dtype(dtype):
    """Ignore masking must be collision-free for every index dtype — incl.
    ids outside int32 range (an id-space sentinel would wrap/merge them)."""
    from torchmetrics_tpu import RetrievalMRR

    if dtype == np.uint32:
        big = np.uint32(2**31)  # wraps to int32 min under an int32 cast
    elif dtype == np.int64:
        big = np.int64(2**40)  # outside int32 range entirely
    else:
        big = dtype(1)
    metric = RetrievalMRR(ignore_index=-1)
    metric.update(jnp.asarray([0.9, 0.2, 0.8, 0.3]), jnp.asarray([1, 0, -1, 1]),
                  indexes=jnp.asarray(np.asarray([0, 0, big, big], dtype)))
    # q0: first hit at rank 1; q_big: its only surviving row is relevant
    np.testing.assert_allclose(float(metric.compute()), 1.0, atol=1e-6)


def test_int32_min_id_is_a_real_query():
    """An id equal to int32 min is legitimate and must not be dropped
    (it used to collide with the ignore sentinel)."""
    from torchmetrics_tpu import RetrievalMRR

    sentinel_like = np.int32(np.iinfo(np.int32).min)
    metric = RetrievalMRR()
    metric.update(jnp.asarray([0.9, 0.2, 0.8, 0.3]), jnp.asarray([0, 1, 1, 0]),
                  indexes=jnp.asarray(np.asarray([sentinel_like, sentinel_like, 0, 0], np.int32)))
    # both queries present: MRR = (1/2 + 1) / 2
    np.testing.assert_allclose(float(metric.compute()), 0.75, atol=1e-6)


def test_negative_query_ids_supported():
    """Real negative ids are legitimate (reference `_flexible_bincount`
    shifts by `x.min()`); only the sentinel row is dropped."""
    from torchmetrics_tpu import RetrievalMRR

    metric = RetrievalMRR()
    metric.update(jnp.asarray([0.9, 0.2, 0.8, 0.3]), jnp.asarray([0, 1, 1, 0]),
                  indexes=jnp.asarray([-1, -1, 0, 0]))
    np.testing.assert_allclose(float(metric.compute()), 0.75, atol=1e-6)


def test_all_rows_ignored_returns_zero():
    from torchmetrics_tpu import RetrievalMAP, RetrievalPrecisionRecallCurve

    m = RetrievalMAP(ignore_index=0)
    m.update(jnp.asarray([0.5, 0.3]), jnp.asarray([0, 0]), jnp.asarray([0, 1]))
    assert float(m.compute()) == 0.0
    c = RetrievalPrecisionRecallCurve(max_k=2, ignore_index=0)
    c.update(jnp.asarray([0.5, 0.3]), jnp.asarray([0, 0]), jnp.asarray([0, 1]))
    prec, rec, ks = c.compute()
    assert np.all(np.asarray(prec) == 0.0) and np.all(np.asarray(rec) == 0.0)
    assert list(np.asarray(ks)) == [1, 2]


def test_pr_curve_class_and_recall_at_fixed_precision():
    m = RetrievalPrecisionRecallCurve(max_k=4)
    m.update(jnp.asarray(PREDS), jnp.asarray(TARGET), jnp.asarray(INDEXES))
    prec, rec, ks = m.compute()
    assert prec.shape == (4,) and rec.shape == (4,)
    # oracle: average per-query precision/recall at each k
    for k in range(1, 5):
        ref_p = _class_oracle(np_precision, top_k=k)
        ref_r = _class_oracle(np_recall, top_k=k)
        np.testing.assert_allclose(float(prec[k - 1]), ref_p, atol=1e-5)
        np.testing.assert_allclose(float(rec[k - 1]), ref_r, atol=1e-5)

    r = RetrievalRecallAtFixedPrecision(min_precision=0.3, max_k=4)
    r.update(jnp.asarray(PREDS), jnp.asarray(TARGET), jnp.asarray(INDEXES))
    max_recall, best_k = r.compute()
    precs = [float(prec[i]) for i in range(4)]
    recs = [float(rec[i]) for i in range(4)]
    valid = [(rc, k + 1) for k, (pc, rc) in enumerate(zip(precs, recs)) if pc >= 0.3]
    if valid:
        ref_recall, ref_k = max(valid)
        np.testing.assert_allclose(float(max_recall), ref_recall, atol=1e-5)
        assert int(best_k) == ref_k


def test_forward_and_reset():
    metric = RetrievalMAP()
    val = metric(jnp.asarray(PREDS[:32]), jnp.asarray(TARGET[:32]), jnp.asarray(INDEXES[:32]))
    assert np.isfinite(float(val))
    metric.reset()
    assert metric.metric_state["preds"] == []


def test_ddp_merge_states():
    full = RetrievalMAP()
    full.update(jnp.asarray(PREDS), jnp.asarray(TARGET), jnp.asarray(INDEXES))
    ref = float(full.compute())

    r0, r1 = RetrievalMAP(), RetrievalMAP()
    r0.update(jnp.asarray(PREDS[: N // 2]), jnp.asarray(TARGET[: N // 2]), jnp.asarray(INDEXES[: N // 2]))
    r1.update(jnp.asarray(PREDS[N // 2 :]), jnp.asarray(TARGET[N // 2 :]), jnp.asarray(INDEXES[N // 2 :]))
    merged = r0.merge_states([r0.metric_state, r1.metric_state])
    res = float(r0.compute_state(merged))
    np.testing.assert_allclose(res, ref, atol=1e-5)
