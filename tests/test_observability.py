"""Unified telemetry subsystem (``torchmetrics_tpu.observability``).

Covers the registry (typed instruments + the CounterGroup facade the
migrated counter islands mutate through), span tracing (disabled-by-default
null path, nesting, the full metric lifecycle, elastic chaos rounds), the
exporters (Perfetto trace_event JSON, Prometheus text format, JSONL event
log), the backward-compat contract of ``executable_cache_stats()``, the
all-island ``reset_cache_stats()`` regression, and the ``strict_mode()``
span report.
"""
import json

import numpy as np
import pytest

import jax.numpy as jnp

import torchmetrics_tpu as tm
import torchmetrics_tpu.metric as M
from torchmetrics_tpu.classification import BinaryAccuracy
from torchmetrics_tpu.debug import StrictModeViolation, strict_mode
from torchmetrics_tpu.observability import (
    Counter,
    Gauge,
    Histogram,
    JsonlEventLog,
    Registry,
    to_perfetto,
    to_prometheus,
    write_perfetto,
)
from torchmetrics_tpu.observability import spans as spans_mod
from torchmetrics_tpu.online import _ONLINE_STATS
from torchmetrics_tpu.parallel import (
    ChaosSchedule,
    ElasticSync,
    SyncPolicy,
    chaos_group,
)
from torchmetrics_tpu.parallel.elastic import _ELASTIC
from torchmetrics_tpu.parallel.strategies import _WIRE, record_collective


@pytest.fixture(autouse=True)
def _clean_tracing():
    spans_mod.disable_tracing()
    spans_mod.clear_spans()
    yield
    spans_mod.disable_tracing()
    spans_mod.clear_spans()


# ------------------------------------------------------------------ registry
def test_counter_inc_and_labels():
    reg = Registry()
    c = reg.counter("req.total", "requests")
    c.inc()
    c.inc(2)
    c.inc(5, route="sync")
    assert c.get() == 3
    assert c.get(route="sync") == 5
    assert c.value == 8
    c.reset()
    assert c.value == 0


def test_gauge_last_written_wins():
    reg = Registry()
    g = reg.gauge("coverage")
    g.set(0.5)
    g.set(0.75)
    assert g.value == 0.75


def test_histogram_buckets_and_snapshot():
    reg = Registry()
    h = reg.histogram("dur", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.005, 0.05):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(0.0605)
    ((labels, counts, total_sum, total),) = h.collect()
    assert labels == ()
    assert counts == [1, 2, 1]
    assert total == 4


def test_registry_get_or_create_idempotent_and_kind_clash():
    reg = Registry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_counter_group_keeps_dict_idiom():
    reg = Registry()
    grp = reg.group("island", {"hits": 0, "misses": 0})
    grp["hits"] += 3  # the historical hot-path mutation idiom
    grp["misses"] = 2
    assert dict(grp) == {"hits": 3, "misses": 2}
    assert isinstance(grp["hits"], int)
    assert reg.get("island.hits").value == 3  # registry-visible
    grp["novel"] = 7  # unknown keys register on first write
    assert reg.get("island.novel").value == 7
    grp.reset()
    assert dict(grp) == {"hits": 0, "misses": 0, "novel": 0}
    with pytest.raises(TypeError):
        del grp["hits"]


def test_registry_prefix_reset_and_as_dict():
    reg = Registry()
    reg.counter("a.x").inc(4)
    reg.counter("b.y").inc(9)
    assert reg.as_dict("a") == {"a.x": 4}
    reg.reset("a")
    assert reg.get("a.x").value == 0
    assert reg.get("b.y").value == 9


# -------------------------------------------------------------------- spans
def test_tracing_disabled_by_default_returns_null_span():
    assert spans_mod.ENABLED is False
    sp = spans_mod.trace_span("anything", k=1)
    assert sp is spans_mod._NULL_SPAN
    with sp:
        pass
    spans_mod.instant("nothing")
    assert spans_mod.collected_spans() == []


def test_span_nesting_and_attrs():
    with spans_mod.tracing():
        with spans_mod.trace_span("outer", a=1) as outer:
            with spans_mod.trace_span("inner") as inner:
                inner.set_attr(b=2)
        spans = spans_mod.collected_spans()
    by_name = {s.name: s for s in spans}
    assert by_name["inner"].parent_id == by_name["outer"].span_id
    assert by_name["outer"].attrs == {"a": 1}
    assert by_name["inner"].attrs == {"b": 2}
    assert by_name["outer"].duration_s >= by_name["inner"].duration_s


def test_span_records_error_attr():
    with spans_mod.tracing():
        with pytest.raises(RuntimeError):
            with spans_mod.trace_span("boom"):
                raise RuntimeError("x")
        (sp,) = spans_mod.collected_spans()
    assert sp.attrs["error"] == "RuntimeError"


def test_traced_decorator_and_phase_totals():
    @spans_mod.traced("my.phase")
    def f(x):
        return x + 1

    assert f(1) == 2  # disabled: plain call
    with spans_mod.tracing():
        f(1)
        f(2)
        totals = spans_mod.phase_totals()
    assert totals["my.phase"]["count"] == 2
    assert totals["my.phase"]["total_s"] >= totals["my.phase"]["max_s"]


def test_tracing_context_restores_state_and_drain():
    with spans_mod.tracing():
        with spans_mod.trace_span("a"):
            pass
    assert spans_mod.ENABLED is False
    assert len(spans_mod.drain_spans()) == 1
    assert spans_mod.collected_spans() == []


# -------------------------------------------------- metric lifecycle spans
def test_metric_lifecycle_spans():
    m = tm.MeanMetric()
    x = jnp.ones((8,))
    m.update(x)  # warm outside tracing
    with spans_mod.tracing():
        m.update(x)
        float(m.compute())
        names = [s.name for s in spans_mod.collected_spans()]
    assert "metric.update" in names
    assert "metric.compute" in names
    upd = next(s for s in spans_mod.drain_spans() if s.name == "metric.update")
    assert upd.attrs.get("metric") == "MeanMetric"


def test_collective_instants_carry_wire_model():
    with spans_mod.tracing():
        record_collective("psum", 1024, 4, dtype=jnp.float32)
        (sp,) = spans_mod.collected_spans()
    assert sp.name == "collective"
    assert sp.attrs["kind"] == "psum"
    assert sp.attrs["bytes"] == 1024
    assert sp.attrs["world"] == 4
    assert sp.attrs["wire_bytes"] == 2 * 3 * 1024 // 4  # ring 2(n-1)S/n
    assert "float32" in sp.attrs["dtype"]


# ------------------------------------------------------ elastic chaos spans
FAST = SyncPolicy(retry_attempts=2, backoff_base_s=0.001)


def _ranked_accuracy(world, seed=0, batches=2, n=32):
    rng = np.random.RandomState(seed)
    ms = [BinaryAccuracy(validate_args=False) for _ in range(world)]
    for m in ms:
        for _ in range(batches):
            p = jnp.asarray(rng.rand(n).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 2, n))
            m.update(p, t)
    return ms, [m.metric_state for m in ms]


def test_chaos_degrade_round_visible_as_nested_spans():
    # the ISSUE acceptance criterion: a seeded timeout -> retry -> degrade
    # round shows up as an elastic.round span with coverage attrs and
    # probe/attempt/backoff children plus a degrade instant
    world = 2
    ms, group = _ranked_accuracy(world)
    backs = chaos_group(group, ChaosSchedule({0: [("timeout", 10)]}))
    ms[0]._sync_backend = ElasticSync(backs[0], policy=FAST)
    backs[0].advance_round()
    with spans_mod.tracing():
        float(ms[0].compute())
        spans = spans_mod.collected_spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    (round_sp,) = by_name["elastic.round"]
    assert round_sp.attrs["degraded"] is True
    assert round_sp.attrs["coverage"] == 0.5
    assert round_sp.attrs["ranks_present"] == 1
    assert round_sp.attrs["ranks_expected"] == world
    # children nest under the round span
    (probe,) = by_name["elastic.probe"]
    assert probe.parent_id == round_sp.span_id
    # attempts nest under the round directly, or under the probe (the probe
    # gather is itself retry-guarded) — the probe in turn nests in the round
    attempts = by_name["elastic.attempt"]
    assert attempts and all(
        a.parent_id in (round_sp.span_id, probe.span_id) for a in attempts
    )
    assert any(a.attrs.get("timeout") for a in attempts)
    assert by_name["elastic.backoff"]
    assert by_name["elastic.degrade"]  # budget-exhaustion instant
    # the round itself nests under the metric.sync phase
    (sync_sp,) = by_name["metric.sync"]
    assert round_sp.parent_id == sync_sp.span_id


# ---------------------------------------------------------------- exporters
def test_perfetto_export_structure():
    with spans_mod.tracing():
        with spans_mod.trace_span("phase.a", k="v"):
            pass
        spans_mod.instant("tick", n=1)
        spans = spans_mod.collected_spans()
    doc = to_perfetto(spans)
    events = doc["traceEvents"]
    assert any(e["ph"] == "M" and e["name"] == "process_name" for e in events)
    (x,) = [e for e in events if e["ph"] == "X"]
    assert x["name"] == "phase.a" and x["dur"] >= 0 and x["args"]["k"] == "v"
    (i,) = [e for e in events if e["ph"] == "i"]
    assert i["name"] == "tick" and i["args"]["n"] == 1


def test_write_perfetto_roundtrips(tmp_path):
    with spans_mod.tracing():
        with spans_mod.trace_span("p"):
            pass
        path = tmp_path / "trace.json"
        write_perfetto(str(path), spans_mod.collected_spans())
    doc = json.loads(path.read_text())
    assert any(e.get("name") == "p" for e in doc["traceEvents"])


def test_prometheus_text_format():
    reg = Registry()
    reg.counter("req.total", "total requests").inc(3, route="sync")
    reg.gauge("cov").set(0.5)
    h = reg.histogram("lat", buckets=(0.01, 0.1))
    h.observe(0.005)
    h.observe(0.05)
    text = to_prometheus(reg, prefix="t")
    assert "# TYPE t_req_total counter" in text
    assert 't_req_total{route="sync"} 3' in text
    assert "t_cov 0.5" in text
    # cumulative buckets + +Inf + _sum/_count
    assert 't_lat_bucket{le="0.01"} 1' in text
    assert 't_lat_bucket{le="0.1"} 2' in text
    assert 't_lat_bucket{le="+Inf"} 2' in text
    assert "t_lat_count 2" in text


def test_jsonl_event_log_skips_partial_trailing_line(tmp_path):
    path = tmp_path / "events.jsonl"
    with JsonlEventLog(str(path)) as log:
        log.write({"kind": "a", "n": 1})
        log.write({"kind": "b", "n": 2})
    # simulate a preemption mid-write: a torn trailing record
    with open(path, "a") as fh:
        fh.write('{"kind": "c", "n":')
    records = JsonlEventLog.read(str(path))
    assert [r["kind"] for r in records] == ["a", "b"]


def test_prometheus_escapes_label_values():
    reg = Registry()
    reg.counter("weird", "w").inc(1, path='C:\\tmp\\"x"\nnext')
    text = to_prometheus(reg, prefix="t")
    (sample,) = [l for l in text.splitlines() if l.startswith("t_weird{")]
    # backslash, double-quote and newline escaped per the exposition format
    assert sample == 't_weird{path="C:\\\\tmp\\\\\\"x\\"\\nnext"} 1'


def test_prometheus_zero_observation_histogram_is_valid():
    reg = Registry()
    reg.histogram("lat", "never observed", buckets=(0.01, 0.1))
    text = to_prometheus(reg, prefix="t")
    # a registered-but-empty histogram still emits a complete series
    assert 't_lat_bucket{le="0.01"} 0' in text
    assert 't_lat_bucket{le="+Inf"} 0' in text
    assert "t_lat_sum 0" in text
    assert "t_lat_count 0" in text
    # every sample line parses as <name>{...} <value>
    for line in text.splitlines():
        if not line.startswith("#"):
            assert line.rsplit(" ", 1)[1] == "0"


def test_jsonl_rotation_at_cap_boundary(tmp_path):
    path = tmp_path / "serve.jsonl"
    line_len = len(json.dumps({"i": 0, "pad": "x" * 16})) + 1
    cap = int(3.5 * line_len)  # 4th record would cross the cap -> rotates
    log = JsonlEventLog(str(path), max_bytes=cap)
    for i in range(5):
        log.write({"i": i, "pad": "x" * 16})
    log.close()
    assert (tmp_path / "serve.jsonl.1").exists()
    # records are never split across the boundary: every line in both
    # generations parses whole, and the logical order is preserved
    assert path.stat().st_size <= cap
    records = JsonlEventLog.read(str(path))
    assert [r["i"] for r in records] == [0, 1, 2, 3, 4]
    # current file alone holds only the post-rotation records
    assert [r["i"] for r in JsonlEventLog.read(str(path), include_rotated=False)] == [3, 4]


def test_jsonl_rotation_preserves_torn_line_recovery(tmp_path):
    path = tmp_path / "serve.jsonl"
    log = JsonlEventLog(str(path), max_bytes=60)
    log.write({"i": 0})
    log.close()
    # preemption tears the trailing line of the active file...
    with open(path, "a") as fh:
        fh.write('{"i": 1, "torn')
    # ...then a restarted writer's next record pushes past the cap and
    # rotates; the torn line rides into the backup generation
    log2 = JsonlEventLog(str(path), max_bytes=60)
    log2.write({"i": 2, "pad": "y" * 40})
    log2.close()
    records = JsonlEventLog.read(str(path))
    assert [r["i"] for r in records] == [0, 2]  # torn line skipped, not merged


def test_histogram_reset_labels_is_scoped():
    reg = Registry()
    h = reg.histogram("shared", buckets=(1.0, 10.0))
    h.observe(0.5, owner="a", phase="x")
    h.observe(0.5, owner="a", phase="y")
    h.observe(0.5, owner="b", phase="x")
    h.reset_labels(owner="a")  # drops every label set containing owner=a
    assert h.snapshot(owner="a", phase="x")["count"] == 0
    assert h.snapshot(owner="a", phase="y")["count"] == 0
    assert h.snapshot(owner="b", phase="x")["count"] == 1


# ------------------------------------------------- StepTimer compat facade
def test_steptimer_facade_keeps_summary_shape():
    # regression for the PR-8-era timing island: StepTimer now stores into
    # the registry histogram but its public surface must not move
    from torchmetrics_tpu.observability.registry import REGISTRY
    from torchmetrics_tpu.utils.profiler import StepTimer

    t = StepTimer(block_until_ready=False)
    with t.phase("update"):
        pass
    with t.phase("update"):
        with t.phase("sync"):  # reentrant nesting still works
            pass
    s = t.summary()
    assert set(s) == {"update", "sync"}
    assert set(s["update"]) == {"total_s", "count", "mean_ms"}
    assert s["update"]["count"] == 2 and s["sync"]["count"] == 1
    assert s["update"]["mean_ms"] == pytest.approx(
        1000.0 * s["update"]["total_s"] / 2
    )
    # the numbers live in the shared registry histogram, per-timer labelled
    hist = REGISTRY.get("profiler.phase_s")
    assert hist.snapshot(timer=t._id, phase="update")["count"] == 2
    # instances are isolated: a second timer neither sees nor clears the first
    t2 = StepTimer(block_until_ready=False)
    with t2.phase("update"):
        pass
    assert t2.summary()["update"]["count"] == 1
    t2.reset()
    assert t2.summary() == {}
    assert t.summary()["update"]["count"] == 2


def test_steptimer_records_time_when_body_raises():
    from torchmetrics_tpu.utils.profiler import StepTimer

    t = StepTimer(block_until_ready=False)
    with pytest.raises(RuntimeError):
        with t.phase("boom"):
            raise RuntimeError("x")
    assert t.summary()["boom"]["count"] == 1


def test_steptimer_emits_spans_when_tracing_armed():
    from torchmetrics_tpu.utils.profiler import StepTimer

    t = StepTimer(block_until_ready=False)
    with spans_mod.tracing():
        with t.phase("step"):
            pass
        names = [s.name for s in spans_mod.collected_spans()]
    assert "profiler.step" in names


# --------------------------------------------- compat + reset regression
EXPECTED_CACHE_STATS_KEYS = {
    "size", "hits", "misses", "compiles", "retraces", "dispatches",
    "bytes_reduced", "bytes_gathered", "collectives_issued", "syncs",
    "sync_retries", "sync_timeouts", "degraded_syncs", "coverage", "online",
    "ledger",
}
EXPECTED_ONLINE_KEYS = {
    "windowed_metrics", "decayed_metrics", "windowed_updates",
    "decayed_updates", "window_rotations",
}


def test_executable_cache_stats_backward_compat_keys():
    # every pre-registry key must survive the registry-backed rewrite, with
    # plain-int values (json-serializable, comparable with == as before)
    stats = M.executable_cache_stats()
    assert set(stats) == EXPECTED_CACHE_STATS_KEYS
    assert set(stats["online"]) == EXPECTED_ONLINE_KEYS
    for key, value in stats.items():
        if key == "coverage":
            assert value is None or isinstance(value, dict)
        elif key == "online":
            assert all(isinstance(v, int) for v in value.values())
        elif key == "ledger":
            assert isinstance(value, dict)
            assert {"enabled", "entries", "flops_total"} <= set(value)
        else:
            assert isinstance(value, int), (key, type(value))
    json.dumps(stats)  # stays serializable


def test_executable_cache_stats_tracks_real_traffic():
    M.reset_cache_stats()
    m = tm.SumMetric()
    m.update(jnp.ones((4,)))
    m.update(jnp.ones((4,)))
    stats = M.executable_cache_stats()
    assert stats["dispatches"] >= 2
    assert stats["compiles"] >= 1


def test_reset_cache_stats_zeroes_every_island():
    # regression: the historical reset only touched the cache island and
    # left wire/elastic/online counters running
    from torchmetrics_tpu.observability import ledger as ledger_mod

    M._CACHE_STATS["hits"] += 1
    record_collective("psum", 512, 2)
    _ELASTIC["retries"] += 3
    _ONLINE_STATS["windowed_updates"] += 5
    with ledger_mod.ledger_observing():
        # a shape no other test dispatches -> guaranteed fresh XLA compile,
        # so the ledger records an entry regardless of test ordering
        tm.MeanMetric().update(jnp.ones((7, 3, 2)))
    stats = M.executable_cache_stats()
    assert stats["bytes_reduced"] > 0
    assert stats["sync_retries"] == 3
    assert stats["online"]["windowed_updates"] == 5
    assert stats["ledger"]["entries"] >= 1
    M.reset_cache_stats()
    stats = M.executable_cache_stats()
    assert stats["hits"] == 0
    assert stats["bytes_reduced"] == 0 and stats["collectives_issued"] == 0
    assert stats["sync_retries"] == 0
    assert stats["online"]["windowed_updates"] == 0
    assert stats["ledger"]["entries"] == 0  # the ledger island resets too
    assert ledger_mod.executable_ledger() == []
    assert dict(_WIRE) == {k: 0 for k in _WIRE}
    assert all(v == 0 for v in dict(_ELASTIC).values())


# --------------------------------------------------- strict_mode span report
def test_strict_mode_fills_span_report_fields():
    m = tm.MeanMetric()
    x = jnp.ones((8,))
    m.update(x)  # warm
    with spans_mod.tracing():
        with strict_mode(transfer_guard=None) as stats:
            m.update(x)
    assert "metric.update" in stats.span_phase_totals
    assert stats.span_phase_totals["metric.update"]["count"] == 1
    assert 1 <= len(stats.slowest_spans) <= 3
    name, dur = stats.slowest_spans[0]
    assert isinstance(name, str) and dur >= 0


def test_strict_mode_violation_names_span_phases():
    m = tm.MeanMetric()
    x = jnp.ones((8,))
    m.update(x)  # warm
    with spans_mod.tracing():
        with pytest.raises(StrictModeViolation) as ei:
            with strict_mode(transfer_guard=None, max_new_executables=0):
                m.update(x)  # warm: completes, leaves a span
                tm.MaxMetric().update(x)  # fresh compile: violation
    assert "span phases" in str(ei.value)
    assert "metric.update" in str(ei.value)


def test_strict_mode_report_empty_when_tracing_off():
    m = tm.MeanMetric()
    x = jnp.ones((8,))
    m.update(x)
    with strict_mode(transfer_guard=None) as stats:
        m.update(x)
    assert stats.span_phase_totals == {}
    assert stats.slowest_spans == []
