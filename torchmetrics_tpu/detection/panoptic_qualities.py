"""PanopticQuality and ModifiedPanopticQuality metric classes.

Parity target: reference ``detection/panoptic_qualities.py`` (401 LoC) —
fixed ``(num_categories,)`` sum states (``:114-117``), update over
``(..., H, W, 2)`` color maps, scalar PQ compute.
"""
from typing import Any, Collection

import jax.numpy as jnp
import numpy as np

from ..functional.detection.panoptic_quality import (
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _validate_inputs,
)
from ..metric import Metric


class PanopticQuality(Metric):
    """Panoptic Quality for panoptic segmentations (things + stuffs).

    Parity: reference ``detection/panoptic_qualities.py:30``. Inputs are
    integer color maps ``(..., height, width, 2)`` where the last dimension
    holds ``(category_id, instance_id)``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import PanopticQuality
        >>> metric = PanopticQuality(things={0}, stuffs={1})
        >>> img = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])
        >>> metric.update(img[None], img[None])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    jittable = False  # segment discovery is host-side np.unique
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    _modified: bool = False

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.things, self.stuffs = _parse_categories(things, stuffs)
        self.allow_unknown_preds_category = allow_unknown_preds_category
        self._compute_jittable = False
        n_cat = len(self.things) + len(self.stuffs)
        self.add_state("iou_sum", jnp.zeros(n_cat, jnp.float32), dist_reduce_fx="sum")
        self.add_state("true_positives", jnp.zeros(n_cat, jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_positives", jnp.zeros(n_cat, jnp.int32), dist_reduce_fx="sum")
        self.add_state("false_negatives", jnp.zeros(n_cat, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Any, target: Any) -> None:
        preds = np.asarray(preds)
        target = np.asarray(target)
        _validate_inputs(preds, target)
        iou_sum, tp, fp, fn = _panoptic_quality_update(
            preds,
            target,
            self.things,
            self.stuffs,
            self.allow_unknown_preds_category,
            modified_stuffs=self.stuffs if self._modified else None,
        )
        self.iou_sum = self.iou_sum + jnp.asarray(iou_sum)
        self.true_positives = self.true_positives + jnp.asarray(tp, self.true_positives.dtype)
        self.false_positives = self.false_positives + jnp.asarray(fp, self.false_positives.dtype)
        self.false_negatives = self.false_negatives + jnp.asarray(fn, self.false_negatives.dtype)

    def compute(self) -> jnp.ndarray:
        return jnp.asarray(
            _panoptic_quality_compute(
                np.asarray(self.iou_sum),
                np.asarray(self.true_positives),
                np.asarray(self.false_positives),
                np.asarray(self.false_negatives),
            ),
            jnp.float32,
        )


class ModifiedPanopticQuality(PanopticQuality):
    """Modified PQ — stuff categories scored per-pixel (IoU > 0, one segment).

    Parity: reference ``detection/panoptic_qualities.py:275``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import ModifiedPanopticQuality
        >>> metric = ModifiedPanopticQuality(things={0}, stuffs={1})
        >>> img = jnp.asarray([[[0, 0], [0, 0], [1, 0]], [[0, 0], [1, 0], [1, 0]]])
        >>> metric.update(img[None], img[None])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    _modified = True
