"""Audio metrics (L4). Parity: reference ``src/torchmetrics/audio/``."""
from .metrics import (
    ComplexScaleInvariantSignalNoiseRatio,
    PerceptualEvaluationSpeechQuality,
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
    SourceAggregatedSignalDistortionRatio,
    SpeechReverberationModulationEnergyRatio,
)

__all__ = [
    "ComplexScaleInvariantSignalNoiseRatio",
    "PerceptualEvaluationSpeechQuality",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "ShortTimeObjectiveIntelligibility",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "SourceAggregatedSignalDistortionRatio",
    "SpeechReverberationModulationEnergyRatio",
]
