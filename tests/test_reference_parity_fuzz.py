"""Seeded randomized parity sweep: degenerate and adversarial inputs.

Deterministic "fuzz" against the reference on the input classes that break
naive implementations: all-tied scores, one-hot-saturated probabilities,
raw logits, targets missing a class entirely. This suite caught the
average-precision empty-class semantics divergence (exact mode excludes
nan classes from macro/weighted averages; binned mode includes them as 0 —
reference ``functional/classification/average_precision.py:56-66`` vs its
``_safe_divide`` binned recall).
"""
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))
from lightning_utilities_stub import install_stub  # noqa: E402

install_stub()
sys.path.insert(0, "/root/reference/src")
torch = pytest.importorskip("torch")

import torchmetrics.functional.classification as RFC  # noqa: E402

import torchmetrics_tpu.functional.classification as FC  # noqa: E402


def _case(trial):
    rng = np.random.RandomState(1000 + trial)
    n = int(rng.randint(4, 40))
    c = int(rng.randint(2, 7))
    kind = trial % 4
    if kind == 0:  # all-tied scores
        p = np.full((n, c), 1.0 / c, np.float32)
    elif kind == 1:  # saturated one-hot probs
        p = np.eye(c, dtype=np.float32)[rng.randint(0, c, n)]
    elif kind == 2:  # raw logits
        p = (rng.randn(n, c) * 5).astype(np.float32)
    else:  # a class absent from target
        p = rng.rand(n, c).astype(np.float32)
        p /= p.sum(-1, keepdims=True)
    t = rng.randint(0, max(1, c - (1 if kind == 3 else 0)), n)
    return p, t, c


@pytest.mark.parametrize("trial", range(12))
def test_fuzz_classification_families(trial):
    p, t, c = _case(trial)
    jp, jt = jnp.asarray(p), jnp.asarray(t)
    tp, tt = torch.tensor(p), torch.tensor(t)
    for avg in ("micro", "macro", "weighted", "none"):
        np.testing.assert_allclose(
            np.asarray(FC.multiclass_accuracy(jp, jt, num_classes=c, average=avg)),
            RFC.multiclass_accuracy(tp, tt, num_classes=c, average=avg).numpy(),
            atol=1e-5, equal_nan=True, err_msg=f"accuracy {avg}",
        )
    np.testing.assert_allclose(
        np.asarray(FC.multiclass_f1_score(jp, jt, num_classes=c, average="macro")),
        RFC.multiclass_f1_score(tp, tt, num_classes=c, average="macro").numpy(),
        atol=1e-5, equal_nan=True, err_msg="f1 macro",
    )
    np.testing.assert_allclose(
        np.asarray(FC.multiclass_auroc(jp, jt, num_classes=c)),
        RFC.multiclass_auroc(tp, tt, num_classes=c).numpy(),
        atol=1e-4, equal_nan=True, err_msg="auroc",
    )
    for thr in (None, 10):
        np.testing.assert_allclose(
            np.asarray(FC.multiclass_average_precision(jp, jt, num_classes=c, thresholds=thr)),
            RFC.multiclass_average_precision(tp, tt, num_classes=c, thresholds=thr).numpy(),
            atol=1e-4, equal_nan=True, err_msg=f"ap thr={thr}",
        )


def test_average_precision_empty_class_semantics():
    """Exact mode: nan per-class, excluded from macro; binned mode: 0,
    included — the reference's (asymmetric) behavior, mirrored exactly."""
    n, c = 6, 3
    p = np.full((n, c), 1.0 / c, np.float32)
    t = np.array([0, 0, 1, 1, 0, 0])  # class 2 absent
    jp, jt, tp, tt = jnp.asarray(p), jnp.asarray(t), torch.tensor(p), torch.tensor(t)
    for thr in (None, 10):
        for avg in ("none", "macro", "weighted"):
            np.testing.assert_allclose(
                np.asarray(FC.multiclass_average_precision(jp, jt, num_classes=c, average=avg, thresholds=thr)),
                RFC.multiclass_average_precision(tp, tt, num_classes=c, average=avg, thresholds=thr).numpy(),
                atol=1e-5, equal_nan=True, err_msg=f"thr={thr} avg={avg}",
            )
    # binary: exact nan / binned 0 with no positives
    zeros = np.zeros(n, np.int64)
    assert np.isnan(float(FC.binary_average_precision(jp[:, 0], jnp.asarray(zeros))))
    assert float(FC.binary_average_precision(jp[:, 0], jnp.asarray(zeros), thresholds=10)) == 0.0
    # class layer takes the same path
    from torchmetrics_tpu.classification import MulticlassAveragePrecision

    m = MulticlassAveragePrecision(num_classes=c)
    m.update(jp, jt)
    np.testing.assert_allclose(float(m.compute()), 0.5, atol=1e-6)


def test_multilabel_ap_empty_label():
    rng = np.random.RandomState(5)
    pl = rng.rand(12, 3).astype(np.float32)
    tl = np.random.RandomState(6).randint(0, 2, (12, 3))
    tl[:, 2] = 0  # label never positive
    for thr in (None, 10):
        np.testing.assert_allclose(
            np.asarray(FC.multilabel_average_precision(
                jnp.asarray(pl), jnp.asarray(tl), num_labels=3, average="macro", thresholds=thr)),
            RFC.multilabel_average_precision(
                torch.tensor(pl), torch.tensor(tl), num_labels=3, average="macro", thresholds=thr).numpy(),
            atol=1e-5, equal_nan=True, err_msg=f"thr={thr}",
        )


def test_ap_all_classes_empty():
    """Every class/label without positives: macro -> nan (reference's empty
    mean); weighted -> 0.0 (reference's empty weighted sum); micro class
    path -> nan."""
    p = np.random.RandomState(2).rand(8, 3).astype(np.float32)
    t = np.zeros((8, 3), np.int64)
    for avg in ("macro", "weighted"):
        ours = FC.multilabel_average_precision(
            jnp.asarray(p), jnp.asarray(t), num_labels=3, average=avg
        )
        ref = RFC.multilabel_average_precision(
            torch.tensor(p), torch.tensor(t), num_labels=3, average=avg
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-6, equal_nan=True, err_msg=avg)

    from torchmetrics_tpu.classification import MultilabelAveragePrecision

    m = MultilabelAveragePrecision(num_labels=3, average="micro")
    m.update(jnp.asarray(p), jnp.asarray(t))
    assert np.isnan(float(m.compute()))


def test_regression_degenerate_inputs():
    """Constant inputs, zero targets — the reference's epsilon-guard paths."""
    import torchmetrics.functional.regression as RFR

    import torchmetrics_tpu.functional.regression as FR

    const = np.full(10, 3.0, np.float32)
    var = np.arange(10, dtype=np.float32)
    cases = [
        ("pearson const-x", FR.pearson_corrcoef, RFR.pearson_corrcoef, (const, var)),
        ("spearman const", FR.spearman_corrcoef, RFR.spearman_corrcoef, (const, const)),
        ("r2 const-target", FR.r2_score, RFR.r2_score, (var, const)),
        ("r2 perfect-const", FR.r2_score, RFR.r2_score, (const, const)),
        ("explained_var const", FR.explained_variance, RFR.explained_variance, (var, const)),
        ("mape zero-target", FR.mean_absolute_percentage_error, RFR.mean_absolute_percentage_error,
         (var, np.zeros(10, np.float32))),
    ]
    for name, ours_fn, ref_fn, (a, b) in cases:
        np.testing.assert_allclose(
            np.asarray(ours_fn(jnp.asarray(a), jnp.asarray(b))),
            ref_fn(torch.tensor(a), torch.tensor(b)).numpy(),
            atol=1e-5, equal_nan=True, err_msg=name,
        )


def test_retrieval_all_negative_query():
    import torchmetrics.functional.retrieval as RFRet

    import torchmetrics_tpu.functional.retrieval as FRet

    p = np.array([0.9, 0.2, 0.4], np.float32)
    tneg = np.zeros(3, np.int64)
    for fn in ("retrieval_average_precision", "retrieval_reciprocal_rank", "retrieval_normalized_dcg",
               "retrieval_hit_rate", "retrieval_fall_out", "retrieval_r_precision"):
        np.testing.assert_allclose(
            np.asarray(getattr(FRet, fn)(jnp.asarray(p), jnp.asarray(tneg))),
            getattr(RFRet, fn)(torch.tensor(p), torch.tensor(tneg)).numpy(),
            atol=1e-6, equal_nan=True, err_msg=fn,
        )


def test_at_fixed_metrics_on_ties():
    pt = np.full(8, 0.5, np.float32)
    tt = np.array([0, 1, 0, 1, 0, 1, 0, 1])
    o = FC.binary_precision_at_fixed_recall(jnp.asarray(pt), jnp.asarray(tt), min_recall=0.5)
    r = RFC.binary_precision_at_fixed_recall(torch.tensor(pt), torch.tensor(tt), min_recall=0.5)
    np.testing.assert_allclose(np.asarray(o[0]), r[0].numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(o[1]), r[1].numpy(), atol=1e-6)
    o = FC.binary_recall_at_fixed_precision(jnp.asarray(pt), jnp.asarray(tt), min_precision=0.5)
    r = RFC.binary_recall_at_fixed_precision(torch.tensor(pt), torch.tensor(tt), min_precision=0.5)
    np.testing.assert_allclose(np.asarray(o[0]), r[0].numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(o[1]), r[1].numpy(), atol=1e-6)


def test_text_empty_strings():
    import torchmetrics.functional.text as RFT

    import torchmetrics_tpu.functional.text as FT

    np.testing.assert_allclose(
        np.asarray(FT.word_error_rate([""], ["hello world"])),
        RFT.word_error_rate([""], ["hello world"]).numpy(), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(FT.char_error_rate(["abc"], ["abc"])),
        RFT.char_error_rate(["abc"], ["abc"]).numpy(), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(FT.bleu_score([""], [["the cat"]])),
        RFT.bleu_score([""], [["the cat"]]).numpy(), atol=1e-6)


def test_multilabel_class_micro_paths():
    """Class-layer MultilabelAveragePrecision micro: exact + binned, with and
    without ignore_index — must match the reference class exactly."""
    from torchmetrics.classification import MultilabelAveragePrecision as RML

    from torchmetrics_tpu.classification import MultilabelAveragePrecision as OML

    rng = np.random.RandomState(4)
    p = rng.rand(16, 3).astype(np.float32)
    t = rng.randint(0, 2, (16, 3))
    t_ig = t.copy()
    t_ig[::5] = -1
    for thr in (None, 10):
        for ig, tt in ((None, t), (-1, t_ig)):
            ours = OML(num_labels=3, average="micro", thresholds=thr, ignore_index=ig)
            ours.update(jnp.asarray(p), jnp.asarray(tt))
            ref = RML(num_labels=3, average="micro", thresholds=thr, ignore_index=ig)
            ref.update(torch.tensor(p), torch.tensor(tt))
            np.testing.assert_allclose(
                float(ours.compute()), float(ref.compute()), atol=1e-5,
                err_msg=f"thr={thr} ignore_index={ig}",
            )


def test_mcc_degenerate_cases():
    """Binary +-1 shortcuts, eps-substituted zero-denominator cases, and
    absent-class multiclass/multilabel MCC (reference matthews_corrcoef.py:36-63)."""
    cases = [
        (np.array([1, 1, 1, 1]), np.array([1, 1, 1, 1])),  # perfect positives
        (np.array([0, 0, 0, 0]), np.array([0, 0, 0, 0])),  # perfect negatives
        (np.array([1, 1, 1, 1]), np.array([0, 0, 0, 0])),  # all wrong
        (np.array([0, 0, 1, 1]), np.array([0, 0, 0, 0])),  # no true positives
        (np.array([1, 1, 0, 0]), np.array([1, 1, 1, 1])),  # no true negatives
    ]
    for pr, tg in cases:
        np.testing.assert_allclose(
            np.asarray(FC.binary_matthews_corrcoef(jnp.asarray(pr.astype(np.float32)), jnp.asarray(tg))),
            RFC.binary_matthews_corrcoef(torch.tensor(pr.astype(np.float32)), torch.tensor(tg)).numpy(),
            atol=1e-5, equal_nan=True, err_msg=f"{pr} vs {tg}",
        )
    rng = np.random.RandomState(9)
    for _ in range(10):
        n, c = int(rng.randint(3, 30)), int(rng.randint(2, 6))
        p = rng.rand(n, c).astype(np.float32)
        p /= p.sum(-1, keepdims=True)
        t = rng.randint(0, max(1, c - 1), n)  # last class absent
        np.testing.assert_allclose(
            np.asarray(FC.multiclass_matthews_corrcoef(jnp.asarray(p), jnp.asarray(t), num_classes=c)),
            RFC.multiclass_matthews_corrcoef(torch.tensor(p), torch.tensor(t), num_classes=c).numpy(),
            atol=1e-5, equal_nan=True,
        )


def test_hinge_loss_on_logits():
    """The reference sigmoids (binary) / softmaxes (multiclass) inputs
    outside [0,1] before the margin computation (hinge.py:118,156) —
    raw-logit inputs must match it, not the unnormalized-margin formula."""
    rng = np.random.RandomState(11)
    n, c = 24, 4
    p = (rng.randn(n, c) * 2).astype(np.float32)
    t = rng.randint(0, c, n)
    for mode in ("crammer-singer", "one-vs-all"):
        for sq in (False, True):
            np.testing.assert_allclose(
                np.asarray(FC.multiclass_hinge_loss(
                    jnp.asarray(p), jnp.asarray(t), num_classes=c, multiclass_mode=mode, squared=sq)),
                RFC.multiclass_hinge_loss(
                    torch.tensor(p), torch.tensor(t), num_classes=c, multiclass_mode=mode, squared=sq).numpy(),
                atol=1e-4, err_msg=f"{mode} squared={sq}",
            )
    pb = (rng.randn(n) * 2).astype(np.float32)
    tb = rng.randint(0, 2, n)
    for sq in (False, True):
        np.testing.assert_allclose(
            np.asarray(FC.binary_hinge_loss(jnp.asarray(pb), jnp.asarray(tb), squared=sq)),
            RFC.binary_hinge_loss(torch.tensor(pb), torch.tensor(tb), squared=sq).numpy(),
            atol=1e-4, err_msg=f"binary squared={sq}",
        )


def test_logit_detection_with_ignored_outlier():
    """An out-of-range pred at an ignore_index position must not flip the
    sigmoid/softmax decision for the rest of the batch — except where the
    reference itself normalizes before masking (stat-scores / multilabel
    confusion-and-curve formats), which we mirror. One probe per format
    family."""
    rng = np.random.RandomState(13)
    p = rng.rand(30).astype(np.float32)
    p[0] = -7.5  # logit at an ignored position
    t = rng.randint(0, 2, 30)
    t[0] = -1
    cases = [
        ("mcc", lambda: (FC.binary_matthews_corrcoef(jnp.asarray(p), jnp.asarray(t), ignore_index=-1),
                         RFC.binary_matthews_corrcoef(torch.tensor(p), torch.tensor(t), ignore_index=-1))),
        ("acc", lambda: (FC.binary_accuracy(jnp.asarray(p), jnp.asarray(t), ignore_index=-1),
                         RFC.binary_accuracy(torch.tensor(p), torch.tensor(t), ignore_index=-1))),
        ("auroc", lambda: (FC.binary_auroc(jnp.asarray(p), jnp.asarray(t), ignore_index=-1),
                           RFC.binary_auroc(torch.tensor(p), torch.tensor(t), ignore_index=-1))),
        ("calibration", lambda: (FC.binary_calibration_error(jnp.asarray(p), jnp.asarray(t), ignore_index=-1),
                                 RFC.binary_calibration_error(torch.tensor(p), torch.tensor(t), ignore_index=-1))),
        ("ap", lambda: (FC.binary_average_precision(jnp.asarray(p), jnp.asarray(t), ignore_index=-1),
                        RFC.binary_average_precision(torch.tensor(p), torch.tensor(t), ignore_index=-1))),
    ]
    for name, fn in cases:
        ours, ref = fn()
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-5, equal_nan=True, err_msg=name)

    pm = rng.rand(20, 4).astype(np.float32)
    pm[0] = np.array([5.0, -3, 0.5, 0.2])
    tm = rng.randint(0, 4, 20)
    tm[0] = -1
    for name, of, rf in [
        ("mc-auroc", FC.multiclass_auroc, RFC.multiclass_auroc),
        ("mc-calibration", FC.multiclass_calibration_error, RFC.multiclass_calibration_error),
        ("mc-acc", FC.multiclass_accuracy, RFC.multiclass_accuracy),
    ]:
        np.testing.assert_allclose(
            np.asarray(of(jnp.asarray(pm), jnp.asarray(tm), num_classes=4, ignore_index=-1)),
            rf(torch.tensor(pm), torch.tensor(tm), num_classes=4, ignore_index=-1).numpy(),
            atol=1e-5, equal_nan=True, err_msg=name)

    pl = rng.rand(20, 3).astype(np.float32)
    pl[0, 0] = 9.0
    tl = rng.randint(0, 2, (20, 3))
    tl[0, 0] = -1
    for name, of, rf in [
        ("ml-f1", FC.multilabel_f1_score, RFC.multilabel_f1_score),
        ("ml-ranking", FC.multilabel_ranking_loss, RFC.multilabel_ranking_loss),
        ("ml-auroc", FC.multilabel_auroc, RFC.multilabel_auroc),
    ]:
        np.testing.assert_allclose(
            np.asarray(of(jnp.asarray(pl), jnp.asarray(tl), num_labels=3, ignore_index=-1)),
            rf(torch.tensor(pl), torch.tensor(tl), num_labels=3, ignore_index=-1).numpy(),
            atol=1e-5, equal_nan=True, err_msg=name)

    # micro AP: the reference routes micro through the MULTILABEL format
    # (sigmoid-if-logits BEFORE the ignore mask) then flattens to the binary
    # compute — the out-of-[0,1] pred at the ignored position must still
    # trigger sigmoid for the whole batch (reference avg_precision.py:291-301)
    for thresholds in (None, 16):
        np.testing.assert_allclose(
            np.asarray(FC.multilabel_average_precision(
                jnp.asarray(pl), jnp.asarray(tl), num_labels=3, average="micro",
                thresholds=thresholds, ignore_index=-1)),
            RFC.multilabel_average_precision(
                torch.tensor(pl), torch.tensor(tl), num_labels=3, average="micro",
                thresholds=thresholds, ignore_index=-1).numpy(),
            atol=1e-5, equal_nan=True, err_msg=f"ml-ap-micro thr={thresholds}")


def test_image_constant_degenerates():
    """Constant / zero images through UQI and SAM must match the reference's
    degenerate outputs exactly: the reference's torch conv cancels
    E[x^2]-E[x]^2 exactly on constant windows (score 0), and its
    acos-of-ratio rounds to exactly 0 for parallel spectra. Our kernels pin
    these via a relative variance noise-floor (uqi.py) and the Kahan
    2*atan2(|u-v|,|u+v|) angle (sam.py)."""
    import torchmetrics.functional.image as RFI

    import torchmetrics_tpu.functional.image as FI

    rng = np.random.RandomState(0)
    const = np.full((2, 3, 16, 16), 0.5, np.float32)
    const2 = np.full((2, 3, 16, 16), 0.7, np.float32)
    zeros = np.zeros((2, 3, 16, 16), np.float32)
    rand = rng.rand(2, 3, 16, 16).astype(np.float32)
    near = const + rng.randn(2, 3, 16, 16).astype(np.float32) * 0.01
    cases = [
        ("const-same", const, const.copy()),
        ("const-diff", const, const2),
        ("const-rand", const, rand),
        ("zero-zero", zeros, zeros.copy()),
        ("zero-rand", zeros, rand),
        ("near-const", near, rand),
    ]
    for name, a, b in cases:
        np.testing.assert_allclose(
            np.asarray(FI.universal_image_quality_index(jnp.asarray(a), jnp.asarray(b))),
            RFI.universal_image_quality_index(torch.tensor(a), torch.tensor(b)).numpy(),
            atol=1e-5, equal_nan=True, err_msg=f"uqi {name}")
        np.testing.assert_allclose(
            np.asarray(FI.spectral_angle_mapper(jnp.asarray(a), jnp.asarray(b))),
            RFI.spectral_angle_mapper(torch.tensor(a), torch.tensor(b)).numpy(),
            atol=1e-5, equal_nan=True, err_msg=f"sam {name}")


def test_chrf_word_ngrams_with_punctuation():
    """CHRF word n-grams separate single leading/trailing punctuation into
    its own token (reference chrf.py:98-131, after sacrebleu) — plain
    whitespace splitting diverges whenever punctuation touches a word."""
    import torchmetrics.functional.text as RFT

    import torchmetrics_tpu.functional.text as FT

    preds = ["hello there general kenobi", "punct! mid-dle, (wrapped)"]
    tgts = [["hello there!"], ["punct! mid-dle (wrapped)"]]
    for nw in (0, 2, 3):
        np.testing.assert_allclose(
            np.asarray(FT.chrf_score(preds, tgts, n_word_order=nw)),
            RFT.chrf_score(preds, tgts, n_word_order=nw).numpy(),
            atol=1e-5, err_msg=f"n_word_order={nw}",
        )
    np.testing.assert_allclose(
        np.asarray(FT.chrf_score(preds, tgts, whitespace=True)),
        RFT.chrf_score(preds, tgts, whitespace=True).numpy(), atol=1e-5)


def test_multidim_samplewise_sweep():
    """Every stat-scores consumer x {global, samplewise} x average x
    ignore_index on (N, C, d) multidim inputs must match the reference —
    the samplewise state path and the macro/weighted stat-scores reductions
    (reference stat_scores.py:422-448) are only reachable this way."""
    rng = np.random.RandomState(7)
    p = rng.rand(6, 5, 4).astype(np.float32)
    p /= p.sum(1, keepdims=True)
    t = rng.randint(0, 5, (6, 4))
    ti = t.copy()
    ti[0, 0] = -1
    fns = ["multiclass_accuracy", "multiclass_precision", "multiclass_recall",
           "multiclass_f1_score", "multiclass_specificity", "multiclass_stat_scores",
           "multiclass_hamming_distance", "multiclass_exact_match"]
    for name in fns:
        for mda in ("global", "samplewise"):
            avgs = ("micro", "macro", "weighted", "none") if "exact" not in name else (None,)
            for avg in avgs:
                for tgt, ii in ((t, None), (ti, -1)):
                    kw = dict(num_classes=5, multidim_average=mda)
                    if avg is not None:
                        kw["average"] = avg
                    if ii is not None:
                        kw["ignore_index"] = ii
                    ours = np.asarray(getattr(FC, name)(jnp.asarray(p), jnp.asarray(tgt), **kw),
                                      dtype=np.float64)
                    ref = np.asarray(getattr(RFC, name)(torch.tensor(p), torch.tensor(tgt), **kw).numpy(),
                                     dtype=np.float64)
                    assert ours.shape == ref.shape, f"{name} {mda} {avg} ii={ii}: {ours.shape} vs {ref.shape}"
                    np.testing.assert_allclose(ours, ref, atol=1e-5, equal_nan=True,
                                               err_msg=f"{name} {mda} {avg} ii={ii}")

    # multilabel: (N, L, d) inputs through the same grid
    pl = rng.rand(6, 4, 3).astype(np.float32)
    tl = rng.randint(0, 2, (6, 4, 3))
    for name in ["multilabel_f1_score", "multilabel_stat_scores", "multilabel_accuracy"]:
        for mda in ("global", "samplewise"):
            for avg in ("micro", "macro", "weighted", "none"):
                ours = np.asarray(getattr(FC, name)(
                    jnp.asarray(pl), jnp.asarray(tl), num_labels=4, multidim_average=mda, average=avg),
                    dtype=np.float64)
                ref = np.asarray(getattr(RFC, name)(
                    torch.tensor(pl), torch.tensor(tl), num_labels=4, multidim_average=mda,
                    average=avg).numpy(), dtype=np.float64)
                assert ours.shape == ref.shape, f"{name} {mda} {avg}: {ours.shape} vs {ref.shape}"
                np.testing.assert_allclose(ours, ref, atol=1e-5, equal_nan=True,
                                           err_msg=f"{name} {mda} {avg}")


def test_top_k_sweep():
    """top_k in {2, 3} through every stat-scores consumer x average x
    ignore_index (the one-hot top-k update path, stat_scores.py:258-272)."""
    rng = np.random.RandomState(11)
    p = rng.rand(40, 6).astype(np.float32)
    p /= p.sum(1, keepdims=True)
    t = rng.randint(0, 6, 40)
    ti = t.copy()
    ti[:3] = -1
    for name in ["multiclass_accuracy", "multiclass_precision", "multiclass_recall",
                 "multiclass_f1_score", "multiclass_specificity", "multiclass_stat_scores"]:
        for k in (2, 3):
            for avg in ("micro", "macro", "weighted", "none"):
                for tgt, ii in ((t, None), (ti, -1)):
                    kw = dict(num_classes=6, top_k=k, average=avg)
                    if ii is not None:
                        kw["ignore_index"] = ii
                    ours = np.asarray(getattr(FC, name)(jnp.asarray(p), jnp.asarray(tgt), **kw),
                                      dtype=np.float64)
                    ref = np.asarray(getattr(RFC, name)(torch.tensor(p), torch.tensor(tgt), **kw).numpy(),
                                     dtype=np.float64)
                    assert ours.shape == ref.shape, f"{name} k={k} {avg} ii={ii}"
                    np.testing.assert_allclose(ours, ref, atol=1e-5, equal_nan=True,
                                               err_msg=f"{name} k={k} {avg} ii={ii}")
