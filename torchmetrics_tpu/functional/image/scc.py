"""Spatial correlation coefficient.

Parity: reference ``src/torchmetrics/functional/image/scc.py`` — high-pass
filter (laplacian) then local window correlation.
"""
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape
from .helper import depthwise_conv2d, reflect_pad_2d

Array = jax.Array

_LAPLACIAN = jnp.asarray([[-1.0, -1.0, -1.0], [-1.0, 8.0, -1.0], [-1.0, -1.0, -1.0]])


def _scc_per_channel(preds: Array, target: Array, hp_filter: Array, window_size: int) -> Array:
    """preds/target: (N, 1, H, W) single channel."""
    pad = (hp_filter.shape[0] - 1) // 2
    kernel = hp_filter[None, None]
    preds_hp = depthwise_conv2d(reflect_pad_2d(preds, pad, pad), kernel)
    target_hp = depthwise_conv2d(reflect_pad_2d(target, pad, pad), kernel)

    win = jnp.ones((1, 1, window_size, window_size))
    n_w = window_size * window_size

    def local_sum(x):
        return depthwise_conv2d(x, win)

    mu_p = local_sum(preds_hp) / n_w
    mu_t = local_sum(target_hp) / n_w
    var_p = local_sum(preds_hp**2) / n_w - mu_p**2
    var_t = local_sum(target_hp**2) / n_w - mu_t**2
    cov = local_sum(preds_hp * target_hp) / n_w - mu_p * mu_t
    denom = var_p * var_t
    scc = jnp.where(denom > 0, cov / jnp.sqrt(jnp.where(denom > 0, denom, 1.0)), 0.0)
    return scc


def spatial_correlation_coefficient(
    preds: Array,
    target: Array,
    hp_filter: Optional[Array] = None,
    window_size: int = 8,
    reduction: Optional[str] = "mean",
) -> Array:
    """Parity: reference ``scc.py:135``."""
    if hp_filter is None:
        hp_filter = _LAPLACIAN
    _check_same_shape(preds, target)
    if preds.ndim == 3:
        preds = preds[:, None]
        target = target[:, None]
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    per_channel = [
        _scc_per_channel(preds[:, i : i + 1], target[:, i : i + 1], hp_filter, window_size)
        for i in range(preds.shape[1])
    ]
    scc = jnp.concatenate(per_channel, axis=1)
    if reduction in ("mean", "elementwise_mean"):
        return jnp.mean(scc)
    if reduction == "none" or reduction is None:
        return jnp.mean(scc, axis=(1, 2, 3))
    raise ValueError(f"Expected reduction to be 'mean' or 'none' but got {reduction}")
