"""Plotting primitives (matplotlib optional).

Parity: reference ``src/torchmetrics/utilities/plot.py`` —
``plot_single_or_multi_val`` :62, ``plot_confusion_matrix`` :199,
``plot_curve`` :270.
"""
from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

from .imports import _MATPLOTLIB_AVAILABLE


def _get_ax(ax=None):
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError("Plotting requires matplotlib. Install it with `pip install matplotlib`.")
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots()
    else:
        fig = ax.get_figure()
    return fig, ax


def plot_single_or_multi_val(
    val: Any,
    ax=None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Point/line plot of one or a sequence of metric values."""
    fig, ax = _get_ax(ax)
    if isinstance(val, dict):
        for k, v in val.items():
            arr = np.atleast_1d(np.asarray(v))
            ax.plot(np.arange(len(arr)), arr, marker="o", label=str(k))
        ax.legend()
    elif isinstance(val, Sequence) and not hasattr(val, "shape"):
        if val and isinstance(val[0], dict):
            # sequence of result dicts (e.g. MetricCollection multi-step):
            # one line per key over the step axis; non-scalar values get one
            # line per component
            for k in val[0]:
                arr = np.stack([np.atleast_1d(np.asarray(v[k])) for v in val])
                if arr.shape[1] == 1:
                    ax.plot(np.arange(arr.shape[0]), arr[:, 0], marker="o", label=str(k))
                else:
                    for i in range(arr.shape[1]):
                        ax.plot(np.arange(arr.shape[0]), arr[:, i], marker="o", label=f"{k} {i}")
            ax.legend()
        else:
            arr = np.stack([np.atleast_1d(np.asarray(v)) for v in val])
            if arr.ndim == 2 and arr.shape[1] > 1:
                for i in range(arr.shape[1]):
                    ax.plot(np.arange(arr.shape[0]), arr[:, i], marker="o",
                            label=f"{legend_name or 'val'} {i}")
                ax.legend()
            else:
                ax.plot(np.arange(arr.shape[0]), arr.reshape(arr.shape[0]), marker="o")
    else:
        arr = np.atleast_1d(np.asarray(val))
        ax.plot(np.arange(len(arr)), arr, marker="o", label=legend_name)
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(lower_bound, upper_bound)
    if name:
        ax.set_title(name)
    return fig, ax


def plot_confusion_matrix(
    confmat,
    ax=None,
    add_text: bool = True,
    labels: Optional[Sequence[str]] = None,
):
    """Heatmap of a (C, C) or (L, 2, 2) confusion matrix."""
    fig, ax = _get_ax(ax)
    cm = np.asarray(confmat)
    if cm.ndim == 3:
        cm = cm.sum(axis=0)
    im = ax.imshow(cm, cmap="Blues")
    fig.colorbar(im, ax=ax)
    n = cm.shape[0]
    ticks = labels if labels is not None else list(range(n))
    ax.set_xticks(range(n), ticks)
    ax.set_yticks(range(n), ticks)
    ax.set_xlabel("Predicted")
    ax.set_ylabel("True")
    if add_text:
        for i in range(n):
            for j in range(n):
                ax.text(j, i, f"{cm[i, j]:.2g}", ha="center", va="center")
    return fig, ax


def plot_curve(
    curve: Tuple,
    score=None,
    ax=None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a (x, y, thresholds) curve tuple (ROC / PR).

    Handles 1D (binary), (C, T) stacked (binned multiclass/multilabel), and
    list-of-arrays per class (exact multiclass/multilabel, ragged lengths).
    """
    fig, ax = _get_ax(ax)
    if isinstance(curve[0], (list, tuple)):
        for i, (xi, yi) in enumerate(zip(curve[0], curve[1])):
            ax.plot(np.asarray(xi), np.asarray(yi), label=f"{legend_name or 'class'} {i}")
        ax.legend()
    else:
        x, y = np.asarray(curve[0]), np.asarray(curve[1])
        if x.ndim == 1:
            ax.plot(x, y, label=legend_name)
        else:
            for i in range(x.shape[0]):
                ax.plot(x[i], y[i], label=f"{legend_name or 'class'} {i}")
            ax.legend()
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if score is not None:
        ax.set_title(f"{name or ''} score={float(np.asarray(score)):.3f}")
    elif name:
        ax.set_title(name)
    return fig, ax
