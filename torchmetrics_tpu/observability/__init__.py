"""Unified telemetry: span tracing, typed counters, exporters.

See ``docs/observability.md`` for the span taxonomy, exporter formats
and sampling knobs. Everything here is host-side and zero-overhead when
tracing is disabled (the default).
"""
from .registry import (
    REGISTRY,
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    Registry,
    get_registry,
)
from .spans import (
    ENABLED,
    Span,
    clear_spans,
    collected_spans,
    disable_tracing,
    drain_spans,
    enable_tracing,
    instant,
    phase_totals,
    slowest_spans,
    start_span,
    trace_span,
    traced,
    tracing,
)
from .export import JsonlEventLog, to_perfetto, to_prometheus, write_perfetto

__all__ = [
    "REGISTRY",
    "Counter",
    "CounterGroup",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "ENABLED",
    "Span",
    "clear_spans",
    "collected_spans",
    "disable_tracing",
    "drain_spans",
    "enable_tracing",
    "instant",
    "phase_totals",
    "slowest_spans",
    "start_span",
    "trace_span",
    "traced",
    "tracing",
    "JsonlEventLog",
    "to_perfetto",
    "to_prometheus",
    "write_perfetto",
]
