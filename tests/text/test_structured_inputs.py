"""Structured corpus families for SacreBLEU / CHRF / TER vs the reference.

Earlier fixtures were short ASCII sentences; the host tokenizers are exactly
where structure bites (unicode classes, punctuation splitting, empty
segments, normalization). Each metric here runs four structurally distinct
corpus families asserted against the reference implementation on identical
inputs, across its tokenizer/normalization options.

Input-family model (patterns, not code): reference
``tests/unittests/text/_inputs.py`` (error-rate/long-sentence mixes).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "helpers"))
from lightning_utilities_stub import install_stub  # noqa: E402

install_stub()
sys.path.insert(0, "/root/reference/src")
pytest.importorskip("torch")

from torchmetrics.functional.text import (  # noqa: E402  (reference)
    chrf_score as ref_chrf,
    sacre_bleu_score as ref_sacre_bleu,
    translation_edit_rate as ref_ter,
)

from torchmetrics_tpu.functional.text import (  # noqa: E402  (ours)
    chrf_score,
    sacre_bleu_score,
    translation_edit_rate,
)

# --- corpus families ---------------------------------------------------------

UNICODE = (
    [
        "Die Straße führt über die Brücke nach Köln",
        "Ο γρήγορος σκύλος πηδά πάνω από τον φράχτη",
        "Быстрая лиса прыгает через ленивую собаку",
        "彼は東京の大学で物理を勉強している",
        "naïve façade jalapeño résumé coöperate",
    ],
    [
        ["Die Strasse führt über eine Brücke nach Köln", "Die Straße geht über die Brücke nach Köln"],
        ["Ο σκύλος πηδά γρήγορα πάνω από τον φράχτη"],
        ["Быстрая рыжая лиса перепрыгивает через ленивую собаку"],
        ["彼は東京の大学で物理学を学んでいる"],
        ["naive facade jalapeno resume cooperate"],
    ],
)

PUNCT = (
    [
        'He said: "Don\'t—ever!—do that again…" (or else?)',
        "Prices rose 5.3%, i.e. $12.40/unit; see p. 47, fig. 3-b.",
        "Well...that's—quite literally—'state-of-the-art', isn't it?!",
        "Email me at a.b@c.org, or call +1 (555) 123-4567!!",
    ],
    [
        ['He said "never do that again" or else'],
        ["Prices rose 5.3 percent, i.e. $12.40 per unit; see page 47, figure 3b."],
        ["Well, that is quite literally state of the art, is it not?"],
        ["Email me at a.b@c.org or call +1 555 123 4567."],
    ],
)

_LONG_P = "the model translates long sentences with repeated phrases " * 12
_LONG_T = "the model translated long sentences containing repeated phrases " * 12
EMPTY_LONG = (
    ["", _LONG_P.strip(), "short one here", ""],
    [["a nonempty reference for an empty hypothesis"], [_LONG_T.strip()], ["a short one here"], ["another reference"]],
)

CASING_WS = (
    [
        "The  QUICK   Brown\tFox",
        "MiXeD CaSe TeXt WiTh   ODD   SpAcInG",
        "ALL CAPS SENTENCE HERE NOW",
        "lower case only words again",
    ],
    [
        ["the quick brown fox"],
        ["mixed case text with odd spacing"],
        ["All Caps Sentence Here Now"],
        ["LOWER CASE ONLY WORDS AGAIN"],
    ],
)

FAMILIES = [
    ("unicode", UNICODE),
    ("punct", PUNCT),
    ("empty-long", EMPTY_LONG),
    ("casing-ws", CASING_WS),
]
IDS = [f[0] for f in FAMILIES]


@pytest.mark.parametrize(("name", "corpus"), FAMILIES, ids=IDS)
@pytest.mark.parametrize("tokenize", ["13a", "intl", "char"])
@pytest.mark.parametrize("lowercase", [False, True])
def test_sacre_bleu_structured(name, corpus, tokenize, lowercase):
    preds, targets = corpus
    ref = float(ref_sacre_bleu(preds, targets, tokenize=tokenize, lowercase=lowercase))
    got = float(sacre_bleu_score(preds, targets, tokenize=tokenize, lowercase=lowercase))
    np.testing.assert_allclose(got, ref, atol=1e-6, err_msg=str((name, tokenize, lowercase)))


@pytest.mark.parametrize(("name", "corpus"), FAMILIES, ids=IDS)
@pytest.mark.parametrize(("n_word_order", "whitespace"), [(2, False), (0, False), (2, True)])
def test_chrf_structured(name, corpus, n_word_order, whitespace):
    preds, targets = corpus
    ref = float(ref_chrf(preds, targets, n_word_order=n_word_order, whitespace=whitespace))
    got = float(chrf_score(preds, targets, n_word_order=n_word_order, whitespace=whitespace))
    np.testing.assert_allclose(got, ref, atol=1e-6, err_msg=str((name, n_word_order, whitespace)))


@pytest.mark.parametrize(("name", "corpus"), FAMILIES, ids=IDS)
@pytest.mark.parametrize(("normalize", "no_punctuation"), [(False, False), (True, False), (False, True)])
def test_ter_structured(name, corpus, normalize, no_punctuation):
    preds, targets = corpus
    ref = float(ref_ter(preds, targets, normalize=normalize, no_punctuation=no_punctuation))
    got = float(translation_edit_rate(preds, targets, normalize=normalize, no_punctuation=no_punctuation))
    np.testing.assert_allclose(got, ref, atol=1e-6, err_msg=str((name, normalize, no_punctuation)))


def test_sentence_level_scores_match_on_structured_corpus():
    """Per-sentence outputs (not just the corpus aggregate) agree on the
    punctuation family — TER and CHRF both expose sentence-level scores."""
    preds, targets = PUNCT
    r_score, r_sent = ref_ter(preds, targets, return_sentence_level_score=True)
    o_score, o_sent = translation_edit_rate(preds, targets, return_sentence_level_score=True)
    np.testing.assert_allclose(np.ravel(np.asarray(o_sent)), np.ravel(np.asarray(r_sent)), atol=1e-6)
    r_score, r_sent = ref_chrf(preds, targets, return_sentence_level_score=True)
    o_score, o_sent = chrf_score(preds, targets, return_sentence_level_score=True)
    np.testing.assert_allclose(np.ravel(np.asarray(o_sent)), np.ravel(np.asarray(r_sent)), atol=1e-6)
