"""Tests for the online-evaluation wrappers (ISSUE 7).

WindowedMetric / DecayedMetric semantics (rotation, decay closed forms),
the rewritten RunningMean/RunningSum ring (exact reference semantics AND
buffered(window=K) equivalence across flush boundaries), sync of windowed
states, and the online dispatch counters.
"""
import pickle

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu import (
    ApproxQuantile,
    CatMetric,
    DecayedMean,
    DecayedSum,
    MaxMetric,
    MeanMetric,
    RunningMean,
    RunningSum,
    SumMetric,
    WindowedMax,
    WindowedMean,
    WindowedSum,
)
from torchmetrics_tpu.metric import executable_cache_stats
from torchmetrics_tpu.online import (
    DecayedMetric,
    WindowedMetric,
    online_stats,
    reset_online_stats,
)
from torchmetrics_tpu.parallel.sync import FakeSync


def _window_slices(stream, horizon, slots):
    """The updates a warm slot ring covers: the last full/partial slot groups."""
    slot_len = horizon // slots
    groups = [stream[i:i + slot_len] for i in range(0, len(stream), slot_len)]
    kept = groups[-slots:]
    return [v for g in kept for v in g]


# ----------------------------------------------------------------- windowed
@pytest.mark.parametrize("horizon,slots,n", [(4, 4, 5), (4, 2, 6), (8, 4, 13), (6, 3, 4)])
def test_windowed_sum_matches_slot_model(horizon, slots, n):
    stream = [float(i + 1) for i in range(n)]
    m = SumMetric().windowed(horizon=horizon, slots=slots)
    for v in stream:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == sum(_window_slices(stream, horizon, slots))


def test_windowed_mean_weights_slots_by_element_counts():
    # variable batch sizes: the window mean must weight by ELEMENT counts of
    # the covered updates, not average the slot means
    batches = [[1.0, 1.0, 1.0], [5.0], [2.0, 4.0], [10.0]]
    m = MeanMetric().windowed(horizon=4, slots=2)
    for b in batches:
        m.update(jnp.asarray(b))
    covered = [v for b in batches[-4:] for v in b]  # ring still warm: all kept
    assert float(m.compute()) == pytest.approx(np.mean(covered))
    m.update(jnp.asarray([100.0]))  # rotates: first slot (batches 0-1) drops
    covered = [v for b in batches[2:] for v in b] + [100.0]
    assert float(m.compute()) == pytest.approx(np.mean(covered))


def test_windowed_max_forgets_old_peak():
    m = MaxMetric().windowed(horizon=2, slots=2)
    m.update(jnp.asarray(99.0))
    m.update(jnp.asarray(1.0))
    assert float(m.compute()) == 99.0
    m.update(jnp.asarray(2.0))  # 99.0's slot rotates out
    assert float(m.compute()) == 2.0


def test_windowed_sketch_quantile_tracks_recent_distribution():
    rng = np.random.RandomState(5)
    m = ApproxQuantile(q=0.5, compression=64).windowed(horizon=8, slots=4)
    for _ in range(8):  # old regime: values around 100
        m.update(jnp.asarray(100.0 + rng.rand(200).astype(np.float32)))
    for _ in range(8):  # new regime: values around 0 — fills the whole ring
        m.update(jnp.asarray(rng.rand(200).astype(np.float32)))
    assert float(m.compute()) < 2.0  # an epoch metric would still sit near ~50


def test_windowed_facades_and_reset():
    m = WindowedSum(horizon=4, slots=4)
    assert isinstance(m, WindowedMetric)
    for v in [1.0, 2.0, 3.0]:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == 6.0
    m.reset()
    assert float(m.compute()) == 0.0
    m.update(jnp.asarray(7.0))
    assert float(m.compute()) == 7.0


def test_windowed_validation_errors():
    with pytest.raises(ValueError, match="multiple of slots"):
        SumMetric().windowed(horizon=5, slots=2)
    with pytest.raises(ValueError, match="slots"):
        SumMetric().windowed(horizon=4, slots=1)
    with pytest.raises(ValueError, match="cannot window CatMetric"):
        CatMetric().windowed(horizon=4, slots=2)  # list states / eager update
    used = SumMetric()
    used.update(jnp.asarray(1.0))
    with pytest.raises(ValueError, match="accumulated state"):
        used.windowed(horizon=4, slots=2)


# ------------------------------------------------------------------ decayed
def test_decayed_sum_matches_closed_form():
    h = 4.0
    d = 0.5 ** (1.0 / h)
    m = SumMetric().decayed(halflife=h)
    n = 10
    for _ in range(n):
        m.update(jnp.asarray(1.0))
    expected = sum(d ** k for k in range(n))
    assert float(m.compute()) == pytest.approx(expected, rel=1e-5)
    # an observation `halflife` updates old carries exactly half weight
    m2 = SumMetric().decayed(halflife=4.0)
    m2.update(jnp.asarray(1.0))
    for _ in range(4):
        m2.update(jnp.asarray(0.0))
    assert float(m2.compute()) == pytest.approx(0.5, rel=1e-5)


def test_decayed_mean_is_ema_of_batch_means():
    d = 0.5 ** (1.0 / 3.0)
    m = DecayedMean(halflife=3.0)
    vals, wsum, wtot = [2.0, 4.0, 8.0], 0.0, 0.0
    for v in vals:
        m.update(jnp.asarray(v))
        wsum = wsum * d + v
        wtot = wtot * d + 1.0
    assert float(m.compute()) == pytest.approx(wsum / wtot, rel=1e-5)


def test_decayed_sketch_quantile_tracks_recent_distribution():
    rng = np.random.RandomState(9)
    m = ApproxQuantile(q=0.5, compression=64).decayed(halflife=4.0)
    for _ in range(10):
        m.update(jnp.asarray(100.0 + rng.rand(200).astype(np.float32)))
    for _ in range(30):  # ~7.5 half-lives: old centroids carry ~0.5% weight
        m.update(jnp.asarray(rng.rand(200).astype(np.float32)))
    assert float(m.compute()) < 2.0


def test_decayed_validation_errors():
    with pytest.raises(ValueError, match="windowed"):
        MaxMetric().decayed(halflife=4.0)
    with pytest.raises(ValueError, match="halflife"):
        SumMetric().decayed(halflife=0.0)
    assert isinstance(DecayedSum(halflife=4.0), DecayedMetric)


# ------------------------------------------- running ring: reference parity
def _naive_running(updates, window):
    """Reference semantics: mean/sum over ELEMENTS of the last `window` updates."""
    kept = [np.asarray(u, dtype=np.float64) for u in updates[-window:]]
    flat = np.concatenate([k.reshape(-1) for k in kept]) if kept else np.zeros((0,))
    finite = flat[~np.isnan(flat)]
    total = float(np.sum(finite))
    mean = total / len(finite) if len(finite) else 0.0
    return total, mean


def test_running_mean_sum_match_reference_semantics():
    rng = np.random.RandomState(13)
    updates = [rng.rand(rng.randint(1, 6)).astype(np.float32) for _ in range(11)]
    rm, rs = RunningMean(window=4), RunningSum(window=4)
    for u in updates:
        rm.update(jnp.asarray(u))
        rs.update(jnp.asarray(u))
    total, mean = _naive_running(updates, 4)
    assert float(rs.compute()) == pytest.approx(total, rel=1e-5)
    assert float(rm.compute()) == pytest.approx(mean, rel=1e-5)


def test_running_mean_ignores_nans_with_ignore_strategy():
    updates = [[1.0, np.nan], [np.nan, np.nan], [3.0]]
    m = RunningMean(window=2, nan_strategy="ignore")
    for u in updates:
        m.update(jnp.asarray(np.asarray(u, dtype=np.float32)))
    _, mean = _naive_running(updates, 2)
    assert float(m.compute()) == pytest.approx(mean)


@pytest.mark.parametrize("cls", [RunningMean, RunningSum])
def test_running_ring_buffered_matches_eager_across_flush_boundaries(cls):
    """The rewritten ring is jittable, so it stages under buffered(window=K);
    staged flushes (including the short valid-masked final flush) must agree
    with the eager twin at EVERY prefix length, i.e. across ring-crop and
    flush boundaries simultaneously."""
    rng = np.random.RandomState(17)
    updates = [rng.rand(3).astype(np.float32) for _ in range(10)]
    for n in (1, 4, 5, 7, 10):  # straddles flush boundary (K=3) and ring (4)
        eager = cls(window=4)
        buff = cls(window=4).buffered(window=3)
        for u in updates[:n]:
            eager.update(jnp.asarray(u))
            buff.update(jnp.asarray(u))
        assert float(buff.compute()) == float(eager.compute())


def test_windowed_buffered_matches_eager():
    rng = np.random.RandomState(19)
    updates = [rng.rand(4).astype(np.float32) for _ in range(11)]
    eager = WindowedMean(horizon=4, slots=2)
    buff = WindowedMean(horizon=4, slots=2).buffered(window=3)
    for u in updates:
        eager.update(jnp.asarray(u))
        buff.update(jnp.asarray(u))
    assert float(buff.compute()) == float(eager.compute())


# --------------------------------------------------------------------- sync
def test_windowed_metric_syncs_slotwise_across_ranks():
    ranks = [WindowedSum(horizon=4, slots=2) for _ in range(2)]
    for r, m in enumerate(ranks):
        for v in (1.0, 2.0, 3.0):  # rank r contributes (r+1)·6 over its window
            m.update(jnp.asarray(v * (r + 1)))
    group = [m.metric_state for m in ranks]
    for r, m in enumerate(ranks):
        m.sync(sync_backend=FakeSync(group, r))
    for m in ranks:
        assert float(m.compute()) == 18.0  # 6 + 12: both ranks' windows
        np.testing.assert_array_equal(np.asarray(m._win_count), [4, 2])  # summed


def test_windowed_sketch_metric_syncs_and_pickles():
    rng = np.random.RandomState(23)
    ranks = [ApproxQuantile(q=0.5, compression=64).windowed(horizon=4, slots=2) for _ in range(2)]
    for r, m in enumerate(ranks):
        for _ in range(3):
            m.update(jnp.asarray(rng.rand(100).astype(np.float32) + r))
    group = [m.metric_state for m in ranks]
    for r, m in enumerate(ranks):
        m.sync(sync_backend=FakeSync(group, r))
    vals = [float(m.compute()) for m in ranks]
    assert vals[0] == vals[1]  # slot-wise sketch merge is replica-identical
    assert 0.0 < vals[0] < 2.0  # pooled median of U(0,1) ∪ U(1,2)
    clone = pickle.loads(pickle.dumps(ranks[0]))  # _SlotwiseMerge round-trips
    assert float(clone.compute()) == vals[0]


# ----------------------------------------------------------------- counters
def test_online_counters_track_updates_and_rotations():
    reset_online_stats()
    w = SumMetric().windowed(horizon=4, slots=2)
    d = SumMetric().decayed(halflife=2.0)
    for v in range(6):
        w.update(jnp.asarray(float(v)))
        d.update(jnp.asarray(float(v)))
    stats = online_stats()
    assert stats["windowed_metrics"] == 1 and stats["decayed_metrics"] == 1
    assert stats["windowed_updates"] == 6 and stats["decayed_updates"] == 6
    assert stats["window_rotations"] == 2  # rotations at updates 3 and 5
    assert executable_cache_stats()["online"] == stats
