"""Safe-numerics helpers.

Parity: reference ``src/torchmetrics/utilities/compute.py`` (``_safe_divide``
:46, ``auc`` :118, ``interp`` :134, ``_safe_xlogy``/``_safe_matmul``).
"""
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Elementwise num/denom with 0-denominator producing ``zero_division``."""
    num = jnp.asarray(num)
    denom = jnp.asarray(denom)
    if not jnp.issubdtype(jnp.result_type(num), jnp.floating):
        num = num.astype(jnp.float32)
    if not jnp.issubdtype(jnp.result_type(denom), jnp.floating):
        denom = denom.astype(jnp.float32)
    zero = denom == 0
    out = num / jnp.where(zero, jnp.ones_like(denom), denom)
    return jnp.where(zero, jnp.asarray(zero_division, dtype=out.dtype), out)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y), with x==0 giving 0 (avoids 0 * -inf NaNs)."""
    out = x * jnp.log(jnp.where(x == 0, jnp.ones_like(y), y))
    return jnp.where(x == 0, jnp.zeros_like(out), out)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    dx = jnp.diff(x, axis=axis)
    mean_y = (y[..., :-1] + y[..., 1:]) / 2.0 if axis == -1 else None
    if mean_y is None:
        y0 = jnp.take(y, jnp.arange(y.shape[axis] - 1), axis=axis)
        y1 = jnp.take(y, jnp.arange(1, y.shape[axis]), axis=axis)
        mean_y = (y0 + y1) / 2.0
    return jnp.sum(mean_y * dx, axis=axis) * direction


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under curve via trapezoidal rule.

    Parity: reference ``utilities/compute.py:118``. The monotonicity *check* of
    the reference raises eagerly; under jit we assume sorted unless
    ``reorder=True``.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.float32)
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    return _auc_compute_without_check(x, y, 1.0)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation, parity with ``utilities/compute.py:134``."""
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(tensor: Array, normalization: Optional[str],
                               valid_mask: Optional[Array] = None) -> Array:
    """Apply sigmoid/softmax only when input looks like logits (outside [0,1]).

    Parity: reference ``utilities/compute.py`` logit handling used by the
    classification ``_format`` stages. The any-outside-[0,1] test is a traced
    reduction, so this stays jittable via ``jnp.where``.

    ``valid_mask`` (broadcastable to ``tensor``) restricts the is-logit test
    to kept entries: the reference filters ``ignore_index`` rows *before*
    deciding, so an out-of-range value at an ignored position must not flip
    the decision for the whole batch (our masked static-shape design keeps
    ignored entries in the array).
    """
    if normalization is None:
        return tensor
    probe = tensor if valid_mask is None else jnp.where(valid_mask, tensor, 0.5)
    is_logit = jnp.logical_or(jnp.any(probe < 0), jnp.any(probe > 1))
    if normalization == "sigmoid":
        return jnp.where(is_logit, jax.nn.sigmoid(tensor), tensor)
    if normalization == "softmax":
        return jnp.where(is_logit, jax.nn.softmax(tensor, axis=1), tensor)
    raise ValueError(f"Unknown normalization {normalization}")
