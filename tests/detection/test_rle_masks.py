"""RLE mask inputs to MeanAveragePrecision must agree with dense masks.

The reference accepts COCO RLE-encoded masks for iou_type='segm'
(``detection/mean_ap.py`` RLE tuple states); here RLEs stay encoded through
the native IoU kernel, so dense and RLE inputs must produce identical mAP.
"""
import numpy as np
import pytest

from torchmetrics_tpu import _native
from torchmetrics_tpu.detection import MeanAveragePrecision


def _random_instances(rng, n, h, w):
    masks = np.zeros((n, h, w), dtype=bool)
    for i in range(n):
        y0, x0 = rng.randint(0, h - 6), rng.randint(0, w - 6)
        dy, dx = rng.randint(4, h - y0), rng.randint(4, w - x0)
        masks[i, y0 : y0 + dy, x0 : x0 + dx] = True
    return masks


@pytest.mark.parametrize("seed", [0, 1])
def test_map_segm_dense_equals_rle(seed):
    rng = np.random.RandomState(seed)
    h = w = 48
    n_det, n_gt = 4, 3
    det_masks = _random_instances(rng, n_det, h, w)
    gt_masks = _random_instances(rng, n_gt, h, w)
    scores = rng.rand(n_det)
    det_labels = rng.randint(0, 2, n_det)
    gt_labels = rng.randint(0, 2, n_gt)

    dense = MeanAveragePrecision(iou_type="segm")
    dense.update(
        [{"masks": det_masks, "scores": scores, "labels": det_labels}],
        [{"masks": gt_masks, "labels": gt_labels}],
    )
    r_dense = dense.compute()

    to_rle = lambda m: {"size": [h, w], "counts": _native.rle_encode(m.astype(np.uint8))}
    rle = MeanAveragePrecision(iou_type="segm")
    rle.update(
        [{"masks": [to_rle(m) for m in det_masks], "scores": scores, "labels": det_labels}],
        [{"masks": [to_rle(m) for m in gt_masks], "labels": gt_labels}],
    )
    r_rle = rle.compute()

    for k in ("map", "map_50", "map_75", "mar_100"):
        assert np.isclose(float(r_dense[k]), float(r_rle[k]), atol=1e-9), k


def test_map_segm_rle_crowd():
    h = w = 32
    gt = np.zeros((1, h, w), bool)
    gt[0, 4:20, 4:20] = True
    det = np.zeros((1, h, w), bool)
    det[0, 4:12, 4:20] = True  # half-covers the crowd region
    to_rle = lambda m: {"size": [h, w], "counts": _native.rle_encode(m.astype(np.uint8))}
    m = MeanAveragePrecision(iou_type="segm")
    m.update(
        [{"masks": [to_rle(det[0])], "scores": np.array([0.9]), "labels": np.array([0])}],
        [{"masks": [to_rle(gt[0])], "labels": np.array([0]), "iscrowd": np.array([1])}],
    )
    res = m.compute()
    # all gts are crowd -> no positives -> mAP is -1 (COCO convention)
    assert float(res["map"]) == -1.0
