"""Bucketed state sync: one collective per (Reduction, dtype) bucket.

Pins the perf PR's collective-count contract with jaxpr inspection and its
correctness contract bitwise: flattening elementwise-reduced leaves into one
concatenated buffer must be bit-identical to reducing each leaf on its own
(psum/pmean/pmax/pmin act elementwise), while cat/NONE/custom states stay
per-leaf. Covers the in-graph SPMD path (``reduce_state_in_graph``), the
eager path (``Metric.sync`` over ``FakeSync``), and mixed dtypes/shapes.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import core

from torchmetrics_tpu import Metric
from torchmetrics_tpu.parallel.reduction import ELEMENTWISE_REDUCTIONS, Reduction
from torchmetrics_tpu.parallel.sync import FakeSync, reduce_state_in_graph, reduce_tensor_in_graph
from torchmetrics_tpu.utils.data import dim_zero_cat

WORLD = 4


def _count_primitives(closed_jaxpr) -> dict:
    counts: dict = {}

    def walk(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
            for val in eqn.params.values():
                for v in val if isinstance(val, (list, tuple)) else (val,):
                    if isinstance(v, core.ClosedJaxpr):
                        walk(v.jaxpr)
                    elif isinstance(v, core.Jaxpr):
                        walk(v)

    walk(closed_jaxpr.jaxpr)
    return counts


def _mixed_state(rank: int):
    """Scalar/vector/matrix leaves across two dtypes + a cat tuple state."""
    r = float(rank + 1)
    state = {
        "a": jnp.float32(r),                                   # SUM f32 scalar
        "b": jnp.arange(6, dtype=jnp.float32).reshape(2, 3) * r,  # SUM f32 matrix
        "c": jnp.asarray([r, -r, 0.5 * r], dtype=jnp.float32),    # MEAN f32 vector
        "d": jnp.float32(1.0) / r,                             # MEAN f32 scalar
        "e": jnp.asarray([rank, rank + 2], dtype=jnp.int32),   # SUM i32 vector
        "f": jnp.asarray([[r, 2 * r]], dtype=jnp.float32),     # MAX f32 matrix
        "g": (jnp.asarray([r, r + 1], dtype=jnp.float32),),    # CAT tuple state
    }
    reds = {
        "a": Reduction.SUM, "b": Reduction.SUM, "c": Reduction.MEAN,
        "d": Reduction.MEAN, "e": Reduction.SUM, "f": Reduction.MAX,
        "g": Reduction.CAT,
    }
    return state, reds


def _per_leaf_reduce(state, reds, axis_name):
    """The pre-bucketing reference: one collective per state leaf."""
    out = {}
    for name, value in state.items():
        red = reds[name]
        if isinstance(value, (list, tuple)):
            out[name] = type(value)(reduce_tensor_in_graph(v, red, axis_name) for v in value)
        else:
            out[name] = reduce_tensor_in_graph(value, red, axis_name)
    return out


def test_one_collective_per_bucket_in_jaxpr():
    state, reds = _mixed_state(0)
    jaxpr = jax.make_jaxpr(
        lambda s: reduce_state_in_graph(s, reds, "dp"), axis_env=[("dp", WORLD)]
    )(state)
    counts = _count_primitives(jaxpr)
    # buckets: (SUM,f32)={a,b} (MEAN,f32)={c,d} (SUM,i32)={e}. pmean lowers
    # to one psum + divide, and the cat state's invariant gather is built on
    # one psum of a masked buffer, so psum == 3 buckets + 1 gather
    assert counts.get("psum", 0) == 4, counts
    assert counts.get("pmax", 0) == 1, counts  # (MAX,f32)={f}
    assert counts.get("pmin", 0) == 0, counts


def test_per_leaf_reference_issues_one_collective_per_leaf():
    # sanity for the comparison itself: without bucketing the same state
    # costs one collective per elementwise LEAF (5: a,b,c,d,e) + 1 for the
    # cat gather, instead of one per BUCKET (3) + 1
    state, reds = _mixed_state(0)
    jaxpr = jax.make_jaxpr(
        lambda s: _per_leaf_reduce(s, reds, "dp"), axis_env=[("dp", WORLD)]
    )(state)
    counts = _count_primitives(jaxpr)
    assert counts.get("psum", 0) == 6, counts
    assert counts.get("pmax", 0) == 1, counts


def test_bucketed_reduce_bitwise_identical_to_per_leaf():
    states = [_mixed_state(r)[0] for r in range(WORLD)]
    reds = _mixed_state(0)[1]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)

    fused = jax.vmap(lambda s: reduce_state_in_graph(s, reds, "dp"), axis_name="dp")(stacked)
    ref = jax.vmap(lambda s: _per_leaf_reduce(s, reds, "dp"), axis_name="dp")(stacked)

    flat_f, tree_f = jax.tree_util.tree_flatten(fused)
    flat_r, tree_r = jax.tree_util.tree_flatten(ref)
    assert tree_f == tree_r
    for a, b in zip(flat_f, flat_r):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # bitwise


def test_single_entry_bucket_matches_per_leaf():
    state = {"x": jnp.asarray([1.0, 2.0], dtype=jnp.float32)}
    reds = {"x": Reduction.SUM}
    stacked = {"x": jnp.stack([jnp.asarray([1.0, 2.0]) * (r + 1) for r in range(WORLD)])}
    fused = jax.vmap(lambda s: reduce_state_in_graph(s, reds, "dp"), axis_name="dp")(stacked)
    np.testing.assert_array_equal(np.asarray(fused["x"][0]), np.asarray([10.0, 20.0]))
    # and no concatenate detour for a lone leaf: exactly one psum, no reshapes needed
    jaxpr = jax.make_jaxpr(
        lambda s: reduce_state_in_graph(s, reds, "dp"), axis_env=[("dp", WORLD)]
    )(state)
    assert _count_primitives(jaxpr).get("concatenate", 0) == 0


def test_elementwise_reductions_frozenset_contract():
    assert ELEMENTWISE_REDUCTIONS == {Reduction.SUM, Reduction.MEAN, Reduction.MAX, Reduction.MIN}
    assert Reduction.CAT not in ELEMENTWISE_REDUCTIONS
    assert Reduction.NONE not in ELEMENTWISE_REDUCTIONS


# ---------------------------------------------------------------- eager FakeSync
class _MultiState(Metric):
    full_state_update = False

    def __init__(self):
        super().__init__()
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("peak", jnp.full((), -jnp.inf), dist_reduce_fx="max")
        self.add_state("vec", jnp.zeros(3), dist_reduce_fx="sum")
        self.add_state("vals", [], dist_reduce_fx="cat")

    def update(self, x):
        self.total = self.total + jnp.sum(x)
        self.count = self.count + jnp.asarray(x.shape[0], dtype=jnp.int32)
        self.peak = jnp.maximum(self.peak, jnp.max(x))
        self.vec = self.vec + x[:3]
        self.vals.append(x)

    def compute(self):
        return self.total / self.count


def test_fake_sync_bucketed_matches_manual_merge():
    ranks = [_MultiState() for _ in range(WORLD)]
    data = [jnp.asarray(np.random.RandomState(r).rand(5).astype(np.float32)) for r in range(WORLD)]
    for m, x in zip(ranks, data):
        m.update(x)
    # FakeSync worlds pre-concat cat states (the backend gathers tensors);
    # dim_zero_cat masks a padded CatBuffer to its valid prefix
    group = [
        {**{k: v for k, v in m.metric_state.items() if k != "vals"},
         "vals": dim_zero_cat(m.metric_state["vals"])}
        for m in ranks
    ]
    for r, m in enumerate(ranks):
        m.sync(sync_backend=FakeSync(group, r))

    total = sum(float(jnp.sum(x)) for x in data)
    count = sum(x.shape[0] for x in data)
    peak = max(float(jnp.max(x)) for x in data)
    vec = np.sum([np.asarray(x[:3]) for x in data], axis=0)
    for m in ranks:
        assert float(m.total) == pytest.approx(total, rel=1e-6)
        assert int(m.count) == count
        assert m.count.dtype == jnp.int32  # i32 bucket must round-trip its dtype
        assert float(m.peak) == pytest.approx(peak, rel=1e-6)
        np.testing.assert_allclose(np.asarray(m.vec), vec, rtol=1e-6)
        gathered = np.concatenate([np.asarray(v) for v in m.vals]) if isinstance(m.vals, list) \
            else np.asarray(m.vals)
        assert gathered.size == sum(x.size for x in data)  # cat state: gathered, not bucketed
        assert float(m.compute()) == pytest.approx(total / count, rel=1e-6)
        m.unsync()
    # unsync restores the local (pre-sync) state
    assert float(ranks[0].total) == pytest.approx(float(jnp.sum(data[0])), rel=1e-6)
