"""plot() smoke suite over representative metrics from every domain.

Parity: reference ``tests/unittests/utilities/test_plot.py`` (~100 metrics
through ``.plot()``) — here driven by the shared example-input registry:
every selected metric is built, updated, and plotted (single-value,
multi-step, and the confusion/curve specializations), asserting a live
matplotlib figure comes back.
"""
import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))
from example_inputs import CASES  # noqa: E402

from torchmetrics_tpu.classification import (  # noqa: E402
    BinaryConfusionMatrix,
    BinaryPrecisionRecallCurve,
    BinaryROC,
    MulticlassConfusionMatrix,
    MulticlassROC,
)

# the whole registry (parity: reference sweeps ~100 classes; this sweeps
# every registered class, ~129), minus the per-sample host audio pipelines
# whose updates dominate runtime without exercising any plot path not
# already covered by the other audio entries
SLOW_HOST_AUDIO = {
    "PerceptualEvaluationSpeechQuality",
    "ShortTimeObjectiveIntelligibility",
    "SpeechReverberationModulationEnergyRatio",
}
EXCLUDED = SLOW_HOST_AUDIO | {
    # (mean, std, raw-distances) ragged tuple output has no generic plot
    "PerceptualPathLength",
    # has its own plot() protocol (list of figures) — tested below
    "MetricCollection",
}
PLOT_NAMES = [n for n in sorted(CASES) if n not in EXCLUDED]


def test_plot_sweep_breadth():
    """Guard the sweep's breadth (VERDICT r2 #8: >= 90 metrics)."""
    assert len(PLOT_NAMES) >= 90


def _built_and_updated(name):
    case = CASES[name]
    m = case.build(name)
    for call in case.make_inputs(np.random.RandomState(0), 8):
        m.update(*call)
    return m


@pytest.mark.parametrize("name", PLOT_NAMES)
def test_plot_single_value(name):
    m = _built_and_updated(name)
    fig, ax = m.plot()
    assert fig is not None and ax is not None
    plt.close(fig)


@pytest.mark.parametrize("name", ["Accuracy", "MeanSquaredError", "RetrievalMRR"])
def test_plot_multiple_values(name):
    m = _built_and_updated(name)
    vals = [m.compute(), m.compute() * 0.5, m.compute() * 0.25]
    fig, ax = m.plot(vals)
    assert fig is not None
    plt.close(fig)


def test_plot_classwise_dict():
    case = CASES["Accuracy"]
    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.wrappers import ClasswiseWrapper

    m = ClasswiseWrapper(MulticlassAccuracy(num_classes=5, average="none"))
    p, t = case.make_inputs(np.random.RandomState(0), 16)[0]
    m.update(p, t)
    fig, _ = m.plot()
    assert fig is not None
    plt.close(fig)


def test_plot_confusion_matrix():
    rng = np.random.RandomState(0)
    for m, args in [
        (BinaryConfusionMatrix(), (jnp.asarray(rng.rand(32).astype(np.float32)), jnp.asarray(rng.randint(0, 2, 32)))),
        (MulticlassConfusionMatrix(num_classes=4),
         (jnp.asarray(rng.rand(32, 4).astype(np.float32)), jnp.asarray(rng.randint(0, 4, 32)))),
    ]:
        m.update(*args)
        fig, ax = m.plot(add_text=True)
        assert fig is not None
        plt.close(fig)


def test_plot_curves():
    rng = np.random.RandomState(0)
    bp = jnp.asarray(rng.rand(64).astype(np.float32))
    bt = jnp.asarray(rng.randint(0, 2, 64))
    for metric in (BinaryROC(), BinaryPrecisionRecallCurve()):
        metric.update(bp, bt)
        fig, ax = metric.plot()
        assert fig is not None
        plt.close(fig)
    mc = MulticlassROC(num_classes=4)
    mc.update(jnp.asarray(rng.rand(64, 4).astype(np.float32)), jnp.asarray(rng.randint(0, 4, 64)))
    fig, _ = mc.plot()
    assert fig is not None
    plt.close(fig)


def test_plot_metric_collection():
    """MetricCollection.plot: per-metric figures, and together-mode over a
    sequence of step results (parity: reference ``collections.py:578``)."""
    import torchmetrics_tpu as M

    coll = M.MetricCollection({"mse": M.MeanSquaredError(), "mae": M.MeanAbsoluteError()},
                              prefix="val_")
    rng = np.random.RandomState(0)
    vals = [coll(jnp.asarray(rng.randn(8).astype(np.float32)),
                 jnp.asarray(rng.randn(8).astype(np.float32))) for _ in range(3)]
    out = coll.plot()
    assert len(out) == 2
    for f, _ in out:
        plt.close(f)
    fig, _ = coll.plot(vals, together=True)
    assert fig is not None
    plt.close(fig)
    with pytest.raises(ValueError, match="together"):
        coll.plot(together="x")


def test_plot_respects_bounds_and_ax():
    m = _built_and_updated("Accuracy")
    fig, ax = plt.subplots()
    fig2, ax2 = m.plot(ax=ax)
    assert ax2 is ax and fig2 is fig
    lo, hi = ax.get_ylim()
    assert 0.0 >= lo - 1e-6 and hi <= 1.0 + 1e-6  # plot_lower/upper_bound applied
    plt.close(fig)
