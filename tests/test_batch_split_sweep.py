"""Accumulation invariance: update(full batch) == update(half) ; update(half).

This is the reference ``MetricTester``'s core class-vs-accumulation check
(``tests/unittests/_helpers/testers.py:206-320``) applied uniformly: a
metric's epoch result must not depend on how the epoch was batched. Runs for
every (class, input-case) pair in the registry — including host/string
metrics — except classes whose semantics are intentionally batch-dependent
(running windows) or stochastic at compute (KID subset sampling).
"""
import os
import sys

import numpy as np
import pytest

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))
from example_inputs import CASES, all_cases  # noqa: E402
from testers import _assert_allclose  # noqa: E402

# batch-dependent by design:
# - Running*/Running: windowed over the last k updates
# - KernelInceptionDistance: subset resampling at compute over pooled state
BATCH_DEPENDENT = {"RunningMean", "RunningSum", "Running", "KernelInceptionDistance"}


def _split_call(args):
    """Split every batch-shaped leaf of one update call in half."""
    def size(x):
        if isinstance(x, (list, tuple)) and not hasattr(x, "shape"):
            return len(x)
        return x.shape[0]

    def cut(x, sl):
        if isinstance(x, dict):
            return {k: cut(v, sl) for k, v in x.items()}
        if isinstance(x, (list, tuple)) and not hasattr(x, "shape"):
            return type(x)(x[sl])
        return x[sl]

    n = min(size(a) for a in args)
    h = n // 2
    if h == 0:
        return None
    return tuple(cut(a, slice(0, h)) for a in args), tuple(cut(a, slice(h, None)) for a in args)


CASE_IDS = [
    f"{name}:{cid}"
    for name in sorted(CASES)
    for cid, case in all_cases(name)
    if name not in BATCH_DEPENDENT and case.batch_axis
]


@pytest.mark.parametrize("case_key", CASE_IDS)
def test_batch_split_invariance(case_key):
    name, cid = case_key.split(":")
    case = dict(all_cases(name))[cid]

    calls = case.make_inputs(np.random.RandomState(11), 16)

    m_full = case.build(name)
    for c in calls:
        m_full.update(*c)
    expected = m_full.compute()

    m_split = case.build(name)
    for c in calls:
        halves = _split_call(c)
        if halves is None:
            m_split.update(*c)
            continue
        m_split.update(*halves[0])
        m_split.update(*halves[1])
    result = m_split.compute()

    _assert_allclose(result, expected, atol=1e-4, rtol=1e-4, msg=f"{case_key} split vs full")
