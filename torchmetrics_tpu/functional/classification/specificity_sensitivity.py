"""Best-X-at-fixed-Y curve scanners.

Parity: reference
``src/torchmetrics/functional/classification/{recall_fixed_precision,
precision_fixed_recall,specificity_sensitivity,sensitivity_specificity}.py``
— all scan the Engine B curve for the best operating point subject to a
constraint. One generic jittable scanner serves all four.
"""
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ...utils.compute import _safe_divide
from .precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_update,
)
from .roc import _binary_roc_compute, _multiclass_roc_compute, _multilabel_roc_compute

Array = jax.Array


def _best_subject_to(
    objective: Array, constraint: Array, thresholds: Array, min_constraint: float
) -> Tuple[Array, Array]:
    """max objective where constraint >= min_constraint; returns (value, threshold).

    Threshold arrays may be shorter by one than curve arrays (PR curve appends
    an endpoint); trailing positions reuse the last threshold, matching the
    reference's 1e6-sentinel-free behavior.
    """
    n = objective.shape[-1]
    if thresholds.shape[-1] < n:
        pad = jnp.broadcast_to(thresholds[..., -1:], thresholds.shape[:-1] + (n - thresholds.shape[-1],))
        thresholds = jnp.concatenate([thresholds, pad], axis=-1)
    feasible = constraint >= min_constraint
    masked = jnp.where(feasible, objective, -1.0)
    best_idx = jnp.argmax(masked, axis=-1)
    best = jnp.take_along_axis(masked, best_idx[..., None], axis=-1)[..., 0]
    thr = jnp.take_along_axis(jnp.broadcast_to(thresholds, objective.shape), best_idx[..., None], axis=-1)[..., 0]
    any_feasible = jnp.any(feasible, axis=-1)
    best = jnp.where(any_feasible, best, 0.0)
    thr = jnp.where(any_feasible, thr, 1e6)
    return best, thr


# -- recall at fixed precision ----------------------------------------------

def binary_recall_at_fixed_precision(
    preds: Array, target: Array, min_precision: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``recall_fixed_precision.py:125``."""
    preds, target, thr, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        precision, recall, t = _binary_precision_recall_curve_compute((preds, target), None)
    else:
        state = _binary_precision_recall_curve_update(preds, target, thr, mask)
        precision, recall, t = _binary_precision_recall_curve_compute(state, thr)
    return _best_subject_to(recall, precision, t, min_precision)


def multiclass_recall_at_fixed_precision(
    preds: Array, target: Array, num_classes: int, min_precision: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    preds, target, thr, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        precision, recall, t = _multiclass_precision_recall_curve_compute((preds, target), num_classes, None)
        outs = [_best_subject_to(r, p, h, min_precision) for p, r, h in zip(precision, recall, t)]
        return jnp.stack([o[0] for o in outs]), jnp.stack([o[1] for o in outs])
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thr, mask)
    precision, recall, t = _multiclass_precision_recall_curve_compute(state, num_classes, thr)
    return _best_subject_to(recall, precision, t, min_precision)


def multilabel_recall_at_fixed_precision(
    preds: Array, target: Array, num_labels: int, min_precision: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    preds, target, thr, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thr is None:
        precision, recall, t = _multilabel_precision_recall_curve_compute(
            (preds, target), num_labels, None, ignore_index
        )
        outs = [_best_subject_to(r, p, h, min_precision) for p, r, h in zip(precision, recall, t)]
        return jnp.stack([o[0] for o in outs]), jnp.stack([o[1] for o in outs])
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thr, mask)
    precision, recall, t = _multilabel_precision_recall_curve_compute(state, num_labels, thr)
    return _best_subject_to(recall, precision, t, min_precision)


# -- precision at fixed recall ----------------------------------------------

def binary_precision_at_fixed_recall(
    preds: Array, target: Array, min_recall: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``precision_fixed_recall.py:84``."""
    preds, target, thr, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        precision, recall, t = _binary_precision_recall_curve_compute((preds, target), None)
    else:
        state = _binary_precision_recall_curve_update(preds, target, thr, mask)
        precision, recall, t = _binary_precision_recall_curve_compute(state, thr)
    return _best_subject_to(precision, recall, t, min_recall)


# -- sensitivity (TPR) at fixed specificity (TNR) and vice versa ------------

def binary_sensitivity_at_specificity(
    preds: Array, target: Array, min_specificity: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``sensitivity_specificity.py``."""
    preds, target, thr, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        fpr, tpr, t = _binary_roc_compute((preds, target), None)
    else:
        state = _binary_precision_recall_curve_update(preds, target, thr, mask)
        fpr, tpr, t = _binary_roc_compute(state, thr)
    specificity = 1 - fpr
    return _best_subject_to(tpr, specificity, t, min_specificity)


def binary_specificity_at_sensitivity(
    preds: Array, target: Array, min_sensitivity: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``specificity_sensitivity.py:109``."""
    preds, target, thr, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        fpr, tpr, t = _binary_roc_compute((preds, target), None)
    else:
        state = _binary_precision_recall_curve_update(preds, target, thr, mask)
        fpr, tpr, t = _binary_roc_compute(state, thr)
    specificity = 1 - fpr
    return _best_subject_to(specificity, tpr, t, min_sensitivity)


# -- remaining multiclass/multilabel variants (generic over curve + roles) --

def _mc_curve(preds, target, num_classes, thresholds, ignore_index, roc: bool):
    preds, target, thr, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    compute = _multiclass_roc_compute if roc else _multiclass_precision_recall_curve_compute
    if thr is None:
        if mask is not None:
            preds, target = preds[mask], target[mask]
        return compute((preds, target), num_classes, None), None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thr, mask)
    return compute(state, num_classes, thr), thr


def _ml_curve(preds, target, num_labels, thresholds, ignore_index, roc: bool):
    preds, target, thr, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    compute = _multilabel_roc_compute if roc else _multilabel_precision_recall_curve_compute
    if thr is None:
        return compute((preds, target), num_labels, None, ignore_index), None
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thr, mask)
    return compute(state, num_labels, thr), thr


def _scan_per_class(curves, thr, pick, min_constraint):
    a, b, t = curves
    if thr is None:  # exact mode: per-class ragged curves in python lists
        outs = [_best_subject_to(*pick(ai, bi), hi, min_constraint) for ai, bi, hi in zip(a, b, t)]
        return jnp.stack([o[0] for o in outs]), jnp.stack([o[1] for o in outs])
    return _best_subject_to(*pick(a, b), t, min_constraint)


def multiclass_precision_at_fixed_recall(
    preds: Array, target: Array, num_classes: int, min_recall: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``precision_fixed_recall.py:multiclass_precision_at_fixed_recall``."""
    curves, thr = _mc_curve(preds, target, num_classes, thresholds, ignore_index, roc=False)
    return _scan_per_class(curves, thr, lambda p, r: (p, r), min_recall)


def multilabel_precision_at_fixed_recall(
    preds: Array, target: Array, num_labels: int, min_recall: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``precision_fixed_recall.py:multilabel_precision_at_fixed_recall``."""
    curves, thr = _ml_curve(preds, target, num_labels, thresholds, ignore_index, roc=False)
    return _scan_per_class(curves, thr, lambda p, r: (p, r), min_recall)


def multiclass_sensitivity_at_specificity(
    preds: Array, target: Array, num_classes: int, min_specificity: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``sensitivity_specificity.py:multiclass_sensitivity_at_specificity``."""
    curves, thr = _mc_curve(preds, target, num_classes, thresholds, ignore_index, roc=True)
    return _scan_per_class(curves, thr, lambda fpr, tpr: (tpr, 1 - fpr), min_specificity)


def multilabel_sensitivity_at_specificity(
    preds: Array, target: Array, num_labels: int, min_specificity: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``sensitivity_specificity.py:multilabel_sensitivity_at_specificity``."""
    curves, thr = _ml_curve(preds, target, num_labels, thresholds, ignore_index, roc=True)
    return _scan_per_class(curves, thr, lambda fpr, tpr: (tpr, 1 - fpr), min_specificity)


def multiclass_specificity_at_sensitivity(
    preds: Array, target: Array, num_classes: int, min_sensitivity: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``specificity_sensitivity.py:multiclass_specificity_at_sensitivity``."""
    curves, thr = _mc_curve(preds, target, num_classes, thresholds, ignore_index, roc=True)
    return _scan_per_class(curves, thr, lambda fpr, tpr: (1 - fpr, tpr), min_sensitivity)


def multilabel_specificity_at_sensitivity(
    preds: Array, target: Array, num_labels: int, min_sensitivity: float, thresholds: Thresholds = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Parity: reference ``specificity_sensitivity.py:multilabel_specificity_at_sensitivity``."""
    curves, thr = _ml_curve(preds, target, num_labels, thresholds, ignore_index, roc=True)
    return _scan_per_class(curves, thr, lambda fpr, tpr: (1 - fpr, tpr), min_sensitivity)


# -- task-dispatch facades (reference functional one-shots) -----------------

def _dispatch(task, binary_fn, mc_fn, ml_fn, preds, target, constraint,
              num_classes=None, num_labels=None, **kw):
    if task == "binary":
        return binary_fn(preds, target, constraint, **kw)
    if task == "multiclass":
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be an int for task='multiclass', got {num_classes}")
        return mc_fn(preds, target, num_classes, constraint, **kw)
    if task == "multilabel":
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be an int for task='multilabel', got {num_labels}")
        return ml_fn(preds, target, num_labels, constraint, **kw)
    raise ValueError(f"Expected argument `task` to be one of 'binary', 'multiclass' or 'multilabel', got {task}")


def recall_at_fixed_precision(preds, target, task, min_precision, num_classes=None, num_labels=None,
                              thresholds=None, ignore_index=None, validate_args=True):
    """Parity: reference ``recall_fixed_precision.py:recall_at_fixed_precision``."""
    return _dispatch(task, binary_recall_at_fixed_precision, multiclass_recall_at_fixed_precision,
                     multilabel_recall_at_fixed_precision, preds, target, min_precision,
                     num_classes, num_labels, thresholds=thresholds, ignore_index=ignore_index,
                     validate_args=validate_args)


def precision_at_fixed_recall(preds, target, task, min_recall, num_classes=None, num_labels=None,
                              thresholds=None, ignore_index=None, validate_args=True):
    """Parity: reference ``precision_fixed_recall.py:precision_at_fixed_recall``."""
    return _dispatch(task, binary_precision_at_fixed_recall, multiclass_precision_at_fixed_recall,
                     multilabel_precision_at_fixed_recall, preds, target, min_recall,
                     num_classes, num_labels, thresholds=thresholds, ignore_index=ignore_index,
                     validate_args=validate_args)


def sensitivity_at_specificity(preds, target, task, min_specificity, num_classes=None, num_labels=None,
                               thresholds=None, ignore_index=None, validate_args=True):
    """Parity: reference ``sensitivity_specificity.py:sensitivity_at_specificity``."""
    return _dispatch(task, binary_sensitivity_at_specificity, multiclass_sensitivity_at_specificity,
                     multilabel_sensitivity_at_specificity, preds, target, min_specificity,
                     num_classes, num_labels, thresholds=thresholds, ignore_index=ignore_index,
                     validate_args=validate_args)


def specificity_at_sensitivity(preds, target, task, min_sensitivity, num_classes=None, num_labels=None,
                               thresholds=None, ignore_index=None, validate_args=True):
    """Parity: reference ``specificity_sensitivity.py:specificity_at_sensitivity``."""
    return _dispatch(task, binary_specificity_at_sensitivity, multiclass_specificity_at_sensitivity,
                     multilabel_specificity_at_sensitivity, preds, target, min_sensitivity,
                     num_classes, num_labels, thresholds=thresholds, ignore_index=ignore_index,
                     validate_args=validate_args)
