"""Sliding-window RMSE (+ ERGAS / RASE which build on it).

Parity: reference ``src/torchmetrics/functional/image/{rmse_sw,ergas,rase}.py``.
The reference's uniform filter reflection-pads to SAME size
(``functional/image/utils.py:112``) and the final means run over the map with
``round(window_size/2)`` border columns/rows cropped; RASE additionally
divides the window-mean target by ``window_size**2``
(``rase.py:45`` — a reference quirk kept for bit-parity).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape
from .helper import depthwise_conv2d, uniform_kernel_2d

Array = jax.Array


def _reflect_pad(x: Array, window_size: int) -> Array:
    """Scipy-style symmetric padding matching the reference's
    ``_single_dimension_pad`` (``functional/image/utils.py:76``): the edge
    element repeats (symmetric, not reflect), with ``window_size // 2``
    elements before and ``window_size // 2 + window_size % 2 - 1`` after —
    making the filtered map exactly input-sized."""
    f = window_size // 2
    after = f + (window_size % 2) - 1
    return jnp.pad(x, ((0, 0), (0, 0), (f, after), (f, after)), mode="symmetric")


def _uniform_filter_same(x: Array, window_size: int) -> Array:
    """Window MEAN with reflection padding; output matches input H/W for even
    windows (one extra row/col for odd, like the reference)."""
    channel = x.shape[1]
    kernel = uniform_kernel_2d(channel, (window_size, window_size))
    return depthwise_conv2d(_reflect_pad(x, window_size), kernel)


def _crop(x: Array, window_size: int) -> Array:
    cs = round(window_size / 2)
    if cs == 0:
        return x
    return x[..., cs:-cs, cs:-cs]


def _rmse_sw_update(
    preds: Array, target: Array, window_size: int
) -> Tuple[Array, Array, Array]:
    """Returns (rmse_cropped_mean_per_batchsum, rmse_map_sum, total_images)."""
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. But got {preds.shape}.")
    if round(window_size / 2) >= preds.shape[2] or round(window_size / 2) >= preds.shape[3]:
        raise ValueError(
            f"Parameter `round(window_size / 2)` is expected to be smaller than "
            f"{min(preds.shape[2], preds.shape[3])} but got {round(window_size / 2)}."
        )
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    mse_map = _uniform_filter_same((preds - target) ** 2, window_size)
    rmse_map = jnp.sqrt(jnp.clip(mse_map, min=0.0))  # (N, C, H', W')
    rmse_val_sum = jnp.mean(jnp.sum(_crop(rmse_map, window_size), axis=0))
    return rmse_val_sum, jnp.sum(rmse_map, axis=0), jnp.asarray(preds.shape[0], jnp.float32)


def root_mean_squared_error_using_sliding_window(
    preds: Array, target: Array, window_size: int = 8, return_rmse_map: bool = False
):
    """Parity: reference ``rmse_sw.py:104`` (cropped-border mean of the
    reflection-padded RMSE map)."""
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_val_sum, rmse_map_sum, total = _rmse_sw_update(preds, target, window_size)
    rmse = rmse_val_sum / total
    if return_rmse_map:
        return rmse, rmse_map_sum / total
    return rmse


def _ergas_update(preds: Array, target: Array, ratio: float = 4.0) -> Array:
    """Per-sample ERGAS. Parity: reference ``ergas.py:28``."""
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    b, c, h, w = preds.shape
    preds_f = preds.reshape(b, c, -1)
    target_f = target.reshape(b, c, -1)
    diff = preds_f - target_f
    rmse_per_band = jnp.sqrt(jnp.mean(diff * diff, axis=-1))
    mean_target = jnp.mean(target_f, axis=-1)
    return 100.0 * ratio * jnp.sqrt(jnp.mean((rmse_per_band / mean_target) ** 2, axis=1))


def error_relative_global_dimensionless_synthesis(
    preds: Array, target: Array, ratio: float = 4.0, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """Parity: reference ``ergas.py:77``."""
    scores = _ergas_update(preds, target, ratio)
    if reduction == "elementwise_mean":
        return jnp.mean(scores)
    if reduction == "sum":
        return jnp.sum(scores)
    return scores


def _rase_update(preds: Array, target: Array, window_size: int) -> Tuple[Array, Array, Array]:
    """Per-batch accumulables: (rmse_map_sum (C,H',W'), target_window_sum
    (C,H',W'), n_images). Parity: reference ``rase.py:24`` (_rase_update)."""
    _, rmse_map_sum, total = _rmse_sw_update(preds, target, window_size)
    target_sum = jnp.sum(_uniform_filter_same(target.astype(jnp.float32), window_size) / (window_size**2), axis=0)
    return rmse_map_sum, target_sum, total


def _rase_compute(rmse_map_sum: Array, target_sum: Array, total: Array, window_size: int) -> Array:
    """Parity: reference ``rase.py:49`` (_rase_compute) — pooled maps over
    ALL images, then the nonlinear RASE map + border crop."""
    rmse_map = rmse_map_sum / total
    target_mean = jnp.mean(target_sum / total, axis=0)  # mean over channels
    rase_map = 100.0 / target_mean * jnp.sqrt(jnp.mean(rmse_map**2, axis=0))
    return jnp.mean(_crop(rase_map[None, None], window_size))


def relative_average_spectral_error(preds: Array, target: Array, window_size: int = 8) -> Array:
    """RASE. Parity: reference ``rase.py:71`` (including the window_size**2
    scaling of the window-mean target, ``rase.py:45``)."""
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    _check_same_shape(preds, target)
    return _rase_compute(*_rase_update(preds.astype(jnp.float32), target, window_size), window_size)
