"""MAPE / SMAPE / WMAPE classes.

Parity: reference ``src/torchmetrics/regression/{mape,symmetric_mape,wmape}.py``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from ..functional.regression.mape import (
    _EPS,
    _mean_absolute_percentage_error_update,
    _symmetric_mean_absolute_percentage_error_update,
    _weighted_mean_absolute_percentage_error_update,
)
from ..metric import Metric

Array = jax.Array


class MeanAbsolutePercentageError(Metric):
    """MeanAbsolutePercentageError.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanAbsolutePercentageError
        >>> metric = MeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([0.5, 1.5, 2.5, 4.0]), jnp.asarray([0.8, 1.0, 3.0, 3.5]))
        >>> round(float(metric.compute()), 4)
        0.2961
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        s, n = _mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + s
        self.total = self.total + n

    def compute(self) -> Array:
        return self.sum_abs_per_error / self.total


class SymmetricMeanAbsolutePercentageError(MeanAbsolutePercentageError):
    """SymmetricMeanAbsolutePercentageError.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import SymmetricMeanAbsolutePercentageError
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([0.5, 1.5, 2.5, 4.0]), jnp.asarray([0.8, 1.0, 3.0, 3.5]))
        >>> round(float(metric.compute()), 4)
        0.2942
    """
    def update(self, preds: Array, target: Array) -> None:
        s, n = _symmetric_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + s
        self.total = self.total + n


class WeightedMeanAbsolutePercentageError(Metric):
    """WeightedMeanAbsolutePercentageError.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import WeightedMeanAbsolutePercentageError
        >>> metric = WeightedMeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([0.5, 1.5, 2.5, 4.0]), jnp.asarray([0.8, 1.0, 3.0, 3.5]))
        >>> round(float(metric.compute()), 4)
        0.2169
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_scale", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        num, denom = _weighted_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + num
        self.sum_scale = self.sum_scale + denom

    def compute(self) -> Array:
        return self.sum_abs_error / jnp.clip(self.sum_scale, min=_EPS)
