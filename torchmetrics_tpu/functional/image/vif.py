"""Visual information fidelity (VIF-p, pixel domain).

Parity: reference ``src/torchmetrics/functional/image/vif.py`` — 4 wavelet-free
scales, gaussian windows of shrinking support, GSM channel model.
"""
import jax
import jax.numpy as jnp

from ...utils.checks import _check_same_shape
from .helper import depthwise_conv2d, gaussian_kernel_2d

Array = jax.Array


def _vif_per_channel(preds: Array, target: Array, sigma_n_sq: float) -> Array:
    """preds/target: (N, H, W) single channel."""
    preds = preds[:, None]
    target = target[:, None]
    eps = 1e-10
    preds_vif = jnp.zeros(preds.shape[0])
    target_vif = jnp.zeros(preds.shape[0])
    for scale in range(4):
        n = 2.0 ** (4 - scale) + 1.0
        kernel_size = int(n)
        sigma = n / 5.0
        if scale > 0:
            kernel = gaussian_kernel_2d(1, (kernel_size, kernel_size), (sigma, sigma))
            preds = depthwise_conv2d(preds, kernel)[:, :, ::2, ::2]
            target = depthwise_conv2d(target, kernel)[:, :, ::2, ::2]
        kernel = gaussian_kernel_2d(1, (kernel_size, kernel_size), (sigma, sigma))
        mu_p = depthwise_conv2d(preds, kernel)
        mu_t = depthwise_conv2d(target, kernel)
        mu_p_sq, mu_t_sq, mu_pt = mu_p**2, mu_t**2, mu_p * mu_t
        sigma_p_sq = jnp.clip(depthwise_conv2d(preds**2, kernel) - mu_p_sq, min=0.0)
        sigma_t_sq = jnp.clip(depthwise_conv2d(target**2, kernel) - mu_t_sq, min=0.0)
        sigma_pt = depthwise_conv2d(preds * target, kernel) - mu_pt

        g = sigma_pt / (sigma_t_sq + eps)
        sv_sq = sigma_p_sq - g * sigma_pt

        g = jnp.where(sigma_t_sq >= eps, g, 0.0)
        sv_sq = jnp.where(sigma_t_sq >= eps, sv_sq, sigma_p_sq)
        sigma_t_sq = jnp.where(sigma_t_sq >= eps, sigma_t_sq, 0.0)

        g = jnp.where(sigma_p_sq >= eps, g, 0.0)
        sv_sq = jnp.where(sigma_p_sq >= eps, sv_sq, 0.0)

        sv_sq = jnp.where(g >= 0, sv_sq, sigma_p_sq)
        g = jnp.clip(g, min=0.0)
        sv_sq = jnp.clip(sv_sq, min=eps)

        preds_vif_scale = jnp.log2(1.0 + g**2 * sigma_t_sq / (sv_sq + sigma_n_sq))
        preds_vif = preds_vif + jnp.sum(preds_vif_scale, axis=(1, 2, 3))
        target_vif = target_vif + jnp.sum(jnp.log2(1.0 + sigma_t_sq / sigma_n_sq), axis=(1, 2, 3))
    return preds_vif / (target_vif + eps)


def visual_information_fidelity(preds: Array, target: Array, sigma_n_sq: float = 2.0) -> Array:
    """Parity: reference ``vif.py:99``."""
    _check_same_shape(preds, target)
    if preds.shape[-1] < 41 or preds.shape[-2] < 41:
        raise ValueError(f"Invalid size of preds. Expected at least 41x41, but got {preds.shape[-2:]}!")
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    per_channel = [
        _vif_per_channel(preds[:, i], target[:, i], sigma_n_sq) for i in range(preds.shape[1])
    ]
    return jnp.mean(jnp.stack(per_channel)) if preds.shape[1] > 1 else jnp.mean(per_channel[0])
