"""Tests for the tpulint abstract-interpretation engine (``dataflow.py``).

Covers the lattice itself (table-driven join/widen cases), the summary
cache, the three SPMD rule families (TPU012/013/014) with positive /
negative / waived / interprocedural fixtures each, the interprocedural
upgrades to TPU003/TPU005, the seeded-bug detection gate, SARIF output
shape, ``--jobs`` determinism, and the callgraph attribute-alias fix.

Fixture layout mirrors ``test_tpulint.py``: kernels in a ``*.functional.*``
module so root detection sees them; the corpus is pure-AST so a stub
``torchmetrics_tpu.metric.Metric`` suffices for MRO resolution.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.tpulint import run_lint
from tools.tpulint.corpus import Corpus
from tools.tpulint.dataflow import (
    BOTTOM,
    HOST,
    RANK_DEP,
    TRACED,
    AbstractValue,
    DataflowEngine,
    join,
    join_env,
    signature_fingerprint,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

METRIC_STUB = """
class Metric:
    def add_state(self, name, default, dist_reduce_fx=None):
        pass
"""

FIXTURE_HEADER = """
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map
"""


def _write_fixture(tmp_path, kernel_src=None, metrics_src=None, header=True):
    (tmp_path / "torchmetrics_tpu").mkdir(parents=True, exist_ok=True)
    (tmp_path / "torchmetrics_tpu" / "metric.py").write_text(METRIC_STUB)
    paths = [str(tmp_path / "torchmetrics_tpu")]
    if kernel_src is not None:
        (tmp_path / "pkg" / "functional").mkdir(parents=True, exist_ok=True)
        src = (FIXTURE_HEADER if header else "") + textwrap.dedent(kernel_src)
        (tmp_path / "pkg" / "functional" / "kern.py").write_text(src)
        paths.append(str(tmp_path / "pkg"))
    if metrics_src is not None:
        (tmp_path / "mpkg").mkdir(exist_ok=True)
        (tmp_path / "mpkg" / "metrics.py").write_text(textwrap.dedent(metrics_src))
        paths.append(str(tmp_path / "mpkg"))
    return paths


def _lint(tmp_path, kernel_src=None, metrics_src=None, **kw):
    paths = _write_fixture(tmp_path, kernel_src, metrics_src)
    return run_lint(paths, root=str(tmp_path), baseline_path=None, **kw)


def _rules(result):
    return sorted({v.rule for v in result.new_violations})


def _corpus_fn(tmp_path, kernel_src):
    """Build a corpus from one kernel module; return (corpus, fn-by-suffix)."""
    paths = _write_fixture(tmp_path, kernel_src)
    corpus = Corpus.build(paths, root=str(tmp_path))

    def by_name(name):
        for qn, fn in corpus.functions.items():
            if qn.endswith(":" + name):
                return fn
        raise KeyError(name)

    return corpus, by_name


# ---------------------------------------------------------------------------
# lattice: table-driven join cases
# ---------------------------------------------------------------------------

JOIN_TABLE = [
    # (a, b, expected) — kind is max, specs merge unless they conflict,
    # deps union
    (AbstractValue(BOTTOM), AbstractValue(HOST), AbstractValue(HOST)),
    (AbstractValue(HOST), AbstractValue(HOST), AbstractValue(HOST)),
    (AbstractValue(HOST), AbstractValue(TRACED), AbstractValue(TRACED)),
    (AbstractValue(TRACED), AbstractValue(RANK_DEP), AbstractValue(RANK_DEP)),
    (AbstractValue(RANK_DEP), AbstractValue(HOST), AbstractValue(RANK_DEP)),
    (
        AbstractValue(TRACED, "P('a')"),
        AbstractValue(TRACED, "P('a')"),
        AbstractValue(TRACED, "P('a')"),
    ),
    (  # one side unsharded: the known spec survives
        AbstractValue(TRACED, "P('a')"),
        AbstractValue(TRACED, None),
        AbstractValue(TRACED, "P('a')"),
    ),
    (  # conflicting specs join to unknown, not to either side
        AbstractValue(TRACED, "P('a')"),
        AbstractValue(TRACED, "P('b')"),
        AbstractValue(TRACED, None),
    ),
    (
        AbstractValue(TRACED, deps=frozenset({0})),
        AbstractValue(HOST, deps=frozenset({1})),
        AbstractValue(TRACED, deps=frozenset({0, 1})),
    ),
]


@pytest.mark.parametrize("a,b,expected", JOIN_TABLE)
def test_lattice_join_table(a, b, expected):
    assert join(a, b) == expected
    assert join(b, a) == expected  # commutative


def test_lattice_join_idempotent_and_associative():
    vals = [
        AbstractValue(HOST),
        AbstractValue(TRACED, "P('x')"),
        AbstractValue(RANK_DEP, deps=frozenset({2})),
    ]
    for v in vals:
        assert join(v, v) == v
    a, b, c = vals
    assert join(join(a, b), c) == join(a, join(b, c))


def test_lattice_join_env_merges_missing_keys():
    a = {"x": AbstractValue(HOST), "y": AbstractValue(TRACED)}
    b = {"y": AbstractValue(RANK_DEP), "z": AbstractValue(HOST)}
    out = join_env(a, b)
    assert out["x"].kind == HOST
    assert out["y"].kind == RANK_DEP
    assert out["z"].kind == HOST


# ---------------------------------------------------------------------------
# branch merge + loop widening through summaries
# ---------------------------------------------------------------------------


def test_branch_merge_returns_join_of_arms(tmp_path):
    corpus, fn = _corpus_fn(tmp_path, """
        from jax import lax

        def _pick(flag, preds):
            if flag:
                out = lax.axis_index("batch")
            else:
                out = 0
            return out
    """)
    summary = DataflowEngine(corpus).summarize(fn("_pick"))
    assert summary.returns.kind == RANK_DEP  # RANK_DEP ⊔ HOST


def test_loop_widening_reaches_fixpoint(tmp_path):
    # acc starts HOST, becomes TRACED through the loop body: the second
    # pass (the widen) must see the joined state, so the return is TRACED
    corpus, fn = _corpus_fn(tmp_path, """
        def _accumulate(preds, target):
            acc = 0
            for _ in range(3):
                acc = preds + acc
            return acc
    """)
    summary = DataflowEngine(corpus).summarize(fn("_accumulate"))
    assert summary.returns.kind == TRACED


def test_summary_cache_hits_and_signature_invalidation(tmp_path):
    corpus, fn = _corpus_fn(tmp_path, """
        def _helper(x):
            return x + 1

        def _same_body(x):
            return x + 1
    """)
    engine = DataflowEngine(corpus)
    target = fn("_helper")
    engine.summarize(target)
    assert engine.stats["misses"] >= 1
    before_hits = engine.stats["hits"]
    engine.summarize(target)
    assert engine.stats["hits"] == before_hits + 1  # second call is cached

    # the cache key is (qualname, signature fingerprint): same signature +
    # same name hits; a signature change produces a different key even when
    # the body is unchanged
    corpus2, fn2 = _corpus_fn(tmp_path / "v2", """
        def _helper(x, extra=None):
            return x + 1
    """)
    old_key = engine.cache_key(target)
    new_key = DataflowEngine(corpus2).cache_key(fn2("_helper"))
    assert old_key != new_key
    assert signature_fingerprint(target) != signature_fingerprint(fn2("_helper"))
    # identical signature under a different name: fingerprint matches, the
    # qualname half of the key still separates the entries
    assert signature_fingerprint(target) == signature_fingerprint(fn("_same_body"))
    assert engine.cache_key(target) != engine.cache_key(fn("_same_body"))


# ---------------------------------------------------------------------------
# TPU012 — collective divergence (positive / negative / waived / interproc)
# ---------------------------------------------------------------------------


def test_tpu012_rank_branch_over_psum_flagged(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _div_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:
                total = lax.psum(preds, "batch")
            else:
                total = preds
            return total
    """)
    assert "TPU012" in _rules(res)


def test_tpu012_rank_value_in_data_flow_passes(tmp_path):
    # rank feeds DATA (the scatter index), not control flow: every rank
    # still issues the same psum — the canonical zeros+psum gather idiom
    res = _lint(tmp_path, kernel_src="""
        def _ok_update(preds, target):
            i = lax.axis_index("batch")
            buf = jnp.zeros((8,)).at[i].set(preds.sum())
            return lax.psum(buf, "batch")
    """)
    assert not res.new_violations


def test_tpu012_waiver_suppresses(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _waived_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:  # tpulint: disable=TPU013(rank-0 probe by protocol), TPU003(ditto)
                return lax.psum(preds, "batch")  # tpulint: disable=TPU012(rank-0 probe by protocol)
            return preds
    """)
    assert not res.new_violations
    assert {v.rule for v in res.waived} == {"TPU012", "TPU013", "TPU003"}


def test_tpu012_interprocedural_rank_arg_flagged(tmp_path):
    # the callee branches on its (neutrally named) first param; passing a
    # rank-dependent value turns that branch divergent — flagged at the
    # CALL SITE, which the old syntactic pass could never see
    res = _lint(tmp_path, kernel_src="""
        def _helper_idx_branch(idx, x):
            if idx == 0:
                return lax.psum(x, "batch")
            return x

        def _interp_update(preds, target):
            r = lax.axis_index("batch")
            return _helper_idx_branch(r, preds)
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU012"]
    assert any("_interp_update" in v.symbol for v in hits)


def test_tpu012_interprocedural_host_arg_passes(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _helper_idx_branch(idx, x):
            if idx == 0:
                return lax.psum(x, "batch")
            return x

        def _cfg_update(preds, target):
            return _helper_idx_branch(0, preds)
    """)
    assert "TPU012" not in _rules(res)


def test_tpu012_eager_elastic_round_flagged(tmp_path):
    # eager divergence: an elastic-round phase behind a process_index
    # branch deadlocks the pod exactly like an in-graph psum
    res = _lint(tmp_path, metrics_src="""
        import jax


        class Backend:
            def begin_round(self, epoch):
                pass

            def end_round(self):
                pass


        class Wrapper:
            def __init__(self):
                self._inner = Backend()

            def risky(self):
                rank = jax.process_index()
                if rank == 0:
                    self._inner.begin_round(0)
                self._inner.end_round()
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU012"]
    assert any("Wrapper.risky" in v.symbol for v in hits)


# ---------------------------------------------------------------------------
# TPU013 — collective-order mismatch (positive / negative / waived / interproc)
# ---------------------------------------------------------------------------


def test_tpu013_early_return_skips_collective_flagged(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _order_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:
                return preds
            return lax.all_gather(preds, "batch")
    """)
    assert "TPU013" in _rules(res)


def test_tpu013_same_sequence_both_arms_passes(tmp_path):
    # both arms issue the identical collective sequence, and the branch is
    # host config anyway: no divergence either way
    res = _lint(tmp_path, kernel_src="""
        def _both_update(preds, target):
            flag = 1
            if flag:
                total = lax.psum(preds, "batch")
            else:
                total = lax.psum(target, "batch")
            return total
    """)
    assert "TPU013" not in _rules(res)


def test_tpu013_waiver_suppresses(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _probe_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:  # tpulint: disable=TPU013(rank-0 probe by protocol), TPU012(ditto), TPU003(ditto)
                g = lax.all_gather(preds, "batch")
            return preds
    """)
    assert "TPU013" not in _rules(res)


def test_tpu013_interprocedural_callee_sequence_inlined(tmp_path):
    # the collective hides one call deep: the caller's paths still differ
    # (helper inlines to ['psum'] vs []) and the divergence is reported in
    # the CALLER where the rank-dependent branch lives
    res = _lint(tmp_path, kernel_src="""
        def _h(x):
            return lax.psum(x, "batch")

        def _seq_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:
                return _h(preds)
            return preds
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU013"]
    assert any("_seq_update" in v.symbol for v in hits)


# ---------------------------------------------------------------------------
# TPU014 — sharding-spec consistency (positive / negative / waived / interproc)
# ---------------------------------------------------------------------------


def test_tpu014_spec_mismatch_flagged(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _shard_update(preds, target, mesh):
            x = jax.device_put(preds, NamedSharding(mesh, P("a")))
            k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("b"), out_specs=P("b"))
            return k(x)
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU014"]
    assert hits and "P('a')" in hits[0].message and "P('b')" in hits[0].message


def test_tpu014_reshard_between_passes(tmp_path):
    # an explicit device_put to the consumer's spec is the legal reshard
    res = _lint(tmp_path, kernel_src="""
        def _reshard_update(preds, target, mesh):
            x = jax.device_put(preds, NamedSharding(mesh, P("a")))
            y = jax.device_put(x, NamedSharding(mesh, P("b")))
            k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("b"), out_specs=P("b"))
            return k(y)
    """)
    assert "TPU014" not in _rules(res)


def test_tpu014_waiver_suppresses(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _shard_update(preds, target, mesh):
            x = jax.device_put(preds, NamedSharding(mesh, P("a")))
            k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("b"), out_specs=P("b"))
            return k(x)  # tpulint: disable=TPU014(replicated probe input, mismatch intended)
    """)
    assert "TPU014" not in _rules(res)
    assert any(v.rule == "TPU014" for v in res.waived)


def test_tpu014_spec_through_helper_return_flagged(tmp_path):
    # the producer spec travels through a helper's return value: only the
    # interprocedural summary knows y is P('rows')
    res = _lint(tmp_path, kernel_src="""
        def _make_sharded(v, mesh):
            return jax.device_put(v, NamedSharding(mesh, P("rows")))

        def _shard2_update(preds, target, mesh):
            y = _make_sharded(preds, mesh)
            k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("cols"), out_specs=P("cols"))
            return k(y)
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU014"]
    assert any("_shard2_update" in v.symbol for v in hits)


# ---------------------------------------------------------------------------
# interprocedural TPU003 / TPU005 (taint through helper calls — the cases
# the old same-function syntactic pass misses)
# ---------------------------------------------------------------------------


def test_tpu003_branch_on_helper_return_flagged(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _arrmaker(x):
            return jnp.sum(x)

        def _ctl_update(preds, target):
            if _arrmaker(preds):
                return preds * 2
            return preds
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU003"]
    assert any("_ctl_update" in v.symbol for v in hits)


def test_tpu003_branch_on_helper_host_return_passes(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _cfg(x):
            return 4

        def _host_update(preds, target):
            if _cfg(preds):
                return preds * 2
            return preds
    """)
    assert "TPU003" not in _rules(res)


def test_tpu005_donation_through_helper_flagged(tmp_path):
    # the donation happens inside the helper; the caller reads the donated
    # buffer afterwards — only the summary's donates_params reveals it
    res = _lint(tmp_path, kernel_src="""
        def _donating_helper(buf, inc):
            step = jax.jit(lambda b, i: b + i, donate_argnums=(0,))
            return step(buf, inc)

        def _donate_update(preds, target):
            out = _donating_helper(preds, target)
            return out + preds.sum()
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU005"]
    assert any("_donate_update" in v.symbol for v in hits)


def test_tpu005_no_read_after_helper_donation_passes(tmp_path):
    res = _lint(tmp_path, kernel_src="""
        def _donating_helper(buf, inc):
            step = jax.jit(lambda b, i: b + i, donate_argnums=(0,))
            return step(buf, inc)

        def _donate_ok_update(preds, target):
            out = _donating_helper(preds, target)
            return out
    """)
    assert "TPU005" not in _rules(res)


# ---------------------------------------------------------------------------
# seeded-bug gate: every planted SPMD bug detected, clean corpus stays clean
# ---------------------------------------------------------------------------

SEEDED_KERNELS = """
    def _div_update(preds, target):
        i = lax.axis_index("batch")
        if i == 0:
            total = lax.psum(preds, "batch")
        else:
            total = preds
        return total

    def _order_update(preds, target):
        i = lax.axis_index("batch")
        if i == 0:
            return preds
        return lax.all_gather(preds, "batch")

    def _shard_update(preds, target, mesh):
        x = jax.device_put(preds, NamedSharding(mesh, P("a")))
        k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("b"), out_specs=P("b"))
        return k(x)

    def _helper_idx_branch(idx, x):
        if idx == 0:
            return lax.psum(x, "batch")
        return x

    def _interp_update(preds, target):
        r = lax.axis_index("batch")
        return _helper_idx_branch(r, preds)

    def _helper_rank_branch(rank, x):
        if rank == 0:
            return lax.psum(x, "batch")
        return x

    def _h(x):
        return lax.psum(x, "batch")

    def _seq_update(preds, target):
        i = lax.axis_index("batch")
        if i == 0:
            return _h(preds)
        return preds

    def _make_sharded(v, mesh):
        return jax.device_put(v, NamedSharding(mesh, P("rows")))

    def _shard2_update(preds, target, mesh):
        y = _make_sharded(preds, mesh)
        k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("cols"), out_specs=P("cols"))
        return k(y)

    def _arrmaker(x):
        return jnp.sum(x)

    def _ctl_update(preds, target):
        if _arrmaker(preds):
            return preds * 2
        return preds

    def _donating_helper(buf, inc):
        step = jax.jit(lambda b, i: b + i, donate_argnums=(0,))
        return step(buf, inc)

    def _donate_update(preds, target):
        out = _donating_helper(preds, target)
        return out + preds.sum()
"""

# (rule, symbol-suffix) for every planted bug: ≥12 distinct findings
SEEDED_EXPECTED = {
    ("TPU012", "_div_update"),
    ("TPU013", "_div_update"),
    ("TPU003", "_div_update"),
    # _helper_idx_branch's param is neutrally named, so nothing fires inside
    # it — the finding lands at _interp_update's call site instead; the
    # rank-named twin fires intraprocedurally
    ("TPU012", "_helper_rank_branch"),
    ("TPU013", "_helper_rank_branch"),
    ("TPU012", "_interp_update"),
    ("TPU013", "_order_update"),
    ("TPU003", "_order_update"),
    ("TPU014", "_shard_update"),
    ("TPU012", "_seq_update"),
    ("TPU013", "_seq_update"),
    ("TPU003", "_seq_update"),
    ("TPU014", "_shard2_update"),
    ("TPU003", "_ctl_update"),
    ("TPU005", "_donate_update"),
}

CLEAN_KERNELS = """
    def _ok_update(preds, target):
        i = lax.axis_index("batch")
        buf = jnp.zeros((8,)).at[i].set(preds.sum())
        return lax.psum(buf, "batch")

    def _both_update(preds, target):
        flag = 1
        if flag:
            total = lax.psum(preds, "batch")
        else:
            total = lax.psum(target, "batch")
        return total

    def _reshard_update(preds, target, mesh):
        x = jax.device_put(preds, NamedSharding(mesh, P("a")))
        y = jax.device_put(x, NamedSharding(mesh, P("b")))
        k = shard_map(lambda v: v * 2, mesh=mesh, in_specs=P("b"), out_specs=P("b"))
        return k(y)

    def _loop_update(preds, target):
        acc = 0
        for _ in range(3):
            acc = preds + acc
        return acc
"""


def test_seeded_bug_gate_full_detection(tmp_path):
    assert len(SEEDED_EXPECTED) >= 12
    res = _lint(tmp_path, kernel_src=SEEDED_KERNELS)
    found = {
        (v.rule, v.symbol.rsplit(":", 1)[1])
        for v in res.new_violations
    }
    missed = SEEDED_EXPECTED - found
    assert not missed, f"seeded bugs not detected: {sorted(missed)}"


def test_seeded_bug_gate_zero_false_positives(tmp_path):
    res = _lint(tmp_path, kernel_src=CLEAN_KERNELS)
    assert not res.new_violations, [v.format() for v in res.new_violations]


# ---------------------------------------------------------------------------
# callgraph attribute-alias resolution (satellite regression)
# ---------------------------------------------------------------------------

ALIAS_METRICS = """
    from torchmetrics_tpu.metric import Metric


    class Backend:
        def grab(self, x):
            return x.item()


    class AliasMetric(Metric):
        def __init__(self):
            self._backend = Backend()
            self.add_state("total", 0)

        def update(self, preds, target):
            b = self._backend
            self.total = b.grab(preds)
"""


def test_callgraph_resolves_attr_local_alias(tmp_path):
    # b = self._backend; b.grab(...) — one hop into the sync stack the old
    # resolver went blind on; Backend.grab must be reachable and flagged
    res = _lint(tmp_path, metrics_src=ALIAS_METRICS)
    hits = [v for v in res.new_violations if v.rule == "TPU001"]
    assert any("Backend.grab" in v.symbol for v in hits)


def test_callgraph_resolves_self_attr_call(tmp_path):
    res = _lint(tmp_path, metrics_src="""
        from torchmetrics_tpu.metric import Metric


        class Backend:
            def grab(self, x):
                return x.item()


        class AttrMetric(Metric):
            def __init__(self):
                self._backend = Backend()
                self.add_state("total", 0)

            def update(self, preds, target):
                self.total = self._backend.grab(preds)
    """)
    hits = [v for v in res.new_violations if v.rule == "TPU001"]
    assert any("Backend.grab" in v.symbol for v in hits)


# ---------------------------------------------------------------------------
# SARIF output + severity tiers
# ---------------------------------------------------------------------------


def test_sarif_output_shape(tmp_path):
    _write_fixture(tmp_path, kernel_src="""
        def _div_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:
                return lax.psum(preds, "batch")
            return preds
    """)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "pkg", "torchmetrics_tpu",
         "--no-baseline", "--sarif"],
        cwd=str(tmp_path),
        env=dict(os.environ, PYTHONPATH=REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 1  # violations present
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpulint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert {"TPU012", "TPU013", "TPU014"} <= rule_ids
    for r in driver["rules"]:
        assert r["defaultConfiguration"]["level"] in ("error", "warning")
    assert run["results"], "expected at least one result"
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["level"] in ("error", "warning")
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1


def test_sarif_waived_become_suppressions(tmp_path):
    _write_fixture(tmp_path, kernel_src="""
        def _w_update(preds, target):
            i = lax.axis_index("batch")
            if i == 0:  # tpulint: disable=TPU013(probe), TPU003(probe)
                return lax.psum(preds, "batch")  # tpulint: disable=TPU012(probe)
            return preds
    """)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "pkg", "torchmetrics_tpu",
         "--no-baseline", "--sarif"],
        cwd=str(tmp_path),
        env=dict(os.environ, PYTHONPATH=REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0  # everything waived
    doc = json.loads(proc.stdout)
    suppressed = [r for r in doc["runs"][0]["results"] if r.get("suppressions")]
    assert suppressed
    assert all(s["suppressions"][0]["kind"] == "inSource" for s in suppressed)


def test_severity_tiers_and_fail_on(tmp_path):
    # TPU006 (float64) is warn-tier; --fail-on error must exit 0 on it,
    # --fail-on warn (the default) must exit 1
    _write_fixture(tmp_path, kernel_src="""
        def _f64_update(preds, target):
            return preds.astype(jnp.float64)
    """)
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    warn = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "pkg", "torchmetrics_tpu", "--no-baseline"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
    )
    assert warn.returncode == 1
    assert "[warn]" in warn.stdout
    err = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "pkg", "torchmetrics_tpu",
         "--no-baseline", "--fail-on", "error"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
    )
    assert err.returncode == 0


# ---------------------------------------------------------------------------
# --jobs N: deterministic output regardless of shard count
# ---------------------------------------------------------------------------


def test_jobs_sharding_is_deterministic(tmp_path):
    paths = _write_fixture(tmp_path, kernel_src=SEEDED_KERNELS)

    def key(res):
        return [
            (v.rule, v.path, v.line, v.col, v.symbol, v.message, v.waived)
            for v in res.violations
        ]

    serial = run_lint(paths, root=str(tmp_path), baseline_path=None)
    pooled = run_lint(paths, root=str(tmp_path), baseline_path=None, jobs=2)
    assert key(serial) == key(pooled)
    assert serial.n_files == pooled.n_files
    assert serial.n_roots == pooled.n_roots


def test_lint_result_reports_wall_time(tmp_path):
    res = _lint(tmp_path, kernel_src=CLEAN_KERNELS)
    assert res.wall_s > 0
    assert res.jobs == 1
