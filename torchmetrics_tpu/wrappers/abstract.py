"""WrapperMetric base.

Parity: reference ``src/torchmetrics/wrappers/abstract.py:19`` — fixes
``forward`` cache semantics for metrics that wrap other metrics (the wrapped
metric handles its own batch-value computation).
"""
from typing import Any

from ..metric import Metric


class WrapperMetric(Metric):
    """Base class for wrapper metrics; inner metrics own their states."""

    jittable = False  # wrappers orchestrate Python objects; inner metrics jit themselves

    def _wrap_compute_value(self, value: Any) -> Any:
        return value

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Default wrapper forward: delegate to update + compute-on-inner."""
        raise NotImplementedError
