"""Universal dtype / differentiability / multi-device sweeps over the class
surface, driven by the ``tests/helpers/example_inputs.py`` registry.

Parity targets (reference ``tests/unittests/_helpers/testers.py``):

- ``run_precision_test_cpu/gpu`` (:463-529): every device metric must accept
  bf16/f16 inputs — the TPU-native dtype — produce finite results, and stay
  near its f32 value (accumulator states are f32 by design; what is being
  bounded here is input-rounding effects).
- ``run_differentiability_test`` (:531-566): ``is_differentiable=True``
  classes must yield finite gradients through a real ``jax.grad`` trace of
  update→compute, not just carry the flag.
- per-metric ``ddp=True`` runs (:398): every array-input metric must produce
  the same result from an 8-device ``shard_map`` update + ``reduce_state``
  as from a single-device update on the full batch.
"""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "helpers"))
from example_inputs import CASES, all_cases  # noqa: E402
from testers import _assert_allclose, _shard_map, sim_devices  # noqa: E402


def _case_ids(pred):
    """[(name, case_id)] over base + variant input cases (VERDICT r2 #3)."""
    out = []
    for name in sorted(CASES):
        for cid, case in all_cases(name):
            if pred(case):
                out.append(f"{name}:{cid}")
    return out


def _lookup(case_key):
    name, cid = case_key.split(":")
    return name, dict(all_cases(name))[cid]

# curve-shaped outputs: low-precision inputs legitimately change tie
# structure / threshold grids (and ROC thresholds start at +inf by design),
# so only nan-freedom is checked there; the ROC at-fixed scanners can
# legitimately return the +inf origin threshold
CURVE_OUTPUT = {"ROC", "PrecisionRecallCurve", "RetrievalPrecisionRecallCurve",
                "SensitivityAtSpecificity", "SpecificityAtSensitivity"}

# value drift under half precision is expected to be large (ratio-of-small-
# numbers metrics, incl. the covariance ratios behind the dummy-net MiFID);
# finiteness-only
FINITE_ONLY = CURVE_OUTPUT | {
    "MatthewsCorrCoef",
    "VisualInformationFidelity",
    "MemorizationInformedFrechetInceptionDistance",
}


def _cast_tree(x, dtype):
    if isinstance(x, dict):
        return {k: _cast_tree(v, dtype) for k, v in x.items()}
    if isinstance(x, (tuple, list)):
        return type(x)(_cast_tree(v, dtype) for v in x)
    if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(dtype)
    return x


def _finite(tree, allow_inf: bool = False) -> bool:
    ok = True
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf, dtype=np.float64)
        good = ~np.isnan(arr) if allow_inf else np.isfinite(arr)
        ok = ok and bool(good.all())
    return ok


DEVICE_CASES = _case_ids(lambda c: c.device)


@pytest.mark.parametrize("dtype_name", ["bfloat16", "float16"])
@pytest.mark.parametrize("case_key", DEVICE_CASES)
def test_low_precision_inputs(case_key, dtype_name):
    """bf16/f16 inputs: runs, finite, and near the f32 result."""
    name, case = _lookup(case_key)
    dtype = jnp.dtype(dtype_name)

    calls32 = case.make_inputs(np.random.RandomState(42), 16)
    m32 = case.build(name)
    for c in calls32:
        m32.update(*c)
    r32 = m32.compute()

    calls_lp = case.make_inputs(np.random.RandomState(42), 16)
    mlp = case.build(name)
    for c in calls_lp:
        mlp.update(*_cast_tree(c, dtype))
    rlp = mlp.compute()

    assert _finite(rlp, allow_inf=name in CURVE_OUTPUT), \
        f"{name}: non-finite result with {dtype_name} inputs"
    if name in FINITE_ONLY or case.finite_only:
        return
    # generous bound: input rounding only — accumulation stays f32
    tol = max(case.tol, 0.1 if dtype == jnp.float16 else 0.0)
    _assert_allclose(rlp, r32, atol=tol, rtol=tol, msg=f"{name} {dtype_name} drift")


GRAD_CASES = _case_ids(lambda c: c.device and c.grad_arg is not None)


@pytest.mark.parametrize("case_key", GRAD_CASES)
def test_differentiability_flag(case_key):
    """is_differentiable=True ⇒ finite grads through update→compute."""
    name, case = _lookup(case_key)
    m = case.build(name)
    args = list(case.make_inputs(np.random.RandomState(0), 8)[0])
    gi = case.grad_arg

    def loss(x):
        a = list(args)
        a[gi] = x
        state = m.init_state()
        state = m.update_state(state, *a)
        result = m.compute_state(state)
        total = 0.0
        for leaf in jax.tree_util.tree_leaves(result):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                total = total + jnp.sum(jnp.nan_to_num(jnp.asarray(leaf)))
        return total

    if not m.is_differentiable:
        pytest.skip(f"{name}: is_differentiable=False (cannot be falsified mechanically)")
    grads = jax.grad(loss)(args[gi])
    arr = np.asarray(grads, dtype=np.float64)
    assert np.isfinite(arr).all(), f"{name}: non-finite gradient but is_differentiable=True"


SHARD_CASES = _case_ids(lambda c: c.device and c.batch_axis)


@pytest.mark.parametrize("case_key", SHARD_CASES)
def test_shard_map_state_sync(case_key):
    """8-device shard_map update + reduce_state == single-device update."""
    from jax.sharding import Mesh, PartitionSpec as P

    devs = sim_devices(8)
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    name, case = _lookup(case_key)
    m = case.build(name)
    if not getattr(m, "jittable", True):
        pytest.skip(f"{name}: not jittable")
    if not m._use_jit:
        # instance-declared eager-only config (e.g. CalibrationError's
        # histogram path with ignore_index filters data-dependently)
        pytest.skip(f"{name}: configuration is eager-only (_use_jit=False)")
    args = case.make_inputs(np.random.RandomState(7), 16)[0]

    state = m.init_state()
    state = m.update_state(state, *args)
    expected = m.compute_state(state)

    mesh = Mesh(np.array(devs), ("dp",))
    shard_map = _shard_map()

    def step(*a):
        st = m.init_state()
        st = m.update_state(st, *a)
        return m.reduce_state(st, "dp")

    fn = shard_map(step, mesh=mesh, in_specs=tuple(P("dp") for _ in args), out_specs=P())
    synced = jax.jit(fn)(*args)
    result = m.compute_state(synced)
    _assert_allclose(result, expected, atol=1e-4, rtol=1e-4, msg=f"{name} sharded vs single")
