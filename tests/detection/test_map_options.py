"""MeanAveragePrecision option-surface parity sweep (VERDICT r2 missing #5).

Grid over ``iou_thresholds`` / ``rec_thresholds`` / ``max_detection_thresholds``
/ ``class_metrics`` / ``box_format`` on shared synthetic scenes, against the
reference's pure-torch legacy COCOeval (``detection/_mean_ap.py`` — the same
oracle as ``test_map_vs_reference.py``; it takes the identical constructor
surface but needs no real pycocotools). Crowd gts are excluded (the legacy
oracle implements no iscrowd handling — see the note in
``test_map_vs_reference.py``).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "helpers"))
from lightning_utilities_stub import install_stub as _lu  # noqa: E402
from pycocotools_stub import install_stub as _pc  # noqa: E402
from torchvision_stub import install_stub as _tv  # noqa: E402

_lu()
_pc()
_tv()
sys.path.insert(0, "/root/reference/src")
torch = pytest.importorskip("torch")

from torchmetrics.detection._mean_ap import MeanAveragePrecision as LegacyMAP  # noqa: E402

from torchmetrics_tpu.detection import MeanAveragePrecision  # noqa: E402

BASE_KEYS = ["map", "map_50", "map_75", "map_small", "map_medium", "map_large",
             "mar_small", "mar_medium", "mar_large"]


def _scenes(seed=3, n=6, n_classes=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        n_gt = rng.randint(1, 6)
        n_det = rng.randint(1, 8)
        gt_xy = rng.rand(n_gt, 2) * 80
        gt_wh = rng.rand(n_gt, 2) * 40 + 3
        gt = np.concatenate([gt_xy, gt_xy + gt_wh], axis=1)
        det = gt[rng.randint(0, n_gt, n_det)] + rng.randn(n_det, 4) * 2
        det = np.sort(det.reshape(n_det, 2, 2), axis=1).reshape(n_det, 4)
        d = {"boxes": det.astype(np.float32), "scores": rng.rand(n_det).astype(np.float32),
             "labels": rng.randint(0, n_classes, n_det)}
        g = {"boxes": gt.astype(np.float32), "labels": rng.randint(0, n_classes, n_gt)}
        out.append((d, g))
    return out


def _to_xywh(b):
    x0, y0, x1, y1 = b.T
    return np.stack([x0, y0, x1 - x0, y1 - y0], axis=1)


def _to_cxcywh(b):
    x0, y0, x1, y1 = b.T
    return np.stack([(x0 + x1) / 2, (y0 + y1) / 2, x1 - x0, y1 - y0], axis=1)


_CONVERT = {"xyxy": lambda b: b, "xywh": _to_xywh, "cxcywh": _to_cxcywh}

# >= 20 combinations across the whole constructor surface
GRID = [
    # (iou_thresholds, rec_thresholds, max_detection_thresholds, class_metrics, box_format)
    (None, None, None, False, "xyxy"),
    (None, None, None, True, "xyxy"),
    ([0.5], None, None, False, "xyxy"),
    ([0.5], None, None, True, "xyxy"),
    ([0.75], None, None, False, "xyxy"),
    ([0.3, 0.5, 0.7], None, None, False, "xyxy"),
    ([0.3, 0.5, 0.7], None, None, True, "xyxy"),
    ([0.5, 0.55, 0.6, 0.65, 0.7], None, None, False, "xyxy"),
    (None, [0.0, 0.25, 0.5, 0.75, 1.0], None, False, "xyxy"),
    (None, [0.0, 0.1, 0.2, 0.3], None, False, "xyxy"),
    ([0.5], [0.0, 0.5, 1.0], None, False, "xyxy"),
    (None, None, [1, 2, 3], False, "xyxy"),
    (None, None, [1, 5, 100], False, "xyxy"),
    (None, None, [2, 4, 6], True, "xyxy"),
    ([0.5, 0.75], None, [1, 3, 5], False, "xyxy"),
    ([0.5, 0.75], [0.0, 0.25, 0.5, 0.75, 1.0], [1, 3, 5], True, "xyxy"),
    (None, None, None, False, "xywh"),
    (None, None, None, False, "cxcywh"),
    ([0.4, 0.6], None, None, False, "xywh"),
    (None, None, [1, 2, 100], False, "cxcywh"),
    ([0.5], [0.0, 1.0], [1, 10, 100], True, "xyxy"),
    (None, [0.5], None, False, "xyxy"),
]


@pytest.mark.parametrize("iou_thr,rec_thr,max_det,class_metrics,box_format",
                         GRID, ids=[f"combo{i}" for i in range(len(GRID))])
def test_map_option_surface_vs_legacy(iou_thr, rec_thr, max_det, class_metrics, box_format):
    scenes = _scenes()
    kwargs = dict(
        iou_thresholds=iou_thr, rec_thresholds=rec_thr,
        max_detection_thresholds=max_det, class_metrics=class_metrics,
        box_format=box_format,
    )
    ours = MeanAveragePrecision(iou_type="bbox", **kwargs)
    ref = LegacyMAP(iou_type="bbox", **kwargs)
    conv = _CONVERT[box_format]
    for d, g in scenes:
        d2 = dict(d, boxes=conv(d["boxes"].astype(np.float64)).astype(np.float32))
        g2 = dict(g, boxes=conv(g["boxes"].astype(np.float64)).astype(np.float32))
        ours.update([d2], [g2])
        ref.update(
            [{k: torch.tensor(v) for k, v in d2.items()}],
            [{k: torch.tensor(v) for k, v in g2.items()}],
        )
    r_ours = {k: np.asarray(v) for k, v in ours.compute().items()}
    r_ref = {k: np.asarray(v.detach().numpy() if hasattr(v, "detach") else v)
             for k, v in ref.compute().items()}

    keys = list(BASE_KEYS)
    mds = sorted(max_det) if max_det is not None else [1, 10, 100]
    keys += [f"mar_{m}" for m in mds if f"mar_{m}" in r_ref]
    if 0.5 not in (iou_thr or [0.5]):
        keys.remove("map_50")
    if 0.75 not in (iou_thr or [0.75]):
        keys.remove("map_75")
    for k in keys:
        assert np.allclose(r_ours[k], r_ref[k], atol=1e-6), f"{k}: ours={r_ours[k]} ref={r_ref[k]}"
    if class_metrics:
        assert np.allclose(r_ours["map_per_class"], r_ref["map_per_class"], atol=1e-6), (
            r_ours["map_per_class"], r_ref["map_per_class"])
