"""Functional kernel layer (L3). Parity: reference ``functional/__init__.py``
(~97 re-exports). Domain namespaces are importable as
``torchmetrics_tpu.functional.<domain>``; the pairwise family is re-exported
flat (it has no modular classes, reference §2.8).
"""
from . import (
    audio,
    classification,
    clustering,
    detection,
    image,
    multimodal,
    nominal,
    pairwise,
    regression,
    retrieval,
    segmentation,
    text,
)
from .pairwise import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
    pairwise_minkowski_distance,
)

__all__ = [
    "audio",
    "classification",
    "clustering",
    "detection",
    "image",
    "multimodal",
    "nominal",
    "pairwise",
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
    "pairwise_minkowski_distance",
    "regression",
    "retrieval",
    "segmentation",
    "text",
]
